"""Asyncio RPC plane used by every runtime process.

The reference's control plane is gRPC with typed async client/server wrappers
(reference: src/ray/rpc/grpc_server.h, client_call.h, 21 .proto services under
src/ray/protobuf/).  The TPU-native build replaces that with a single lean
length-prefixed pickle protocol over TCP — one connection class serves the
GCS, raylet, and worker-to-worker planes.  Rationale: the control plane rides
DCN either way; what matters on TPU is that the per-message Python overhead is
tiny (the reference pays gRPC+protobuf serialization per task push; we pay one
pickle).  Messages:

  REQ(id, method, body) -> REP(id, result) | ERR(id, exception)
  PUSH(method, body)                       (one-way notification)
  BATCH(frames)                            (coalesced burst of the above)
  BLOB(id, method, header, raw bytes)      (request/notify w/ raw payload)
  BLOB_REP(id, header, raw bytes)          (reply w/ raw payload)

Hot-path design (the RPC fast path, see README "RPC fast path"):

* REQ/PUSH payloads carry the method name OUT OF BAND as a 2-byte
  length-prefixed utf-8 string ahead of the pickled body, so the
  envelope tuple ``(method, body)`` is never pickled: the per-method
  prefix is encoded once and cached (`_envelope_prefix`), and the
  receive side interns the decoded name — hot methods
  (``push_actor_task``, ``push_task``, object-plane calls) pay zero
  envelope encode/decode after the first call.
* Inbound REQ/PUSH frames are dispatched INLINE on the read loop: the
  handler coroutine is stepped once synchronously, and only a handler
  that actually suspends (awaits something unfinished) is handed to a
  task (`_Resume` replays the pending yield into the Task protocol).
  Handlers that complete without awaiting — the common case for
  replies, acks, and table lookups — never allocate a Task.
* KIND_BATCH coalesces a burst of small requests to one peer into one
  frame (one header read + one write syscall for the whole burst);
  the worker's per-actor send queue uses it for pipelined submission.

Raw-buffer frames (the object transfer plane, see README "Object
transfer plane"): KIND_BLOB / KIND_BLOB_REP carry ``(method, small
pickled header, raw payload)`` where the payload NEVER touches pickle —
the sender hands the transport a single ``memoryview`` (e.g. an object
store arena slice) and the receiver copies socket bytes straight into a
destination buffer resolved BEFORE the body is read (a pre-registered
reply sink, or the connection's ``blob_provider`` for inbound pushes).
Cross-node object chunks ride these frames; everything else is pickled
with protocol 5.
"""

from __future__ import annotations

import asyncio
import io
import logging
import os
import pickle
import struct
import time
import traceback
import weakref

from ray_tpu._private import failpoints
from ray_tpu._private.config import GLOBAL_CONFIG as cfg

logger = logging.getLogger(__name__)

_HDR = struct.Struct("<IBQ")  # payload_len, kind, msg_id
KIND_REQ = 0
KIND_REP = 1
KIND_ERR = 2
KIND_PUSH = 3
KIND_BATCH = 4
KIND_BLOB = 5       # method + pickled header + raw payload (msg_id 0 = one-way)
KIND_BLOB_REP = 6   # pickled header + raw payload into a registered sink
KIND_PING = 7       # keepalive probe (no payload; answered with PONG)
KIND_PONG = 8       # keepalive answer (any inbound frame proves liveness)

# Every live Connection in this process, for the fault-injection plane:
# when failpoints.set_conn_rules changes partition/slow-link rules, the
# flags of existing connections are re-resolved through this set.
_LIVE_CONNS: "weakref.WeakSet" = weakref.WeakSet()

# Sentinel distinguishing "caller gave no timeout" (-> the config
# default deadline applies) from an EXPLICIT timeout=None (the caller
# wants an unbounded wait: push_task on a long task, lease requests
# parked as autoscaler demand).
_DEFAULT_TIMEOUT = object()


def _default_timeout(timeout):
    if timeout is _DEFAULT_TIMEOUT:
        t = cfg.rpc_request_timeout_s
        return t if t and t > 0 else None
    return timeout


class _InjectedDisconnect(ConnectionError):
    """Raised inside the read loop by a 'disconnect' failpoint; the
    loop's OSError handling turns it into a normal connection loss."""

_MLEN = struct.Struct("<H")  # method-name length (REQ/PUSH payload prefix)
_HLEN = struct.Struct("<I")  # pickled-header length (BLOB/BLOB_REP prefix)

# Raw blob bodies are consumed from the stream in bounded slices: one
# memcpy from the socket buffer into the destination view, never a
# whole-object intermediate allocation.
_BLOB_IO_CHUNK = 1 << 20

_PICKLE_PROTO = 5

# method name -> encoded `<len><utf8>` payload prefix (sender side), and
# raw method bytes -> interned str (receiver side).  Both are tiny,
# append-only, and process-lifetime: method names are a closed set.
_ENV_PREFIX: dict[str, bytes] = {}
_METHOD_INTERN: dict[bytes, str] = {}


def _envelope_prefix(method: str) -> bytes:
    pre = _ENV_PREFIX.get(method)
    if pre is None:
        mb = method.encode("utf-8")
        pre = _ENV_PREFIX[method] = _MLEN.pack(len(mb)) + mb
    return pre


def _intern_method(raw: bytes) -> str:
    m = _METHOD_INTERN.get(raw)
    if m is None:
        m = _METHOD_INTERN[raw] = raw.decode("utf-8")
    return m


class RpcError(Exception):
    pass


def enable_eager_tasks(loop=None) -> None:
    """Eager task execution (py3.12+): create_task runs the coroutine
    synchronously until its first true suspension, removing a loop-
    scheduling hop from every RPC serve/submit on the control plane.
    Semantics note: task bodies may now run BEFORE create_task returns —
    callers must not rely on deferred start (reviewed: protocol/worker/
    raylet/gcs call sites hold no such assumption)."""
    factory = getattr(asyncio, "eager_task_factory", None)
    if factory is None:
        return
    if loop is None:
        loop = asyncio.get_event_loop()
    loop.set_task_factory(factory)


# Per-method handler service-time accounting for every RPC served by this
# process (reference: the instrumented asio event loop's per-handler stats,
# src/ray/common/event_stats.h).  Accumulation is three float ops per call;
# snapshots ride the telemetry push and back `handler_stats()` debugging.
HANDLER_STATS: dict = {}


def _record_handler(method: str, dt: float, inline: bool = False) -> None:
    s = HANDLER_STATS.get(method)
    if s is None:
        s = HANDLER_STATS[method] = [0, 0.0, 0.0]
    s[0] += 1
    s[1] += dt
    if dt > s[2]:
        s[2] = dt
    # Slow INLINE handlers land in the span ring (rpc.slow): one float
    # compare on the hot path; the import resolves once.  A wedged IO
    # plane then shows WHICH handler ate the loop, with timestamps,
    # instead of only the aggregate mean in handler_stats.  Only the
    # inline path qualifies — a handler that suspended was awaiting
    # (long-polls, task execution), not blocking the loop, and flagging
    # those would flood the ring with healthy calls.
    slow_ms = cfg.trace_rpc_slow_ms
    if inline and slow_ms > 0 and dt * 1000.0 >= slow_ms:
        global _tracing
        if _tracing is None:
            from ray_tpu._private import tracing as _tracing_mod
            _tracing = _tracing_mod
        _tracing.record("rpc", "rpc.slow", time.time() - dt, dt,
                        args={"method": method})


_tracing = None  # lazily bound by _record_handler's slow path


def handler_stats_snapshot() -> dict:
    """{method: {count, total_s, max_s, mean_ms}} served by this process."""
    out = {}
    for m, (c, t, mx) in HANDLER_STATS.items():
        out[m] = {"count": c, "total_s": round(t, 6),
                  "max_s": round(mx, 6),
                  "mean_ms": round(1000.0 * t / c, 3) if c else 0.0}
    return out


class RemoteError(RpcError):
    """Raised on the caller when the handler raised; carries remote traceback."""

    def __init__(self, cause_repr: str, tb: str = ""):
        super().__init__(f"{cause_repr}\nRemote traceback:\n{tb}")
        self.cause_repr = cause_repr
        self.remote_traceback = tb

    def __reduce__(self):
        return (RemoteError, (self.cause_repr, self.remote_traceback))


class ConnectionLost(RpcError):
    pass


class _Resume:
    """Awaitable adopting a handler coroutine that was stepped inline on
    the read loop and suspended: replays the pending yield (the future
    the coroutine is waiting on, `_asyncio_future_blocking` flag intact)
    to the driving Task, then delegates the rest like ``yield from``.
    This is what lets inline dispatch fall back to a task ONLY for
    handlers that actually await, without re-running any side effects."""

    __slots__ = ("coro", "first")

    def __init__(self, coro, first):
        self.coro = coro
        self.first = first

    def __await__(self):
        coro = self.coro
        pending = self.first
        while True:
            try:
                value = yield pending
            except BaseException as e:
                try:
                    pending = coro.throw(e)
                except StopIteration as si:
                    return si.value
                continue
            try:
                pending = coro.send(value)
            except StopIteration as si:
                return si.value


def dumps(obj) -> bytes:
    return pickle.dumps(obj, protocol=_PICKLE_PROTO)


def pubsub_batch_messages(body) -> list:
    """Decode one coalesced ``pubsub_batch`` push body: either plain
    ``messages`` or ``raw`` (per-message blobs the GCS pickled ONCE and
    fanned out to every subscriber)."""
    msgs = body.get("messages")
    if msgs is not None:
        return msgs
    return [loads(b) for b in body.get("raw", ())]


def loads(data):
    return pickle.loads(data)


class Blob:
    """Handler return value carrying a raw payload: the reply rides a
    KIND_BLOB_REP frame instead of a pickled KIND_REP, so ``data`` (any
    buffer, typically an arena memoryview) is handed to the transport
    as-is — no pickle, no staging copy.  ``on_sent`` fires once the
    transport no longer references the buffer (used to drop object
    store read pins)."""

    __slots__ = ("header", "data", "on_sent")

    def __init__(self, header, data, on_sent=None):
        self.header = header
        self.data = data
        self.on_sent = on_sent

    def release(self):
        cb, self.on_sent = self.on_sent, None
        if cb is not None:
            try:
                cb()
            except Exception:
                logger.exception("blob on_sent callback failed")


class BlobFrame:
    """Inbound KIND_BLOB body handed to the handler.  ``data`` is the
    raw payload bytes, or None when the connection's blob_provider
    already routed the payload into its destination buffer (the
    zero-staging-copy receive path); ``size`` is the raw byte count
    either way."""

    __slots__ = ("header", "data", "size")

    def __init__(self, header, data, size):
        self.header = header
        self.data = data
        self.size = size


class Connection:
    """One bidirectional RPC connection.

    Both sides can issue requests and serve them; ``handler(method, body)``
    is an async callable returning the reply value.
    """

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 handler=None, name: str = "?", on_close=None,
                 blob_provider=None):
        self.reader = reader
        self.writer = writer
        self.handler = handler
        self.name = name
        self.on_close = on_close
        # Synchronous (conn, method, header, nbytes) -> writable
        # memoryview | None, consulted on the read loop BEFORE an
        # inbound KIND_BLOB body is consumed so payload bytes land
        # straight in their destination (e.g. the store arena).
        self.blob_provider = blob_provider
        self._next_id = 1
        self._pending: dict[int, asyncio.Future] = {}
        # msg_id -> writable memoryview awaiting a KIND_BLOB_REP; a
        # timed-out/cancelled request MUST unregister its sink (a late
        # reply would otherwise scribble on a recycled buffer) — late
        # frames with no sink are drained and discarded.
        self._blob_sinks: dict[int, memoryview] = {}
        # Count of blob bodies CURRENTLY being read into a sink someone
        # else owns (arena extents).  A transfer that aborts must wait
        # for this to quiesce before freeing its extent, or the read
        # loop could scribble on recycled memory (drain_sink_reads).
        self._sink_reads = 0
        self._closed = False
        self._write_lock = asyncio.Lock()
        self._drain_task: asyncio.Task | None = None
        self.close_reason: str | None = None
        self._loop = asyncio.get_running_loop()
        # Outbound frame coalescing: frames buffered within one loop
        # iteration ride ONE socket write (call_soon flushes before the
        # loop can block in the selector, so latency is unaffected).
        self._wbuf: list = []
        self._wflush_scheduled = False
        self._wflush_delayed = False
        # Bumped by every direct _flush_wbuf so a stale scheduled flush
        # callback (call_soon/call_later already queued on the loop)
        # can't flush frames admitted after it — without this, a frame
        # owed an injected delay would ride an earlier frame's pending
        # call_soon and ship undelayed.
        self._wflush_gen = 0
        # Fault-injection flags (partitions / slow links); None when the
        # fault plane is idle, so the hot path pays one attribute test.
        self._fault = (failpoints.conn_fault_for(name)
                       if failpoints.CONN_RULES else None)
        _LIVE_CONNS.add(self)
        self._last_rx = time.monotonic()
        self._ka_task: asyncio.Task | None = None
        if cfg.rpc_keepalive_idle_s > 0:
            self._ka_task = self._loop.create_task(self._keepalive_loop())
            self._ka_task.add_done_callback(
                lambda t: t.cancelled() or t.exception())
        # Last: under an eager task factory this may start reading (and
        # serving) immediately, so every attribute must already exist.
        self._reader_task = self._loop.create_task(self._read_loop())

    @classmethod
    async def connect(cls, host: str, port: int, handler=None, name: str = "?",
                      on_close=None, timeout: float = 30.0,
                      blob_provider=None):
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout)
        sock = writer.get_extra_info("socket")
        if sock is not None:
            import socket as _s
            sock.setsockopt(_s.IPPROTO_TCP, _s.TCP_NODELAY, 1)
        return cls(reader, writer, handler=handler, name=name,
                   on_close=on_close, blob_provider=blob_provider)

    @property
    def closed(self):
        return self._closed

    async def _read_loop(self):
        try:
            while True:
                hdr = await self.reader.readexactly(_HDR.size)
                plen, kind, msg_id = _HDR.unpack(hdr)
                fate = None
                if self._fault is not None or failpoints.ACTIVE:
                    fate = await self._apply_recv_fault(plen, kind)
                    if fate == "drop":
                        # A dropped frame never "arrived": _last_rx stays
                        # stale so keepalive reads a partitioned link as
                        # silence (half-open detection).
                        continue
                self._last_rx = time.monotonic()
                if kind == KIND_BLOB:
                    # Raw-payload frames stream their body into a
                    # resolved destination instead of materializing the
                    # whole payload.
                    await self._recv_blob(plen, msg_id)
                    continue
                if kind == KIND_BLOB_REP:
                    await self._recv_blob_rep(plen, msg_id)
                    continue
                payload = await self.reader.readexactly(plen) if plen else b""
                if kind == KIND_REQ:
                    self._dispatch_frame(msg_id, payload, False)
                    if fate == "dup":
                        self._dispatch_frame(msg_id, payload, False)
                elif kind == KIND_REP:
                    fut = self._pending.pop(msg_id, None)
                    if fut is not None and not fut.done():
                        fut.set_result(loads(payload))
                elif kind == KIND_ERR:
                    fut = self._pending.pop(msg_id, None)
                    if fut is not None and not fut.done():
                        cause_repr, tb = loads(payload)
                        fut.set_exception(RemoteError(cause_repr, tb))
                elif kind == KIND_PUSH:
                    self._dispatch_frame(0, payload, True)
                    if fate == "dup":
                        self._dispatch_frame(0, payload, True)
                elif kind == KIND_BATCH:
                    self._dispatch_batch(payload)
                    if fate == "dup":
                        self._dispatch_batch(payload)
                elif kind == KIND_PING:
                    try:
                        self._send_nowait(KIND_PONG, 0, b"")
                    except ConnectionLost:
                        pass
                elif kind == KIND_PONG:
                    pass  # _last_rx above is the whole point
        except asyncio.IncompleteReadError:
            self.close_reason = self.close_reason or "peer closed connection"
        except (ConnectionResetError, OSError) as e:
            self.close_reason = self.close_reason or (
                f"{type(e).__name__}: {e}")
        except asyncio.CancelledError:
            self.close_reason = self.close_reason or "closed locally"
            return
        except Exception as e:
            self.close_reason = self.close_reason or (
                f"read loop error: {e!r}")
            logger.exception("rpc read loop error on %s", self.name)
        finally:
            await self._do_close()

    def _dispatch_batch(self, payload: bytes):
        """Unpack a KIND_BATCH frame and dispatch each sub-frame in
        order (sub-frames reuse the outer header layout)."""
        view = memoryview(payload)
        off, size, end = 0, _HDR.size, len(payload)
        while off + size <= end:
            plen, kind, msg_id = _HDR.unpack_from(payload, off)
            off += size
            sub = view[off:off + plen]
            off += plen
            if kind == KIND_REQ:
                self._dispatch_frame(msg_id, sub, False)
            elif kind == KIND_PUSH:
                self._dispatch_frame(0, sub, True)
            else:
                logger.error("unexpected kind %d inside batch on %s",
                             kind, self.name)

    # ------------------------------------------------- fault injection
    async def _apply_recv_fault(self, plen: int, kind: int):
        """Consult partition flags + the protocol.recv failpoint for one
        inbound frame.  Returns "drop" (body consumed and discarded),
        "dup" (dispatch the frame twice), or None; may sleep (delay /
        slow link) or raise (injected disconnect)."""
        f = self._fault
        if f is not None:
            if f.drop_rx:
                await self._read_discard(plen)
                return "drop"
            if f.delay_rx_s:
                await asyncio.sleep(f.delay_rx_s)
        if failpoints.ACTIVE:
            act = failpoints.check("protocol.recv", peer=self.name)
            if act is not None:
                if act.kind == "drop":
                    await self._read_discard(plen)
                    return "drop"
                if act.kind == "delay":
                    await asyncio.sleep(act.delay_s)
                elif act.kind == "dup":
                    if kind in (KIND_REQ, KIND_PUSH, KIND_BATCH):
                        return "dup"
                elif act.kind in ("disconnect", "error"):
                    self.close_reason = (
                        f"failpoint: injected {act.kind}"
                        + (f" ({act.arg})" if act.arg else ""))
                    raise _InjectedDisconnect(self.close_reason)
                elif act.kind == "kill":
                    os._exit(int(act.arg or 1))
        return None

    def _send_faulted(self, kind: int):
        """Outbound fault filter.  Returns ``(fate, delay_s)``: fate
        "drop" means the frame must be silently discarded (partition /
        drop action), "dup" means it goes on the wire twice; delay_s is
        injected outbound latency for this frame (slow-link rule and/or
        a delay action — senders are sync, so the delay is applied by
        deferring the flush, not by sleeping here).  error/disconnect
        actions raise ConnectionLost like a real dead socket would."""
        delay_s = 0.0
        f = self._fault
        if f is not None:
            if f.drop_tx:
                return "drop", 0.0
            delay_s = f.delay_tx_s
        if failpoints.ACTIVE:
            act = failpoints.check("protocol.send", peer=self.name)
            if act is not None:
                if act.kind == "drop":
                    return "drop", 0.0
                if act.kind == "dup":
                    return "dup", delay_s
                if act.kind == "delay":
                    delay_s = max(delay_s, act.delay_s)
                elif act.kind == "error":
                    raise ConnectionLost(
                        f"failpoint: injected send error on {self.name}"
                        + (f" ({act.arg})" if act.arg else ""))
                elif act.kind == "disconnect":
                    self.close_reason = "failpoint: injected disconnect"
                    self._reader_task.cancel()
                    raise ConnectionLost(
                        f"failpoint: injected disconnect on {self.name}")
                elif act.kind == "kill":
                    os._exit(int(act.arg or 1))
        return None, delay_s

    async def _keepalive_loop(self):
        """Probe an idle connection that has work in flight: no inbound
        traffic for idle_s -> PING; still nothing for timeout_s after
        the probe -> the link is half-open (or the peer wedged), so fail
        it NOW — every in-flight future gets ConnectionLost instead of
        hanging forever.  Config is re-read each cycle so tests can
        tighten it on live connections."""
        while not self._closed:
            idle = cfg.rpc_keepalive_idle_s
            if idle <= 0:
                return
            await asyncio.sleep(idle)
            if self._closed:
                return
            if not self._pending and not self._blob_sinks:
                continue
            if time.monotonic() - self._last_rx < idle:
                continue
            probe_t = time.monotonic()
            try:
                self._send_nowait(KIND_PING, 0, b"")
            except Exception:
                return  # closed (or injected-closed) under us
            await asyncio.sleep(max(0.001, cfg.rpc_keepalive_timeout_s))
            if self._closed:
                return
            if self._last_rx < probe_t:
                silent = time.monotonic() - self._last_rx
                self.close_reason = (
                    f"keepalive timeout: no traffic for {silent:.1f}s "
                    f"with {len(self._pending)} in-flight request(s) "
                    "(half-open connection?)")
                self._reader_task.cancel()
                return

    async def _read_into(self, sink, n: int):
        """Consume n raw bytes off the stream into a writable view —
        bounded slices, one memcpy each, no whole-body allocation.
        Each slice refreshes _last_rx: a large body trickling over a
        slow-but-live link is PROGRESS, and keepalive (which only sees
        frame headers otherwise) must not read the long body read as
        half-open silence and kill a transfer that is advancing."""
        pos = 0
        while pos < n:
            data = await self.reader.readexactly(
                min(n - pos, _BLOB_IO_CHUNK))
            self._last_rx = time.monotonic()
            sink[pos:pos + len(data)] = data
            pos += len(data)

    async def _read_discard(self, n: int):
        # NO _last_rx refresh here: discarded bodies belong to DROPPED
        # frames (partition rules), and a partitioned link must read as
        # silence to keepalive even while bytes still hit the socket.
        while n > 0:
            data = await self.reader.readexactly(min(n, _BLOB_IO_CHUNK))
            n -= len(data)

    # Connections that carry blob traffic read the socket in 4 MiB
    # slices instead of the transport's 256 KiB default — ~2x fewer
    # loop iterations per transferred GB.  Only blob-carrying
    # connections pay the bigger recv buffer, so small-RPC latency on
    # the control plane is untouched.
    _BLOB_READ_SIZE = 4 * 1024 * 1024

    def _boost_read_size(self):
        transport = getattr(self.reader, "_transport", None)
        if transport is not None and hasattr(transport, "max_size") \
                and transport.max_size < self._BLOB_READ_SIZE:
            transport.max_size = self._BLOB_READ_SIZE

    async def _recv_blob(self, plen: int, msg_id: int):
        """Inbound KIND_BLOB: parse the method + pickled header, then
        route the raw body straight into the buffer the blob_provider
        resolves (or a scratch bytes when it declines), and dispatch
        the handler with a BlobFrame body."""
        self._boost_read_size()
        r = self.reader
        mlen, = _MLEN.unpack(await r.readexactly(_MLEN.size))
        method = _intern_method(bytes(await r.readexactly(mlen)))
        hlen, = _HLEN.unpack(await r.readexactly(_HLEN.size))
        header = loads(await r.readexactly(hlen)) if hlen else None
        nraw = plen - _MLEN.size - mlen - _HLEN.size - hlen
        sink = None
        if self.blob_provider is not None:
            try:
                sink = self.blob_provider(self, method, header, nraw)
            except Exception:
                logger.exception("blob_provider failed on %s", self.name)
                sink = None
        if sink is not None:
            self._sink_reads += 1
            try:
                await self._read_into(sink, nraw)
            finally:
                self._sink_reads -= 1
            data = None
        elif nraw:
            data = await r.readexactly(nraw)
        else:
            data = b""
        self._dispatch_body(msg_id, method, BlobFrame(header, data, nraw),
                            push=(msg_id == 0))

    async def _recv_blob_rep(self, plen: int, msg_id: int):
        """Inbound KIND_BLOB_REP: raw body goes into the sink the
        requester registered (request_blob); replies whose sink is gone
        (timed out, cancelled) are drained and dropped."""
        self._boost_read_size()
        r = self.reader
        hlen, = _HLEN.unpack(await r.readexactly(_HLEN.size))
        header = loads(await r.readexactly(hlen)) if hlen else None
        nraw = plen - _HLEN.size - hlen
        sink = self._blob_sinks.pop(msg_id, None)
        fut = self._pending.pop(msg_id, None)
        delivered = False
        if nraw == 0:
            delivered = True
        elif sink is not None and fut is not None \
                and not fut.done() and nraw <= len(sink):
            self._sink_reads += 1
            try:
                await self._read_into(sink, nraw)
            finally:
                self._sink_reads -= 1
            delivered = True
        else:
            await self._read_discard(nraw)
        if fut is not None and not fut.done():
            if delivered:
                fut.set_result(header)
            else:
                fut.set_exception(RpcError(
                    f"blob reply of {nraw} bytes had no usable sink"))

    def _dispatch_frame(self, msg_id: int, payload, push: bool):
        """Parse one inbound REQ/PUSH envelope and dispatch it."""
        try:
            mlen, = _MLEN.unpack_from(payload, 0)
            method = _intern_method(bytes(payload[2:2 + mlen]))
            body = loads(memoryview(payload)[2 + mlen:])
        except Exception:
            logger.exception("bad rpc payload on %s", self.name)
            return
        self._dispatch_body(msg_id, method, body, push)

    def _dispatch_body(self, msg_id: int, method: str, body, push: bool):
        """Serve one inbound REQ/PUSH.  The handler coroutine is stepped
        inline on the read loop; only a handler that truly suspends is
        handed to a task.  Inline-dispatch rule: a handler may run on
        the read loop iff its synchronous prefix is non-blocking — all
        rpc_* handlers satisfy this (blocking work rides executors,
        which is itself an await and thus moves to the task path)."""
        if self.handler is None:
            if not push:
                self._reply_error(msg_id, RpcError(
                    f"connection {self.name} has no handler"), "")
            return
        t0 = time.perf_counter()
        try:
            coro = self.handler(self, method, body)
            first = coro.send(None)
        except StopIteration as si:
            # Completed without awaiting: reply inline, no task.
            _record_handler(method, time.perf_counter() - t0, inline=True)
            if not push:
                self._reply_result(msg_id, method, si.value)
            return
        except Exception as e:
            # Failing handlers count too — they are exactly the calls
            # these stats exist to surface.
            _record_handler(method, time.perf_counter() - t0, inline=True)
            if push:
                logger.exception("push handler %s failed on %s",
                                 method, self.name)
            else:
                self._reply_error(msg_id, e, traceback.format_exc())
            return
        asyncio.get_running_loop().create_task(
            self._serve_rest(coro, first, msg_id, method, push, t0))

    async def _serve_rest(self, coro, first, msg_id: int, method: str,
                          push: bool, t0: float):
        """Finish a handler that suspended during inline dispatch."""
        try:
            result = await _Resume(coro, first)
        except Exception as e:
            _record_handler(method, time.perf_counter() - t0)
            if push:
                logger.exception("push handler %s failed on %s",
                                 method, self.name)
            else:
                self._reply_error(msg_id, e, traceback.format_exc())
            return
        _record_handler(method, time.perf_counter() - t0)
        if not push:
            self._reply_result(msg_id, method, result)

    def _reply_result(self, msg_id: int, method: str, result):
        if isinstance(result, Blob):
            # _send_blob_nowait takes ownership of on_sent: it runs the
            # callback (immediately, deferred, or on failure) exactly
            # once in every path.
            cb, result.on_sent = result.on_sent, None
            try:
                self._send_blob_nowait(KIND_BLOB_REP, msg_id, None,
                                       result.header, result.data,
                                       on_sent=cb)
            except ConnectionLost:
                pass
            return
        try:
            payload = dumps(result)
        except Exception as e:
            self._reply_error(msg_id, e, traceback.format_exc())
            return
        try:
            self._send_nowait(KIND_REP, msg_id, payload)
        except ConnectionLost:
            pass

    def _reply_error(self, msg_id: int, exc: Exception, tb: str):
        try:
            self._send_nowait(KIND_ERR, msg_id, dumps((repr(exc), tb)))
        except Exception:
            pass

    # Payloads at least this large skip the coalescing buffer (joining
    # would copy them); the pending small frames are flushed first so
    # wire order is preserved.
    _COALESCE_MAX = 1 << 16

    def _send_nowait(self, kind: int, msg_id: int, payload,
                     prefix: bytes = b""):
        """Queue one frame for the coalesced flush (or write it through
        for large payloads).  Loop-thread only; frames queued within one
        loop iteration ride one syscall.  No lock: nothing yields
        between the appends, so header+prefix+payload can't interleave
        with another sender.  drain() only matters for backpressure —
        once the send buffer is deep a background drain is scheduled."""
        if self._closed:
            raise ConnectionLost(
                f"connection {self.name} closed"
                + (f" ({self.close_reason})" if self.close_reason else ""))
        delay_tx = 0.0
        repeat = 1
        if self._fault is not None or failpoints.ACTIVE:
            fate, delay_tx = self._send_faulted(kind)
            if fate == "drop":
                return
            if fate == "dup":
                repeat = 2
        if (delay_tx and self._wflush_scheduled
                and not self._wflush_delayed):
            # Frames already queued this tick were admitted WITHOUT the
            # delay; ship them now so the deferred flush below actually
            # defers THIS frame instead of it riding their call_soon
            # (the stale callback no-ops via the generation guard).  A
            # pending DELAYED flush is left alone — this frame joins its
            # late batch, preserving both the delay and frame order.
            self._flush_wbuf()
        wbuf = self._wbuf
        hdr = _HDR.pack(len(prefix) + len(payload), kind, msg_id)
        for _ in range(repeat):
            wbuf.append(hdr)
            if prefix:
                wbuf.append(prefix)
            if len(payload) >= self._COALESCE_MAX and not delay_tx:
                self._flush_wbuf()  # pending smalls first, keep order
                try:
                    self.writer.write(payload)
                except (ConnectionResetError, OSError) as e:
                    self.close_reason = self.close_reason or (
                        f"{type(e).__name__}: {e}")
                    raise ConnectionLost(str(e)) from e
            else:
                wbuf.append(payload)
                if not self._wflush_scheduled:
                    self._wflush_scheduled = True
                    self._wflush_delayed = bool(delay_tx)
                    if delay_tx:
                        # Slow link: the whole buffered batch ships
                        # late, preserving frame order.
                        self._loop.call_later(delay_tx,
                                              self._scheduled_flush,
                                              self._wflush_gen)
                    else:
                        self._loop.call_soon(self._scheduled_flush,
                                             self._wflush_gen)
        transport = self.writer.transport
        if (transport is not None
                and transport.get_write_buffer_size() > 1 << 20):
            self._ensure_drain()

    def _send_blob_nowait(self, kind: int, msg_id: int, method: str | None,
                          header, data, on_sent=None):
        """Put one raw-payload frame on the wire.  The small parts
        (frame header, method, pickled header) ride the coalescing
        buffer; ``data`` is handed to the transport as ONE buffer — a
        memoryview over the arena goes out without ever being copied
        into a Python bytes.  Loop-thread only, same ordering rules as
        _send_nowait."""
        if self._closed:
            if on_sent is not None:
                on_sent()
            raise ConnectionLost(
                f"connection {self.name} closed"
                + (f" ({self.close_reason})" if self.close_reason else ""))
        if self._fault is not None or failpoints.ACTIVE:
            try:
                # "dup" is a no-op here: raw-body frames are not
                # duplicated at the transport (the transfer plane dups
                # whole chunks instead — see TransferManager).
                fate, delay_s = self._send_faulted(kind)
                if fate == "drop":
                    if on_sent is not None:
                        on_sent()
                    return
            except ConnectionLost:
                if on_sent is not None:
                    on_sent()
                raise
            if delay_s:
                # Slow link: defer the WHOLE frame (header + body), so
                # blob traffic honors injected latency like every other
                # frame.  Equal-delay call_later callbacks fire in
                # scheduling order, so successive chunks keep their
                # order; a send error after the delay can only surface
                # via the connection dying (the caller is long gone).
                def _late():
                    if self._closed:
                        if on_sent is not None:
                            on_sent()
                        return
                    try:
                        self._send_blob_now(kind, msg_id, method, header,
                                            data, on_sent)
                    except ConnectionLost:
                        pass
                self._loop.call_later(delay_s, _late)
                return
        self._send_blob_now(kind, msg_id, method, header, data, on_sent)

    def _send_blob_now(self, kind: int, msg_id: int, method: str | None,
                       header, data, on_sent=None):
        try:
            hp = dumps(header)
        except Exception:
            if on_sent is not None:
                on_sent()
            raise
        pre = _envelope_prefix(method) if method is not None else b""
        plen = len(pre) + _HLEN.size + len(hp) + len(data)
        wbuf = self._wbuf
        wbuf.append(_HDR.pack(plen, kind, msg_id))
        if pre:
            wbuf.append(pre)
        wbuf.append(_HLEN.pack(len(hp)))
        wbuf.append(hp)
        if len(data) < self._COALESCE_MAX and on_sent is None:
            # Small raw bodies (bucketed collective tails, tiny chunks)
            # ride the coalescing buffer like any other frame — one
            # write syscall per loop iteration instead of a forced
            # flush + dedicated write per blob.  Copied into a bytes
            # NOW so the caller may reuse its buffer immediately (the
            # zero-copy discipline only pays off for large payloads).
            wbuf.append(bytes(data))
            if not self._wflush_scheduled:
                self._wflush_scheduled = True
                self._loop.call_soon(self._scheduled_flush,
                                     self._wflush_gen)
            transport = self.writer.transport
            if (transport is not None
                    and transport.get_write_buffer_size() > 1 << 20):
                self._ensure_drain()
            return
        self._flush_wbuf()  # everything queued before the raw body first
        try:
            self.writer.write(data)
        except (ConnectionResetError, OSError) as e:
            if on_sent is not None:
                on_sent()
            self.close_reason = self.close_reason or (
                f"{type(e).__name__}: {e}")
            raise ConnectionLost(str(e)) from e
        transport = self.writer.transport
        if on_sent is not None:
            # py>=3.12 transports keep a REFERENCE to unsent buffers
            # (no copy); the pin behind `data` may only drop once the
            # transport no longer holds it.
            if transport is None or transport.get_write_buffer_size() == 0:
                on_sent()
            else:
                t = self._loop.create_task(self._call_when_flushed(on_sent))
                t.add_done_callback(lambda t: t.cancelled() or t.exception())
        if (transport is not None
                and transport.get_write_buffer_size() > 1 << 20):
            self._ensure_drain()

    async def _call_when_flushed(self, cb):
        """Run cb once the transport's write buffer has fully drained
        (or the connection died — buffers are gone either way)."""
        try:
            while not self._closed:
                transport = self.writer.transport
                if transport is None \
                        or transport.get_write_buffer_size() == 0:
                    break
                try:
                    await self._drain()
                except RpcError:
                    break
                if transport.get_write_buffer_size() == 0:
                    break
                await asyncio.sleep(0.005)
        finally:
            cb()

    def _scheduled_flush(self, gen: int):
        if gen == self._wflush_gen:
            self._flush_wbuf()

    def _flush_wbuf(self):
        self._wflush_scheduled = False
        self._wflush_delayed = False
        self._wflush_gen += 1
        if not self._wbuf:
            return
        buf, self._wbuf = self._wbuf, []
        if self._closed:
            return
        try:
            self.writer.write(buf[0] if len(buf) == 1 else b"".join(buf))
        except (ConnectionResetError, OSError) as e:
            # Senders already returned; the read loop notices the dead
            # socket and fails all in-flight futures via _do_close.
            self.close_reason = self.close_reason or (
                f"{type(e).__name__}: {e}")

    def _ensure_drain(self):
        if self._drain_task is None or self._drain_task.done():
            self._drain_task = asyncio.get_running_loop().create_task(
                self._drain())
            # Backpressure awaiters observe the failure through
            # backpressure(); without an awaiter the exception must
            # still be consumed (the read loop reports the dead socket).
            self._drain_task.add_done_callback(
                lambda t: t.cancelled() or t.exception())

    async def _drain(self):
        async with self._write_lock:
            try:
                await self.writer.drain()
            except (ConnectionResetError, OSError) as e:
                raise ConnectionLost(str(e)) from e

    async def backpressure(self):
        """Block while the send buffer is past the high-water mark (a
        drain is in flight).  Senders on the nowait paths call this
        between bursts so a stalled peer throttles them at ~1 MiB of
        buffered frames instead of growing the transport buffer without
        bound."""
        t = self._drain_task
        if t is not None and not t.done():
            await asyncio.shield(t)

    async def _send(self, kind: int, msg_id: int, payload,
                    prefix: bytes = b""):
        self._send_nowait(kind, msg_id, payload, prefix)
        await self.backpressure()

    def request_send_nowait(self, method: str, body=None):
        """Put a request on the wire synchronously and return the reply
        future.  Loop-thread only.  Wire order == call order (nothing
        yields), which is what the actor send queue needs for sequence
        numbering."""
        msg_id = self._next_id
        self._next_id += 1
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        try:
            self._send_nowait(KIND_REQ, msg_id, dumps(body),
                              prefix=_envelope_prefix(method))
        except BaseException:
            self._pending.pop(msg_id, None)
            raise
        return fut

    def request_send_many_nowait(self, method: str, bodies) -> list:
        """Send a burst of requests for ONE method as a single
        KIND_BATCH frame (one write, one syscall) and return their reply
        futures in order.  All-or-nothing: a write failure leaves no
        request registered."""
        prefix = _envelope_prefix(method)
        loop = asyncio.get_running_loop()
        buf = bytearray()
        futs, ids = [], []
        for body in bodies:
            msg_id = self._next_id
            self._next_id += 1
            payload = dumps(body)
            buf += _HDR.pack(len(prefix) + len(payload), KIND_REQ, msg_id)
            buf += prefix
            buf += payload
            ids.append(msg_id)
            futs.append(loop.create_future())
        for msg_id, fut in zip(ids, futs):
            self._pending[msg_id] = fut
        try:
            self._send_nowait(KIND_BATCH, 0, buf)
        except BaseException:
            for msg_id in ids:
                self._pending.pop(msg_id, None)
            raise
        return futs

    def push_send_many_nowait(self, items) -> None:
        """Send a burst of one-way pushes — ``items`` is a sequence of
        ``(method, body)`` — as a single KIND_BATCH frame (one write,
        one header read on the peer).  Sub-frames are ordinary
        KIND_PUSH frames, so receivers need no new handling beyond the
        batch unpack that request bursts already use.  The GCS pubsub
        pump rides this to fold a multi-channel drain into one
        syscall."""
        buf = bytearray()
        for method, body in items:
            prefix = _envelope_prefix(method)
            payload = dumps(body)
            buf += _HDR.pack(len(prefix) + len(payload), KIND_PUSH, 0)
            buf += prefix
            buf += payload
        self._send_nowait(KIND_BATCH, 0, buf)

    async def request_send(self, method: str, body=None):
        """Send a request and return the reply future WITHOUT awaiting it.
        Used where wire-order must be controlled by the caller (e.g. actor
        task sequence numbers) while replies are awaited concurrently."""
        fut = self.request_send_nowait(method, body)
        await self.backpressure()
        return fut

    async def request(self, method: str, body=None,
                      timeout=_DEFAULT_TIMEOUT):
        """Round-trip RPC.  An unspecified ``timeout`` gets the config
        default deadline (cfg.rpc_request_timeout_s) so no request path
        can wait unbounded by accident; pass ``timeout=None`` explicitly
        to opt into an unbounded wait."""
        timeout = _default_timeout(timeout)
        msg_id = self._next_id
        self._next_id += 1
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        try:
            self._send_nowait(KIND_REQ, msg_id, dumps(body),
                              prefix=_envelope_prefix(method))
        except BaseException:
            self._pending.pop(msg_id, None)
            raise
        await self.backpressure()
        if timeout is not None:
            try:
                return await asyncio.wait_for(fut, timeout)
            finally:
                self._pending.pop(msg_id, None)
        return await fut

    async def request_blob(self, method: str, body, sink,
                           timeout=_DEFAULT_TIMEOUT):
        """Send a pickled request whose reply arrives as a raw
        KIND_BLOB_REP written DIRECTLY into ``sink`` (a writable
        memoryview, e.g. an arena slice).  Returns the reply's small
        pickled header; a handler that answers with a plain value (an
        error dict) resolves the same future via the normal REP path.
        On timeout/cancel the sink is unregistered before re-raising so
        a late frame can never scribble on a recycled buffer."""
        timeout = _default_timeout(timeout)
        msg_id = self._next_id
        self._next_id += 1
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        self._blob_sinks[msg_id] = sink
        try:
            self._send_nowait(KIND_REQ, msg_id, dumps(body),
                              prefix=_envelope_prefix(method))
        except BaseException:
            self._pending.pop(msg_id, None)
            self._blob_sinks.pop(msg_id, None)
            raise
        await self.backpressure()
        try:
            if timeout is not None:
                return await asyncio.wait_for(fut, timeout)
            return await fut
        finally:
            self._pending.pop(msg_id, None)
            self._blob_sinks.pop(msg_id, None)

    async def blob_request(self, method: str, header, data,
                           timeout=_DEFAULT_TIMEOUT):
        """Send a raw-payload request (KIND_BLOB) — ``data`` rides the
        wire as one memoryview handoff, never pickled — and await the
        handler's (small, pickled) reply."""
        timeout = _default_timeout(timeout)
        msg_id = self._next_id
        self._next_id += 1
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        try:
            self._send_blob_nowait(KIND_BLOB, msg_id, method, header, data)
        except BaseException:
            self._pending.pop(msg_id, None)
            raise
        await self.backpressure()
        try:
            if timeout is not None:
                return await asyncio.wait_for(fut, timeout)
            return await fut
        finally:
            self._pending.pop(msg_id, None)

    async def blob_push(self, method: str, header, data):
        """One-way raw-payload frame (no reply expected)."""
        self._send_blob_nowait(KIND_BLOB, 0, method, header, data)
        await self.backpressure()

    async def drain_sink_reads(self, timeout: float = 30.0):
        """Wait until no blob body is mid-read into a caller-owned sink
        on this connection.  An aborting transfer calls this BEFORE
        freeing its destination extent; bounded because a read either
        progresses or the connection dies."""
        deadline = time.monotonic() + timeout
        while self._sink_reads and not self._closed \
                and time.monotonic() < deadline:
            await asyncio.sleep(0.002)

    async def push(self, method: str, body=None):
        await self._send(KIND_PUSH, 0, dumps(body),
                         prefix=_envelope_prefix(method))

    async def _do_close(self):
        if self._closed:
            return
        try:
            self._flush_wbuf()  # last replies out before the FIN
        except Exception:
            pass
        self._closed = True
        if self._ka_task is not None:
            self._ka_task.cancel()
        reason = self.close_reason or "connection lost"
        exc = ConnectionLost(
            f"connection to {self.name} lost ({reason}); "
            "in-flight request failed")
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()
        self._blob_sinks.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        if self.on_close is not None:
            try:
                res = self.on_close(self)
                if asyncio.iscoroutine(res):
                    await res
            except Exception:
                logger.exception("on_close for %s failed", self.name)

    async def close(self):
        self.close_reason = self.close_reason or "closed locally"
        self._reader_task.cancel()
        await self._do_close()


class RpcServer:
    """Listens for connections; each served by ``handler(conn, method, body)``."""

    def __init__(self, handler, host: str = "127.0.0.1", name: str = "server",
                 on_connect=None, on_disconnect=None, blob_provider=None):
        self.handler = handler
        self.host = host
        self.name = name
        self.on_connect = on_connect
        self.on_disconnect = on_disconnect
        self.blob_provider = blob_provider
        self.port: int | None = None
        self._server: asyncio.AbstractServer | None = None
        self.connections: set[Connection] = set()

    async def start(self, port: int = 0):
        self._server = await asyncio.start_server(self._on_client, self.host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def _on_client(self, reader, writer):
        sock = writer.get_extra_info("socket")
        if sock is not None:
            import socket as _s
            sock.setsockopt(_s.IPPROTO_TCP, _s.TCP_NODELAY, 1)
        conn = Connection(reader, writer, handler=self.handler,
                          name=f"{self.name}-peer", on_close=self._on_conn_close,
                          blob_provider=self.blob_provider)
        self.connections.add(conn)
        if self.on_connect is not None:
            res = self.on_connect(conn)
            if asyncio.iscoroutine(res):
                await res

    async def _on_conn_close(self, conn):
        self.connections.discard(conn)
        if self.on_disconnect is not None:
            res = self.on_disconnect(conn)
            if asyncio.iscoroutine(res):
                await res

    async def stop(self):
        if self._server is not None:
            self._server.close()
        # Close live conns BEFORE wait_closed: in py3.12 wait_closed blocks
        # until every transport the server spawned has closed.
        for conn in list(self.connections):
            await conn.close()
        if self._server is not None:
            try:
                await self._server.wait_closed()
            except Exception:
                pass
