"""Asyncio RPC plane used by every runtime process.

The reference's control plane is gRPC with typed async client/server wrappers
(reference: src/ray/rpc/grpc_server.h, client_call.h, 21 .proto services under
src/ray/protobuf/).  The TPU-native build replaces that with a single lean
length-prefixed pickle protocol over TCP — one connection class serves the
GCS, raylet, and worker-to-worker planes.  Rationale: the control plane rides
DCN either way; what matters on TPU is that the per-message Python overhead is
tiny (the reference pays gRPC+protobuf serialization per task push; we pay one
pickle).  Messages:

  REQ(id, method, body) -> REP(id, result) | ERR(id, exception)
  PUSH(method, body)                       (one-way notification)
  BATCH(frames)                            (coalesced burst of the above)

Hot-path design (the RPC fast path, see README "RPC fast path"):

* REQ/PUSH payloads carry the method name OUT OF BAND as a 2-byte
  length-prefixed utf-8 string ahead of the pickled body, so the
  envelope tuple ``(method, body)`` is never pickled: the per-method
  prefix is encoded once and cached (`_envelope_prefix`), and the
  receive side interns the decoded name — hot methods
  (``push_actor_task``, ``push_task``, object-plane calls) pay zero
  envelope encode/decode after the first call.
* Inbound REQ/PUSH frames are dispatched INLINE on the read loop: the
  handler coroutine is stepped once synchronously, and only a handler
  that actually suspends (awaits something unfinished) is handed to a
  task (`_Resume` replays the pending yield into the Task protocol).
  Handlers that complete without awaiting — the common case for
  replies, acks, and table lookups — never allocate a Task.
* KIND_BATCH coalesces a burst of small requests to one peer into one
  frame (one header read + one write syscall for the whole burst);
  the worker's per-actor send queue uses it for pipelined submission.

All payloads are pickled with protocol 5; large buffers never travel this
plane (they go through the shared-memory object store, see shm_store.py).
"""

from __future__ import annotations

import asyncio
import io
import logging
import pickle
import struct
import time
import traceback

logger = logging.getLogger(__name__)

_HDR = struct.Struct("<IBQ")  # payload_len, kind, msg_id
KIND_REQ = 0
KIND_REP = 1
KIND_ERR = 2
KIND_PUSH = 3
KIND_BATCH = 4

_MLEN = struct.Struct("<H")  # method-name length (REQ/PUSH payload prefix)

_PICKLE_PROTO = 5

# method name -> encoded `<len><utf8>` payload prefix (sender side), and
# raw method bytes -> interned str (receiver side).  Both are tiny,
# append-only, and process-lifetime: method names are a closed set.
_ENV_PREFIX: dict[str, bytes] = {}
_METHOD_INTERN: dict[bytes, str] = {}


def _envelope_prefix(method: str) -> bytes:
    pre = _ENV_PREFIX.get(method)
    if pre is None:
        mb = method.encode("utf-8")
        pre = _ENV_PREFIX[method] = _MLEN.pack(len(mb)) + mb
    return pre


def _intern_method(raw: bytes) -> str:
    m = _METHOD_INTERN.get(raw)
    if m is None:
        m = _METHOD_INTERN[raw] = raw.decode("utf-8")
    return m


class RpcError(Exception):
    pass


def enable_eager_tasks(loop=None) -> None:
    """Eager task execution (py3.12+): create_task runs the coroutine
    synchronously until its first true suspension, removing a loop-
    scheduling hop from every RPC serve/submit on the control plane.
    Semantics note: task bodies may now run BEFORE create_task returns —
    callers must not rely on deferred start (reviewed: protocol/worker/
    raylet/gcs call sites hold no such assumption)."""
    factory = getattr(asyncio, "eager_task_factory", None)
    if factory is None:
        return
    if loop is None:
        loop = asyncio.get_event_loop()
    loop.set_task_factory(factory)


# Per-method handler service-time accounting for every RPC served by this
# process (reference: the instrumented asio event loop's per-handler stats,
# src/ray/common/event_stats.h).  Accumulation is three float ops per call;
# snapshots ride the telemetry push and back `handler_stats()` debugging.
HANDLER_STATS: dict = {}


def _record_handler(method: str, dt: float) -> None:
    s = HANDLER_STATS.get(method)
    if s is None:
        s = HANDLER_STATS[method] = [0, 0.0, 0.0]
    s[0] += 1
    s[1] += dt
    if dt > s[2]:
        s[2] = dt


def handler_stats_snapshot() -> dict:
    """{method: {count, total_s, max_s, mean_ms}} served by this process."""
    out = {}
    for m, (c, t, mx) in HANDLER_STATS.items():
        out[m] = {"count": c, "total_s": round(t, 6),
                  "max_s": round(mx, 6),
                  "mean_ms": round(1000.0 * t / c, 3) if c else 0.0}
    return out


class RemoteError(RpcError):
    """Raised on the caller when the handler raised; carries remote traceback."""

    def __init__(self, cause_repr: str, tb: str = ""):
        super().__init__(f"{cause_repr}\nRemote traceback:\n{tb}")
        self.cause_repr = cause_repr
        self.remote_traceback = tb

    def __reduce__(self):
        return (RemoteError, (self.cause_repr, self.remote_traceback))


class ConnectionLost(RpcError):
    pass


class _Resume:
    """Awaitable adopting a handler coroutine that was stepped inline on
    the read loop and suspended: replays the pending yield (the future
    the coroutine is waiting on, `_asyncio_future_blocking` flag intact)
    to the driving Task, then delegates the rest like ``yield from``.
    This is what lets inline dispatch fall back to a task ONLY for
    handlers that actually await, without re-running any side effects."""

    __slots__ = ("coro", "first")

    def __init__(self, coro, first):
        self.coro = coro
        self.first = first

    def __await__(self):
        coro = self.coro
        pending = self.first
        while True:
            try:
                value = yield pending
            except BaseException as e:
                try:
                    pending = coro.throw(e)
                except StopIteration as si:
                    return si.value
                continue
            try:
                pending = coro.send(value)
            except StopIteration as si:
                return si.value


def dumps(obj) -> bytes:
    return pickle.dumps(obj, protocol=_PICKLE_PROTO)


def loads(data):
    return pickle.loads(data)


class Connection:
    """One bidirectional RPC connection.

    Both sides can issue requests and serve them; ``handler(method, body)``
    is an async callable returning the reply value.
    """

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 handler=None, name: str = "?", on_close=None):
        self.reader = reader
        self.writer = writer
        self.handler = handler
        self.name = name
        self.on_close = on_close
        self._next_id = 1
        self._pending: dict[int, asyncio.Future] = {}
        self._closed = False
        self._write_lock = asyncio.Lock()
        self._drain_task: asyncio.Task | None = None
        self.close_reason: str | None = None
        self._loop = asyncio.get_running_loop()
        # Outbound frame coalescing: frames buffered within one loop
        # iteration ride ONE socket write (call_soon flushes before the
        # loop can block in the selector, so latency is unaffected).
        self._wbuf: list = []
        self._wflush_scheduled = False
        # Last: under an eager task factory this may start reading (and
        # serving) immediately, so every attribute must already exist.
        self._reader_task = self._loop.create_task(self._read_loop())

    @classmethod
    async def connect(cls, host: str, port: int, handler=None, name: str = "?",
                      on_close=None, timeout: float = 30.0):
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout)
        sock = writer.get_extra_info("socket")
        if sock is not None:
            import socket as _s
            sock.setsockopt(_s.IPPROTO_TCP, _s.TCP_NODELAY, 1)
        return cls(reader, writer, handler=handler, name=name, on_close=on_close)

    @property
    def closed(self):
        return self._closed

    async def _read_loop(self):
        try:
            while True:
                hdr = await self.reader.readexactly(_HDR.size)
                plen, kind, msg_id = _HDR.unpack(hdr)
                payload = await self.reader.readexactly(plen) if plen else b""
                if kind == KIND_REQ:
                    self._dispatch_frame(msg_id, payload, False)
                elif kind == KIND_REP:
                    fut = self._pending.pop(msg_id, None)
                    if fut is not None and not fut.done():
                        fut.set_result(loads(payload))
                elif kind == KIND_ERR:
                    fut = self._pending.pop(msg_id, None)
                    if fut is not None and not fut.done():
                        cause_repr, tb = loads(payload)
                        fut.set_exception(RemoteError(cause_repr, tb))
                elif kind == KIND_PUSH:
                    self._dispatch_frame(0, payload, True)
                elif kind == KIND_BATCH:
                    self._dispatch_batch(payload)
        except asyncio.IncompleteReadError:
            self.close_reason = self.close_reason or "peer closed connection"
        except (ConnectionResetError, OSError) as e:
            self.close_reason = self.close_reason or (
                f"{type(e).__name__}: {e}")
        except asyncio.CancelledError:
            self.close_reason = self.close_reason or "closed locally"
            return
        except Exception as e:
            self.close_reason = self.close_reason or (
                f"read loop error: {e!r}")
            logger.exception("rpc read loop error on %s", self.name)
        finally:
            await self._do_close()

    def _dispatch_batch(self, payload: bytes):
        """Unpack a KIND_BATCH frame and dispatch each sub-frame in
        order (sub-frames reuse the outer header layout)."""
        view = memoryview(payload)
        off, size, end = 0, _HDR.size, len(payload)
        while off + size <= end:
            plen, kind, msg_id = _HDR.unpack_from(payload, off)
            off += size
            sub = view[off:off + plen]
            off += plen
            if kind == KIND_REQ:
                self._dispatch_frame(msg_id, sub, False)
            elif kind == KIND_PUSH:
                self._dispatch_frame(0, sub, True)
            else:
                logger.error("unexpected kind %d inside batch on %s",
                             kind, self.name)

    def _dispatch_frame(self, msg_id: int, payload, push: bool):
        """Serve one inbound REQ/PUSH.  The handler coroutine is stepped
        inline on the read loop; only a handler that truly suspends is
        handed to a task.  Inline-dispatch rule: a handler may run on
        the read loop iff its synchronous prefix is non-blocking — all
        rpc_* handlers satisfy this (blocking work rides executors,
        which is itself an await and thus moves to the task path)."""
        try:
            mlen, = _MLEN.unpack_from(payload, 0)
            method = _intern_method(bytes(payload[2:2 + mlen]))
            body = loads(memoryview(payload)[2 + mlen:])
        except Exception:
            logger.exception("bad rpc payload on %s", self.name)
            return
        if self.handler is None:
            if not push:
                self._reply_error(msg_id, RpcError(
                    f"connection {self.name} has no handler"), "")
            return
        t0 = time.perf_counter()
        try:
            coro = self.handler(self, method, body)
            first = coro.send(None)
        except StopIteration as si:
            # Completed without awaiting: reply inline, no task.
            _record_handler(method, time.perf_counter() - t0)
            if not push:
                self._reply_result(msg_id, method, si.value)
            return
        except Exception as e:
            # Failing handlers count too — they are exactly the calls
            # these stats exist to surface.
            _record_handler(method, time.perf_counter() - t0)
            if push:
                logger.exception("push handler %s failed on %s",
                                 method, self.name)
            else:
                self._reply_error(msg_id, e, traceback.format_exc())
            return
        asyncio.get_running_loop().create_task(
            self._serve_rest(coro, first, msg_id, method, push, t0))

    async def _serve_rest(self, coro, first, msg_id: int, method: str,
                          push: bool, t0: float):
        """Finish a handler that suspended during inline dispatch."""
        try:
            result = await _Resume(coro, first)
        except Exception as e:
            _record_handler(method, time.perf_counter() - t0)
            if push:
                logger.exception("push handler %s failed on %s",
                                 method, self.name)
            else:
                self._reply_error(msg_id, e, traceback.format_exc())
            return
        _record_handler(method, time.perf_counter() - t0)
        if not push:
            self._reply_result(msg_id, method, result)

    def _reply_result(self, msg_id: int, method: str, result):
        try:
            payload = dumps(result)
        except Exception as e:
            self._reply_error(msg_id, e, traceback.format_exc())
            return
        try:
            self._send_nowait(KIND_REP, msg_id, payload)
        except ConnectionLost:
            pass

    def _reply_error(self, msg_id: int, exc: Exception, tb: str):
        try:
            self._send_nowait(KIND_ERR, msg_id, dumps((repr(exc), tb)))
        except Exception:
            pass

    # Payloads at least this large skip the coalescing buffer (joining
    # would copy them); the pending small frames are flushed first so
    # wire order is preserved.
    _COALESCE_MAX = 1 << 16

    def _send_nowait(self, kind: int, msg_id: int, payload,
                     prefix: bytes = b""):
        """Queue one frame for the coalesced flush (or write it through
        for large payloads).  Loop-thread only; frames queued within one
        loop iteration ride one syscall.  No lock: nothing yields
        between the appends, so header+prefix+payload can't interleave
        with another sender.  drain() only matters for backpressure —
        once the send buffer is deep a background drain is scheduled."""
        if self._closed:
            raise ConnectionLost(
                f"connection {self.name} closed"
                + (f" ({self.close_reason})" if self.close_reason else ""))
        wbuf = self._wbuf
        wbuf.append(_HDR.pack(len(prefix) + len(payload), kind, msg_id))
        if prefix:
            wbuf.append(prefix)
        if len(payload) >= self._COALESCE_MAX:
            self._flush_wbuf()  # pending smalls first, keep order
            try:
                self.writer.write(payload)
            except (ConnectionResetError, OSError) as e:
                self.close_reason = self.close_reason or (
                    f"{type(e).__name__}: {e}")
                raise ConnectionLost(str(e)) from e
        else:
            wbuf.append(payload)
            if not self._wflush_scheduled:
                self._wflush_scheduled = True
                self._loop.call_soon(self._flush_wbuf)
        transport = self.writer.transport
        if (transport is not None
                and transport.get_write_buffer_size() > 1 << 20):
            self._ensure_drain()

    def _flush_wbuf(self):
        self._wflush_scheduled = False
        if not self._wbuf:
            return
        buf, self._wbuf = self._wbuf, []
        if self._closed:
            return
        try:
            self.writer.write(buf[0] if len(buf) == 1 else b"".join(buf))
        except (ConnectionResetError, OSError) as e:
            # Senders already returned; the read loop notices the dead
            # socket and fails all in-flight futures via _do_close.
            self.close_reason = self.close_reason or (
                f"{type(e).__name__}: {e}")

    def _ensure_drain(self):
        if self._drain_task is None or self._drain_task.done():
            self._drain_task = asyncio.get_running_loop().create_task(
                self._drain())
            # Backpressure awaiters observe the failure through
            # backpressure(); without an awaiter the exception must
            # still be consumed (the read loop reports the dead socket).
            self._drain_task.add_done_callback(
                lambda t: t.cancelled() or t.exception())

    async def _drain(self):
        async with self._write_lock:
            try:
                await self.writer.drain()
            except (ConnectionResetError, OSError) as e:
                raise ConnectionLost(str(e)) from e

    async def backpressure(self):
        """Block while the send buffer is past the high-water mark (a
        drain is in flight).  Senders on the nowait paths call this
        between bursts so a stalled peer throttles them at ~1 MiB of
        buffered frames instead of growing the transport buffer without
        bound."""
        t = self._drain_task
        if t is not None and not t.done():
            await asyncio.shield(t)

    async def _send(self, kind: int, msg_id: int, payload,
                    prefix: bytes = b""):
        self._send_nowait(kind, msg_id, payload, prefix)
        await self.backpressure()

    def request_send_nowait(self, method: str, body=None):
        """Put a request on the wire synchronously and return the reply
        future.  Loop-thread only.  Wire order == call order (nothing
        yields), which is what the actor send queue needs for sequence
        numbering."""
        msg_id = self._next_id
        self._next_id += 1
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        try:
            self._send_nowait(KIND_REQ, msg_id, dumps(body),
                              prefix=_envelope_prefix(method))
        except BaseException:
            self._pending.pop(msg_id, None)
            raise
        return fut

    def request_send_many_nowait(self, method: str, bodies) -> list:
        """Send a burst of requests for ONE method as a single
        KIND_BATCH frame (one write, one syscall) and return their reply
        futures in order.  All-or-nothing: a write failure leaves no
        request registered."""
        prefix = _envelope_prefix(method)
        loop = asyncio.get_running_loop()
        buf = bytearray()
        futs, ids = [], []
        for body in bodies:
            msg_id = self._next_id
            self._next_id += 1
            payload = dumps(body)
            buf += _HDR.pack(len(prefix) + len(payload), KIND_REQ, msg_id)
            buf += prefix
            buf += payload
            ids.append(msg_id)
            futs.append(loop.create_future())
        for msg_id, fut in zip(ids, futs):
            self._pending[msg_id] = fut
        try:
            self._send_nowait(KIND_BATCH, 0, buf)
        except BaseException:
            for msg_id in ids:
                self._pending.pop(msg_id, None)
            raise
        return futs

    async def request_send(self, method: str, body=None):
        """Send a request and return the reply future WITHOUT awaiting it.
        Used where wire-order must be controlled by the caller (e.g. actor
        task sequence numbers) while replies are awaited concurrently."""
        fut = self.request_send_nowait(method, body)
        await self.backpressure()
        return fut

    async def request(self, method: str, body=None, timeout: float | None = None):
        msg_id = self._next_id
        self._next_id += 1
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        try:
            self._send_nowait(KIND_REQ, msg_id, dumps(body),
                              prefix=_envelope_prefix(method))
        except BaseException:
            self._pending.pop(msg_id, None)
            raise
        await self.backpressure()
        if timeout is not None:
            try:
                return await asyncio.wait_for(fut, timeout)
            finally:
                self._pending.pop(msg_id, None)
        return await fut

    async def push(self, method: str, body=None):
        await self._send(KIND_PUSH, 0, dumps(body),
                         prefix=_envelope_prefix(method))

    async def _do_close(self):
        if self._closed:
            return
        try:
            self._flush_wbuf()  # last replies out before the FIN
        except Exception:
            pass
        self._closed = True
        reason = self.close_reason or "connection lost"
        exc = ConnectionLost(
            f"connection to {self.name} lost ({reason}); "
            "in-flight request failed")
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(exc)
        self._pending.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        if self.on_close is not None:
            try:
                res = self.on_close(self)
                if asyncio.iscoroutine(res):
                    await res
            except Exception:
                logger.exception("on_close for %s failed", self.name)

    async def close(self):
        self.close_reason = self.close_reason or "closed locally"
        self._reader_task.cancel()
        await self._do_close()


class RpcServer:
    """Listens for connections; each served by ``handler(conn, method, body)``."""

    def __init__(self, handler, host: str = "127.0.0.1", name: str = "server",
                 on_connect=None, on_disconnect=None):
        self.handler = handler
        self.host = host
        self.name = name
        self.on_connect = on_connect
        self.on_disconnect = on_disconnect
        self.port: int | None = None
        self._server: asyncio.AbstractServer | None = None
        self.connections: set[Connection] = set()

    async def start(self, port: int = 0):
        self._server = await asyncio.start_server(self._on_client, self.host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def _on_client(self, reader, writer):
        sock = writer.get_extra_info("socket")
        if sock is not None:
            import socket as _s
            sock.setsockopt(_s.IPPROTO_TCP, _s.TCP_NODELAY, 1)
        conn = Connection(reader, writer, handler=self.handler,
                          name=f"{self.name}-peer", on_close=self._on_conn_close)
        self.connections.add(conn)
        if self.on_connect is not None:
            res = self.on_connect(conn)
            if asyncio.iscoroutine(res):
                await res

    async def _on_conn_close(self, conn):
        self.connections.discard(conn)
        if self.on_disconnect is not None:
            res = self.on_disconnect(conn)
            if asyncio.iscoroutine(res):
                await res

    async def stop(self):
        if self._server is not None:
            self._server.close()
        # Close live conns BEFORE wait_closed: in py3.12 wait_closed blocks
        # until every transport the server spawned has closed.
        for conn in list(self.connections):
            await conn.close()
        if self._server is not None:
            try:
                await self._server.wait_closed()
            except Exception:
                pass
