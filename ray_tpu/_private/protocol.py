"""Asyncio RPC plane used by every runtime process.

The reference's control plane is gRPC with typed async client/server wrappers
(reference: src/ray/rpc/grpc_server.h, client_call.h, 21 .proto services under
src/ray/protobuf/).  The TPU-native build replaces that with a single lean
length-prefixed pickle protocol over TCP — one connection class serves the
GCS, raylet, and worker-to-worker planes.  Rationale: the control plane rides
DCN either way; what matters on TPU is that the per-message Python overhead is
tiny (the reference pays gRPC+protobuf serialization per task push; we pay one
pickle).  Messages:

  REQ(id, method, body) -> REP(id, result) | ERR(id, exception)
  PUSH(method, body)                       (one-way notification)

All payloads are pickled with protocol 5; large buffers never travel this
plane (they go through the shared-memory object store, see shm_store.py).
"""

from __future__ import annotations

import asyncio
import io
import logging
import pickle
import struct
import time
import traceback

logger = logging.getLogger(__name__)

_HDR = struct.Struct("<IBQ")  # payload_len, kind, msg_id
KIND_REQ = 0
KIND_REP = 1
KIND_ERR = 2
KIND_PUSH = 3

_PICKLE_PROTO = 5


class RpcError(Exception):
    pass


def enable_eager_tasks(loop=None) -> None:
    """Eager task execution (py3.12+): create_task runs the coroutine
    synchronously until its first true suspension, removing a loop-
    scheduling hop from every RPC serve/submit on the control plane.
    Semantics note: task bodies may now run BEFORE create_task returns —
    callers must not rely on deferred start (reviewed: protocol/worker/
    raylet/gcs call sites hold no such assumption)."""
    factory = getattr(asyncio, "eager_task_factory", None)
    if factory is None:
        return
    if loop is None:
        loop = asyncio.get_event_loop()
    loop.set_task_factory(factory)


# Per-method handler service-time accounting for every RPC served by this
# process (reference: the instrumented asio event loop's per-handler stats,
# src/ray/common/event_stats.h).  Accumulation is three float ops per call;
# snapshots ride the telemetry push and back `handler_stats()` debugging.
HANDLER_STATS: dict = {}


def _record_handler(method: str, dt: float) -> None:
    s = HANDLER_STATS.get(method)
    if s is None:
        s = HANDLER_STATS[method] = [0, 0.0, 0.0]
    s[0] += 1
    s[1] += dt
    if dt > s[2]:
        s[2] = dt


def handler_stats_snapshot() -> dict:
    """{method: {count, total_s, max_s, mean_ms}} served by this process."""
    out = {}
    for m, (c, t, mx) in HANDLER_STATS.items():
        out[m] = {"count": c, "total_s": round(t, 6),
                  "max_s": round(mx, 6),
                  "mean_ms": round(1000.0 * t / c, 3) if c else 0.0}
    return out


class RemoteError(RpcError):
    """Raised on the caller when the handler raised; carries remote traceback."""

    def __init__(self, cause_repr: str, tb: str = ""):
        super().__init__(f"{cause_repr}\nRemote traceback:\n{tb}")
        self.cause_repr = cause_repr
        self.remote_traceback = tb

    def __reduce__(self):
        return (RemoteError, (self.cause_repr, self.remote_traceback))


class ConnectionLost(RpcError):
    pass


def dumps(obj) -> bytes:
    return pickle.dumps(obj, protocol=_PICKLE_PROTO)


def loads(data):
    return pickle.loads(data)


class Connection:
    """One bidirectional RPC connection.

    Both sides can issue requests and serve them; ``handler(method, body)``
    is an async callable returning the reply value.
    """

    def __init__(self, reader: asyncio.StreamReader, writer: asyncio.StreamWriter,
                 handler=None, name: str = "?", on_close=None):
        self.reader = reader
        self.writer = writer
        self.handler = handler
        self.name = name
        self.on_close = on_close
        self._next_id = 1
        self._pending: dict[int, asyncio.Future] = {}
        self._closed = False
        self._write_lock = asyncio.Lock()
        # Last: under an eager task factory this may start reading (and
        # serving) immediately, so every attribute must already exist.
        self._reader_task = asyncio.get_running_loop().create_task(self._read_loop())

    @classmethod
    async def connect(cls, host: str, port: int, handler=None, name: str = "?",
                      on_close=None, timeout: float = 30.0):
        reader, writer = await asyncio.wait_for(
            asyncio.open_connection(host, port), timeout)
        sock = writer.get_extra_info("socket")
        if sock is not None:
            import socket as _s
            sock.setsockopt(_s.IPPROTO_TCP, _s.TCP_NODELAY, 1)
        return cls(reader, writer, handler=handler, name=name, on_close=on_close)

    @property
    def closed(self):
        return self._closed

    async def _read_loop(self):
        try:
            while True:
                hdr = await self.reader.readexactly(_HDR.size)
                plen, kind, msg_id = _HDR.unpack(hdr)
                payload = await self.reader.readexactly(plen) if plen else b""
                if kind == KIND_REQ:
                    asyncio.get_running_loop().create_task(
                        self._serve(msg_id, payload))
                elif kind == KIND_REP:
                    fut = self._pending.pop(msg_id, None)
                    if fut is not None and not fut.done():
                        fut.set_result(loads(payload))
                elif kind == KIND_ERR:
                    fut = self._pending.pop(msg_id, None)
                    if fut is not None and not fut.done():
                        cause_repr, tb = loads(payload)
                        fut.set_exception(RemoteError(cause_repr, tb))
                elif kind == KIND_PUSH:
                    asyncio.get_running_loop().create_task(
                        self._serve(0, payload, push=True))
        except (asyncio.IncompleteReadError, ConnectionResetError, OSError):
            pass
        except asyncio.CancelledError:
            return
        except Exception:
            logger.exception("rpc read loop error on %s", self.name)
        finally:
            await self._do_close()

    async def _serve(self, msg_id: int, payload: bytes, push: bool = False):
        try:
            method, body = loads(payload)
        except Exception:
            logger.exception("bad rpc payload on %s", self.name)
            return
        try:
            if self.handler is None:
                raise RpcError(f"connection {self.name} has no handler")
            _t0 = time.perf_counter()
            try:
                result = await self.handler(self, method, body)
            finally:
                # Failing handlers count too — they are exactly the calls
                # these stats exist to surface.
                _record_handler(method, time.perf_counter() - _t0)
            if not push:
                await self._send(KIND_REP, msg_id, dumps(result))
        except Exception as e:
            if push:
                logger.exception("push handler %s failed on %s", method, self.name)
            else:
                try:
                    await self._send(KIND_ERR, msg_id,
                                     dumps((repr(e), traceback.format_exc())))
                except Exception:
                    pass

    async def _send(self, kind: int, msg_id: int, payload: bytes):
        if self._closed:
            raise ConnectionLost(f"connection {self.name} closed")
        # Buffered writes, no lock: StreamWriter.write is synchronous and
        # there is no await between the two calls, so header+payload can't
        # interleave with another sender (and skipping concatenation
        # avoids copying large payloads).  drain() (an await + lock-step
        # with the transport) only matters for backpressure — apply it
        # once the send buffer is actually deep.
        try:
            self.writer.write(_HDR.pack(len(payload), kind, msg_id))
            self.writer.write(payload)
        except (ConnectionResetError, OSError) as e:
            raise ConnectionLost(str(e)) from e
        transport = self.writer.transport
        if (transport is not None
                and transport.get_write_buffer_size() > 1 << 20):
            async with self._write_lock:
                try:
                    await self.writer.drain()
                except (ConnectionResetError, OSError) as e:
                    raise ConnectionLost(str(e)) from e

    async def request_send(self, method: str, body=None):
        """Send a request and return the reply future WITHOUT awaiting it.
        Used where wire-order must be controlled by the caller (e.g. actor
        task sequence numbers) while replies are awaited concurrently."""
        msg_id = self._next_id
        self._next_id += 1
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        await self._send(KIND_REQ, msg_id, dumps((method, body)))
        return fut

    async def request(self, method: str, body=None, timeout: float | None = None):
        msg_id = self._next_id
        self._next_id += 1
        fut = asyncio.get_running_loop().create_future()
        self._pending[msg_id] = fut
        await self._send(KIND_REQ, msg_id, dumps((method, body)))
        if timeout is not None:
            try:
                return await asyncio.wait_for(fut, timeout)
            finally:
                self._pending.pop(msg_id, None)
        return await fut

    async def push(self, method: str, body=None):
        await self._send(KIND_PUSH, 0, dumps((method, body)))

    async def _do_close(self):
        if self._closed:
            return
        self._closed = True
        for fut in self._pending.values():
            if not fut.done():
                fut.set_exception(ConnectionLost(f"connection {self.name} lost"))
        self._pending.clear()
        try:
            self.writer.close()
        except Exception:
            pass
        if self.on_close is not None:
            try:
                res = self.on_close(self)
                if asyncio.iscoroutine(res):
                    await res
            except Exception:
                logger.exception("on_close for %s failed", self.name)

    async def close(self):
        self._reader_task.cancel()
        await self._do_close()


class RpcServer:
    """Listens for connections; each served by ``handler(conn, method, body)``."""

    def __init__(self, handler, host: str = "127.0.0.1", name: str = "server",
                 on_connect=None, on_disconnect=None):
        self.handler = handler
        self.host = host
        self.name = name
        self.on_connect = on_connect
        self.on_disconnect = on_disconnect
        self.port: int | None = None
        self._server: asyncio.AbstractServer | None = None
        self.connections: set[Connection] = set()

    async def start(self, port: int = 0):
        self._server = await asyncio.start_server(self._on_client, self.host, port)
        self.port = self._server.sockets[0].getsockname()[1]
        return self.port

    async def _on_client(self, reader, writer):
        sock = writer.get_extra_info("socket")
        if sock is not None:
            import socket as _s
            sock.setsockopt(_s.IPPROTO_TCP, _s.TCP_NODELAY, 1)
        conn = Connection(reader, writer, handler=self.handler,
                          name=f"{self.name}-peer", on_close=self._on_conn_close)
        self.connections.add(conn)
        if self.on_connect is not None:
            res = self.on_connect(conn)
            if asyncio.iscoroutine(res):
                await res

    async def _on_conn_close(self, conn):
        self.connections.discard(conn)
        if self.on_disconnect is not None:
            res = self.on_disconnect(conn)
            if asyncio.iscoroutine(res):
                await res

    async def stop(self):
        if self._server is not None:
            self._server.close()
        # Close live conns BEFORE wait_closed: in py3.12 wait_closed blocks
        # until every transport the server spawned has closed.
        for conn in list(self.connections):
            await conn.close()
        if self._server is not None:
            try:
                await self._server.wait_closed()
            except Exception:
                pass
