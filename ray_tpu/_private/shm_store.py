"""Python side of the shared-memory object store.

``StoreServer`` is the ctypes binding over the native allocator
(src/shm_store.cc) — instantiated only inside the raylet process, which is
the metadata authority for its node (reference: the plasma store runs inside
the raylet process too, src/ray/object_manager/plasma/store_runner.cc).

``StoreMapping`` is the client-side zero-copy view: any process on the node
mmaps the same arena file and reads/writes object bytes directly at offsets
handed out by the raylet over RPC (reference: plasma client protocol,
src/ray/object_manager/plasma/client.h — clients receive fds + offsets and
memcpy into shared memory themselves).
"""

from __future__ import annotations

import ctypes
import mmap
import os
import subprocess
import threading

from ray_tpu._private import locksan

_LIB_LOCK = locksan.make_lock("shm_store._LIB_LOCK")
_LIB = None

_SRC = os.path.join(os.path.dirname(__file__), "..", "..", "src", "shm_store.cc")
_SO = os.path.join(os.path.dirname(__file__), "_shm_store.so")


def _load_lib():
    global _LIB
    with _LIB_LOCK:
        if _LIB is not None:
            return _LIB
        src = os.path.abspath(_SRC)
        so = os.path.abspath(_SO)
        if (not os.path.exists(so)
                or os.path.getmtime(so) < os.path.getmtime(src)):
            tmp = so + f".tmp{os.getpid()}"
            subprocess.check_call(
                ["g++", "-O2", "-std=c++17", "-shared", "-fPIC", "-o", tmp, src])
            os.replace(tmp, so)
        lib = ctypes.CDLL(so)
        lib.store_create.restype = ctypes.c_void_p
        lib.store_create.argtypes = [ctypes.c_char_p, ctypes.c_uint64]
        lib.store_destroy.argtypes = [ctypes.c_void_p]
        lib.store_alloc.restype = ctypes.c_int
        lib.store_alloc.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                    ctypes.c_uint64, ctypes.POINTER(ctypes.c_uint64)]
        lib.store_seal.restype = ctypes.c_int
        lib.store_seal.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.store_get.restype = ctypes.c_int
        lib.store_get.argtypes = [ctypes.c_void_p, ctypes.c_char_p,
                                  ctypes.POINTER(ctypes.c_uint64),
                                  ctypes.POINTER(ctypes.c_uint64),
                                  ctypes.POINTER(ctypes.c_int)]
        lib.store_release.restype = ctypes.c_int
        lib.store_release.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.store_abort.restype = ctypes.c_int
        lib.store_abort.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.store_delete.restype = ctypes.c_int
        lib.store_delete.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.store_contains.restype = ctypes.c_int
        lib.store_contains.argtypes = [ctypes.c_void_p, ctypes.c_char_p]
        lib.store_used.restype = ctypes.c_uint64
        lib.store_used.argtypes = [ctypes.c_void_p]
        lib.store_capacity.restype = ctypes.c_uint64
        lib.store_capacity.argtypes = [ctypes.c_void_p]
        lib.store_evict.restype = ctypes.c_int
        lib.store_evict.argtypes = [ctypes.c_void_p, ctypes.c_uint64]
        lib.store_stats.restype = None
        lib.store_stats.argtypes = [ctypes.c_void_p] + \
            [ctypes.POINTER(ctypes.c_uint64)] * 6
        _LIB = lib
        return lib


class StoreServer:
    """Owns the arena; runs inside the raylet process."""

    def __init__(self, path: str, capacity: int):
        self.lib = _load_lib()
        self.path = path
        self.capacity = capacity
        self.handle = self.lib.store_create(path.encode(), capacity)
        if not self.handle:
            raise RuntimeError(f"failed to create shm store at {path}")

    def alloc(self, object_id: bytes, size: int) -> int | None:
        if not self.handle:  # closed: callers treat as OOM / absent
            return None
        off = ctypes.c_uint64()
        rc = self.lib.store_alloc(self.handle, object_id, size, ctypes.byref(off))
        if rc == 0:
            return off.value
        if rc == -2:
            raise KeyError(f"object {object_id.hex()} already exists")
        return None  # OOM

    def seal(self, object_id: bytes) -> bool:
        if not self.handle:
            return False
        return self.lib.store_seal(self.handle, object_id) == 0

    def get(self, object_id: bytes):
        """Returns (offset, size, sealed) or None; pins when sealed."""
        if not self.handle:
            return None
        off = ctypes.c_uint64()
        size = ctypes.c_uint64()
        sealed = ctypes.c_int()
        rc = self.lib.store_get(self.handle, object_id, ctypes.byref(off),
                                ctypes.byref(size), ctypes.byref(sealed))
        if rc != 0:
            return None
        return off.value, size.value, bool(sealed.value)

    def release(self, object_id: bytes) -> bool:
        if not self.handle:
            return False
        return self.lib.store_release(self.handle, object_id) == 0

    def abort(self, object_id: bytes) -> bool:
        """Drop an UNSEALED creation (creator pin + extent) — the only
        legal way to free an in-progress allocation; release() refuses
        unsealed entries (src/shm_store.cc Release: -3)."""
        if not self.handle:
            return False
        return self.lib.store_abort(self.handle, object_id) == 0

    def delete(self, object_id: bytes) -> bool:
        if not self.handle:
            return False
        return self.lib.store_delete(self.handle, object_id) == 0

    def contains(self, object_id: bytes) -> bool:
        if not self.handle:
            return False
        return self.lib.store_contains(self.handle, object_id) == 1

    def used(self) -> int:
        if not self.handle:
            return 0
        return self.lib.store_used(self.handle)

    def stats(self) -> dict:
        """Fragmentation/pin diagnostics (largest_free is the biggest
        contiguous hole — the real bound on the next large alloc)."""
        if not self.handle:
            return {k: 0 for k in ("used", "largest_free", "lru_bytes",
                                   "pinned_bytes", "unsealed_bytes",
                                   "n_objects")}
        vals = [ctypes.c_uint64() for _ in range(6)]
        self.lib.store_stats(self.handle, *[ctypes.byref(v) for v in vals])
        keys = ("used", "largest_free", "lru_bytes", "pinned_bytes",
                "unsealed_bytes", "n_objects")
        return dict(zip(keys, (v.value for v in vals)))

    def close(self):
        if self.handle:
            self.lib.store_destroy(self.handle)
            self.handle = None
        try:
            os.unlink(self.path)
        except OSError:
            pass


class StoreMapping:
    """Client-side mmap of the node's arena file (zero-copy data plane).

    ``readonly=True`` maps a PEER raylet's arena for the same-host
    zero-copy pull fast path — reads only, the peer stays the metadata
    authority and the reader must hold a remote pin for the duration."""

    def __init__(self, path: str, capacity: int, readonly: bool = False):
        self.path = path
        self.capacity = capacity
        self._fd = os.open(path, os.O_RDONLY if readonly else os.O_RDWR)
        self._mmap = mmap.mmap(
            self._fd, capacity,
            access=mmap.ACCESS_READ if readonly else mmap.ACCESS_WRITE)
        self.view = memoryview(self._mmap)

    def slice(self, offset: int, size: int) -> memoryview:
        return self.view[offset:offset + size]

    def writable(self, offset: int, size: int) -> memoryview:
        """Writable view of an UNSEALED allocation for in-place receive:
        the transfer plane copies socket bytes straight into this view
        (protocol blob frames), relying on the alloc-time creator pin to
        keep the extent stable until seal/abort.  Never hand one out for
        a sealed object — readers may hold zero-copy views of it."""
        return self.view[offset:offset + size]

    def close(self):
        try:
            self.view.release()
            self._mmap.close()
            os.close(self._fd)
        except Exception:
            pass


def default_store_path(session_dir: str, node_id_hex: str) -> str:
    shm = "/dev/shm"
    if os.path.isdir(shm) and os.access(shm, os.W_OK):
        return os.path.join(shm, f"rt_store_{node_id_hex[:12]}_{os.getpid()}")
    return os.path.join(session_dir, f"rt_store_{node_id_hex[:12]}")
