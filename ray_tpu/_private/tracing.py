"""Shared cross-plane span runtime: one bounded ring per process.

Reference: the reference's observability stack spans every plane —
`ray timeline` dumps chrome-trace events aggregated from per-process
profilers (src/ray/core_worker/profiling.h), the dashboard's metrics
pipeline relays them, and OpenTelemetry spans ride TaskSpecs
(python/ray/util/tracing/tracing_helper.py).  Before this module, our
coverage stopped at task/actor submit+execute in `_private/worker.py`:
the transfer plane, collectives, control-plane pubsub/scheduling, serve
request lifecycles, and the data executor were tracing black holes.

Design:

* **One ring per process** (`TraceRing`): a bounded deque of
  chrome-trace events with drop-oldest semantics and a drop counter —
  cheap enough to leave always on (an append is one dict build + one
  deque append; the disabled fast path is a single bool check).  The
  capacity / enablement / sampling knobs are ``RT_TRACE_*`` (see
  config.py).
* **Trace context** rides a contextvar, propagated inside TaskSpecs
  (worker.py) and adopted at execution with a fresh span id, so spans
  link parent→child across processes.  Cross-process edges additionally
  emit chrome flow events (``ph:"s"`` at the submit/request site,
  ``ph:"f"`` at the serving site, same ``id``) so the waterfall
  connects in the chrome trace viewer.
* **Pull, not push, is authoritative**: every worker/raylet/GCS serves
  a ``dump_trace`` RPC draining this ring on demand
  (`ray_tpu.cluster_trace()`, ``rt timeline --cluster``,
  ``rt trace <id>``).  The periodic telemetry KV push keeps feeding
  ``ray_tpu.timeline()`` as a stale convenience view — it truncates to
  the freshest events and lags by the push period.
* **Assembly** (`assemble`, `format_trace`): given a merged event list
  and a trace id, build the span tree (parent_id links) and derive a
  per-stage latency breakdown — for serve requests the TTFT decomposes
  into queue / prefill / first-tick from the engine's span taxonomy.

Span taxonomy (cat.name — see README "Observability"):
  task.*            submit flows + task/actor execution (worker.py)
  transfer.*        pull/push windows, chunk retries, source deaths
  collective.*      per-op spans (rendezvous→bulk→fold), buckets
  gcs.*             scheduling decisions, pubsub batch flushes
  rpc.slow          any RPC handler over cfg.trace_rpc_slow_ms
  serve.*           proxy request, router assign/QoS wait, failover
  engine.*          queue / prefill / first_tick / decode_tick (sampled)
  data.*            streaming execute + shuffle exchange
"""

from __future__ import annotations

import contextvars
import itertools
import os
import threading
import time
from collections import deque
from contextlib import contextmanager

from ray_tpu._private import locksan
from ray_tpu._private.config import GLOBAL_CONFIG as cfg

# ---------------------------------------------------------------- context

# Distributed trace context (trace_id, span_id) | None.  Reference:
# util/tracing/tracing_helper.py — otel context rides the TaskSpec; here
# the span tree lands in the per-process ring and ray_tpu.timeline().
_TRACE: contextvars.ContextVar = contextvars.ContextVar(
    "rt_trace", default=None)

# Fresh ids: a per-process random base + counter instead of one
# os.urandom syscall per span (urandom is painfully expensive on
# syscall-filtered hosts; uniqueness only needs process entropy once).
_ID_BASE = os.urandom(5).hex()
_id_counter = itertools.count(1).__next__
# getpid() is a real syscall on every call (glibc stopped caching it);
# under this container's syscall-filtered sandbox that is measurable on
# the per-event path — cache it, refresh at fork.
_PID = os.getpid()
# Live OTel export bridge: poked by util.tracing.enable/disable_tracing
# so the record() hot path pays ONE identity check, not a module lookup
# + probe per event.
_LIVE_EXPORT = None


def _reseed_id_base():
    """At-fork hook: zygote-forked workers must not mint the parent's
    id stream (same rationale as ids._reseed_id_bases)."""
    global _ID_BASE, _id_counter, _PID
    _ID_BASE = os.urandom(5).hex()
    _id_counter = itertools.count(1).__next__
    _PID = os.getpid()


os.register_at_fork(after_in_child=_reseed_id_base)


def fresh_id() -> str:
    return f"{_ID_BASE}{_id_counter():06x}"


def current():
    """(trace_id, span_id) of the active span, or None."""
    return _TRACE.get()


def current_dict():
    """Active context as the wire shape ({"trace_id","parent_id"})
    propagated in task specs / plane RPC bodies, or None."""
    ctx = _TRACE.get()
    if ctx is None:
        return None
    return {"trace_id": ctx[0], "parent_id": ctx[1]}


def set_current(trace_id: str, span_id: str):
    """Install a context; returns the reset token."""
    return _TRACE.set((trace_id, span_id))


def reset_current(token):
    _TRACE.reset(token)


def child_span() -> dict | None:
    """A span-linkage dict (fresh span id) parented under the ACTIVE
    span — for call sites that measure t0/dur themselves (record())
    instead of wrapping a with-block in span()."""
    ctx = _TRACE.get()
    if ctx is None:
        return None
    return {"trace_id": ctx[0], "span_id": fresh_id(),
            "parent_id": ctx[1]}


def trace_for_submit() -> dict:
    """Current (or fresh) trace context to stamp on an outgoing task —
    plus, when a span is ACTIVE, a flow id connecting the submit site
    to the execution span (chrome ``ph:"s"``/``"f"`` pair).  An
    un-spanned submit gets no flow id: there is no submit-side span to
    connect from, and the two extra ring events per call are exactly
    the always-on overhead the <=5% bench gate polices."""
    ctx = _TRACE.get()
    if ctx is None:
        return {"trace_id": fresh_id(), "parent_id": None}
    return {"trace_id": ctx[0], "parent_id": ctx[1], "flow": fresh_id()}


def adopt(trace, cat: str = "task"):
    """Adopt a submitter's trace context with a fresh span id so work
    submitted from here links as children; emits the closing flow event
    when the context carries a flow id.  Returns the span dict to stamp
    on the recorded event (or None)."""
    if not trace:
        return None
    span = {"trace_id": trace["trace_id"], "span_id": fresh_id(),
            "parent_id": trace.get("parent_id")}
    _TRACE.set((span["trace_id"], span["span_id"]))
    flow = trace.get("flow")
    if flow is not None:
        flow_end(flow, cat)
        span["flow"] = flow
    return span


async def bind_agen(agen, ctx):
    """Re-install ``ctx`` (a (trace_id, span_id) pair) around EVERY
    step of ``agen``: async-generator frames execute in the driving
    task's context, so a stream created under a span but consumed from
    another thread/loop (serve handles hop to the router loop) would
    otherwise lose its trace — and every actor call it makes would mint
    a fresh root instead of linking under the caller.  Closing the
    wrapper closes the inner generator (its finally blocks run)."""
    try:
        while True:
            token = _TRACE.set(ctx)
            try:
                item = await agen.__anext__()
            except StopAsyncIteration:
                return
            finally:
                _TRACE.reset(token)
            yield item
    finally:
        await agen.aclose()


# ------------------------------------------------------------------- ring

class TraceRing:
    """Bounded ring of chrome-trace events: drop-oldest + drop counter.

    Appends are one ``deque.append`` (thread-safe under the GIL); the
    drop counter tolerates racy increments — it feeds a monitoring
    counter, not an invariant."""

    def __init__(self, capacity: int | None = None):
        cap = capacity if capacity is not None \
            else max(64, cfg.trace_ring_capacity)
        self.capacity = cap
        self._q: deque = deque(maxlen=cap)
        self.dropped = 0

    def append(self, event: dict) -> None:
        if len(self._q) >= self.capacity:
            self.dropped += 1
        self._q.append(event)

    def __len__(self):
        return len(self._q)

    def tail(self, n: int) -> list:
        q = self._q
        if len(q) <= n:
            return list(q)
        return list(q)[-n:]

    def snapshot(self, clear: bool = False) -> list:
        out = list(self._q)
        if clear:
            self._q.clear()
        return out

    def stats(self) -> dict:
        q = self._q
        ts_min = ts_max = None
        if q:
            try:
                ts_min = q[0].get("ts")
                ts_max = q[-1].get("ts")
            except IndexError:  # racing append/clear; stats stay best-effort
                pass
        return {"depth": len(q), "capacity": self.capacity,
                "dropped": self.dropped,
                "ts_min": ts_min, "ts_max": ts_max}


_RING = TraceRing()
_ENABLED = bool(cfg.trace_enabled)
# Drops already surfaced through the prometheus counter (export_metrics
# incs by the delta so the counter is monotonic across snapshots).
_exported_drops = 0
_export_lock = locksan.make_lock("tracing._export_lock")
_metrics = None  # (drop Counter, depth Gauge) once built


def ring() -> TraceRing:
    return _RING


def enabled() -> bool:
    return _ENABLED


def set_enabled(on: bool) -> None:
    """Runtime switch (benches / tests); processes normally inherit
    RT_TRACE_ENABLED through the environment."""
    global _ENABLED
    _ENABLED = bool(on)


# ---------------------------------------------------------------- record

def record(cat: str, name: str, t0: float, dur_s: float,
           trace: dict | None = None, args: dict | None = None) -> None:
    """One chrome-trace complete event ({ts,dur} in us since epoch).
    ``trace`` carries the span linkage (trace_id/span_id/parent_id);
    ``args`` any extra annotations.  Events shorter than
    cfg.trace_min_dur_us are skipped UNLESS they carry span linkage —
    dropping linked spans would hole the tree."""
    if not _ENABLED:
        return
    dur_us = dur_s * 1e6
    if trace is None and dur_us < cfg.trace_min_dur_us:
        return
    event = {
        "cat": cat, "name": name, "ph": "X",
        "pid": _PID,
        "tid": threading.get_ident() & 0xFFFF,
        "ts": t0 * 1e6, "dur": dur_us,
    }
    a = {}
    if trace:
        a.update(trace)
    if args:
        a.update(args)
    if a:
        event["args"] = a
    _RING.append(event)
    if _LIVE_EXPORT is not None:
        _maybe_export(event)


def event(cat: str, name: str, args: dict | None = None) -> None:
    """Instant event (ph "i"), stamped with the current trace context —
    annotations like a transfer source death or a serve failover."""
    if not _ENABLED:
        return
    ev = {"cat": cat, "name": name, "ph": "i", "s": "p",
          "pid": _PID, "tid": threading.get_ident() & 0xFFFF,
          "ts": time.time() * 1e6}
    a = dict(args or ())
    ctx = _TRACE.get()
    if ctx is not None:
        a.setdefault("trace_id", ctx[0])
        a.setdefault("parent_id", ctx[1])
    if a:
        ev["args"] = a
    _RING.append(ev)


def flow_start(flow_id: str, cat: str = "task") -> None:
    """Chrome flow-start (ph "s") at the requesting site of a
    cross-process edge."""
    if not _ENABLED:
        return
    _RING.append({"cat": cat, "name": f"{cat}.flow", "ph": "s",
                  "id": flow_id, "pid": _PID,
                  "tid": threading.get_ident() & 0xFFFF,
                  "ts": time.time() * 1e6})


def flow_end(flow_id: str, cat: str = "task") -> None:
    """Chrome flow-finish (ph "f", bp "e") at the serving site."""
    if not _ENABLED:
        return
    _RING.append({"cat": cat, "name": f"{cat}.flow", "ph": "f",
                  "bp": "e", "id": flow_id, "pid": _PID,
                  "tid": threading.get_ident() & 0xFFFF,
                  "ts": time.time() * 1e6})


class _SpanHandle:
    """Yielded by span(): lets the body annotate (``h.args[...]``) and
    read the ids (the proxy returns h.trace_id to the client)."""

    __slots__ = ("trace_id", "span_id", "parent_id", "args")

    def __init__(self, trace_id, span_id, parent_id):
        self.trace_id = trace_id
        self.span_id = span_id
        self.parent_id = parent_id
        self.args = {}


@contextmanager
def span(cat: str, name: str, args: dict | None = None,
         root: bool = False):
    """Record a complete event covering the with-body, as a child of
    the active span (or a fresh root when none is active or
    ``root=True``).  The context is installed for the body, so nested
    spans / submitted tasks / plane RPCs link as children — including
    across processes.  Always manages context even when recording is
    disabled (continuity is semantic, the ring is observability)."""
    ctx = None if root else _TRACE.get()
    trace_id = fresh_id() if ctx is None else ctx[0]
    parent_id = None if ctx is None else ctx[1]
    span_id = fresh_id()
    token = _TRACE.set((trace_id, span_id))
    h = _SpanHandle(trace_id, span_id, parent_id)
    if args:
        h.args.update(args)
    t0 = time.time()
    try:
        yield h
    finally:
        _TRACE.reset(token)
        if _ENABLED:
            record(cat, name, t0, time.time() - t0,
                   trace={"trace_id": trace_id, "span_id": span_id,
                          "parent_id": parent_id},
                   args=h.args or None)


def _maybe_export(ev: dict) -> None:
    """Bridge to util.tracing's optional live tracer (OTel), lazily —
    the bridge is a no-op unless enable_tracing() ran here."""
    try:
        from ray_tpu.util import tracing as _ut
        if _ut.is_enabled():
            _ut.maybe_export(ev)
    except Exception:
        pass


# ------------------------------------------------------------- dump/pull

def dump(stats_only: bool = False, clear: bool = False) -> dict:
    """The ``dump_trace`` RPC payload: this process's ring, stats
    first.  The pull path is authoritative — unlike the telemetry KV
    push it delivers the WHOLE ring, with its drop counter and coverage
    window, at the moment of the call."""
    out = {"pid": _PID, "ring_id": _ID_BASE, **_RING.stats()}
    if not stats_only:
        out["events"] = _RING.snapshot(clear=clear)
    return out


def meta_event(stats: dict | None = None) -> dict:
    """Self-description for a (possibly truncated) trace dump: an
    instant event recording this process's drop count and ring coverage
    window, so a reader knows what the ring could NOT retain."""
    s = stats or _RING.stats()
    return {"cat": "trace", "name": "trace.ring_meta", "ph": "i",
            "s": "p", "pid": s.get("pid", os.getpid()), "tid": 0,
            "ts": (s.get("ts_max") or time.time() * 1e6),
            "args": {"events_dropped": s["dropped"],
                     "ring_depth": s["depth"],
                     "ring_capacity": s["capacity"],
                     "window_start_ts": s["ts_min"],
                     "window_end_ts": s["ts_max"]}}


def export_metrics() -> None:
    """Update the prometheus-facing series (rides the telemetry push):
    ``tracing_events_dropped_total`` (monotonic counter; nonzero only
    when the ring actually overflowed) and ``tracing_ring_depth``."""
    global _metrics, _exported_drops
    try:
        from ray_tpu.util.metrics import Counter, Gauge
        with _export_lock:
            if _metrics is None:
                _metrics = (
                    Counter("tracing_events_dropped_total",
                            "Span events dropped from this process's "
                            "trace ring (drop-oldest overflow)"),
                    Gauge("tracing_ring_depth",
                          "Events currently held in this process's "
                          "trace ring"))
            delta = _RING.dropped - _exported_drops
            if delta > 0:
                _metrics[0].inc(delta)
                _exported_drops += delta
            _metrics[1].set(float(len(_RING)))
    except Exception:
        pass


# ------------------------------------------------------------- assembly

def trace_events(events: list, trace_id: str) -> list:
    """Events belonging to one trace (span + instant events carrying
    the id in args)."""
    out = []
    for e in events:
        a = e.get("args")
        if a and a.get("trace_id") == trace_id:
            out.append(e)
    return out


def trace_ids(events: list) -> dict:
    """{trace_id: (n_events, first_ts, root_name)} — newest-first
    listing for ``rt trace`` without an id."""
    acc: dict = {}
    for e in events:
        a = e.get("args")
        tid = a.get("trace_id") if a else None
        if tid is None:
            continue
        n, ts, name = acc.get(tid, (0, None, None))
        ets = e.get("ts")
        if ts is None or (ets is not None and ets < ts):
            ts = ets
            if e.get("ph") == "X":
                name = e.get("name")
        acc[tid] = (n + 1, ts, name or e.get("name"))
    return acc


def assemble(events: list, trace_id: str) -> dict:
    """Build one request's span tree.

    Returns {"trace_id", "spans": [span...], "roots": [span...],
    "processes": sorted pids, "annotations": [instant events],
    "breakdown": derived per-stage latencies (TTFT decomposition when
    engine spans are present)}.  Each span dict: name/cat/pid/ts/dur/
    span_id/parent_id/args/children."""
    mine = trace_events(events, trace_id)
    spans = []
    notes = []
    by_id = {}
    for e in mine:
        if e.get("ph") != "X":
            if e.get("ph") == "i":
                notes.append(e)
            continue
        a = e.get("args") or {}
        s = {"name": e.get("name"), "cat": e.get("cat"),
             "pid": e.get("pid"), "ts": e.get("ts", 0.0),
             "dur": e.get("dur", 0.0),
             "span_id": a.get("span_id"),
             "parent_id": a.get("parent_id"),
             "args": {k: v for k, v in a.items()
                      if k not in ("trace_id", "span_id", "parent_id",
                                   "flow")},
             "children": []}
        spans.append(s)
        if s["span_id"]:
            by_id[s["span_id"]] = s
    roots = []
    for s in spans:
        parent = by_id.get(s["parent_id"]) if s["parent_id"] else None
        if parent is not None and parent is not s:
            parent["children"].append(s)
        else:
            roots.append(s)
    for s in spans:
        s["children"].sort(key=lambda c: c["ts"])
    roots.sort(key=lambda s: s["ts"])
    # Attach annotations to their parent span where possible.
    for n in notes:
        a = n.get("args") or {}
        parent = by_id.get(a.get("parent_id"))
        if parent is not None:
            parent.setdefault("events", []).append(
                {"name": n.get("name"), "ts": n.get("ts"),
                 "args": {k: v for k, v in a.items()
                          if k not in ("trace_id", "parent_id")}})
    return {"trace_id": trace_id, "spans": spans, "roots": roots,
            "processes": sorted({s["pid"] for s in spans}),
            "annotations": notes,
            "breakdown": _breakdown(spans)}


def _breakdown(spans: list) -> dict:
    """Per-stage latency breakdown.  Stages are keyed by span name;
    the serve taxonomy additionally derives the TTFT decomposition
    (queue vs prefill vs first tick) as dedicated fields."""
    stages: dict = {}
    for s in spans:
        ms = s["dur"] / 1000.0
        agg = stages.setdefault(s["name"], {"count": 0, "total_ms": 0.0,
                                            "max_ms": 0.0})
        agg["count"] += 1
        agg["total_ms"] += ms
        if ms > agg["max_ms"]:
            agg["max_ms"] = ms
    out = {"stages": {k: {kk: round(vv, 3) if isinstance(vv, float)
                          else vv for kk, vv in v.items()}
                      for k, v in sorted(stages.items())}}
    # TTFT decomposes the FIRST engine submission only: a trace can
    # hold several engine requests (sequential streams, a failover
    # resume), and summing every triple would report their total as
    # one request's TTFT.  Engine spans carry request_id for grouping.
    engine = [s for s in spans
              if s["name"] in ("engine.queue", "engine.prefill",
                               "engine.first_tick")]
    if engine:
        rid = min(engine, key=lambda s: s["ts"])["args"].get("request_id")
        sel = [s for s in engine if s["args"].get("request_id") == rid]

        def _ms(name):
            return sum(s["dur"] for s in sel
                       if s["name"] == name) / 1000.0
        q, p, f = (_ms("engine.queue"), _ms("engine.prefill"),
                   _ms("engine.first_tick"))
        out["ttft"] = {"queue_ms": round(q, 3),
                       "prefill_ms": round(p, 3),
                       "first_tick_ms": round(f, 3),
                       "ttft_ms": round(q + p + f, 3)}
        if rid is not None:
            out["ttft"]["request_id"] = rid
    return out


def format_trace(tree: dict) -> str:
    """Human-readable rendering of assemble()'s result for
    ``rt trace``: indented span tree (name, duration, pid,
    annotations) + the per-stage breakdown."""
    lines = [f"trace {tree['trace_id']}: {len(tree['spans'])} spans "
             f"across {len(tree['processes'])} process(es) "
             f"{tree['processes']}"]

    def _fmt(s, depth):
        args = s["args"]
        extra = ""
        if args:
            kv = ", ".join(f"{k}={v}" for k, v in sorted(args.items()))
            extra = f"  [{kv}]"
        lines.append(f"{'  ' * depth}{s['name']} "
                     f"{s['dur'] / 1000.0:.2f}ms  pid={s['pid']}{extra}")
        for n in s.get("events", ()):
            nkv = ", ".join(f"{k}={v}" for k, v in
                            sorted((n.get("args") or {}).items())
                            if k != "parent_id")
            lines.append(f"{'  ' * (depth + 1)}* {n['name']}"
                         + (f"  [{nkv}]" if nkv else ""))
        for c in s["children"]:
            _fmt(c, depth + 1)

    for r in tree["roots"]:
        _fmt(r, 1)
    bd = tree["breakdown"]
    if bd.get("ttft"):
        t = bd["ttft"]
        lines.append(f"  TTFT {t['ttft_ms']}ms = queue {t['queue_ms']}ms"
                     f" + prefill {t['prefill_ms']}ms + first tick "
                     f"{t['first_tick_ms']}ms")
    lines.append("  stages:")
    for name, agg in bd["stages"].items():
        lines.append(f"    {name}: n={agg['count']} "
                     f"total={agg['total_ms']}ms max={agg['max_ms']}ms")
    return "\n".join(lines)
