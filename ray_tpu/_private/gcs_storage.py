"""Pluggable GCS metadata persistence.

Reference: src/ray/gcs/store_client/ — StoreClient (store_client.h) with
InMemoryStoreClient and RedisStoreClient (redis_store_client.h:28), the
seam that makes head-node loss survivable: put the backend somewhere that
outlives the head machine and a fresh GCS on ANY machine reloads cluster
metadata from it.

Backends here: file snapshots (default, same behavior as before),
sqlite (transactional, versioned history — point at a shared mount for
cross-machine failover), and a registry for external schemes (an
object-store/redis-like service registers a factory).  Addressed by URI:

    /plain/path or file:///path  -> FileStoreClient
    sqlite:///path/to/db         -> SqliteStoreClient
    <scheme>://...               -> via register_gcs_store
"""

from __future__ import annotations

import os
from typing import Callable, Dict, Optional


class GcsStoreClient:
    """Snapshot-blob storage (reference: store_client.h — narrowed to the
    snapshot granularity the GCS persists at)."""

    def write(self, data: bytes) -> None:
        raise NotImplementedError

    def read(self) -> Optional[bytes]:
        raise NotImplementedError

    def describe(self) -> str:
        return type(self).__name__


class FileStoreClient(GcsStoreClient):
    """Atomic-rename file snapshot (the in-tree default)."""

    def __init__(self, path: str):
        self.path = path

    def write(self, data: bytes) -> None:
        os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
        tmp = self.path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, self.path)

    def read(self) -> Optional[bytes]:
        if not os.path.exists(self.path):
            return None
        with open(self.path, "rb") as f:
            return f.read()

    def describe(self) -> str:
        return f"file:{self.path}"


class SqliteStoreClient(GcsStoreClient):
    """Transactional versioned snapshots in sqlite (the external-backend
    role of redis_store_client.h:28 without a network dependency: place
    the db on storage that outlives the head node and a replacement GCS
    restores from it).  Keeps a bounded history of recent snapshots."""

    KEEP = 8

    def __init__(self, path: str):
        import sqlite3
        self.path = path
        os.makedirs(os.path.dirname(path) or ".", exist_ok=True)
        self._conn = sqlite3.connect(path, check_same_thread=False)
        self._conn.execute(
            "CREATE TABLE IF NOT EXISTS gcs_snapshots ("
            "version INTEGER PRIMARY KEY AUTOINCREMENT, "
            "ts REAL, data BLOB)")
        self._conn.commit()

    def write(self, data: bytes) -> None:
        import time
        with self._conn:
            self._conn.execute(
                "INSERT INTO gcs_snapshots (ts, data) VALUES (?, ?)",
                (time.time(), data))
            self._conn.execute(
                "DELETE FROM gcs_snapshots WHERE version NOT IN "
                "(SELECT version FROM gcs_snapshots "
                "ORDER BY version DESC LIMIT ?)", (self.KEEP,))

    def read(self) -> Optional[bytes]:
        row = self._conn.execute(
            "SELECT data FROM gcs_snapshots "
            "ORDER BY version DESC LIMIT 1").fetchone()
        return bytes(row[0]) if row else None

    def describe(self) -> str:
        return f"sqlite:{self.path}"


_SCHEMES: Dict[str, Callable[[str], GcsStoreClient]] = {
    # `rest` is everything after "://": "scheme:///abs/path" -> "/abs/path",
    # "scheme://rel/path" -> "rel/path".
    "file": lambda rest: FileStoreClient(rest),
    "sqlite": lambda rest: SqliteStoreClient(rest),
}


def register_gcs_store(scheme: str,
                       factory: Callable[[str], GcsStoreClient]) -> None:
    """Plug an external metadata backend (e.g. a redis-like service).
    Registering an existing scheme overrides the built-in."""
    _SCHEMES[scheme] = factory


class _StorageBlobAdapter(GcsStoreClient):
    """Adapts a generic ray_tpu.util.storage backend to the snapshot-blob
    interface, so external schemes registered ONCE in util.storage (the
    seam tune and workflow share) also serve GCS persistence — no double
    registration."""

    _KEY = "gcs_snapshot.pkl"

    def __init__(self, storage):
        self._st = storage

    def write(self, data: bytes) -> None:
        self._st.write_bytes(self._KEY, data)

    def read(self):
        if not self._st.exists(self._KEY):
            return None
        return self._st.read_bytes(self._KEY)

    def describe(self) -> str:
        return f"util.storage:{type(self._st).__name__}"


def get_store_client(uri: str) -> GcsStoreClient:
    if "://" in uri:
        scheme, rest = uri.split("://", 1)
        if scheme in _SCHEMES:
            return _SCHEMES[scheme](rest)
        # Fall back to the shared byte-storage registry (mem://,
        # externally registered schemes) via the blob adapter.
        try:
            from ray_tpu.util.storage import get_storage
            return _StorageBlobAdapter(get_storage(uri))
        except ValueError:
            raise ValueError(
                f"no GCS storage backend for scheme {scheme!r} (register "
                f"one with register_gcs_store or util.storage."
                f"register_storage)")
    return FileStoreClient(uri)
