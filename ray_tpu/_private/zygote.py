"""Zygote: fork-based fast worker spawn.

A Python worker cold-start on this runtime costs ~2s (interpreter boot +
sitecustomize's jax import).  The reference amortizes process starts with a
prestarted worker pool (reference: src/ray/raylet/worker_pool.h:153
PrestartWorkers / maximum_startup_concurrency), but a pool can't keep up
with actor-launch storms where every actor consumes a fresh process.  The
zygote pays the import cost ONCE per node: the raylet spawns this process at
startup, it preloads the worker stack, and every subsequent worker is an
``os.fork()`` of the warm image (~10ms) — the same trick Android's zygote
and Ray's own prestart pool approximate.

Protocol: one unix-socket connection per fork request.  Request is a JSON
line ``{"env": {...}, "logfile": path}``; reply is ``{"pid": N}``.  The
forked child detaches (setsid), redirects stdio to its logfile, applies the
env, and runs the normal worker entry (worker_main.main()).  The zygote
reaps its children on SIGCHLD so kill(pid, 0) liveness probes see clean
deaths.
"""

from __future__ import annotations

import asyncio
import json
import os
import signal
import socket
import sys


# --------------------------------------------------------------- server side

def _reap(signum, frame):
    try:
        while True:
            pid, _ = os.waitpid(-1, os.WNOHANG)
            if pid == 0:
                break
    except ChildProcessError:
        pass


def _child_exec(conn: socket.socket, srv: socket.socket, req: dict):
    """Runs in the forked child; never returns."""
    try:
        signal.signal(signal.SIGCHLD, signal.SIG_DFL)
        os.setsid()
        conn.close()
        srv.close()
        logfile = req.get("logfile")
        if logfile:
            os.makedirs(os.path.dirname(logfile), exist_ok=True)
            fd = os.open(logfile, os.O_APPEND | os.O_CREAT | os.O_WRONLY,
                         0o644)
            os.dup2(fd, 1)
            os.dup2(fd, 2)
            os.close(fd)
        os.environ.update(req.get("env") or {})
        for k in req.get("unset_env") or []:
            os.environ.pop(k, None)
        import random
        random.seed()  # forked children must not share the parent's stream
        from ray_tpu._private import worker_main
        worker_main.main()
    except BaseException:
        import traceback
        traceback.print_exc()
    finally:
        os._exit(0)


def main():
    sock_path = sys.argv[1]
    try:
        # Die with the raylet that spawned us (PR_SET_PDEATHSIG) — a
        # SIGKILLed raylet must not leave a warm fork-server behind.  The
        # flag is cleared in forked children, so workers are unaffected
        # (they exit when their raylet socket closes).
        import ctypes
        ctypes.CDLL("libc.so.6", use_errno=True).prctl(
            1, signal.SIGKILL, 0, 0, 0)  # PR_SET_PDEATHSIG = 1
    except Exception:
        pass
    signal.signal(signal.SIGCHLD, _reap)
    # Preload the worker stack while we're still single-purpose: every
    # import done here is an import no forked worker pays again.
    import ray_tpu._private.worker  # noqa: F401
    import ray_tpu._private.worker_main  # noqa: F401
    import ray_tpu.actor  # noqa: F401
    try:
        os.unlink(sock_path)
    except OSError:
        pass
    srv = socket.socket(socket.AF_UNIX, socket.SOCK_STREAM)
    srv.bind(sock_path)
    srv.listen(128)
    print("ZYGOTE_READY", flush=True)
    while True:
        try:
            conn, _ = srv.accept()
        except OSError:
            break
        try:
            buf = b""
            while not buf.endswith(b"\n"):
                chunk = conn.recv(65536)
                if not chunk:
                    break
                buf += chunk
            if not buf:
                continue
            req = json.loads(buf)
            sys.stdout.flush()
            sys.stderr.flush()
            pid = os.fork()
            if pid == 0:
                _child_exec(conn, srv, req)  # never returns
            conn.sendall(json.dumps({"pid": pid}).encode() + b"\n")
        except Exception:
            import traceback
            traceback.print_exc()
        finally:
            try:
                conn.close()
            except OSError:
                pass


# --------------------------------------------------------------- client side

class ZygoteClient:
    """Raylet-side handle to the zygote process."""

    def __init__(self, sock_path: str, proc):
        self.sock_path = sock_path
        self.proc = proc
        self.ready = False

    async def wait_ready(self, timeout: float = 120.0):
        """Wait for the zygote to finish preloading (its READY line)."""
        loop = asyncio.get_running_loop()
        deadline = loop.time() + timeout
        while loop.time() < deadline:
            if self.proc.poll() is not None:
                return False
            if os.path.exists(self.sock_path):
                try:
                    r, w = await asyncio.wait_for(
                        asyncio.open_unix_connection(self.sock_path), 5)
                    w.close()
                    self.ready = True
                    return True
                except OSError:
                    pass
            await asyncio.sleep(0.05)
        return False

    async def fork(self, env: dict, logfile: str,
                   unset_env=None, timeout: float = 10.0) -> int:
        reader, writer = await asyncio.wait_for(
            asyncio.open_unix_connection(self.sock_path), timeout)
        try:
            writer.write(json.dumps({"env": env, "logfile": logfile,
                                     "unset_env": list(unset_env or [])})
                         .encode() + b"\n")
            await writer.drain()
            line = await asyncio.wait_for(reader.readline(), timeout)
            reply = json.loads(line)
            return reply["pid"]
        finally:
            writer.close()

    def kill(self):
        if self.proc is not None and self.proc.poll() is None:
            try:
                self.proc.kill()
            except OSError:
                pass
        try:
            os.unlink(self.sock_path)
        except OSError:
            pass


class PidHandle:
    """Popen-compatible shim for a fork-spawned worker (the zygote is its
    parent, so the raylet probes liveness with kill(pid, 0) instead of
    waitpid)."""

    def __init__(self, pid: int):
        self.pid = pid
        self.returncode = None

    def poll(self):
        if self.returncode is not None:
            return self.returncode
        try:
            os.kill(self.pid, 0)
            return None
        except (ProcessLookupError, PermissionError):
            self.returncode = -1
            return self.returncode

    def terminate(self):
        try:
            os.kill(self.pid, signal.SIGTERM)
        except (ProcessLookupError, PermissionError):
            pass

    def kill(self):
        try:
            os.kill(self.pid, signal.SIGKILL)
        except (ProcessLookupError, PermissionError):
            pass


if __name__ == "__main__":
    main()
