"""Node-to-node object transfer plane: windowed, multi-source, zero-pickle.

The data-plane twin of the RPC fast path (reference:
src/ray/object_manager/pull_manager.h and push_manager.h — the object
manager keeps a sliding window of chunk requests in flight per transfer
and admits transfers against byte budgets).  ``TransferManager`` owns
admission, retries, and cancellation for both directions:

* **Pulls** — ``pull()`` resolves candidate sources (the owner's hinted
  location plus any sealed copies the GCS object directory knows of),
  allocates the destination extent once, then keeps
  ``cfg.transfer_window_chunks`` chunk requests in flight.  Chunk bytes
  ride raw KIND_BLOB_REP frames straight into the arena mapping
  (protocol.request_blob) — no pickle, no staging copy.  With 2+ sealed
  sources and a large enough object, chunk ranges stripe round-robin
  across peers; a peer that dies or errors mid-transfer is dropped and
  its chunks are reissued to the survivors.
* **Pushes** — ``push()`` opens the transfer with ``os_push_begin``
  (receiver allocates; dedup against live transfers/pulls), then
  streams chunks as KIND_BLOB frames from the arena mapping — one
  memoryview handoff per chunk — with the same window.
* **Admission** — a per-peer in-flight byte cap
  (``cfg.transfer_inflight_bytes_per_peer``) across ALL transfers in
  both directions, so N concurrent pulls can't buffer-bloat one
  receiver.  At least one chunk per peer is always admitted so a chunk
  larger than the cap still makes progress.

Deadline semantics: a pull gets ONE deadline for the whole transfer
(plumbed down from the caller's ``ray.get`` timeout) — not a fresh
timeout per chunk.
"""

from __future__ import annotations

import asyncio
import logging
import time
from collections import deque

from ray_tpu._private import failpoints
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.config import GLOBAL_CONFIG as cfg

logger = logging.getLogger(__name__)


def _remain(deadline):
    if deadline is None:
        return None
    return max(0.001, deadline - time.monotonic())


def _node_tag(nid) -> str:
    """Short node tag for failpoint peer-scoping (NodeID or bytes)."""
    h = getattr(nid, "hex", None)
    return h()[:8] if callable(h) else str(nid)[:8]


def _stepped_copy(dest, src, size, step=8 * 1024 * 1024):
    for pos in range(0, size, step):
        n = min(step, size - pos)
        dest[pos:pos + n] = src[pos:pos + n]


async def run_windowed(makers, window: int):
    """Drive coroutine factories keeping at most ``window`` in flight —
    the transfer plane's sliding-window discipline, factored out so the
    push path and the collective bulk-data plane share one pump.

    ``makers`` yields zero-arg callables returning awaitables; they are
    started in order as slots free up.  Fail-fast: the first exception
    cancels everything in flight AND waits for the cancellations to be
    delivered (the same rule as _fail_pending — a cancelled chunk's
    cleanup is what unregisters its reply sink) before re-raising."""
    window = max(1, window)
    pending: set = set()
    it = iter(makers)
    exhausted = False
    try:
        while True:
            while not exhausted and len(pending) < window:
                try:
                    maker = next(it)
                except StopIteration:
                    exhausted = True
                    break
                pending.add(asyncio.ensure_future(maker()))
            if not pending:
                return
            done, pending = await asyncio.wait(
                pending, return_when=asyncio.FIRST_COMPLETED)
            for task in done:
                task.result()  # re-raises the first failure
    except BaseException:
        for task in pending:
            task.cancel()
        if pending:
            await asyncio.gather(*pending, return_exceptions=True)
        raise


class _PushChunkFailed(Exception):
    """A push chunk's receiver reported an error (run_windowed turns it
    into fail-fast cancellation of the rest of the window)."""


class TransferManager:
    """Windowed object transfers for one raylet (both directions)."""

    def __init__(self, raylet):
        self.raylet = raylet
        # Per-peer bytes currently on the wire (both directions), plus
        # FIFO waiters blocked on the cap.
        self._peer_inflight: dict = {}
        self._peer_waiters: dict = {}
        # Read-only mmaps of co-located peers' arena files (the
        # same-host zero-copy fast path); dropped with the peer.
        self._peer_arenas: dict = {}
        # Peers probed and found NOT co-located: skip the os_map RPC
        # (plus its remote pin churn) on every later pull.  Cleared
        # with drop_peer — a node id never moves hosts while alive.
        self._peer_no_arena: set = set()
        self.stats = {"pulls": 0, "pull_bytes": 0, "pull_chunks": 0,
                      "striped_pulls": 0, "chunk_retries": 0,
                      "mmap_pulls": 0, "pushes": 0, "push_bytes": 0}

    def drop_peer(self, node_id):
        arena = self._peer_arenas.pop(node_id, None)
        if arena is not None:
            arena.close()
        self._peer_no_arena.discard(node_id)

    def close(self):
        for node_id in list(self._peer_arenas):
            self.drop_peer(node_id)

    # ------------------------------------------------------------ admission
    async def _acquire_peer(self, node_id, n: int, deadline):
        """Block until n more bytes may be in flight to/from node_id.
        Always admits when the peer is idle, so one chunk larger than
        the cap can still move."""
        cap = max(1, cfg.transfer_inflight_bytes_per_peer)
        while self._peer_inflight.get(node_id, 0) > 0 \
                and self._peer_inflight.get(node_id, 0) + n > cap:
            fut = asyncio.get_running_loop().create_future()
            self._peer_waiters.setdefault(node_id, deque()).append(fut)
            try:
                remain = _remain(deadline)
                if remain is None:
                    await fut
                else:
                    await asyncio.wait_for(fut, remain)
            except asyncio.TimeoutError:
                q = self._peer_waiters.get(node_id)
                if q is not None:
                    try:
                        q.remove(fut)
                    except ValueError:
                        pass
                raise
            except asyncio.CancelledError:
                q = self._peer_waiters.get(node_id)
                if q is not None:
                    try:
                        q.remove(fut)
                    except ValueError:
                        pass
                raise
        self._peer_inflight[node_id] = \
            self._peer_inflight.get(node_id, 0) + n

    def _release_peer(self, node_id, n: int):
        left = self._peer_inflight.get(node_id, 0) - n
        if left <= 0:
            self._peer_inflight.pop(node_id, None)
        else:
            self._peer_inflight[node_id] = left
        q = self._peer_waiters.get(node_id)
        while q:
            fut = q.popleft()
            if not fut.done():
                fut.set_result(None)
                break
        if q is not None and not q:
            self._peer_waiters.pop(node_id, None)

    # ----------------------------------------------------------- pull side
    async def pull(self, oid: bytes, location, deadline,
                   trace=None) -> bool:
        """Pull oid into the local arena under ONE deadline.  Returns
        True once a sealed local copy exists.

        ``trace`` is the requesting worker's span context (riding the
        os_get body): the transfer span links as a child of the task
        span — a task-graph trace crosses into its transfer pulls.
        (The worker→raylet flow edge is closed by rpc_os_get, which
        reaches here only when a fresh pull actually runs.)"""
        token = None
        if trace is not None:
            token = _tracing.set_current(trace["trace_id"],
                                         trace.get("parent_id"))
        try:
            with _tracing.span("transfer", "transfer.pull",
                               args={"oid": oid.hex()[:12]}) as h:
                ok = await self._pull_impl(oid, location, deadline, h)
                h.args["ok"] = ok
                return ok
        finally:
            if token is not None:
                _tracing.reset_current(token)

    async def _pull_impl(self, oid: bytes, location, deadline, h) -> bool:
        r = self.raylet
        sources, size = await self._stat_sources(oid, location, deadline)
        if not sources:
            h.args["no_source"] = True
            return False
        h.args["size"] = size
        h.args["sources"] = len(sources)
        try:
            off = await r._alloc_with_spill(oid, size)
        except KeyError:
            # Concurrent pull/push already owns an allocation for this
            # oid; only a SEALED copy counts as success.
            got = r.store.get(oid)
            if got is not None and got[2]:
                r.store.release(oid)
                return True
            return False
        if off is None:
            return False
        dest = r.mapping.writable(off, size)
        self.stats["pulls"] += 1
        try:
            ok = False
            if cfg.transfer_same_host_mmap:
                ok = await self._mmap_pull(oid, size, dest, sources,
                                           deadline)
                if ok:
                    h.args["mmap"] = True
            if not ok:
                if len(sources) > 1:
                    self.stats["striped_pulls"] += 1
                    h.args["striped"] = True
                ok = await self._windowed_fetch(oid, size, dest, sources,
                                                deadline)
        except BaseException:
            await self._quiesce_and_discard(oid, sources)
            raise
        if not ok:
            # Before freeing the extent, wait out any blob body the
            # read loops are still copying into it (a timed-out chunk's
            # reply may be mid-read) — freeing under the write would
            # corrupt whatever reuses the memory.
            await self._quiesce_and_discard(oid, sources)
            return False
        r._seal_release_notify(oid)
        self.stats["pull_bytes"] += size
        return True

    # ------------------------------------------- same-host zero-copy path
    async def _mmap_pull(self, oid, size, dest, sources, deadline) -> bool:
        """Try each source as a co-located raylet: pin the object there
        (os_map), mmap its arena file read-only, and memcpy the extent
        straight across — no socket, no chunking.  Arena paths embed
        the node id, so a remote peer's path simply doesn't exist here
        and we fall back to the wire path."""
        import os as _os
        r = self.raylet
        loop = asyncio.get_running_loop()
        for nid, peer in sources:
            if nid in self._peer_no_arena:
                continue
            arena = self._peer_arenas.get(nid)
            if arena is None:
                probe = await peer.request("os_map", {"oid": oid},
                                           timeout=_remain(deadline))
                if probe.get("error"):
                    continue
                try:
                    if not _os.path.exists(probe["store_path"]):
                        raise OSError("peer arena not on this host")
                    from ray_tpu._private.shm_store import StoreMapping
                    arena = StoreMapping(probe["store_path"],
                                         probe["capacity"], readonly=True)
                    self._peer_arenas[nid] = arena
                except OSError:
                    self._peer_no_arena.add(nid)
                    self._release_remote_pin(peer, oid)
                    continue
                meta = probe
            else:
                meta = await peer.request("os_map", {"oid": oid},
                                          timeout=_remain(deadline))
                if meta.get("error"):
                    continue
            try:
                src = arena.slice(meta["offset"], meta["size"])
                # Copy on an executor thread, in 8 MiB steps: each step
                # is one C-level memcpy (GIL held ~ms), and the loop
                # keeps serving RPCs between steps.
                await loop.run_in_executor(
                    None, _stepped_copy, dest, src, size)
                self.stats["mmap_pulls"] += 1
                return True
            except Exception as e:
                logger.warning("same-host mmap pull of %s from %s "
                               "failed: %s", oid.hex()[:8], nid, e)
                continue
            finally:
                self._release_remote_pin(peer, oid)
        return False

    def _release_remote_pin(self, peer, oid):
        try:
            task = asyncio.get_running_loop().create_task(
                peer.request("os_release", {"oid": oid}, timeout=30))
            task.add_done_callback(
                lambda t: t.cancelled() or t.exception())
        except Exception:
            pass

    async def _quiesce_and_discard(self, oid: bytes, sources):
        for _nid, peer in sources:
            try:
                await peer.drain_sink_reads()
            except Exception:
                pass
        self.raylet._discard_unsealed(oid)

    async def _stat_sources(self, oid: bytes, location, deadline):
        """Candidate source nodes holding a sealed copy, stat-verified,
        hinted location first.  Striping only kicks in past
        cfg.transfer_stripe_min_bytes — and a live hinted source below
        that threshold answers alone, WITHOUT a GCS directory round
        trip or extra peer stats (the common small-object pull stays
        one os_stat, as before the striping engine existed)."""
        r = self.raylet

        async def _stat(nid):
            peer = await r._peer(nid)
            if peer is None:
                return None
            try:
                meta = await peer.request("os_stat", {"oid": oid},
                                          timeout=_remain(deadline))
            except Exception:
                return None
            if meta.get("error"):
                return None
            return nid, peer, meta["size"]

        hinted = None
        if location is not None and location != r.node_id:
            hinted = await _stat(location)
            if hinted is not None \
                    and hinted[2] < cfg.transfer_stripe_min_bytes:
                return [(hinted[0], hinted[1])], hinted[2]
        # Hint missing/dead, or the object is big enough to stripe:
        # consult the directory for more sealed copies.
        candidates = []
        if r.gcs is not None and not r.gcs.closed:
            try:
                remain = _remain(deadline)
                reply = await r.gcs.request(
                    "get_object_locations", {"oid": oid},
                    timeout=min(5.0, remain) if remain else 5.0)
                for nid in reply.get("locations", []):
                    if nid != r.node_id and nid not in candidates \
                            and (hinted is None or nid != hinted[0]):
                        candidates.append(nid)
            except Exception:
                pass  # directory is an optimization, not a dependency
        have = 1 if hinted is not None else 0
        candidates = candidates[:max(1, cfg.transfer_max_sources) - have]
        stats = await asyncio.gather(*[_stat(n) for n in candidates])
        sources = ([hinted] if hinted is not None else []) \
            + [s for s in stats if s is not None]
        if not sources:
            return [], None
        size = sources[0][2]
        sources = [(nid, peer) for nid, peer, sz in sources if sz == size]
        if size < cfg.transfer_stripe_min_bytes:
            sources = sources[:1]
        return sources, size

    async def _windowed_fetch(self, oid: bytes, size: int, dest,
                              sources, deadline) -> bool:
        """Keep up to cfg.transfer_window_chunks chunk requests in
        flight, striped round-robin across sources; chunks from a
        failed source requeue onto survivors.  The window records a
        child span under the transfer.pull span (chunk counts, retries,
        sources lost) with instant events marking each source death."""
        with _tracing.span("transfer", "transfer.window") as _h:
            ok = await self._windowed_fetch_impl(oid, size, dest,
                                                 sources, deadline, _h)
            _h.args["ok"] = ok
            return ok

    async def _windowed_fetch_impl(self, oid: bytes, size: int, dest,
                                   sources, deadline, _h) -> bool:
        chunk = max(1, cfg.fetch_chunk_bytes)
        todo = deque([pos, min(chunk, size - pos), set()]
                     for pos in range(0, size, chunk))
        total = len(todo)
        _h.args["chunks"] = total
        _h.args["sources"] = len(sources)
        retries = 0
        live = dict(sources)  # node_id -> peer conn
        window = max(1, cfg.transfer_window_chunks)
        pending: dict = {}  # task -> (entry, node_id)
        order = list(live)
        rr = 0
        done = 0
        while done < total:
            while todo and len(pending) < window:
                ent = todo.popleft()
                nid = None
                for i in range(len(order)):
                    cand = order[(rr + i) % len(order)]
                    if cand in live and cand not in ent[2]:
                        nid = cand
                        rr = (rr + i + 1) % len(order)
                        break
                if nid is None:
                    # Every live source already failed this chunk.
                    await self._fail_pending(pending)
                    logger.warning(
                        "pull %s failed: no live source for chunk @%d "
                        "(%d/%d chunks done)", oid.hex()[:8], ent[0],
                        done, total)
                    return False
                task = asyncio.get_running_loop().create_task(
                    self._fetch_chunk(live[nid], nid, oid, ent, dest,
                                      deadline))
                pending[task] = (ent, nid)
            if not pending:
                if todo:
                    return False
                break
            remain = _remain(deadline)
            finished, _ = await asyncio.wait(
                pending, timeout=remain,
                return_when=asyncio.FIRST_COMPLETED)
            if not finished:
                await self._fail_pending(pending)
                logger.warning(
                    "pull %s deadline exceeded after %d/%d chunks",
                    oid.hex()[:8], done, total)
                return False
            for task in finished:
                ent, nid = pending.pop(task)
                err = task.result()
                if err is None:
                    done += 1
                    self.stats["pull_chunks"] += 1
                    continue
                # Source failed mid-transfer: drop it, reissue the
                # chunk to a surviving source.
                live.pop(nid, None)
                ent[2].add(nid)
                self.stats["chunk_retries"] += 1
                retries += 1
                _h.args["retries"] = retries
                _tracing.event(
                    "transfer", "transfer.source_dead",
                    args={"oid": oid.hex()[:12],
                          "source": _node_tag(nid), "chunk_at": ent[0],
                          "survivors": len(live), "err": str(err)})
                logger.info("pull %s chunk @%d from %s failed (%s); "
                            "%d source(s) left", oid.hex()[:8], ent[0],
                            getattr(nid, "hex", lambda: str(nid))()[:8],
                            err, len(live))
                todo.appendleft(ent)
        return True

    async def _fail_pending(self, pending):
        """Cancel in-flight chunk tasks AND wait for the cancellations
        to be delivered: request_blob's finally is what unregisters the
        reply sink, so returning before it runs would let a late frame
        write through the still-registered sink into memory the caller
        is about to free."""
        tasks = list(pending)
        pending.clear()
        for task in tasks:
            task.cancel()
        if tasks:
            await asyncio.gather(*tasks, return_exceptions=True)

    async def _fetch_chunk(self, peer, nid, oid, ent, dest, deadline):
        """Fetch one chunk into its arena slice.  Returns None on
        success, an error string otherwise (the chunk is then rerouted
        by the caller)."""
        pos, n, _tried = ent
        if failpoints.ACTIVE:
            act = failpoints.check("transfer.pull_chunk",
                                   peer=_node_tag(nid))
            if act is not None:
                if act.kind == "error":
                    return "failpoint: injected pull-chunk error"
                if act.kind == "delay":
                    await asyncio.sleep(act.delay_s)
                elif act.kind == "drop":
                    # A lost chunk request: nothing comes back until
                    # the transfer deadline charges it.
                    rem = _remain(deadline)
                    await asyncio.sleep(min(rem if rem is not None
                                            else 60.0, 60.0))
                    return "failpoint: chunk request dropped"
        try:
            await self._acquire_peer(nid, n, deadline)
        except asyncio.TimeoutError:
            return "peer admission timed out"
        try:
            reply = await peer.request_blob(
                "os_read_chunk", {"oid": oid, "offset": pos, "len": n},
                dest[pos:pos + n], timeout=_remain(deadline))
            if isinstance(reply, dict) and reply.get("error"):
                return str(reply["error"])
            # A short delivery (truncated spill file, short pread) fills
            # only a prefix of the slice: counting it done would seal
            # silent garbage in the tail.  The header's len is what the
            # source actually sent (the transport wrote exactly that).
            got = reply.get("len") if isinstance(reply, dict) else None
            if got != n:
                return f"short chunk: {got} of {n} bytes"
            return None
        except asyncio.CancelledError:
            raise
        except Exception as e:
            return f"{type(e).__name__}: {e}"
        finally:
            self._release_peer(nid, n)

    # ----------------------------------------------------------- push side
    async def push(self, oid: bytes, target_node_id) -> bool:
        """Stream a local sealed object to one peer: os_push_begin
        (receiver allocates / dedups), then windowed raw chunk frames
        out of the arena mapping."""
        with _tracing.span("transfer", "transfer.push",
                           args={"oid": oid.hex()[:12],
                                 "target": _node_tag(target_node_id)}) \
                as h:
            ok = await self._push_impl(oid, target_node_id, h)
            h.args["ok"] = ok
            return ok

    async def _push_impl(self, oid: bytes, target_node_id, h) -> bool:
        r = self.raylet
        got = r.store.get(oid)  # pins while we stream
        if got is None:
            # Spilled locally? Restore, then stream.
            if oid in r.spilled and await r._restore_spilled(oid):
                got = r.store.get(oid)
            if got is None:
                return False
        offset, size, sealed = got
        if not sealed:
            r.store.release(oid)
            return False
        h.args["size"] = size
        try:
            peer = await r._peer(target_node_id)
            if peer is None:
                return False
            begin = await peer.request(
                "os_push_begin", {"oid": oid, "size": size}, timeout=30)
            if begin.get("skip"):
                return True  # receiver already has / is getting it
            if begin.get("error"):
                return False
            gen = begin.get("gen")
            chunk = max(1, cfg.fetch_chunk_bytes)

            async def _one(pos: int, n: int):
                rep = await self._push_chunk(peer, target_node_id, oid,
                                             gen, offset, pos, n)
                if rep.get("error"):
                    raise _PushChunkFailed(str(rep["error"]))

            try:
                await run_windowed(
                    (lambda pos=pos, n=min(chunk, size - pos):
                     _one(pos, n) for pos in range(0, size, chunk)),
                    cfg.transfer_window_chunks)
            except _PushChunkFailed as e:
                logger.warning("push %s to %s failed: %s",
                               oid.hex()[:8], target_node_id, e)
                return False
            self.stats["pushes"] += 1
            self.stats["push_bytes"] += size
            return True
        except Exception as e:
            logger.warning("push %s to %s failed: %s", oid.hex()[:8],
                           target_node_id, e)
            return False
        finally:
            r.store.release(oid)

    async def _push_chunk(self, peer, nid, oid, gen, offset, pos, n):
        """One outbound chunk: arena memoryview -> KIND_BLOB frame.
        Never raises; failures come back as {"error": ...}.  ``gen`` is
        the receiver's transfer generation from os_push_begin — echoed
        in every chunk header so a restarted transfer's stale in-flight
        chunks can't be double-counted into the new one."""
        dup = False
        if failpoints.ACTIVE:
            act = failpoints.check("transfer.push_chunk",
                                   peer=_node_tag(nid))
            if act is not None:
                if act.kind == "error":
                    return {"error": "failpoint: injected "
                                     "push-chunk error"}
                if act.kind == "drop":
                    return {"error": "failpoint: push chunk dropped"}
                if act.kind == "delay":
                    await asyncio.sleep(act.delay_s)
                elif act.kind == "dup":
                    dup = True
        try:
            await self._acquire_peer(nid, n, time.monotonic() + 60)
        except asyncio.TimeoutError:
            return {"error": "peer admission timed out"}
        try:
            mv = self.raylet.mapping.slice(offset + pos, n)
            reply = await peer.blob_request(
                "os_push", {"oid": oid, "gen": gen, "offset": pos,
                            "len": n}, mv,
                timeout=60)
            if dup:
                # Duplicate delivery of the SAME chunk: the receiver
                # must dedupe by offset, not double-count it toward the
                # seal.  The dup's reply AND any transport error it hits
                # are ignored — the chunk already landed and was acked.
                try:
                    await peer.blob_request(
                        "os_push", {"oid": oid, "gen": gen, "offset": pos,
                                    "len": n}, mv,
                        timeout=60)
                except asyncio.CancelledError:
                    raise
                except Exception:
                    pass
            return reply
        except asyncio.CancelledError:
            raise
        except Exception as e:
            return {"error": f"{type(e).__name__}: {e}"}
        finally:
            self._release_peer(nid, n)
