"""GCS — Global Control Service: the cluster's control plane.

TPU-native re-design of the reference GCS server (reference:
src/ray/gcs/gcs_server/gcs_server.h:70 and its managers —
GcsNodeManager gcs_node_manager.h:36, GcsActorManager gcs_actor_manager.h:213
with the actor state machine documented at :181-232, GcsPlacementGroupManager
gcs_placement_group_manager.h:173 with 2-phase Prepare/Commit reservation,
GcsJobManager, InternalKV gcs_kv_manager.h:31, pubsub hub src/ray/pubsub/).

One asyncio process on the head node holding:
  * node table + heartbeat liveness + load aggregation
  * actor table + scheduling + restart state machine
  * placement groups with 2-phase bundle reservation (PACK/SPREAD/STRICT_*),
    including an ICI-topology-aware STRICT_PACK for TPU sub-meshes
  * internal KV (function/class exports, named actors, collective rendezvous)
  * long-poll-free pubsub: subscribers hold a persistent connection and
    receive pushes (the reference batches over long-polls; a persistent
    duplex conn gives the same O(#subscribers) property more simply)
"""

from __future__ import annotations

import asyncio
import logging
import os
import time
from collections import deque

from ray_tpu._private import protocol
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu._private.ids import ActorID, NodeID, PlacementGroupID
from ray_tpu._private.placement import (choose_nodes_for_bundles,
                                        PlacementError)

logger = logging.getLogger(__name__)

# Actor lifecycle states (reference: gcs_actor_manager.h:181-232).
DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


class NodeInfo:
    def __init__(self, node_id, addr, resources, labels, conn):
        self.node_id: NodeID = node_id
        self.addr: tuple[str, int] = tuple(addr)
        self.total_resources: dict = dict(resources)
        self.available_resources: dict = dict(resources)
        self.labels: dict = dict(labels or {})
        self.conn: protocol.Connection = conn
        self.alive = True
        self.draining = False  # planned shutdown announced (drain RPC)
        self.drain_deadline = None  # monotonic expiry of the drain flag
        # Autopilot reservation: while set (to the beneficiary workload
        # id) the node drains its current leases instead of accepting
        # new low-priority ones — sched filters treat it like draining,
        # but GCS actor placement (serve replicas / train workers)
        # ignores it so the reclaim beneficiary can land there.
        self.reserved: str | None = None
        self.reserve_deadline = None
        self.last_heartbeat = time.monotonic()
        self.load = 0  # queued lease count reported by the raylet
        self.pending_shapes: list = []
        self.node_stats: dict = {}  # hardware report (cpu/mem/disk/store)
        # Versioned resource sync (reference: ray_syncer.h).
        self.sync_version = 0
        self.sync_beats = 0
        self.sync_payloads = 0

    def view(self):
        return {
            "node_id": self.node_id,
            "addr": self.addr,
            "resources": self.total_resources,
            "available": self.available_resources,
            "labels": self.labels,
            "alive": self.alive,
            "draining": self.draining,
            "reserved": self.reserved,
            "load": self.load,
            # Versioned-sync introspection (beats = all heartbeats,
            # payloads = beats that carried a resource snapshot).
            "sync_version": self.sync_version,
            "sync_beats": self.sync_beats,
            "sync_payloads": self.sync_payloads,
            "node_stats": self.node_stats,
        }


class ActorInfo:
    def __init__(self, actor_id, spec, owner_conn_id, job_id):
        self.actor_id: ActorID = actor_id
        self.spec = spec  # dict: class_key, init payload, resources, opts
        self.state = PENDING_CREATION
        self.node_id: NodeID | None = None
        self.addr: tuple[str, int] | None = None
        self.worker_id = None
        self.num_restarts = 0
        self.max_restarts = spec.get("max_restarts", 0)
        self.name = spec.get("name")
        self.namespace = spec.get("namespace", "default")
        self.detached = spec.get("detached", False)
        self.owner_conn_id = owner_conn_id
        self.job_id = job_id
        self.death_cause: str | None = None
        self.init_error_blob: bytes | None = None
        self.pg_id = spec.get("placement_group_id")

    def view(self):
        return {
            "actor_id": self.actor_id,
            "state": self.state,
            "addr": self.addr,
            "node_id": self.node_id,
            "name": self.name,
            "num_restarts": self.num_restarts,
            "max_restarts": self.max_restarts,
            "death_cause": self.death_cause,
            "init_error": self.init_error_blob,
            "class_name": self.spec.get("class_name"),
            "pid": self.spec.get("pid"),
        }


class PlacementGroupInfo:
    def __init__(self, pg_id, bundles, strategy, name, job_id):
        self.pg_id: PlacementGroupID = pg_id
        self.bundles: list[dict] = bundles
        self.strategy = strategy
        self.name = name
        self.job_id = job_id
        self.state = "PENDING"
        self.bundle_nodes: list[NodeID] = []
        # Bundle indices released back to their node by an elastic
        # shrink (release_bundles RPC); grow re-reserves them through
        # the same two-phase prepare/commit before spawning joiners.
        self.released_bundles: set[int] = set()

    def view(self):
        return {
            "pg_id": self.pg_id,
            "bundles": self.bundles,
            "strategy": self.strategy,
            "state": self.state,
            "bundle_nodes": self.bundle_nodes,
            "name": self.name,
        }


class _Subscriber:
    """Per-subscriber outbound pubsub state: a bounded FIFO of
    (channel, message) drained by one pump task.  The pump folds a
    backlog into batch frames, so a slow subscriber throttles only its
    own queue (and starts losing its OLDEST events past the bound)
    instead of head-of-line-blocking every other subscriber's
    broadcast."""

    __slots__ = ("conn", "queue", "wake", "task", "dropped", "gapped")

    def __init__(self, conn):
        self.conn = conn
        self.queue = deque()
        self.wake = asyncio.Event()
        self.task = None
        self.dropped = 0
        # Channels whose events this subscriber LOST to the queue
        # bound; the pump follows up with a pubsub_gap notification so
        # the consumer can re-seed authoritatively instead of running
        # on a silently-holed view forever.
        self.gapped: set = set()


class GcsServer:
    def __init__(self, host="127.0.0.1", persist_path: str | None = None):
        self.host = host
        self.server = protocol.RpcServer(self._handle, host=host, name="gcs",
                                         on_disconnect=self._on_disconnect)
        self.nodes: dict[NodeID, NodeInfo] = {}
        self.actors: dict[ActorID, ActorInfo] = {}
        self.named_actors: dict[tuple[str, str], ActorID] = {}
        self.placement_groups: dict[PlacementGroupID, PlacementGroupInfo] = {}
        self.kv: dict[str, dict[bytes, bytes]] = {}
        # Object directory: oid -> node ids reporting a sealed copy
        # (reference: gcs object location table backing the pull
        # manager's source selection).  Fed by best-effort raylet
        # reports; consumers stat-verify, so staleness is tolerated.
        self.object_locations: dict[bytes, set] = {}
        self._dir_writes = 0  # object-directory mutation counter
        self.subscribers: dict[str, set[protocol.Connection]] = {}
        # Coalesced pubsub (id(conn) -> _Subscriber) + broadcast stats.
        self._subs: dict[int, _Subscriber] = {}
        self.pubsub_stats = {"published": 0, "sent_msgs": 0,
                             "sent_frames": 0, "batches": 0,
                             "batched_msgs": 0, "max_batch": 0,
                             "dropped": 0, "evicted": 0}
        # Incremental cluster-resource aggregation: totals/availability
        # maintained from registration + heartbeat deltas so
        # cluster_resources / autoscaler demand polls don't rescan every
        # node view (the per-heartbeat-period full-rescan hot spot).
        self._agg_total: dict = {}
        self._agg_avail: dict = {}
        self._demand_nodes: set = set()  # nodes with queued lease shapes
        self.jobs: dict = {}
        self._pending_actor_creations: dict[ActorID, asyncio.Task] = {}
        self._actor_waiters: dict[ActorID, list[asyncio.Future]] = {}
        self._node_waiters: list[asyncio.Future] = []
        self._probing: set = set()  # node ids with a death probe in flight
        self._drivers: dict[int, dict] = {}  # conn-id -> {job_id}
        self._start_time = time.time()
        # Persistence (reference: gcs/store_client/redis_store_client.h:28 —
        # table storage that survives GCS restart; pluggable backends per
        # gcs/store_client — persist_path accepts a URI: plain/file://
        # (atomic-rename snapshot), sqlite:// (transactional versioned,
        # point at a shared mount for cross-machine failover), or a
        # registered external scheme).
        self._persist_path = persist_path
        self._store_client = None
        if persist_path:
            from ray_tpu._private.gcs_storage import get_store_client
            self._store_client = get_store_client(persist_path)
        self._kv_writes = 0
        # Structured cluster events (reference: src/ray/util/event.h:102
        # EventManager + dashboard/modules/event): bounded ring
        # (RT_GCS_EVENTS_MAX) with an explicit drop count, surfaced via
        # the state API and dashboard.
        self.events = deque(maxlen=max(1, cfg.gcs_events_max))
        self.events_dropped = 0
        self._events_seq = 0
        # Snapshot bookkeeping (age/size exported as metrics).
        self.restored_from_snapshot = False
        self._last_snapshot_ts = None
        self._last_snapshot_bytes = 0
        self._snapshot_count = 0
        self._metrics = None
        # Cluster autopilot: the SLO-driven resource broker.  Policy
        # state is deliberately NOT persisted (see _snapshot_state) —
        # a restarted GCS starts with zero grants and rebuilds the
        # table from client reports within one report period, which is
        # what makes "no stale grants after snapshot restore" hold by
        # construction.
        from ray_tpu._private.arbiter import ArbiterPolicy
        self.arbiter = ArbiterPolicy()
        # Gang elasticity registry (wid -> bool) fed by train-gang
        # reports, so rt resize can answer NOT_ELASTIC structurally.
        self._gang_elastic: dict[str, bool] = {}
        self._arbiter_last_counts = {"grants": 0, "revocations": 0,
                                     "breach_s": 0.0}
        if persist_path:
            self._load_snapshot()

    async def start(self, port=0):
        port = await self.server.start(port)
        self._bg_tasks = [
            asyncio.get_running_loop().create_task(self._liveness_loop()),
            asyncio.get_running_loop().create_task(self._arbiter_loop())]
        if self._persist_path:
            self._bg_tasks.append(
                asyncio.get_running_loop().create_task(
                    self._snapshot_loop()))
        logger.info("GCS listening on %s:%s", self.host, port)
        return port

    async def stop(self):
        for t in getattr(self, "_bg_tasks", []):
            t.cancel()
        for sub in list(self._subs.values()):
            if sub.task is not None:
                sub.task.cancel()
        self._subs.clear()
        await self.server.stop()

    # ----------------------------------------------------------- persistence
    # KV namespaces that are ephemeral push-streams, not recovery state —
    # excluded from snapshots (they would dominate the write cost).
    _EPHEMERAL_KV_NS = ("telemetry",)

    def _snapshot_state(self) -> dict:
        """Copy the durable tables.  MUST run on the event-loop thread
        (concurrent RPCs mutate these dicts); the pickle+write then happens
        off-loop on the copies."""
        return {
            "kv": {ns: dict(d) for ns, d in self.kv.items()
                   if ns not in self._EPHEMERAL_KV_NS},
            "named_actors": dict(self.named_actors),
            "jobs": dict(self.jobs),
            # Node table: a restarted GCS seeds these as alive-pending-
            # re-register entries with a fresh heartbeat grace window,
            # so mid-restart churn never produces a false NODE_DEAD and
            # actors keep their placements while raylets reconnect.
            "nodes": [
                {"node_id": n.node_id, "addr": n.addr,
                 "resources": dict(n.total_resources),
                 "available": dict(n.available_resources),
                 "labels": dict(n.labels), "load": n.load,
                 "draining": n.draining}
                for n in self.nodes.values() if n.alive
            ],
            # Object directory (stripe-size objects only, so compact):
            # restored entries are stat-verified by consumers, making
            # staleness harmless.
            "object_locations": {oid: list(locs) for oid, locs
                                 in self.object_locations.items()},
            # Event-log tail: recent history survives the restart
            # instead of being replayed from scratch (or lost).
            "events": (list(self.events)[-cfg.gcs_snapshot_events_tail:]
                       if cfg.gcs_snapshot_events_tail > 0 else []),
            "events_dropped": self.events_dropped,
            "actors": [
                {"actor_id": a.actor_id, "spec": dict(a.spec),
                 "state": a.state, "addr": a.addr, "node_id": a.node_id,
                 "worker_id": a.worker_id, "num_restarts": a.num_restarts,
                 "death_cause": a.death_cause, "job_id": a.job_id}
                for a in self.actors.values()
            ],
            "placement_groups": [
                {"pg_id": p.pg_id, "bundles": list(p.bundles),
                 "strategy": p.strategy, "name": p.name,
                 "job_id": p.job_id, "state": p.state,
                 "bundle_nodes": list(p.bundle_nodes),
                 "released_bundles": list(p.released_bundles)}
                for p in self.placement_groups.values()
            ],
            # Autopilot broker state (declarations, grants, breach
            # timers) is INTENTIONALLY absent: grants are leases over
            # live capacity, and resurrecting them from a snapshot
            # could hand out budget against nodes/workloads that died
            # with the old GCS.  Clients re-report within one
            # autopilot_report_period_s, rebuilding the table from
            # scratch — a restart can only under-grant, never leak.
        }

    def _write_snapshot(self, state: dict):
        import pickle
        blob = pickle.dumps(state)
        self._store_client.write(blob)
        self._last_snapshot_ts = time.monotonic()
        self._last_snapshot_bytes = len(blob)
        self._snapshot_count += 1

    def _load_snapshot(self):
        import pickle
        try:
            blob = self._store_client.read()
            if blob is None:
                return
            snap = pickle.loads(blob)
        except Exception as e:
            logger.warning("GCS snapshot load failed: %s", e)
            return
        self.kv = snap.get("kv", {})
        self.named_actors = dict(snap.get("named_actors", {}))
        self.jobs = dict(snap.get("jobs", {}))
        for nv in snap.get("nodes", []):
            # Restored as alive with conn=None ("recovering"): the
            # raylet's reconnect loop re-registers within a heartbeat,
            # and until then the fresh last_heartbeat grants the full
            # grace window — a restart mid-churn must not flip healthy
            # nodes to NODE_DEAD (nor orphan the actors placed there).
            info = NodeInfo(nv["node_id"], nv["addr"], nv["resources"],
                            nv.get("labels"), None)
            info.available_resources = dict(
                nv.get("available", nv["resources"]))
            info.load = nv.get("load", 0)
            info.draining = bool(nv.get("draining", False))
            if info.draining:
                # Restored drain flags get a fresh bounded window — a
                # deadline-less flag would never expire (and so never
                # un-exclude a node that lingers instead of exiting).
                info.drain_deadline = time.monotonic() + \
                    cfg.heartbeat_timeout_ms / 1000.0 * 2
            self.nodes[info.node_id] = info
            self._agg_add(self._agg_total, info.total_resources)
            self._agg_add(self._agg_avail, info.available_resources)
        for oid, locs in snap.get("object_locations", {}).items():
            self.object_locations[oid] = set(locs)
        for ev in snap.get("events", []):
            self.events.append(ev)
        self.events_dropped = snap.get("events_dropped", 0)
        for a in snap.get("actors", []):
            info = ActorInfo(a["actor_id"], a["spec"], None, a["job_id"])
            info.state = a["state"]
            info.addr = a["addr"]
            info.node_id = a["node_id"]
            info.worker_id = a["worker_id"]
            info.num_restarts = a["num_restarts"]
            info.death_cause = a["death_cause"]
            self.actors[info.actor_id] = info
        for p in snap.get("placement_groups", []):
            info = PlacementGroupInfo(p["pg_id"], p["bundles"],
                                      p["strategy"], p["name"], p["job_id"])
            info.state = p["state"]
            info.bundle_nodes = p["bundle_nodes"]
            info.released_bundles = set(p.get("released_bundles", ()))
            self.placement_groups[info.pg_id] = info
        self.restored_from_snapshot = True
        self._record_event(
            "INFO", "GCS_RESTORED",
            f"restored {len(self.nodes)} nodes / {len(self.actors)} "
            f"actors / {len(self.placement_groups)} PGs / "
            f"{len(self.kv)} kv namespaces from snapshot")
        logger.info("GCS restored %d nodes / %d actors / %d PGs / %d kv "
                    "namespaces from %s", len(self.nodes),
                    len(self.actors), len(self.placement_groups),
                    len(self.kv), self._persist_path)

    def _state_fingerprint(self):
        """Cheap change detector so the snapshot loop writes only when
        durable state moved — KV can hold 100MB runtime_env packages, and
        re-pickling them twice a second would be sustained disk churn."""
        kv_sizes = (self._kv_writes,) + tuple(sorted(
            (ns, len(d)) for ns, d in self.kv.items()
            if ns not in self._EPHEMERAL_KV_NS))
        actors = tuple(sorted(
            (a.actor_id.binary(), a.state, a.num_restarts)
            for a in self.actors.values()))
        pgs = tuple(sorted((p.pg_id.binary(), p.state)
                           for p in self.placement_groups.values()))
        jobs = tuple(sorted((bytes(k) if isinstance(k, bytes) else str(k),
                             str(v.get("state")))
                            for k, v in self.jobs.items()))
        nodes = tuple(sorted(
            (n.node_id.binary(), n.draining) for n in self.nodes.values()
            if n.alive))
        return hash((kv_sizes, actors, pgs, jobs, nodes,
                     self._dir_writes, self._events_seq,
                     len(self.named_actors)))

    async def _snapshot_loop(self):
        loop = asyncio.get_running_loop()
        last_fp = None
        while True:
            await asyncio.sleep(max(0.05, cfg.gcs_snapshot_period_s))
            try:
                fp = self._state_fingerprint()
                if fp == last_fp:
                    continue
                state = self._snapshot_state()  # copy on the loop thread
                await loop.run_in_executor(None, self._write_snapshot,
                                           state)
                last_fp = fp
            except Exception as e:
                logger.warning("GCS snapshot write failed: %s", e)

    # ------------------------------------------------------------------ rpc
    async def _handle(self, conn, method, body):
        fn = getattr(self, "rpc_" + method, None)
        if fn is None:
            raise protocol.RpcError(f"GCS: no method {method}")
        return await fn(conn, body)

    async def _on_disconnect(self, conn):
        # A raylet died, or a driver exited.
        self._evict_subscriber(conn)
        for node in list(self.nodes.values()):
            if node.conn is conn and node.alive:
                if self._drain_active(node):
                    # Planned shutdown (drain RPC preceded the close):
                    # not a failure — don't page operators with a
                    # NODE_DEAD error for an orderly exit.
                    await self._mark_node_dead(
                        node, "drained (planned shutdown)", planned=True)
                else:
                    # An UNANNOUNCED connection loss is not proof of
                    # death: the raylet may have failed a suspect
                    # half-open link on purpose (keepalive) or be
                    # partitioned from us while healthy.  Probe its
                    # server: refusal proves the process is gone; an
                    # unreachable node keeps the heartbeat-timeout
                    # grace window (_liveness_loop is the backstop).
                    asyncio.get_running_loop().create_task(
                        self._probe_suspect_node(node))
        drv = self._drivers.pop(id(conn), None)
        if drv is not None:
            await self._cleanup_job(drv["job_id"])

    async def _probe_suspect_node(self, node: NodeInfo):
        if node.node_id in self._probing or not node.alive:
            return
        self._probing.add(node.node_id)
        tag = node.node_id.hex()[:8]
        try:
            probe = await protocol.Connection.connect(
                node.addr[0], node.addr[1],
                name=f"gcs->raylet:{tag}",
                timeout=cfg.node_probe_timeout_s)
            try:
                await probe.request("ping", {},
                                    timeout=cfg.node_probe_timeout_s)
            finally:
                try:
                    await probe.close()
                except Exception:
                    pass
            logger.info(
                "node %s dropped its GCS connection but answers pings; "
                "keeping it alive pending re-register", tag)
        except (ConnectionRefusedError, ConnectionResetError) as e:
            # Nothing is listening on the raylet's port: the process is
            # gone — declare death NOW (reconstruction, actor restarts
            # and directory pruning must not wait a full grace window).
            if node.alive:
                await self._mark_node_dead(
                    node, f"raylet connection lost (probe: "
                          f"{type(e).__name__})")
        except Exception as e:
            # Unreachable (timeout / partition / injected fault): NOT
            # proof of death.  The node stays alive until its heartbeat
            # grace window expires or it re-registers.
            logger.info(
                "node %s unreachable after connection loss (%s); "
                "liveness grace window decides", tag, e)
        finally:
            self._probing.discard(node.node_id)

    # ---------------------------------------------------------------- nodes
    async def rpc_node_draining(self, conn, body):
        """A raylet announces its own PLANNED shutdown — the subsequent
        connection close is then an orderly removal, not a death.
        (Distinct from rpc_drain_node below, the autoscaler-initiated
        COMMAND telling a raylet to exit.)  Only the node's OWN
        connection may announce its drain (a misdirected announcement
        would permanently downgrade a later genuine crash to an orderly
        drain), and the flag expires: a node that announces draining
        but then lingers past the grace window is again reported as an
        unplanned death if it crashes."""
        node_id = body["node_id"]
        node = self.nodes.get(node_id)
        ok = node is not None and node.conn is conn
        if ok:
            node.draining = True
            node.drain_deadline = time.monotonic() + \
                cfg.heartbeat_timeout_ms / 1000.0 * 2
            # Tell the schedulers: spillback/spread targets must stop
            # selecting a node that announced its exit.
            await self._publish("nodes", {
                "event": "updated", "node_id": node.node_id,
                "draining": True})
        return {"ok": ok}

    @staticmethod
    def _drain_active(node) -> bool:
        return node.draining and (
            node.drain_deadline is None
            or time.monotonic() < node.drain_deadline)

    @staticmethod
    def _agg_add(agg: dict, d: dict):
        for k, v in d.items():
            agg[k] = agg.get(k, 0) + v

    @staticmethod
    def _agg_sub(agg: dict, d: dict):
        for k, v in d.items():
            left = agg.get(k, 0) - v
            if -1e-9 < left < 1e-9:
                agg.pop(k, None)
            else:
                agg[k] = left

    def _agg_drop_node(self, node: "NodeInfo"):
        """Remove a node's contribution from the incremental cluster
        aggregates (death / re-registration replacing a live entry)."""
        self._agg_sub(self._agg_total, node.total_resources)
        self._agg_sub(self._agg_avail, node.available_resources)
        self._demand_nodes.discard(node.node_id)

    async def rpc_register_node(self, conn, body):
        node_id = body["node_id"]
        prev = self.nodes.get(node_id)
        if prev is not None and prev.alive:
            # Re-registration (GCS restart / reconnect): replace the
            # entry's aggregate contribution instead of double-counting.
            self._agg_drop_node(prev)
        info = NodeInfo(node_id, body["addr"], body["resources"],
                        body.get("labels"), conn)
        self.nodes[node_id] = info
        self._agg_add(self._agg_total, info.total_resources)
        self._agg_add(self._agg_avail, info.available_resources)
        # Implicit "nodes" subscription BEFORE the reply snapshot is
        # built: a node registering between this reply and an explicit
        # subscribe RPC would otherwise be missed forever (the reply
        # and the event stream must be atomic for the event-fed
        # scheduling views).  The raylet's explicit subscribe stays
        # idempotent.
        if cfg.gcs_pubsub_coalesce:
            self._ensure_subscriber(conn)
        self.subscribers.setdefault("nodes", set()).add(conn)
        await self._publish("nodes", {"event": "added", "node": info.view()})
        for fut in self._node_waiters:
            if not fut.done():
                fut.set_result(None)
        self._node_waiters.clear()
        # Seed view: ALIVE nodes only — a dead node will never emit the
        # "removed" event that would prune it from the joiner's
        # scheduling view, so it must not be handed out in the first
        # place.
        return {"ok": True, "cluster_nodes": [
            n.view() for n in self.nodes.values() if n.alive]}

    async def rpc_heartbeat(self, conn, body):
        """Liveness + versioned resource sync: payload-free beats just
        refresh liveness; beats carrying a payload advance the node's
        acked sync version (reference: ray_syncer.h versioned
        snapshots)."""
        node = self.nodes.get(body["node_id"])
        if node is None:
            return {"ok": False, "reason": "unknown node (gcs restarted?)"}
        if not node.alive:
            # Late heartbeat from a node we already declared dead (or a
            # zombie that outlived its timeout): it must NOT leak into
            # the demand set or re-advertise the node to schedulers —
            # tell it to re-register instead ("unknown node" is the
            # phrase the raylet's re-register path matches on).
            return {"ok": False,
                    "reason": "unknown node (marked dead; re-register)"}
        node.last_heartbeat = time.monotonic()
        if "available" in body:
            avail = body["available"]
            load = body.get("load", node.load)
            changed = (avail != node.available_resources
                       or load != node.load)
            # Incremental aggregate maintenance: swap this node's
            # availability contribution in place of a full rescan (the
            # node is alive — dead nodes were bounced above).
            self._agg_sub(self._agg_avail, node.available_resources)
            self._agg_add(self._agg_avail, avail)
            node.available_resources = avail
            node.load = load
            node.pending_shapes = body.get("pending_shapes", [])
            if node.pending_shapes:
                self._demand_nodes.add(node.node_id)
            else:
                self._demand_nodes.discard(node.node_id)
            node.sync_version = body.get("version", 0)
            node.sync_payloads += 1
            if changed and cfg.gcs_publish_resource_updates:
                # Delta broadcast keeping raylet-side scheduling views
                # (spillback/spread/hybrid indexes) fresh; coalesced
                # pubsub folds these into batch frames.
                await self._publish("nodes", {
                    "event": "updated", "node_id": node.node_id,
                    "available": avail, "load": load})
        if "node_stats" in body:
            # Hardware utilization relayed by the node's reporter
            # (reference: reporter_agent stats feeding the dashboard).
            node.node_stats = body["node_stats"]
        node.sync_beats += 1
        return {"ok": True, "acked_version": node.sync_version}

    async def rpc_get_resource_demands(self, conn, body):
        """Aggregate demand for the autoscaler: queued lease shapes from
        every raylet + unplaced placement-group bundles (reference:
        LoadMetrics + pending PG demand in autoscaler.py:346)."""
        shapes = []
        # Only nodes that reported queued shapes are visited (the
        # _demand_nodes set is maintained from heartbeat deltas) — the
        # autoscaler poll no longer rescans every node view.
        for nid in self._demand_nodes:
            n = self.nodes.get(nid)
            if n is not None and n.alive:
                shapes.extend(n.pending_shapes)
        pending_pgs = []
        for pg in self.placement_groups.values():
            if pg.state in ("PENDING", "INFEASIBLE", "RESCHEDULING"):
                pending_pgs.append({"pg_id": pg.pg_id,
                                    "bundles": pg.bundles,
                                    "strategy": pg.strategy})
        return {"shapes": shapes, "pending_pgs": pending_pgs}

    async def rpc_get_nodes(self, conn, body):
        return [n.view() for n in self.nodes.values()]

    async def rpc_wait_for_nodes(self, conn, body):
        count = body["count"]
        timeout = body.get("timeout", 60.0)
        deadline = time.monotonic() + timeout
        while len([n for n in self.nodes.values() if n.alive]) < count:
            fut = asyncio.get_running_loop().create_future()
            self._node_waiters.append(fut)
            try:
                await asyncio.wait_for(fut, max(0.01, deadline - time.monotonic()))
            except asyncio.TimeoutError:
                return {"ok": False}
        return {"ok": True}

    async def rpc_drain_node(self, conn, body):
        node = self.nodes.get(body["node_id"])
        if node is None or not node.alive:
            return {"ok": False}
        node.draining = True
        try:
            await node.conn.request("shutdown", {})
        except Exception:
            pass
        # Autoscaler downscale is intentional — an orderly drain, not a
        # node death (no ERROR event, no operator page).
        await self._mark_node_dead(node, "drained", planned=True)
        return {"ok": True}

    async def _liveness_loop(self):
        period = cfg.heartbeat_period_ms / 1000.0
        timeout = cfg.heartbeat_timeout_ms / 1000.0
        while True:
            await asyncio.sleep(period)
            try:
                self._update_metrics()
            except Exception:
                pass
            now = time.monotonic()
            for node in list(self.nodes.values()):
                if node.alive and node.draining \
                        and not self._drain_active(node):
                    # The announced drain expired but the node lingers
                    # alive: clear the flag AND broadcast it — the
                    # draining=True update permanently excluded the
                    # node from every raylet's spillback/spread/hybrid
                    # targeting, and nothing else would ever publish
                    # the reversal.
                    node.draining = False
                    node.drain_deadline = None
                    await self._publish("nodes", {
                        "event": "updated", "node_id": node.node_id,
                        "draining": False})
                if node.alive and node.reserved is not None \
                        and (node.reserve_deadline is None
                             or now >= node.reserve_deadline):
                    # Same shape as the drain-expiry reversal above: a
                    # reservation permanently excluded the node from
                    # lease scheduling, so its expiry must be broadcast
                    # or the node stays fenced forever.
                    node.reserved = None
                    node.reserve_deadline = None
                    await self._publish("nodes", {
                        "event": "updated", "node_id": node.node_id,
                        "reserved": None})
                if node.alive and now - node.last_heartbeat > timeout:
                    # A node that announced its drain and then stalled
                    # during teardown is still an orderly exit, not a
                    # failure to page on — unless the drain window
                    # expired (then it's a genuine wedge/crash).
                    if self._drain_active(node):
                        await self._mark_node_dead(
                            node, "drain timed out (heartbeat lost "
                            "while draining)", planned=True)
                    else:
                        await self._mark_node_dead(node,
                                                   "heartbeat timeout")

    def _record_event(self, severity: str, label: str, message: str,
                      source: str = "gcs"):
        if self.events.maxlen and len(self.events) >= self.events.maxlen:
            # The ring is about to shed its oldest entry: count it so
            # operators can see history was lost (and how much).
            self.events_dropped += 1
        self._events_seq += 1
        self.events.append({"ts": time.time(), "severity": severity,
                            "label": label, "message": message,
                            "source": source})

    async def rpc_list_events(self, conn, body):
        limit = body.get("limit", 200)
        events = list(self.events)[-limit:]
        if body.get("with_stats"):
            return {"events": events, "dropped": self.events_dropped,
                    "cap": self.events.maxlen}
        return events

    async def rpc_record_event(self, conn, body):
        self._record_event(body.get("severity", "INFO"),
                           body.get("label", ""),
                           body.get("message", ""),
                           body.get("source", "client"))
        return {"ok": True}

    async def rpc_set_failpoints(self, conn, body):
        """Runtime fault-plane toggle: tests flip failpoints / partition
        rules on a live GCS mid-run (see failpoints.apply_rpc)."""
        from ray_tpu._private import failpoints
        return failpoints.apply_rpc(body)

    async def _mark_node_dead(self, node: NodeInfo, reason: str,
                              planned: bool = False):
        if not node.alive:
            return
        node.alive = False
        self._agg_drop_node(node)
        if planned:
            logger.info("node %s removed: %s", node.node_id.hex()[:8],
                        reason)
            self._record_event("INFO", "NODE_DRAINED",
                               f"node {node.node_id.hex()[:8]}: {reason}")
        else:
            logger.warning("node %s dead: %s", node.node_id.hex()[:8],
                           reason)
            self._record_event("ERROR", "NODE_DEAD",
                               f"node {node.node_id.hex()[:8]}: {reason}")
        await self._publish("nodes", {"event": "removed",
                                      "node_id": node.node_id,
                                      "reason": reason})
        # Restart or fail actors that lived there.
        for actor in list(self.actors.values()):
            if actor.node_id == node.node_id and actor.state in (ALIVE,
                                                                 PENDING_CREATION,
                                                                 RESTARTING):
                await self._on_actor_interrupted(actor,
                                                 f"node died: {reason}")
        # Invalidate placement groups with bundles there (reschedule).
        for pg in self.placement_groups.values():
            if node.node_id in pg.bundle_nodes and pg.state == "CREATED":
                pg.state = "RESCHEDULING"
                asyncio.get_running_loop().create_task(self._schedule_pg(pg))
        # Drop the dead node from the object directory so striped pulls
        # stop selecting it as a source.
        held = [o for o, locs in self.object_locations.items()
                if node.node_id in locs]
        for oid in held:
            locs = self.object_locations[oid]
            locs.discard(node.node_id)
            if not locs:
                del self.object_locations[oid]
        if held:
            self._dir_writes += 1

    # ----------------------------------------------------- object directory
    async def rpc_object_locations_added(self, conn, body):
        node_id = body["node_id"]
        for oid in body["oids"]:
            locs = self.object_locations.setdefault(oid, set())
            if node_id not in locs:
                locs.add(node_id)
                # Only real mutations dirty the snapshot fingerprint:
                # raylet location reports are idempotent best-effort,
                # and a no-op report must not trigger a full-state
                # snapshot rewrite every period.
                self._dir_writes += 1
        return {"ok": True}

    async def rpc_object_locations_removed(self, conn, body):
        node_id = body["node_id"]
        for oid in body["oids"]:
            locs = self.object_locations.get(oid)
            if locs is not None and node_id in locs:
                locs.discard(node_id)
                if not locs:
                    self.object_locations.pop(oid, None)
                self._dir_writes += 1
        return {"ok": True}

    async def rpc_get_object_locations(self, conn, body):
        """Alive nodes believed to hold a sealed copy of oid (striped
        pulls fan chunk ranges across these)."""
        locs = self.object_locations.get(body["oid"], ())
        alive = []
        for nid in locs:
            info = self.nodes.get(nid)
            if info is not None and info.alive:
                alive.append(nid)
        return {"locations": alive}

    # ------------------------------------------------------------------- kv
    async def rpc_kv_put(self, conn, body):
        ns_name = body.get("ns", "")
        ns = self.kv.setdefault(ns_name, {})
        overwrite = body.get("overwrite", True)
        if not overwrite and body["key"] in ns:
            return {"ok": False, "exists": True}
        ns[body["key"]] = body["value"]
        if ns_name not in self._EPHEMERAL_KV_NS:
            # In-place overwrites don't change namespace sizes, so the
            # snapshot fingerprint needs an explicit write counter.
            self._kv_writes += 1
        return {"ok": True}

    async def rpc_kv_get(self, conn, body):
        ns = self.kv.get(body.get("ns", ""), {})
        return {"value": ns.get(body["key"])}

    async def rpc_kv_del(self, conn, body):
        ns_name = body.get("ns", "")
        ns = self.kv.get(ns_name, {})
        existed = ns.pop(body["key"], None) is not None
        if existed and ns_name not in self._EPHEMERAL_KV_NS:
            self._kv_writes += 1
        return {"ok": existed}

    async def rpc_kv_keys(self, conn, body):
        ns = self.kv.get(body.get("ns", ""), {})
        prefix = body.get("prefix", b"")
        return {"keys": [k for k in ns if k.startswith(prefix)]}

    # --------------------------------------------------------------- pubsub
    # Coalesced broadcast (reference: src/ray/pubsub/publisher.h — the
    # publisher buffers per-subscriber mailboxes and ships batches; here
    # the mailbox is a bounded deque drained by a per-subscriber pump).
    # _publish is O(#subscribers) dict appends; the pumps fold bursts
    # into KIND_BATCH frames (one write per drain) and same-channel runs
    # into ONE pubsub_batch message, so an actor-event storm costs
    # O(events) instead of O(events x subscribers) serialized awaits.

    async def rpc_subscribe(self, conn, body):
        if cfg.gcs_pubsub_coalesce:
            self._ensure_subscriber(conn)
        for channel in body["channels"]:
            self.subscribers.setdefault(channel, set()).add(conn)
        return {"ok": True}

    async def rpc_publish(self, conn, body):
        await self._publish(body["channel"], body["message"])
        return {"ok": True}

    def _ensure_subscriber(self, conn) -> _Subscriber:
        sub = self._subs.get(id(conn))
        if sub is None:
            sub = _Subscriber(conn)
            self._subs[id(conn)] = sub
            sub.task = asyncio.get_running_loop().create_task(
                self._sub_pump(sub))
        return sub

    def _evict_subscriber(self, conn):
        sub = self._subs.pop(id(conn), None)
        for members in self.subscribers.values():
            members.discard(conn)
        if sub is not None:
            self.pubsub_stats["evicted"] += 1
            if sub.task is not None \
                    and sub.task is not asyncio.current_task():
                sub.task.cancel()

    async def _publish(self, channel: str, message):
        subs = self.subscribers.get(channel)
        if not subs:
            return
        self.pubsub_stats["published"] += 1
        if not cfg.gcs_pubsub_coalesce:
            await self._publish_legacy(channel, subs, message)
            return
        qmax = cfg.gcs_pubsub_queue_max
        # ONE shared cell per event: every subscriber queue holds the
        # same [channel, message, blob] list, so when a pump needs the
        # pickled form for a batch frame it serializes ONCE and every
        # other pump reuses it — fan-out serialization is O(events),
        # not O(events x subscribers).
        cell = [channel, message, None]
        dead = None
        for conn in subs:
            if conn.closed:
                dead = dead or []
                dead.append(conn)
                continue
            sub = self._ensure_subscriber(conn)
            if len(sub.queue) >= qmax:
                # Slow-subscriber bound: shed the OLDEST queued event
                # and remember its channel — the pump tells the
                # subscriber about the gap so it can re-seed instead
                # of running on a silently-holed view.
                shed = sub.queue.popleft()
                sub.gapped.add(shed[0])
                sub.dropped += 1
                self.pubsub_stats["dropped"] += 1
            sub.queue.append(cell)
            sub.wake.set()
        for conn in dead or ():
            self._evict_subscriber(conn)

    async def _publish_legacy(self, channel: str, subs, message):
        """Pre-coalescing path (one awaited push per subscriber per
        event) — kept as the bench baseline and the
        RT_GCS_PUBSUB_COALESCE=0 escape hatch."""
        dead = []
        # Snapshot: the awaits below yield, and concurrent
        # subscribe/disconnect handlers mutate the live set.
        for conn in list(subs):
            if conn.closed:
                dead.append(conn)
                continue
            try:
                await conn.push("pubsub",
                                {"channel": channel, "message": message})
                self.pubsub_stats["sent_msgs"] += 1
                self.pubsub_stats["sent_frames"] += 1
            except Exception:
                dead.append(conn)
        for conn in dead:
            subs.discard(conn)

    async def _sub_pump(self, sub: _Subscriber):
        """Drain one subscriber's queue: each pass ships everything
        queued (bounded by gcs_pubsub_batch_max) as one KIND_BATCH
        frame, with consecutive same-channel messages folded into a
        single pubsub_batch push carrying PRE-PICKLED message blobs
        (serialized once per event in the shared cell, reused by every
        subscriber's pump).  Within a channel, delivery order ==
        publish order (the queue is FIFO and runs preserve it)."""
        conn = sub.conn
        st = self.pubsub_stats
        try:
            while not conn.closed:
                if not sub.queue:
                    sub.wake.clear()
                    if not sub.queue:
                        await sub.wake.wait()
                    continue
                batch_max = max(1, cfg.gcs_pubsub_batch_max)
                drained = []
                q = sub.queue
                t_flush = time.time()
                while q and len(drained) < batch_max:
                    drained.append(q.popleft())
                st["sent_msgs"] += len(drained)
                # Every run — singletons included — ships the shared
                # pre-pickled blob: interleaved channels must not
                # degrade fan-out serialization back to
                # O(events x subscribers).
                items = []
                i = 0
                while i < len(drained):
                    ch = drained[i][0]
                    j = i
                    while j < len(drained) and drained[j][0] == ch:
                        j += 1
                    run = drained[i:j]
                    for c in run:
                        if c[2] is None:
                            c[2] = protocol.dumps(c[1])
                    items.append(("pubsub_batch",
                                  {"channel": ch,
                                   "raw": [c[2] for c in run]}))
                    i = j
                st["batches"] += 1
                st["batched_msgs"] += len(drained)
                if len(drained) > st["max_batch"]:
                    st["max_batch"] = len(drained)
                if sub.gapped and not q:
                    # Only once the queue is FULLY drained: everything
                    # queued at shed time predates the gap notice, so
                    # the consumer's authoritative re-seed can never be
                    # overwritten by a stale event still in flight
                    # behind it.  (A backlog deeper than one
                    # batch_max drain keeps the flag for a later pass.)
                    items.append(("pubsub_gap",
                                  {"channels": sorted(sub.gapped)}))
                    sub.gapped.clear()
                st["sent_frames"] += len(items)
                conn.push_send_many_nowait(items)
                # Batch flushes (2+ coalesced events) land in the span
                # ring: the timeline shows WHEN fan-out bursts happened
                # and how much one frame folded.  Singleton pushes are
                # steady-state noise and stay out of the ring.
                if len(drained) > 1:
                    _tracing.record(
                        "gcs", "gcs.pubsub_flush", t_flush,
                        time.time() - t_flush,
                        args={"events": len(drained),
                              "frames": len(items),
                              "subscriber": getattr(conn, "name", "?")})
                await conn.backpressure()
        except asyncio.CancelledError:
            return
        except Exception as e:
            # Evicting a subscriber whose conn still looks healthy
            # must leave a trace: it silently stops ALL its event
            # delivery (unlike queue overflow, which sends a gap
            # notice), so a swallowed pump bug would present as a
            # permanently stale consumer with zero diagnostics.
            logger.warning("pubsub pump for %s failed (%s: %s); "
                           "evicting subscriber",
                           getattr(conn, "name", conn),
                           type(e).__name__, e)
        finally:
            self._evict_subscriber(conn)

    # ----------------------------------------------------------------- jobs
    async def rpc_register_driver(self, conn, body):
        job_id = body["job_id"]
        self._drivers[id(conn)] = {"job_id": job_id}
        self.jobs[job_id] = {"job_id": job_id, "start_time": time.time(),
                             "driver_pid": body.get("pid"), "state": "RUNNING",
                             "entrypoint": body.get("entrypoint", "")}
        return {"ok": True, "nodes": [n.view() for n in self.nodes.values()]}

    async def _cleanup_job(self, job_id):
        if job_id in self.jobs:
            self.jobs[job_id]["state"] = "FINISHED"
        for actor in list(self.actors.values()):
            if actor.job_id == job_id and not actor.detached and actor.state != DEAD:
                await self._kill_actor(actor, "job finished", no_restart=True)
        for pg in list(self.placement_groups.values()):
            if pg.job_id == job_id:
                await self._remove_pg(pg)

    async def rpc_list_jobs(self, conn, body):
        return list(self.jobs.values())

    # --------------------------------------------------------------- actors
    async def rpc_create_actor(self, conn, body):
        """Register + schedule an actor (reference: GcsActorManager::
        RegisterActor + GcsActorScheduler::Schedule, gcs_actor_scheduler.cc:49)."""
        actor_id = body["actor_id"]
        spec = body["spec"]
        actor = ActorInfo(actor_id, spec, id(conn), body.get("job_id"))
        if actor.name:
            key = (actor.namespace, actor.name)
            if key in self.named_actors:
                existing = self.actors.get(self.named_actors[key])
                if existing is not None and existing.state != DEAD:
                    return {"ok": False,
                            "reason": f"actor name '{actor.name}' already taken"}
            self.named_actors[key] = actor_id
        self.actors[actor_id] = actor
        task = asyncio.get_running_loop().create_task(self._schedule_actor(actor))
        self._pending_actor_creations[actor_id] = task
        # Completed schedules must not accumulate (one dead Task per
        # actor EVER created is a control-plane leak at scale).
        task.add_done_callback(
            lambda _t, aid=actor_id:
            self._pending_actor_creations.pop(aid, None))
        return {"ok": True}

    async def _schedule_actor(self, actor: ActorInfo):
        resources = dict(actor.spec.get("resources") or {})
        strategy = actor.spec.get("scheduling_strategy")
        deadline = time.monotonic() + 120.0
        t_sched = time.time()
        attempts = 0
        while time.monotonic() < deadline:
            attempts += 1
            node = self._pick_node(resources, strategy, actor.pg_id,
                                   actor.spec.get("bundle_index"))
            if node is None:
                await asyncio.sleep(0.05)
                continue
            try:
                reply = await node.conn.request("lease_worker_for_actor", {
                    "actor_id": actor.actor_id,
                    "resources": resources,
                    "pg_id": actor.pg_id,
                    "bundle_index": actor.spec.get("bundle_index"),
                    "spec": actor.spec,
                }, timeout=max(cfg.worker_register_timeout_s, 60.0))
            except Exception as e:
                logger.warning("actor lease on node %s failed: %s",
                               node.node_id.hex()[:8], e)
                await asyncio.sleep(0.05)
                continue
            if not reply.get("ok"):
                if reply.get("init_error") is not None:
                    # Deterministic failure inside the actor's __init__ /
                    # class unpickle — retrying cannot help (reference:
                    # GcsActorManager marks the actor DEAD on creation-task
                    # failure, gcs_actor_manager.h:181-232).
                    actor.state = DEAD
                    actor.death_cause = reply.get("reason", "init failed")
                    actor.init_error_blob = reply.get("init_error")
                    await self._publish("actors", {"event": "dead",
                                                   "actor": actor.view()})
                    self._wake_actor_waiters(actor)
                    return
                await asyncio.sleep(0.02)
                continue
            actor.node_id = node.node_id
            actor.addr = tuple(reply["worker_addr"])
            actor.worker_id = reply.get("worker_id")
            actor.spec["pid"] = reply.get("pid")
            actor.state = ALIVE
            # Scheduling-decision span: queue-to-ALIVE latency with the
            # chosen node and how many pick/lease rounds it took.
            _tracing.record(
                "gcs", "gcs.schedule_actor", t_sched,
                time.time() - t_sched,
                args={"actor_id": actor.actor_id.hex()[:12],
                      "node": node.node_id.hex()[:12],
                      "attempts": attempts})
            await self._publish("actors", {"event": "alive",
                                           "actor": actor.view()})
            self._wake_actor_waiters(actor)
            return
        actor.state = DEAD
        actor.death_cause = "scheduling timed out (infeasible resources?)"
        await self._publish("actors", {"event": "dead", "actor": actor.view()})
        self._wake_actor_waiters(actor)

    def _pick_node(self, resources, strategy, pg_id=None, bundle_index=None):
        """Hybrid pack policy with PG/node-affinity support (reference:
        hybrid_scheduling_policy.h:48, node_affinity; bundle policies)."""
        if pg_id is not None:
            pg = self.placement_groups.get(pg_id)
            if pg is None or pg.state != "CREATED":
                return None
            if bundle_index is not None and bundle_index >= 0:
                nid = pg.bundle_nodes[bundle_index]
                node = self.nodes.get(nid)
                return node if node and node.alive else None
            candidates = [self.nodes[n] for n in pg.bundle_nodes
                          if n in self.nodes and self.nodes[n].alive
                          and self.nodes[n].conn is not None]
        else:
            # conn None = snapshot-restored node still reconnecting:
            # alive for liveness purposes, but not leasable yet.
            candidates = [n for n in self.nodes.values()
                          if n.alive and n.conn is not None]
        if strategy and strategy.get("type") == "node_affinity":
            nid = strategy["node_id"]
            node = self.nodes.get(nid)
            if node is None and isinstance(nid, str):
                # Callers commonly pass the hex form from ray_tpu.nodes().
                node = next((n for k, n in self.nodes.items()
                             if k.hex() == nid), None)
            if node and node.alive and node.conn is not None \
                    and self._fits(node, resources):
                return node
            if not strategy.get("soft", False):
                return None
        feasible = [n for n in candidates if self._fits_total(n, resources)]
        if not feasible:
            return None
        avail = [n for n in feasible if self._fits(n, resources)]
        pool = avail or feasible
        if strategy and strategy.get("type") == "spread":
            return min(pool, key=lambda n: n.load)
        # pack: prefer most-utilized node that still fits (hybrid policy).
        return max(pool, key=lambda n: n.load if avail else -n.load)

    @staticmethod
    def _fits(node: NodeInfo, resources: dict) -> bool:
        return all(node.available_resources.get(k, 0) >= v
                   for k, v in resources.items())

    @staticmethod
    def _fits_total(node: NodeInfo, resources: dict) -> bool:
        return all(node.total_resources.get(k, 0) >= v
                   for k, v in resources.items())

    async def rpc_get_actor(self, conn, body):
        actor = self.actors.get(body["actor_id"])
        if actor is None:
            return None
        return actor.view()

    async def rpc_wait_actor_alive(self, conn, body):
        actor = self.actors.get(body["actor_id"])
        if actor is None:
            return None
        if actor.state in (ALIVE, DEAD):
            return actor.view()
        fut = asyncio.get_running_loop().create_future()
        self._actor_waiters.setdefault(actor.actor_id, []).append(fut)
        try:
            await asyncio.wait_for(fut, body.get("timeout", 120.0))
        except asyncio.TimeoutError:
            pass
        return actor.view()

    def _wake_actor_waiters(self, actor: ActorInfo):
        for fut in self._actor_waiters.pop(actor.actor_id, []):
            if not fut.done():
                fut.set_result(None)

    async def rpc_get_named_actor(self, conn, body):
        key = (body.get("namespace", "default"), body["name"])
        actor_id = self.named_actors.get(key)
        if actor_id is None:
            return None
        actor = self.actors.get(actor_id)
        return actor.view() if actor and actor.state != DEAD else None

    async def rpc_list_named_actors(self, conn, body):
        out = []
        for (ns, name), aid in self.named_actors.items():
            a = self.actors.get(aid)
            if a is not None and a.state != DEAD:
                out.append({"name": name, "namespace": ns})
        return out

    async def rpc_report_actor_death(self, conn, body):
        """A raylet reports that an actor's worker process died."""
        actor = self.actors.get(body["actor_id"])
        if actor is None or actor.state == DEAD:
            return {"ok": True}
        await self._on_actor_interrupted(actor, body.get("reason", "worker died"))
        return {"ok": True}

    async def _on_actor_interrupted(self, actor: ActorInfo, reason: str):
        """Actor restart state machine (reference: gcs_actor_manager.h:181-232:
        ALIVE -> RESTARTING while restarts remain, else -> DEAD)."""
        if actor.max_restarts != 0 and (
                actor.max_restarts < 0 or actor.num_restarts < actor.max_restarts):
            actor.num_restarts += 1
            actor.state = RESTARTING
            actor.addr = None
            self._record_event(
                "WARNING", "ACTOR_RESTARTING",
                f"actor {actor.actor_id.hex()[:8]} "
                f"({actor.spec.get('class_name')}): {reason}")
            await self._publish("actors", {"event": "restarting",
                                           "actor": actor.view()})
            asyncio.get_running_loop().create_task(self._schedule_actor(actor))
        else:
            actor.state = DEAD
            actor.death_cause = reason
            self._record_event(
                "ERROR", "ACTOR_DEAD",
                f"actor {actor.actor_id.hex()[:8]} "
                f"({actor.spec.get('class_name')}): {reason}")
            await self._publish("actors", {"event": "dead",
                                           "actor": actor.view()})
            self._wake_actor_waiters(actor)

    async def rpc_kill_actor(self, conn, body):
        actor = self.actors.get(body["actor_id"])
        if actor is None:
            return {"ok": False}
        await self._kill_actor(actor, "ray_tpu.kill",
                               no_restart=body.get("no_restart", True))
        return {"ok": True}

    async def _kill_actor(self, actor: ActorInfo, reason, no_restart=True):
        if no_restart:
            actor.max_restarts = 0
        if actor.node_id is not None:
            node = self.nodes.get(actor.node_id)
            if node is not None and node.alive:
                try:
                    await node.conn.request("kill_worker",
                                            {"worker_id": actor.worker_id})
                except Exception:
                    pass
        if no_restart:
            actor.state = DEAD
            actor.death_cause = str(reason)
            await self._publish("actors", {"event": "dead", "actor": actor.view()})
            self._wake_actor_waiters(actor)

    async def rpc_list_actors(self, conn, body):
        return [a.view() for a in self.actors.values()]

    # ----------------------------------------------------- placement groups
    async def rpc_create_placement_group(self, conn, body):
        pg = PlacementGroupInfo(body["pg_id"], body["bundles"],
                                body.get("strategy", "PACK"),
                                body.get("name"), body.get("job_id"))
        self.placement_groups[pg.pg_id] = pg
        asyncio.get_running_loop().create_task(self._schedule_pg(pg))
        return {"ok": True}

    async def _schedule_pg(self, pg: PlacementGroupInfo):
        """Two-phase bundle reservation (reference:
        gcs_placement_group_scheduler.h:264 — Prepare on all nodes, then
        Commit; bundle policies PACK/SPREAD/STRICT_* in
        raylet/scheduling/policy/bundle_scheduling_policy.h)."""
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            alive = [n for n in self.nodes.values()
                     if n.alive and n.conn is not None]
            try:
                assignment = choose_nodes_for_bundles(
                    pg.bundles, pg.strategy, alive)
            except PlacementError:
                assignment = None
            if assignment is None:
                await asyncio.sleep(0.05)
                continue
            # Phase 1: prepare (reserve) on each node.
            prepared = []
            ok = True
            for bundle_index, (node, bundle) in enumerate(
                    zip(assignment, pg.bundles)):
                try:
                    r = await node.conn.request("prepare_bundle", {
                        "pg_id": pg.pg_id, "bundle_index": bundle_index,
                        "resources": bundle})
                except Exception:
                    r = {"ok": False}
                if r.get("ok"):
                    prepared.append((node, bundle_index))
                else:
                    ok = False
                    break
            if not ok:
                for node, bundle_index in prepared:
                    try:
                        await node.conn.request("return_bundle", {
                            "pg_id": pg.pg_id, "bundle_index": bundle_index})
                    except Exception:
                        pass
                await asyncio.sleep(0.05)
                continue
            # Phase 2: commit.
            for node, bundle_index in prepared:
                try:
                    await node.conn.request("commit_bundle", {
                        "pg_id": pg.pg_id, "bundle_index": bundle_index})
                except Exception:
                    pass
            pg.bundle_nodes = [n.node_id for n in assignment]
            pg.state = "CREATED"
            await self._publish("placement_groups",
                                {"event": "created", "pg": pg.view()})
            return
        pg.state = "INFEASIBLE"
        await self._publish("placement_groups",
                            {"event": "infeasible", "pg": pg.view()})

    async def rpc_get_placement_group(self, conn, body):
        pg = self.placement_groups.get(body["pg_id"])
        return pg.view() if pg else None

    async def rpc_wait_placement_group(self, conn, body):
        deadline = time.monotonic() + body.get("timeout", 60.0)
        while time.monotonic() < deadline:
            pg = self.placement_groups.get(body["pg_id"])
            if pg is None:
                return None
            if pg.state in ("CREATED", "INFEASIBLE"):
                return pg.view()
            await asyncio.sleep(0.01)
        return pg.view() if pg else None

    async def rpc_remove_placement_group(self, conn, body):
        pg = self.placement_groups.get(body["pg_id"])
        if pg is None:
            return {"ok": False}
        await self._remove_pg(pg)
        return {"ok": True}

    async def _remove_pg(self, pg: PlacementGroupInfo):
        for bundle_index, node_id in enumerate(pg.bundle_nodes):
            node = self.nodes.get(node_id)
            if node is not None and node.alive:
                try:
                    await node.conn.request("return_bundle", {
                        "pg_id": pg.pg_id, "bundle_index": bundle_index})
                except Exception:
                    pass
        pg.state = "REMOVED"
        self.placement_groups.pop(pg.pg_id, None)
        await self._publish("placement_groups",
                            {"event": "removed", "pg": pg.view()})

    async def rpc_list_placement_groups(self, conn, body):
        return [pg.view() for pg in self.placement_groups.values()]

    # ------------------------------------------------------------ stats/etc
    async def rpc_cluster_resources(self, conn, body):
        # Served from the incrementally-maintained aggregates (swapped
        # in/out on register / heartbeat delta / death) — O(resource
        # kinds), not O(nodes).
        return {"total": dict(self._agg_total),
                "available": dict(self._agg_avail)}

    async def rpc_ping(self, conn, body):
        return {"ok": True, "uptime": time.time() - self._start_time}

    # ------------------------------------------------------------ autopilot
    def _arbiter_capacity(self) -> int:
        """Arbitration currency: aggregate CPU slots across alive
        nodes (1 unit backs 1 serve replica / train worker / data
        task slot; the autopilot bench provisions 1-CPU nodes so a
        unit is a node)."""
        return int(self._agg_total.get("CPU", 0))

    async def _arbiter_loop(self):
        while True:
            await asyncio.sleep(max(0.02, cfg.autopilot_period_s))
            try:
                await self._arbiter_tick()
            except asyncio.CancelledError:
                return
            except Exception:
                logger.exception("arbiter tick failed")

    async def _arbiter_tick(self):
        t0 = time.time()
        capacity = self._arbiter_capacity()
        decisions = self.arbiter.tick(capacity=capacity)
        if not decisions:
            return
        breached = [w for w in
                    self.arbiter._workloads.values()
                    if w.kind == "serve" and w.breached]
        beneficiary = breached[0].wid if breached else None
        for dec in decisions:
            reclaim = (dec["action"] == "revoke"
                       and dec["kind"] in ("train", "data")
                       and beneficiary is not None)
            if reclaim:
                # Fence the reclaimed capacity: the most-idle alive
                # nodes stop admitting new low-priority leases (sched
                # filters treat reserved like draining) while the
                # beneficiary's replicas can still land there.
                await self._reserve_nodes(dec["from"] - dec["to"],
                                          beneficiary)
            sev = "WARNING" if dec["action"] == "revoke" else "INFO"
            self._record_event(
                sev, "AUTOPILOT_" + dec["action"].upper(),
                f"{dec['wid']}: {dec['from']} -> {dec['to']} units "
                f"({dec['reason']})")
            await self._publish("arbiter", dict(dec))
        _tracing.record(
            "gcs", "gcs.arbitrate", t0, time.time() - t0,
            args={"capacity": capacity,
                  "decisions": [
                      {"wid": d["wid"], "action": d["action"],
                       "from": d["from"], "to": d["to"],
                       "reason": d["reason"]} for d in decisions]})

    async def _reserve_nodes(self, count: int, beneficiary: str):
        if count <= 0:
            return
        idle = sorted(
            (n for n in self.nodes.values()
             if n.alive and not n.draining and n.reserved is None),
            key=lambda n: -n.available_resources.get("CPU", 0))
        now = time.monotonic()
        for node in idle[:count]:
            node.reserved = beneficiary
            node.reserve_deadline = now + cfg.autopilot_reserve_ttl_s
            await self._publish("nodes", {
                "event": "updated", "node_id": node.node_id,
                "reserved": beneficiary})

    async def rpc_arbiter_register(self, conn, body):
        try:
            wl = self.arbiter.register(
                body["wid"], body["kind"],
                priority=body.get("priority", 100),
                min_units=body.get("min_units", 0),
                max_units=body.get("max_units"),
                slo=body.get("slo"))
        except ValueError as e:
            return {"ok": False, "error": {"code": "BAD_DECLARATION",
                                           "message": str(e)}}
        if body["kind"] == "train":
            self._gang_elastic[wl.wid] = bool(body.get("elastic", True))
        return {"ok": True, "granted": wl.granted}

    async def rpc_arbiter_report(self, conn, body):
        decl = body.get("decl") or {}
        if decl.get("kind") == "train" and "elastic" in decl:
            self._gang_elastic[body["wid"]] = bool(decl["elastic"])
        return self.arbiter.report(
            body["wid"], want=body.get("want", 0),
            units_now=body.get("units_now", 0),
            signals=body.get("signals"),
            **{k: v for k, v in decl.items() if k != "elastic"})

    async def rpc_arbiter_unregister(self, conn, body):
        self._gang_elastic.pop(body["wid"], None)
        return {"ok": self.arbiter.unregister(body["wid"])}

    async def rpc_arbiter_status(self, conn, body):
        st = self.arbiter.status()
        st["capacity"] = self._arbiter_capacity()
        st["reserved_nodes"] = {
            n.node_id.hex()[:8]: n.reserved
            for n in self.nodes.values()
            if n.alive and n.reserved is not None}
        return st

    async def rpc_resize_gang(self, conn, body):
        """Operator/broker entry point for elastic gang resize: the
        target rides the gang's next report reply as a directive, so
        `rt resize` and the arbiter's own grants share one path into
        BackendExecutor.request_elastic_resize."""
        gang = body["gang"]
        wid = gang if gang.startswith("train:") else f"train:{gang}"
        wl = self.arbiter.get(wid)
        if wl is None or wl.kind != "train":
            known = sorted(w.wid for w in self.arbiter._workloads.values()
                           if w.kind == "train")
            return {"ok": False, "error": {
                "code": "UNKNOWN_GANG",
                "message": f"no train gang {gang!r} is registered with "
                           f"the arbiter (known: {known})"}}
        if not self._gang_elastic.get(wid, True):
            return {"ok": False, "error": {
                "code": "NOT_ELASTIC",
                "message": f"gang {gang!r} was not started with "
                           f"ScalingConfig(elastic=True); only elastic "
                           f"gangs can be resized in place"}}
        target = int(body["target"])
        if target < wl.min_units:
            return {"ok": False, "error": {
                "code": "BELOW_QUORUM",
                "message": f"target {target} is below the gang's "
                           f"elastic_min_workers floor "
                           f"({wl.min_units})"}}
        if wl.max_units is not None and target > wl.max_units:
            return {"ok": False, "error": {
                "code": "ABOVE_CAPACITY",
                "message": f"target {target} exceeds the gang's "
                           f"placement-group capacity "
                           f"({wl.max_units})"}}
        self.arbiter.set_directive(wid, target)
        self._record_event(
            "INFO", "GANG_RESIZE_REQUESTED",
            f"{wid}: operator/broker directive -> {target} workers")
        return {"ok": True, "wid": wid, "target": target}

    async def rpc_release_bundles(self, conn, body):
        """Elastic shrink support: hand named PG bundle indices back to
        their nodes so the freed CPU really returns to the cluster pool
        (a shrunk gang must not keep its old reservation pinned)."""
        pg = self.placement_groups.get(body["pg_id"])
        if pg is None:
            return {"ok": False, "reason": "no such placement group"}
        released = []
        for bundle_index in body["indices"]:
            if bundle_index in pg.released_bundles \
                    or bundle_index >= len(pg.bundle_nodes):
                continue
            node = self.nodes.get(pg.bundle_nodes[bundle_index])
            if node is not None and node.alive and node.conn is not None:
                try:
                    await node.conn.request("return_bundle", {
                        "pg_id": pg.pg_id, "bundle_index": bundle_index})
                except Exception:
                    pass
            pg.released_bundles.add(bundle_index)
            released.append(bundle_index)
        return {"ok": True, "released": released}

    async def rpc_reacquire_bundles(self, conn, body):
        """Elastic grow support: re-reserve previously released bundle
        indices through the same two-phase prepare/commit used at PG
        creation.  Failure (capacity taken by another tenant) is a
        clean refusal — the caller retries on a later grant."""
        pg = self.placement_groups.get(body["pg_id"])
        if pg is None:
            return {"ok": False, "reason": "no such placement group"}
        reacquired, failed = [], []
        for bundle_index in body["indices"]:
            if bundle_index not in pg.released_bundles:
                continue
            node = self.nodes.get(pg.bundle_nodes[bundle_index])
            ok = False
            if node is not None and node.alive and node.conn is not None:
                try:
                    r = await node.conn.request("prepare_bundle", {
                        "pg_id": pg.pg_id, "bundle_index": bundle_index,
                        "resources": pg.bundles[bundle_index]})
                    if r.get("ok"):
                        await node.conn.request("commit_bundle", {
                            "pg_id": pg.pg_id,
                            "bundle_index": bundle_index})
                        ok = True
                except Exception:
                    ok = False
            if ok:
                pg.released_bundles.discard(bundle_index)
                reacquired.append(bundle_index)
            else:
                failed.append(bundle_index)
        return {"ok": not failed, "reacquired": reacquired,
                "failed": failed}

    # -------------------------------------------------------------- metrics
    def _ensure_metrics(self):
        """GCS control-plane gauges/counters on the shared
        ray_tpu.util.metrics registry (in-process clusters see them in
        the driver's registry; the standalone GCS process self-exports
        them through the telemetry KV below)."""
        if self._metrics is not None:
            return self._metrics
        from ray_tpu.util.metrics import Counter, Gauge
        self._metrics = {
            "queue_depth": Gauge(
                "gcs_pubsub_queue_depth",
                "deepest per-subscriber outbound pubsub queue"),
            "subscribers": Gauge(
                "gcs_pubsub_subscribers", "live pubsub subscriber conns"),
            "batch_avg": Gauge(
                "gcs_pubsub_batch_size_avg",
                "mean messages folded per coalesced batch frame"),
            "dropped": Counter(
                "gcs_pubsub_dropped_total",
                "pubsub events shed by slow-subscriber queue bounds"),
            "pending_actors": Gauge(
                "gcs_pending_actor_creations",
                "actor creations awaiting scheduling"),
            "events_dropped": Counter(
                "gcs_events_dropped_total",
                "cluster events shed by the bounded event ring"),
            "snapshot_age": Gauge(
                "gcs_snapshot_age_seconds",
                "seconds since the last durable snapshot write"),
            "snapshot_bytes": Gauge(
                "gcs_snapshot_bytes", "size of the last snapshot blob"),
            "autopilot_grants": Counter(
                "autopilot_grants_total",
                "arbiter decisions that raised a workload budget"),
            "autopilot_revocations": Counter(
                "autopilot_revocations_total",
                "arbiter decisions that lowered a workload budget"),
            "autopilot_breach": Counter(
                "autopilot_slo_breach_seconds",
                "cumulative seconds any serve workload spent over its "
                "declared p99 TTFT SLO"),
            "autopilot_budget": Gauge(
                "autopilot_budget_units",
                "current arbiter-granted budget per workload"),
            "autopilot_workloads": Gauge(
                "autopilot_workloads",
                "workloads registered with the arbiter"),
        }
        # Counters exported as monotonic totals: remember last values.
        self._metric_last = {"dropped": 0, "events_dropped": 0,
                             "autopilot_grants": 0,
                             "autopilot_revocations": 0,
                             "autopilot_breach": 0.0}
        return self._metrics

    def _update_metrics(self):
        try:
            m = self._ensure_metrics()
        except Exception:
            return
        st = self.pubsub_stats
        depth = max((len(s.queue) for s in self._subs.values()),
                    default=0)
        m["queue_depth"].set(depth)
        m["subscribers"].set(len(self._subs))
        m["batch_avg"].set(
            round(st["batched_msgs"] / st["batches"], 2)
            if st["batches"] else 0.0)

        def export_counter(key, metric, current):
            delta = current - self._metric_last[key]
            if delta > 0:
                metric.inc(delta)
                self._metric_last[key] = current

        export_counter("dropped", m["dropped"], st["dropped"])
        export_counter("events_dropped", m["events_dropped"],
                       self.events_dropped)
        export_counter("autopilot_grants", m["autopilot_grants"],
                       self.arbiter.grants_total)
        export_counter("autopilot_revocations",
                       m["autopilot_revocations"],
                       self.arbiter.revocations_total)
        export_counter("autopilot_breach", m["autopilot_breach"],
                       self.arbiter.slo_breach_seconds)
        m["autopilot_workloads"].set(len(self.arbiter._workloads))
        for wl in self.arbiter._workloads.values():
            m["autopilot_budget"].set(
                wl.granted, tags={"workload": wl.wid, "kind": wl.kind})
        m["pending_actors"].set(len(self._pending_actor_creations))
        if self._last_snapshot_ts is not None:
            m["snapshot_age"].set(
                round(time.monotonic() - self._last_snapshot_ts, 3))
            m["snapshot_bytes"].set(self._last_snapshot_bytes)
        # Standalone GCS process: self-publish the gcs_* series into the
        # telemetry KV so the dashboard head aggregates them like any
        # worker's.  In-process clusters skip this (the driver's own
        # telemetry loop already exports the shared registry).
        try:
            from ray_tpu._private import worker as worker_mod
            if worker_mod.global_worker is None:
                import pickle
                from ray_tpu.util.metrics import registry_snapshot
                snaps = [s for s in registry_snapshot()
                         if s["name"].startswith("gcs_")]
                self.kv.setdefault("telemetry", {})[b"__gcs__"] = \
                    pickle.dumps({"snapshots": snaps,
                                  "profile": [],
                                  "pid": os.getpid(), "mode": "gcs"})
        except Exception:
            pass

    async def rpc_control_plane_stats(self, conn, body):
        """Raw control-plane instrumentation (bench + tests): pubsub
        queue/batch/drop counters, event-ring stats, snapshot age/size,
        scheduling table sizes."""
        self._update_metrics()
        return {
            "pubsub": {
                **self.pubsub_stats,
                "subscribers": len(self._subs),
                "queue_depth": max(
                    (len(s.queue) for s in self._subs.values()),
                    default=0),
            },
            "events": {"len": len(self.events),
                       "cap": self.events.maxlen,
                       "dropped": self.events_dropped},
            "snapshot": {
                "count": self._snapshot_count,
                "bytes": self._last_snapshot_bytes,
                "age_s": (round(time.monotonic() - self._last_snapshot_ts,
                                3)
                          if self._last_snapshot_ts is not None else None),
                "restored": self.restored_from_snapshot,
            },
            "nodes": {"alive": sum(1 for n in self.nodes.values()
                                   if n.alive),
                      "total": len(self.nodes),
                      "demand_nodes": len(self._demand_nodes)},
            "pending_actor_creations": len(self._pending_actor_creations),
        }

    async def rpc_dump_trace(self, conn, body):
        """Pull-path trace dump: the GCS process's span ring
        (scheduling decisions, pubsub batch flushes, slow RPC
        handlers) for rt timeline --cluster / rt trace."""
        body = body or {}
        return dict(_tracing.dump(stats_only=bool(body.get("stats_only")),
                                  clear=bool(body.get("clear"))),
                    role="gcs")


def main():
    import argparse
    import sys
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--persist-path", default=None)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="[gcs] %(levelname)s %(message)s")

    async def run():
        gcs = GcsServer(host=args.host, persist_path=args.persist_path)
        protocol.enable_eager_tasks()
        port = await gcs.start(args.port)
        print(f"GCS_PORT={port}", flush=True)
        sys.stdout.flush()
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
