"""GCS — Global Control Service: the cluster's control plane.

TPU-native re-design of the reference GCS server (reference:
src/ray/gcs/gcs_server/gcs_server.h:70 and its managers —
GcsNodeManager gcs_node_manager.h:36, GcsActorManager gcs_actor_manager.h:213
with the actor state machine documented at :181-232, GcsPlacementGroupManager
gcs_placement_group_manager.h:173 with 2-phase Prepare/Commit reservation,
GcsJobManager, InternalKV gcs_kv_manager.h:31, pubsub hub src/ray/pubsub/).

One asyncio process on the head node holding:
  * node table + heartbeat liveness + load aggregation
  * actor table + scheduling + restart state machine
  * placement groups with 2-phase bundle reservation (PACK/SPREAD/STRICT_*),
    including an ICI-topology-aware STRICT_PACK for TPU sub-meshes
  * internal KV (function/class exports, named actors, collective rendezvous)
  * long-poll-free pubsub: subscribers hold a persistent connection and
    receive pushes (the reference batches over long-polls; a persistent
    duplex conn gives the same O(#subscribers) property more simply)
"""

from __future__ import annotations

import asyncio
import logging
import os
import time

from ray_tpu._private import protocol
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu._private.ids import ActorID, NodeID, PlacementGroupID
from ray_tpu._private.placement import (choose_nodes_for_bundles,
                                        PlacementError)

logger = logging.getLogger(__name__)

# Actor lifecycle states (reference: gcs_actor_manager.h:181-232).
DEPENDENCIES_UNREADY = "DEPENDENCIES_UNREADY"
PENDING_CREATION = "PENDING_CREATION"
ALIVE = "ALIVE"
RESTARTING = "RESTARTING"
DEAD = "DEAD"


class NodeInfo:
    def __init__(self, node_id, addr, resources, labels, conn):
        self.node_id: NodeID = node_id
        self.addr: tuple[str, int] = tuple(addr)
        self.total_resources: dict = dict(resources)
        self.available_resources: dict = dict(resources)
        self.labels: dict = dict(labels or {})
        self.conn: protocol.Connection = conn
        self.alive = True
        self.draining = False  # planned shutdown announced (drain RPC)
        self.drain_deadline = None  # monotonic expiry of the drain flag
        self.last_heartbeat = time.monotonic()
        self.load = 0  # queued lease count reported by the raylet
        self.pending_shapes: list = []
        self.node_stats: dict = {}  # hardware report (cpu/mem/disk/store)
        # Versioned resource sync (reference: ray_syncer.h).
        self.sync_version = 0
        self.sync_beats = 0
        self.sync_payloads = 0

    def view(self):
        return {
            "node_id": self.node_id,
            "addr": self.addr,
            "resources": self.total_resources,
            "available": self.available_resources,
            "labels": self.labels,
            "alive": self.alive,
            "load": self.load,
            # Versioned-sync introspection (beats = all heartbeats,
            # payloads = beats that carried a resource snapshot).
            "sync_version": self.sync_version,
            "sync_beats": self.sync_beats,
            "sync_payloads": self.sync_payloads,
            "node_stats": self.node_stats,
        }


class ActorInfo:
    def __init__(self, actor_id, spec, owner_conn_id, job_id):
        self.actor_id: ActorID = actor_id
        self.spec = spec  # dict: class_key, init payload, resources, opts
        self.state = PENDING_CREATION
        self.node_id: NodeID | None = None
        self.addr: tuple[str, int] | None = None
        self.worker_id = None
        self.num_restarts = 0
        self.max_restarts = spec.get("max_restarts", 0)
        self.name = spec.get("name")
        self.namespace = spec.get("namespace", "default")
        self.detached = spec.get("detached", False)
        self.owner_conn_id = owner_conn_id
        self.job_id = job_id
        self.death_cause: str | None = None
        self.init_error_blob: bytes | None = None
        self.pg_id = spec.get("placement_group_id")

    def view(self):
        return {
            "actor_id": self.actor_id,
            "state": self.state,
            "addr": self.addr,
            "node_id": self.node_id,
            "name": self.name,
            "num_restarts": self.num_restarts,
            "max_restarts": self.max_restarts,
            "death_cause": self.death_cause,
            "init_error": self.init_error_blob,
            "class_name": self.spec.get("class_name"),
            "pid": self.spec.get("pid"),
        }


class PlacementGroupInfo:
    def __init__(self, pg_id, bundles, strategy, name, job_id):
        self.pg_id: PlacementGroupID = pg_id
        self.bundles: list[dict] = bundles
        self.strategy = strategy
        self.name = name
        self.job_id = job_id
        self.state = "PENDING"
        self.bundle_nodes: list[NodeID] = []

    def view(self):
        return {
            "pg_id": self.pg_id,
            "bundles": self.bundles,
            "strategy": self.strategy,
            "state": self.state,
            "bundle_nodes": self.bundle_nodes,
            "name": self.name,
        }


class GcsServer:
    def __init__(self, host="127.0.0.1", persist_path: str | None = None):
        self.host = host
        self.server = protocol.RpcServer(self._handle, host=host, name="gcs",
                                         on_disconnect=self._on_disconnect)
        self.nodes: dict[NodeID, NodeInfo] = {}
        self.actors: dict[ActorID, ActorInfo] = {}
        self.named_actors: dict[tuple[str, str], ActorID] = {}
        self.placement_groups: dict[PlacementGroupID, PlacementGroupInfo] = {}
        self.kv: dict[str, dict[bytes, bytes]] = {}
        # Object directory: oid -> node ids reporting a sealed copy
        # (reference: gcs object location table backing the pull
        # manager's source selection).  Fed by best-effort raylet
        # reports; consumers stat-verify, so staleness is tolerated.
        self.object_locations: dict[bytes, set] = {}
        self.subscribers: dict[str, set[protocol.Connection]] = {}
        self.jobs: dict = {}
        self._pending_actor_creations: dict[ActorID, asyncio.Task] = {}
        self._actor_waiters: dict[ActorID, list[asyncio.Future]] = {}
        self._node_waiters: list[asyncio.Future] = []
        self._probing: set = set()  # node ids with a death probe in flight
        self._drivers: dict[int, dict] = {}  # conn-id -> {job_id}
        self._start_time = time.time()
        # Persistence (reference: gcs/store_client/redis_store_client.h:28 —
        # table storage that survives GCS restart; pluggable backends per
        # gcs/store_client — persist_path accepts a URI: plain/file://
        # (atomic-rename snapshot), sqlite:// (transactional versioned,
        # point at a shared mount for cross-machine failover), or a
        # registered external scheme).
        self._persist_path = persist_path
        self._store_client = None
        if persist_path:
            from ray_tpu._private.gcs_storage import get_store_client
            self._store_client = get_store_client(persist_path)
        self._kv_writes = 0
        # Structured cluster events (reference: src/ray/util/event.h:102
        # EventManager + dashboard/modules/event): bounded ring, surfaced
        # via the state API and dashboard.
        from collections import deque
        self.events = deque(maxlen=1000)
        if persist_path:
            self._load_snapshot()

    async def start(self, port=0):
        port = await self.server.start(port)
        self._bg_tasks = [
            asyncio.get_running_loop().create_task(self._liveness_loop())]
        if self._persist_path:
            self._bg_tasks.append(
                asyncio.get_running_loop().create_task(
                    self._snapshot_loop()))
        logger.info("GCS listening on %s:%s", self.host, port)
        return port

    async def stop(self):
        for t in getattr(self, "_bg_tasks", []):
            t.cancel()
        await self.server.stop()

    # ----------------------------------------------------------- persistence
    # KV namespaces that are ephemeral push-streams, not recovery state —
    # excluded from snapshots (they would dominate the write cost).
    _EPHEMERAL_KV_NS = ("telemetry",)

    def _snapshot_state(self) -> dict:
        """Copy the durable tables.  MUST run on the event-loop thread
        (concurrent RPCs mutate these dicts); the pickle+write then happens
        off-loop on the copies."""
        return {
            "kv": {ns: dict(d) for ns, d in self.kv.items()
                   if ns not in self._EPHEMERAL_KV_NS},
            "named_actors": dict(self.named_actors),
            "jobs": dict(self.jobs),
            "actors": [
                {"actor_id": a.actor_id, "spec": dict(a.spec),
                 "state": a.state, "addr": a.addr, "node_id": a.node_id,
                 "worker_id": a.worker_id, "num_restarts": a.num_restarts,
                 "death_cause": a.death_cause, "job_id": a.job_id}
                for a in self.actors.values()
            ],
            "placement_groups": [
                {"pg_id": p.pg_id, "bundles": list(p.bundles),
                 "strategy": p.strategy, "name": p.name,
                 "job_id": p.job_id, "state": p.state,
                 "bundle_nodes": list(p.bundle_nodes)}
                for p in self.placement_groups.values()
            ],
        }

    def _write_snapshot(self, state: dict):
        import pickle
        self._store_client.write(pickle.dumps(state))

    def _load_snapshot(self):
        import pickle
        try:
            blob = self._store_client.read()
            if blob is None:
                return
            snap = pickle.loads(blob)
        except Exception as e:
            logger.warning("GCS snapshot load failed: %s", e)
            return
        self.kv = snap.get("kv", {})
        self.named_actors = dict(snap.get("named_actors", {}))
        self.jobs = dict(snap.get("jobs", {}))
        for a in snap.get("actors", []):
            info = ActorInfo(a["actor_id"], a["spec"], None, a["job_id"])
            info.state = a["state"]
            info.addr = a["addr"]
            info.node_id = a["node_id"]
            info.worker_id = a["worker_id"]
            info.num_restarts = a["num_restarts"]
            info.death_cause = a["death_cause"]
            self.actors[info.actor_id] = info
        for p in snap.get("placement_groups", []):
            info = PlacementGroupInfo(p["pg_id"], p["bundles"],
                                      p["strategy"], p["name"], p["job_id"])
            info.state = p["state"]
            info.bundle_nodes = p["bundle_nodes"]
            self.placement_groups[info.pg_id] = info
        logger.info("GCS restored %d actors / %d PGs / %d kv namespaces "
                    "from %s", len(self.actors), len(self.placement_groups),
                    len(self.kv), self._persist_path)

    def _state_fingerprint(self):
        """Cheap change detector so the snapshot loop writes only when
        durable state moved — KV can hold 100MB runtime_env packages, and
        re-pickling them twice a second would be sustained disk churn."""
        kv_sizes = (self._kv_writes,) + tuple(sorted(
            (ns, len(d)) for ns, d in self.kv.items()
            if ns not in self._EPHEMERAL_KV_NS))
        actors = tuple(sorted(
            (a.actor_id.binary(), a.state, a.num_restarts)
            for a in self.actors.values()))
        pgs = tuple(sorted((p.pg_id.binary(), p.state)
                           for p in self.placement_groups.values()))
        jobs = tuple(sorted((bytes(k) if isinstance(k, bytes) else str(k),
                             str(v.get("state")))
                            for k, v in self.jobs.items()))
        return hash((kv_sizes, actors, pgs, jobs,
                     len(self.named_actors)))

    async def _snapshot_loop(self):
        loop = asyncio.get_running_loop()
        last_fp = None
        while True:
            await asyncio.sleep(0.5)
            try:
                fp = self._state_fingerprint()
                if fp == last_fp:
                    continue
                state = self._snapshot_state()  # copy on the loop thread
                await loop.run_in_executor(None, self._write_snapshot,
                                           state)
                last_fp = fp
            except Exception as e:
                logger.warning("GCS snapshot write failed: %s", e)

    # ------------------------------------------------------------------ rpc
    async def _handle(self, conn, method, body):
        fn = getattr(self, "rpc_" + method, None)
        if fn is None:
            raise protocol.RpcError(f"GCS: no method {method}")
        return await fn(conn, body)

    async def _on_disconnect(self, conn):
        # A raylet died, or a driver exited.
        for node in list(self.nodes.values()):
            if node.conn is conn and node.alive:
                if self._drain_active(node):
                    # Planned shutdown (drain RPC preceded the close):
                    # not a failure — don't page operators with a
                    # NODE_DEAD error for an orderly exit.
                    await self._mark_node_dead(
                        node, "drained (planned shutdown)", planned=True)
                else:
                    # An UNANNOUNCED connection loss is not proof of
                    # death: the raylet may have failed a suspect
                    # half-open link on purpose (keepalive) or be
                    # partitioned from us while healthy.  Probe its
                    # server: refusal proves the process is gone; an
                    # unreachable node keeps the heartbeat-timeout
                    # grace window (_liveness_loop is the backstop).
                    asyncio.get_running_loop().create_task(
                        self._probe_suspect_node(node))
        drv = self._drivers.pop(id(conn), None)
        if drv is not None:
            await self._cleanup_job(drv["job_id"])

    async def _probe_suspect_node(self, node: NodeInfo):
        if node.node_id in self._probing or not node.alive:
            return
        self._probing.add(node.node_id)
        tag = node.node_id.hex()[:8]
        try:
            probe = await protocol.Connection.connect(
                node.addr[0], node.addr[1],
                name=f"gcs->raylet:{tag}",
                timeout=cfg.node_probe_timeout_s)
            try:
                await probe.request("ping", {},
                                    timeout=cfg.node_probe_timeout_s)
            finally:
                try:
                    await probe.close()
                except Exception:
                    pass
            logger.info(
                "node %s dropped its GCS connection but answers pings; "
                "keeping it alive pending re-register", tag)
        except (ConnectionRefusedError, ConnectionResetError) as e:
            # Nothing is listening on the raylet's port: the process is
            # gone — declare death NOW (reconstruction, actor restarts
            # and directory pruning must not wait a full grace window).
            if node.alive:
                await self._mark_node_dead(
                    node, f"raylet connection lost (probe: "
                          f"{type(e).__name__})")
        except Exception as e:
            # Unreachable (timeout / partition / injected fault): NOT
            # proof of death.  The node stays alive until its heartbeat
            # grace window expires or it re-registers.
            logger.info(
                "node %s unreachable after connection loss (%s); "
                "liveness grace window decides", tag, e)
        finally:
            self._probing.discard(node.node_id)

    # ---------------------------------------------------------------- nodes
    async def rpc_node_draining(self, conn, body):
        """A raylet announces its own PLANNED shutdown — the subsequent
        connection close is then an orderly removal, not a death.
        (Distinct from rpc_drain_node below, the autoscaler-initiated
        COMMAND telling a raylet to exit.)  Only the node's OWN
        connection may announce its drain (a misdirected announcement
        would permanently downgrade a later genuine crash to an orderly
        drain), and the flag expires: a node that announces draining
        but then lingers past the grace window is again reported as an
        unplanned death if it crashes."""
        node_id = body["node_id"]
        node = self.nodes.get(node_id)
        ok = node is not None and node.conn is conn
        if ok:
            node.draining = True
            node.drain_deadline = time.monotonic() + \
                cfg.heartbeat_timeout_ms / 1000.0 * 2
        return {"ok": ok}

    @staticmethod
    def _drain_active(node) -> bool:
        return node.draining and (
            node.drain_deadline is None
            or time.monotonic() < node.drain_deadline)

    async def rpc_register_node(self, conn, body):
        node_id = body["node_id"]
        info = NodeInfo(node_id, body["addr"], body["resources"],
                        body.get("labels"), conn)
        self.nodes[node_id] = info
        await self._publish("nodes", {"event": "added", "node": info.view()})
        for fut in self._node_waiters:
            if not fut.done():
                fut.set_result(None)
        self._node_waiters.clear()
        return {"ok": True, "cluster_nodes": [n.view() for n in self.nodes.values()]}

    async def rpc_heartbeat(self, conn, body):
        """Liveness + versioned resource sync: payload-free beats just
        refresh liveness; beats carrying a payload advance the node's
        acked sync version (reference: ray_syncer.h versioned
        snapshots)."""
        node = self.nodes.get(body["node_id"])
        if node is None:
            return {"ok": False, "reason": "unknown node (gcs restarted?)"}
        node.last_heartbeat = time.monotonic()
        if "available" in body:
            node.available_resources = body["available"]
            node.load = body.get("load", node.load)
            node.pending_shapes = body.get("pending_shapes", [])
            node.sync_version = body.get("version", 0)
            node.sync_payloads += 1
        if "node_stats" in body:
            # Hardware utilization relayed by the node's reporter
            # (reference: reporter_agent stats feeding the dashboard).
            node.node_stats = body["node_stats"]
        node.sync_beats += 1
        return {"ok": True, "acked_version": node.sync_version}

    async def rpc_get_resource_demands(self, conn, body):
        """Aggregate demand for the autoscaler: queued lease shapes from
        every raylet + unplaced placement-group bundles (reference:
        LoadMetrics + pending PG demand in autoscaler.py:346)."""
        shapes = []
        for n in self.nodes.values():
            if n.alive:
                shapes.extend(getattr(n, "pending_shapes", []))
        pending_pgs = []
        for pg in self.placement_groups.values():
            if pg.state in ("PENDING", "INFEASIBLE", "RESCHEDULING"):
                pending_pgs.append({"pg_id": pg.pg_id,
                                    "bundles": pg.bundles,
                                    "strategy": pg.strategy})
        return {"shapes": shapes, "pending_pgs": pending_pgs}

    async def rpc_get_nodes(self, conn, body):
        return [n.view() for n in self.nodes.values()]

    async def rpc_wait_for_nodes(self, conn, body):
        count = body["count"]
        timeout = body.get("timeout", 60.0)
        deadline = time.monotonic() + timeout
        while len([n for n in self.nodes.values() if n.alive]) < count:
            fut = asyncio.get_running_loop().create_future()
            self._node_waiters.append(fut)
            try:
                await asyncio.wait_for(fut, max(0.01, deadline - time.monotonic()))
            except asyncio.TimeoutError:
                return {"ok": False}
        return {"ok": True}

    async def rpc_drain_node(self, conn, body):
        node = self.nodes.get(body["node_id"])
        if node is None or not node.alive:
            return {"ok": False}
        node.draining = True
        try:
            await node.conn.request("shutdown", {})
        except Exception:
            pass
        # Autoscaler downscale is intentional — an orderly drain, not a
        # node death (no ERROR event, no operator page).
        await self._mark_node_dead(node, "drained", planned=True)
        return {"ok": True}

    async def _liveness_loop(self):
        period = cfg.heartbeat_period_ms / 1000.0
        timeout = cfg.heartbeat_timeout_ms / 1000.0
        while True:
            await asyncio.sleep(period)
            now = time.monotonic()
            for node in list(self.nodes.values()):
                if node.alive and now - node.last_heartbeat > timeout:
                    # A node that announced its drain and then stalled
                    # during teardown is still an orderly exit, not a
                    # failure to page on — unless the drain window
                    # expired (then it's a genuine wedge/crash).
                    if self._drain_active(node):
                        await self._mark_node_dead(
                            node, "drain timed out (heartbeat lost "
                            "while draining)", planned=True)
                    else:
                        await self._mark_node_dead(node,
                                                   "heartbeat timeout")

    def _record_event(self, severity: str, label: str, message: str,
                      source: str = "gcs"):
        self.events.append({"ts": time.time(), "severity": severity,
                            "label": label, "message": message,
                            "source": source})

    async def rpc_list_events(self, conn, body):
        limit = body.get("limit", 200)
        return list(self.events)[-limit:]

    async def rpc_record_event(self, conn, body):
        self._record_event(body.get("severity", "INFO"),
                           body.get("label", ""),
                           body.get("message", ""),
                           body.get("source", "client"))
        return {"ok": True}

    async def rpc_set_failpoints(self, conn, body):
        """Runtime fault-plane toggle: tests flip failpoints / partition
        rules on a live GCS mid-run (see failpoints.apply_rpc)."""
        from ray_tpu._private import failpoints
        return failpoints.apply_rpc(body)

    async def _mark_node_dead(self, node: NodeInfo, reason: str,
                              planned: bool = False):
        if not node.alive:
            return
        node.alive = False
        if planned:
            logger.info("node %s removed: %s", node.node_id.hex()[:8],
                        reason)
            self._record_event("INFO", "NODE_DRAINED",
                               f"node {node.node_id.hex()[:8]}: {reason}")
        else:
            logger.warning("node %s dead: %s", node.node_id.hex()[:8],
                           reason)
            self._record_event("ERROR", "NODE_DEAD",
                               f"node {node.node_id.hex()[:8]}: {reason}")
        await self._publish("nodes", {"event": "removed",
                                      "node_id": node.node_id,
                                      "reason": reason})
        # Restart or fail actors that lived there.
        for actor in list(self.actors.values()):
            if actor.node_id == node.node_id and actor.state in (ALIVE,
                                                                 PENDING_CREATION,
                                                                 RESTARTING):
                await self._on_actor_interrupted(actor,
                                                 f"node died: {reason}")
        # Invalidate placement groups with bundles there (reschedule).
        for pg in self.placement_groups.values():
            if node.node_id in pg.bundle_nodes and pg.state == "CREATED":
                pg.state = "RESCHEDULING"
                asyncio.get_running_loop().create_task(self._schedule_pg(pg))
        # Drop the dead node from the object directory so striped pulls
        # stop selecting it as a source.
        for oid in [o for o, locs in self.object_locations.items()
                    if node.node_id in locs]:
            locs = self.object_locations[oid]
            locs.discard(node.node_id)
            if not locs:
                del self.object_locations[oid]

    # ----------------------------------------------------- object directory
    async def rpc_object_locations_added(self, conn, body):
        node_id = body["node_id"]
        for oid in body["oids"]:
            self.object_locations.setdefault(oid, set()).add(node_id)
        return {"ok": True}

    async def rpc_object_locations_removed(self, conn, body):
        node_id = body["node_id"]
        for oid in body["oids"]:
            locs = self.object_locations.get(oid)
            if locs is not None:
                locs.discard(node_id)
                if not locs:
                    self.object_locations.pop(oid, None)
        return {"ok": True}

    async def rpc_get_object_locations(self, conn, body):
        """Alive nodes believed to hold a sealed copy of oid (striped
        pulls fan chunk ranges across these)."""
        locs = self.object_locations.get(body["oid"], ())
        alive = []
        for nid in locs:
            info = self.nodes.get(nid)
            if info is not None and info.alive:
                alive.append(nid)
        return {"locations": alive}

    # ------------------------------------------------------------------- kv
    async def rpc_kv_put(self, conn, body):
        ns_name = body.get("ns", "")
        ns = self.kv.setdefault(ns_name, {})
        overwrite = body.get("overwrite", True)
        if not overwrite and body["key"] in ns:
            return {"ok": False, "exists": True}
        ns[body["key"]] = body["value"]
        if ns_name not in self._EPHEMERAL_KV_NS:
            # In-place overwrites don't change namespace sizes, so the
            # snapshot fingerprint needs an explicit write counter.
            self._kv_writes += 1
        return {"ok": True}

    async def rpc_kv_get(self, conn, body):
        ns = self.kv.get(body.get("ns", ""), {})
        return {"value": ns.get(body["key"])}

    async def rpc_kv_del(self, conn, body):
        ns_name = body.get("ns", "")
        ns = self.kv.get(ns_name, {})
        existed = ns.pop(body["key"], None) is not None
        if existed and ns_name not in self._EPHEMERAL_KV_NS:
            self._kv_writes += 1
        return {"ok": existed}

    async def rpc_kv_keys(self, conn, body):
        ns = self.kv.get(body.get("ns", ""), {})
        prefix = body.get("prefix", b"")
        return {"keys": [k for k in ns if k.startswith(prefix)]}

    # --------------------------------------------------------------- pubsub
    async def rpc_subscribe(self, conn, body):
        for channel in body["channels"]:
            self.subscribers.setdefault(channel, set()).add(conn)
        return {"ok": True}

    async def rpc_publish(self, conn, body):
        await self._publish(body["channel"], body["message"])
        return {"ok": True}

    async def _publish(self, channel: str, message):
        subs = self.subscribers.get(channel)
        if not subs:
            return
        dead = []
        # Snapshot: the awaits below yield, and concurrent
        # subscribe/disconnect handlers mutate the live set.
        for conn in list(subs):
            if conn.closed:
                dead.append(conn)
                continue
            try:
                await conn.push("pubsub", {"channel": channel, "message": message})
            except Exception:
                dead.append(conn)
        for conn in dead:
            subs.discard(conn)

    # ----------------------------------------------------------------- jobs
    async def rpc_register_driver(self, conn, body):
        job_id = body["job_id"]
        self._drivers[id(conn)] = {"job_id": job_id}
        self.jobs[job_id] = {"job_id": job_id, "start_time": time.time(),
                             "driver_pid": body.get("pid"), "state": "RUNNING",
                             "entrypoint": body.get("entrypoint", "")}
        return {"ok": True, "nodes": [n.view() for n in self.nodes.values()]}

    async def _cleanup_job(self, job_id):
        if job_id in self.jobs:
            self.jobs[job_id]["state"] = "FINISHED"
        for actor in list(self.actors.values()):
            if actor.job_id == job_id and not actor.detached and actor.state != DEAD:
                await self._kill_actor(actor, "job finished", no_restart=True)
        for pg in list(self.placement_groups.values()):
            if pg.job_id == job_id:
                await self._remove_pg(pg)

    async def rpc_list_jobs(self, conn, body):
        return list(self.jobs.values())

    # --------------------------------------------------------------- actors
    async def rpc_create_actor(self, conn, body):
        """Register + schedule an actor (reference: GcsActorManager::
        RegisterActor + GcsActorScheduler::Schedule, gcs_actor_scheduler.cc:49)."""
        actor_id = body["actor_id"]
        spec = body["spec"]
        actor = ActorInfo(actor_id, spec, id(conn), body.get("job_id"))
        if actor.name:
            key = (actor.namespace, actor.name)
            if key in self.named_actors:
                existing = self.actors.get(self.named_actors[key])
                if existing is not None and existing.state != DEAD:
                    return {"ok": False,
                            "reason": f"actor name '{actor.name}' already taken"}
            self.named_actors[key] = actor_id
        self.actors[actor_id] = actor
        task = asyncio.get_running_loop().create_task(self._schedule_actor(actor))
        self._pending_actor_creations[actor_id] = task
        return {"ok": True}

    async def _schedule_actor(self, actor: ActorInfo):
        resources = dict(actor.spec.get("resources") or {})
        strategy = actor.spec.get("scheduling_strategy")
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            node = self._pick_node(resources, strategy, actor.pg_id,
                                   actor.spec.get("bundle_index"))
            if node is None:
                await asyncio.sleep(0.05)
                continue
            try:
                reply = await node.conn.request("lease_worker_for_actor", {
                    "actor_id": actor.actor_id,
                    "resources": resources,
                    "pg_id": actor.pg_id,
                    "bundle_index": actor.spec.get("bundle_index"),
                    "spec": actor.spec,
                }, timeout=max(cfg.worker_register_timeout_s, 60.0))
            except Exception as e:
                logger.warning("actor lease on node %s failed: %s",
                               node.node_id.hex()[:8], e)
                await asyncio.sleep(0.05)
                continue
            if not reply.get("ok"):
                if reply.get("init_error") is not None:
                    # Deterministic failure inside the actor's __init__ /
                    # class unpickle — retrying cannot help (reference:
                    # GcsActorManager marks the actor DEAD on creation-task
                    # failure, gcs_actor_manager.h:181-232).
                    actor.state = DEAD
                    actor.death_cause = reply.get("reason", "init failed")
                    actor.init_error_blob = reply.get("init_error")
                    await self._publish("actors", {"event": "dead",
                                                   "actor": actor.view()})
                    self._wake_actor_waiters(actor)
                    return
                await asyncio.sleep(0.02)
                continue
            actor.node_id = node.node_id
            actor.addr = tuple(reply["worker_addr"])
            actor.worker_id = reply.get("worker_id")
            actor.spec["pid"] = reply.get("pid")
            actor.state = ALIVE
            await self._publish("actors", {"event": "alive",
                                           "actor": actor.view()})
            self._wake_actor_waiters(actor)
            return
        actor.state = DEAD
        actor.death_cause = "scheduling timed out (infeasible resources?)"
        await self._publish("actors", {"event": "dead", "actor": actor.view()})
        self._wake_actor_waiters(actor)

    def _pick_node(self, resources, strategy, pg_id=None, bundle_index=None):
        """Hybrid pack policy with PG/node-affinity support (reference:
        hybrid_scheduling_policy.h:48, node_affinity; bundle policies)."""
        if pg_id is not None:
            pg = self.placement_groups.get(pg_id)
            if pg is None or pg.state != "CREATED":
                return None
            if bundle_index is not None and bundle_index >= 0:
                nid = pg.bundle_nodes[bundle_index]
                node = self.nodes.get(nid)
                return node if node and node.alive else None
            candidates = [self.nodes[n] for n in pg.bundle_nodes
                          if n in self.nodes and self.nodes[n].alive]
        else:
            candidates = [n for n in self.nodes.values() if n.alive]
        if strategy and strategy.get("type") == "node_affinity":
            nid = strategy["node_id"]
            node = self.nodes.get(nid)
            if node is None and isinstance(nid, str):
                # Callers commonly pass the hex form from ray_tpu.nodes().
                node = next((n for k, n in self.nodes.items()
                             if k.hex() == nid), None)
            if node and node.alive and self._fits(node, resources):
                return node
            if not strategy.get("soft", False):
                return None
        feasible = [n for n in candidates if self._fits_total(n, resources)]
        if not feasible:
            return None
        avail = [n for n in feasible if self._fits(n, resources)]
        pool = avail or feasible
        if strategy and strategy.get("type") == "spread":
            return min(pool, key=lambda n: n.load)
        # pack: prefer most-utilized node that still fits (hybrid policy).
        return max(pool, key=lambda n: n.load if avail else -n.load)

    @staticmethod
    def _fits(node: NodeInfo, resources: dict) -> bool:
        return all(node.available_resources.get(k, 0) >= v
                   for k, v in resources.items())

    @staticmethod
    def _fits_total(node: NodeInfo, resources: dict) -> bool:
        return all(node.total_resources.get(k, 0) >= v
                   for k, v in resources.items())

    async def rpc_get_actor(self, conn, body):
        actor = self.actors.get(body["actor_id"])
        if actor is None:
            return None
        return actor.view()

    async def rpc_wait_actor_alive(self, conn, body):
        actor = self.actors.get(body["actor_id"])
        if actor is None:
            return None
        if actor.state in (ALIVE, DEAD):
            return actor.view()
        fut = asyncio.get_running_loop().create_future()
        self._actor_waiters.setdefault(actor.actor_id, []).append(fut)
        try:
            await asyncio.wait_for(fut, body.get("timeout", 120.0))
        except asyncio.TimeoutError:
            pass
        return actor.view()

    def _wake_actor_waiters(self, actor: ActorInfo):
        for fut in self._actor_waiters.pop(actor.actor_id, []):
            if not fut.done():
                fut.set_result(None)

    async def rpc_get_named_actor(self, conn, body):
        key = (body.get("namespace", "default"), body["name"])
        actor_id = self.named_actors.get(key)
        if actor_id is None:
            return None
        actor = self.actors.get(actor_id)
        return actor.view() if actor and actor.state != DEAD else None

    async def rpc_list_named_actors(self, conn, body):
        out = []
        for (ns, name), aid in self.named_actors.items():
            a = self.actors.get(aid)
            if a is not None and a.state != DEAD:
                out.append({"name": name, "namespace": ns})
        return out

    async def rpc_report_actor_death(self, conn, body):
        """A raylet reports that an actor's worker process died."""
        actor = self.actors.get(body["actor_id"])
        if actor is None or actor.state == DEAD:
            return {"ok": True}
        await self._on_actor_interrupted(actor, body.get("reason", "worker died"))
        return {"ok": True}

    async def _on_actor_interrupted(self, actor: ActorInfo, reason: str):
        """Actor restart state machine (reference: gcs_actor_manager.h:181-232:
        ALIVE -> RESTARTING while restarts remain, else -> DEAD)."""
        if actor.max_restarts != 0 and (
                actor.max_restarts < 0 or actor.num_restarts < actor.max_restarts):
            actor.num_restarts += 1
            actor.state = RESTARTING
            actor.addr = None
            self._record_event(
                "WARNING", "ACTOR_RESTARTING",
                f"actor {actor.actor_id.hex()[:8]} "
                f"({actor.spec.get('class_name')}): {reason}")
            await self._publish("actors", {"event": "restarting",
                                           "actor": actor.view()})
            asyncio.get_running_loop().create_task(self._schedule_actor(actor))
        else:
            actor.state = DEAD
            actor.death_cause = reason
            self._record_event(
                "ERROR", "ACTOR_DEAD",
                f"actor {actor.actor_id.hex()[:8]} "
                f"({actor.spec.get('class_name')}): {reason}")
            await self._publish("actors", {"event": "dead",
                                           "actor": actor.view()})
            self._wake_actor_waiters(actor)

    async def rpc_kill_actor(self, conn, body):
        actor = self.actors.get(body["actor_id"])
        if actor is None:
            return {"ok": False}
        await self._kill_actor(actor, "ray_tpu.kill",
                               no_restart=body.get("no_restart", True))
        return {"ok": True}

    async def _kill_actor(self, actor: ActorInfo, reason, no_restart=True):
        if no_restart:
            actor.max_restarts = 0
        if actor.node_id is not None:
            node = self.nodes.get(actor.node_id)
            if node is not None and node.alive:
                try:
                    await node.conn.request("kill_worker",
                                            {"worker_id": actor.worker_id})
                except Exception:
                    pass
        if no_restart:
            actor.state = DEAD
            actor.death_cause = str(reason)
            await self._publish("actors", {"event": "dead", "actor": actor.view()})
            self._wake_actor_waiters(actor)

    async def rpc_list_actors(self, conn, body):
        return [a.view() for a in self.actors.values()]

    # ----------------------------------------------------- placement groups
    async def rpc_create_placement_group(self, conn, body):
        pg = PlacementGroupInfo(body["pg_id"], body["bundles"],
                                body.get("strategy", "PACK"),
                                body.get("name"), body.get("job_id"))
        self.placement_groups[pg.pg_id] = pg
        asyncio.get_running_loop().create_task(self._schedule_pg(pg))
        return {"ok": True}

    async def _schedule_pg(self, pg: PlacementGroupInfo):
        """Two-phase bundle reservation (reference:
        gcs_placement_group_scheduler.h:264 — Prepare on all nodes, then
        Commit; bundle policies PACK/SPREAD/STRICT_* in
        raylet/scheduling/policy/bundle_scheduling_policy.h)."""
        deadline = time.monotonic() + 120.0
        while time.monotonic() < deadline:
            alive = [n for n in self.nodes.values() if n.alive]
            try:
                assignment = choose_nodes_for_bundles(
                    pg.bundles, pg.strategy, alive)
            except PlacementError:
                assignment = None
            if assignment is None:
                await asyncio.sleep(0.05)
                continue
            # Phase 1: prepare (reserve) on each node.
            prepared = []
            ok = True
            for bundle_index, (node, bundle) in enumerate(
                    zip(assignment, pg.bundles)):
                try:
                    r = await node.conn.request("prepare_bundle", {
                        "pg_id": pg.pg_id, "bundle_index": bundle_index,
                        "resources": bundle})
                except Exception:
                    r = {"ok": False}
                if r.get("ok"):
                    prepared.append((node, bundle_index))
                else:
                    ok = False
                    break
            if not ok:
                for node, bundle_index in prepared:
                    try:
                        await node.conn.request("return_bundle", {
                            "pg_id": pg.pg_id, "bundle_index": bundle_index})
                    except Exception:
                        pass
                await asyncio.sleep(0.05)
                continue
            # Phase 2: commit.
            for node, bundle_index in prepared:
                try:
                    await node.conn.request("commit_bundle", {
                        "pg_id": pg.pg_id, "bundle_index": bundle_index})
                except Exception:
                    pass
            pg.bundle_nodes = [n.node_id for n in assignment]
            pg.state = "CREATED"
            await self._publish("placement_groups",
                                {"event": "created", "pg": pg.view()})
            return
        pg.state = "INFEASIBLE"
        await self._publish("placement_groups",
                            {"event": "infeasible", "pg": pg.view()})

    async def rpc_get_placement_group(self, conn, body):
        pg = self.placement_groups.get(body["pg_id"])
        return pg.view() if pg else None

    async def rpc_wait_placement_group(self, conn, body):
        deadline = time.monotonic() + body.get("timeout", 60.0)
        while time.monotonic() < deadline:
            pg = self.placement_groups.get(body["pg_id"])
            if pg is None:
                return None
            if pg.state in ("CREATED", "INFEASIBLE"):
                return pg.view()
            await asyncio.sleep(0.01)
        return pg.view() if pg else None

    async def rpc_remove_placement_group(self, conn, body):
        pg = self.placement_groups.get(body["pg_id"])
        if pg is None:
            return {"ok": False}
        await self._remove_pg(pg)
        return {"ok": True}

    async def _remove_pg(self, pg: PlacementGroupInfo):
        for bundle_index, node_id in enumerate(pg.bundle_nodes):
            node = self.nodes.get(node_id)
            if node is not None and node.alive:
                try:
                    await node.conn.request("return_bundle", {
                        "pg_id": pg.pg_id, "bundle_index": bundle_index})
                except Exception:
                    pass
        pg.state = "REMOVED"
        self.placement_groups.pop(pg.pg_id, None)
        await self._publish("placement_groups",
                            {"event": "removed", "pg": pg.view()})

    async def rpc_list_placement_groups(self, conn, body):
        return [pg.view() for pg in self.placement_groups.values()]

    # ------------------------------------------------------------ stats/etc
    async def rpc_cluster_resources(self, conn, body):
        total: dict = {}
        avail: dict = {}
        for n in self.nodes.values():
            if not n.alive:
                continue
            for k, v in n.total_resources.items():
                total[k] = total.get(k, 0) + v
            for k, v in n.available_resources.items():
                avail[k] = avail.get(k, 0) + v
        return {"total": total, "available": avail}

    async def rpc_ping(self, conn, body):
        return {"ok": True, "uptime": time.time() - self._start_time}


def main():
    import argparse
    import sys
    parser = argparse.ArgumentParser()
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--persist-path", default=None)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="[gcs] %(levelname)s %(message)s")

    async def run():
        gcs = GcsServer(host=args.host, persist_path=args.persist_path)
        protocol.enable_eager_tasks()
        port = await gcs.start(args.port)
        print(f"GCS_PORT={port}", flush=True)
        sys.stdout.flush()
        await asyncio.Event().wait()

    asyncio.run(run())


if __name__ == "__main__":
    main()
