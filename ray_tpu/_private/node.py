"""Node: spawns and supervises the cluster processes on one machine.

Reference: python/ray/_private/node.py:1084 start_ray_processes /
:896 start_gcs_server / :928 start_raylet, with command assembly in
_private/services.py:1381,1440.  Head nodes run the GCS; every node runs a
raylet (which embeds the shared-memory store).  In-process variants
(`start_in_process`) run GCS + raylet coroutines inside the driver's event
loop — that is what the multi-node-in-one-process test Cluster uses
(reference analogue: python/ray/cluster_utils.py Cluster.add_node spawning
real raylets locally).
"""

from __future__ import annotations

import asyncio
import atexit
import os
import re
import subprocess
import sys
import tempfile
import time
import uuid

from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu._private.resources import detect_node_resources


def new_session_dir():
    base = os.path.join(tempfile.gettempdir(), "ray_tpu")
    session = os.path.join(base,
                           f"session_{time.strftime('%Y%m%d-%H%M%S')}"
                           f"_{uuid.uuid4().hex[:8]}")
    os.makedirs(os.path.join(session, "logs"), exist_ok=True)
    return session


def _read_tag(proc, tag, timeout=30.0, convert=int):
    pattern = re.compile(rf"{tag}=(\S+)")
    deadline = time.monotonic() + timeout
    while time.monotonic() < deadline:
        line = proc.stdout.readline()
        if not line:
            if proc.poll() is not None:
                raise RuntimeError(f"{tag} process exited "
                                   f"with {proc.returncode}")
            time.sleep(0.01)
            continue
        m = pattern.search(line.decode(errors="replace"))
        if m:
            return convert(m.group(1))
    raise RuntimeError(f"timed out waiting for {tag}")


def _read_port(proc, tag, timeout=30.0):
    return _read_tag(proc, tag, timeout, convert=int)


class NodeProcesses:
    """Out-of-process GCS + raylet — a REAL node, reachable across hosts.

    Reference: python/ray/_private/node.py:1084 start_ray_processes with
    command assembly services.py:1381 (gcs_server) / :1440 (raylet).  The
    head node spawns the GCS process; every node spawns a raylet process
    (which owns the node's shm store and worker pool).  ``host`` is the
    bind + advertise address — pass the machine's routable IP for
    multi-host clusters (the default loopback only works single-machine).
    ``rt start --head`` / ``rt start --address`` (scripts/cli.py) and the
    out-of-process test ``ProcessCluster`` both build on this."""

    def __init__(self, session_dir=None, num_cpus=None, num_tpus=None,
                 resources=None, object_store_memory=None, head=True,
                 gcs_addr=None, host="127.0.0.1", gcs_port=0, labels=None,
                 node_name=None, register_atexit=True):
        self.session_dir = session_dir or new_session_dir()
        self.gcs_proc: subprocess.Popen | None = None
        self.raylet_proc: subprocess.Popen | None = None
        self.gcs_addr = tuple(gcs_addr) if gcs_addr else None
        self.raylet_addr = None
        self.head = head
        self.host = host
        self.gcs_port = gcs_port
        self.node_name = node_name
        self._register_atexit = register_atexit
        self._resources, self._labels = detect_node_resources(
            num_cpus=num_cpus, num_tpus=num_tpus, resources=resources)
        if labels:
            self._labels.update(labels)
        self._object_store_memory = (object_store_memory
                                     or cfg.object_store_memory_bytes)

    def _logfile(self, tag):
        path = os.path.join(self.session_dir, "logs", f"{tag}.err")
        os.makedirs(os.path.dirname(path), exist_ok=True)
        return open(path, "ab")

    def start(self):
        env = dict(os.environ)
        env.update(cfg.to_env())
        if self.head:
            self.gcs_proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.gcs",
                 "--host", self.host,
                 "--port", str(self.gcs_port),
                 "--persist-path",
                 os.path.join(self.session_dir, "gcs_snapshot.pkl")],
                stdout=subprocess.PIPE, stderr=self._logfile("gcs"),
                env=env, start_new_session=True)
            port = _read_port(self.gcs_proc, "GCS_PORT")
            self.gcs_addr = (self.host, port)
        self.start_raylet()
        if self._register_atexit:
            atexit.register(self.kill)
        return self

    def start_raylet(self):
        """(Re)spawn this node's raylet (also used after a SIGKILL in
        chaos flows to simulate a machine coming back)."""
        import json
        env = dict(os.environ)
        env.update(cfg.to_env())
        self.raylet_proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.raylet",
             "--host", self.host,
             "--gcs-host", self.gcs_addr[0],
             "--gcs-port", str(self.gcs_addr[1]),
             "--resources", json.dumps(self._resources),
             "--labels", json.dumps(self._labels),
             "--session-dir", self.session_dir,
             "--store-capacity", str(self._object_store_memory)]
            + (["--node-name", self.node_name] if self.node_name else []),
            stdout=subprocess.PIPE, stderr=self._logfile("raylet"),
            env=env, start_new_session=True)
        rport = _read_port(self.raylet_proc, "RAYLET_PORT")
        self.raylet_addr = (self.host, rport)
        self.raylet_node_id = _read_tag(self.raylet_proc, "RAYLET_NODE_ID",
                                        convert=str)
        return self.raylet_addr

    def restart_gcs(self):
        """Respawn the GCS on its previous port, reloading the snapshot
        (reference: GCS failover with Redis persistence)."""
        if not self.head or self.gcs_addr is None:
            raise RuntimeError("not a head node")
        env = dict(os.environ)
        env.update(cfg.to_env())
        self.gcs_proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.gcs",
             "--host", self.host,
             "--port", str(self.gcs_addr[1]),
             "--persist-path",
             os.path.join(self.session_dir, "gcs_snapshot.pkl")],
            stdout=subprocess.PIPE, stderr=self._logfile("gcs"),
            env=env, start_new_session=True)
        _read_port(self.gcs_proc, "GCS_PORT")

    @property
    def procs(self):
        return [p for p in (self.gcs_proc, self.raylet_proc)
                if p is not None]

    def pids(self):
        return {("gcs" if p is self.gcs_proc else "raylet"): p.pid
                for p in self.procs}

    def kill_raylet(self, sig=None):
        """SIGKILL (default) the raylet process — real fault injection;
        its workers die with it (they exit when the raylet socket
        closes)."""
        import signal as _signal
        p = self.raylet_proc
        if p is not None and p.poll() is None:
            try:
                os.kill(p.pid, sig or _signal.SIGKILL)
                p.wait(10)
            except Exception:
                pass

    def kill_gcs(self, sig=None):
        import signal as _signal
        p = self.gcs_proc
        if p is not None and p.poll() is None:
            try:
                os.kill(p.pid, sig or _signal.SIGKILL)
                p.wait(10)
            except Exception:
                pass

    def kill(self):
        self.kill_raylet()
        self.kill_gcs()
        self.gcs_proc = None
        self.raylet_proc = None


class InProcessNode:
    """GCS and/or raylet running as coroutines inside the current process's
    background event loop — used by the test Cluster fixture and by
    ray_tpu.init() for fast single-machine bring-up."""

    def __init__(self, loop, head=True, gcs_addr=None, num_cpus=None,
                 num_tpus=None, resources=None, labels=None,
                 object_store_memory=None, session_dir=None, node_name=None):
        self.loop = loop
        self.head = head
        self.gcs_addr = gcs_addr
        self.session_dir = session_dir or new_session_dir()
        self.gcs_server = None
        self.raylet = None
        self.raylet_addr = None
        self._resources, self._labels = detect_node_resources(
            num_cpus=num_cpus, num_tpus=num_tpus, resources=resources)
        if labels:
            self._labels.update(labels)
        self._object_store_memory = (object_store_memory
                                     or cfg.object_store_memory_bytes)
        self.node_name = node_name

    def start(self):
        fut = asyncio.run_coroutine_threadsafe(self._start_async(), self.loop)
        fut.result(60)
        return self

    async def _start_async(self):
        if self.head:
            from ray_tpu._private.gcs import GcsServer
            self.gcs_server = GcsServer(persist_path=os.path.join(
                self.session_dir, "gcs_snapshot.pkl"))
            port = await self.gcs_server.start(0)
            self.gcs_addr = ("127.0.0.1", port)
        from ray_tpu._private.raylet import Raylet
        self.raylet = Raylet(self.gcs_addr, self._resources,
                             labels=self._labels,
                             session_dir=self.session_dir,
                             store_capacity=self._object_store_memory,
                             node_name=self.node_name)
        rport = await self.raylet.start(0)
        self.raylet_addr = ("127.0.0.1", rport)
        n_warm = min(2, max(1, int(self._resources.get("CPU", 1))))
        self.raylet.prestart_workers(n_warm)

    @property
    def node_id(self):
        return self.raylet.node_id if self.raylet else None

    def kill(self, stop_gcs=True):
        async def _kill():
            if self.raylet is not None:
                await self.raylet.shutdown()
            if stop_gcs and self.gcs_server is not None:
                await self.gcs_server.stop()
        try:
            asyncio.run_coroutine_threadsafe(_kill(), self.loop).result(10)
        except Exception:
            pass
