"""Central runtime-tunable table, overridable by environment variables.

TPU-native equivalent of the reference's single macro table of flags
(reference: src/ray/common/ray_config_def.h:18-22 — RAY_CONFIG(type, name,
default), env-overridable per process, distributed cluster-wide).  Here the
table is a plain dataclass-like registry; every entry can be overridden with
``RT_<NAME>`` in the environment, and ``ray_tpu.init(_system_config=...)``
overrides are forwarded to spawned processes through the environment.
"""

from __future__ import annotations

import json
import os

_DEFS: dict[str, tuple[type, object]] = {}


def _def(name: str, typ: type, default):
    _DEFS[name] = (typ, default)
    return default


class _Config:
    # --- timing / liveness ---
    heartbeat_period_ms = _def("heartbeat_period_ms", int, 1000)
    heartbeat_timeout_ms = _def("heartbeat_timeout_ms", int, 30000)
    resource_report_period_ms = _def("resource_report_period_ms", int, 100)
    worker_register_timeout_s = _def("worker_register_timeout_s", float, 60.0)
    connect_timeout_s = _def("connect_timeout_s", float, 30.0)
    # Default deadline for Connection.request() when the caller gives
    # none: no RPC may wait unbounded by accident (a hung peer must
    # surface as an error, not a wedged future).  Call sites that WANT
    # an unbounded wait (push_task on a long task, infeasible lease
    # requests parked as autoscaler demand) pass timeout=None
    # explicitly.  <= 0 disables the default.
    rpc_request_timeout_s = _def("rpc_request_timeout_s", float, 300.0)
    # Idle keepalive on the RPC plane: a connection with in-flight
    # requests but no inbound traffic for idle_s sends a PING; no
    # traffic for another timeout_s after that fails the connection
    # (half-open links — one direction dead — otherwise hang their
    # futures forever).  idle_s <= 0 disables.
    rpc_keepalive_idle_s = _def("rpc_keepalive_idle_s", float, 20.0)
    rpc_keepalive_timeout_s = _def("rpc_keepalive_timeout_s", float, 20.0)
    # Core-worker GCS reconnect: bounded attempts with full-jitter
    # backoff (was: reconnect exactly once per connection loss).
    gcs_reconnect_attempts = _def("gcs_reconnect_attempts", int, 8)
    gcs_reconnect_base_s = _def("gcs_reconnect_base_s", float, 0.25)
    gcs_reconnect_cap_s = _def("gcs_reconnect_cap_s", float, 5.0)
    # When a raylet's GCS connection drops WITHOUT a drain announcement,
    # the GCS probes the raylet's server before declaring it dead:
    # connection refused proves the process is gone (fast crash
    # detection), while an unreachable-but-maybe-alive node (partition,
    # suspect half-open link the raylet failed on purpose) keeps its
    # heartbeat-timeout grace window.
    node_probe_timeout_s = _def("node_probe_timeout_s", float, 2.0)

    # --- object store ---
    object_store_memory_bytes = _def("object_store_memory_bytes", int, 2 * 1024**3)
    # Below this size objects are inlined in the owner's memory store and on
    # the wire instead of going through shared memory (reference:
    # ray_config_def.h max_direct_call_object_size = 100KiB).
    max_direct_call_object_size = _def("max_direct_call_object_size", int, 100 * 1024)
    fetch_chunk_bytes = _def("fetch_chunk_bytes", int, 8 * 1024**2)
    # How long an object creation may wait for transiently-pinned memory
    # to free before reporting OOM (reference: plasma's create-request
    # queue + object_store_full_delay semantics).
    create_retry_timeout_s = _def("create_retry_timeout_s", float, 120.0)

    # --- object transfer plane (node-to-node pulls/pushes) ---
    # Sliding window of in-flight chunks per transfer in BOTH directions
    # (reference: pull_manager.h keeps several chunk requests outstanding
    # so throughput is wire-bound, not RTT-bound).
    transfer_window_chunks = _def("transfer_window_chunks", int, 4)
    # Admission cap on bytes in flight to/from any single peer across
    # ALL transfers, so many concurrent pulls can't buffer-bloat or OOM
    # a receiver.
    transfer_inflight_bytes_per_peer = _def(
        "transfer_inflight_bytes_per_peer", int, 64 * 1024**2)
    # Objects at least this large stripe chunk ranges across multiple
    # sealed locations when the GCS object directory knows of 2+.
    transfer_stripe_min_bytes = _def("transfer_stripe_min_bytes",
                                     int, 32 * 1024**2)
    # Most peers one striped pull will read from.
    transfer_max_sources = _def("transfer_max_sources", int, 4)
    # Same-host zero-copy fast path: when a source raylet's arena file
    # is reachable on this host, pin the object remotely and memcpy
    # straight out of a read-only mmap of the peer arena instead of
    # chunking it through the socket (the plasma model — one shared
    # store per node — recovered across co-located raylets).
    transfer_same_host_mmap = _def("transfer_same_host_mmap", bool, True)
    # Push-receive transfers with no chunk activity for this long are
    # swept (sender died mid-stream); also bounds the idle lifetime of
    # cached spill-file read fds.
    push_stale_sweep_s = _def("push_stale_sweep_s", float, 120.0)

    # --- data plane (ray_tpu.data streaming executor) ---
    # Use the operator-graph streaming executor for Dataset consumption
    # and all-to-all ops (random_shuffle/repartition): fused map
    # operators with per-operator output budgets + pull-based
    # backpressure, and a windowed shuffle whose partition movement
    # rides the TransferManager instead of round-accumulated store
    # hops.  Set false to restore the legacy bounded-window map loop +
    # push-based round shuffle (kept as the bench baseline).
    data_streaming = _def("data_streaming", bool, True)
    # Per-operator output budget: an operator stops admitting new input
    # blocks while its submitted-but-unconsumed output bytes exceed
    # this, so a slow consumer throttles the whole chain and peak
    # memory is O(sum of budgets), not O(dataset).
    data_op_budget_bytes = _def("data_op_budget_bytes", int, 128 * 1024**2)
    # Concurrent map/reduce tasks per shuffle phase (and the map
    # operator's in-flight task window).  <= 0 means auto (the block
    # count, capped at 16).
    data_shuffle_parallelism = _def("data_shuffle_parallelism", int, 0)
    # One deadline for every data-layer ray_tpu.get/wait (block fetch,
    # materialize, row counts) — was a hardcoded 600 s module constant
    # in data/streaming.py + data/dataset.py.
    data_get_timeout_s = _def("data_get_timeout_s", float, 600.0)

    # --- host collectives (util/collective) ---
    # One deadline for EVERY collective wait: coordinator rounds,
    # mailbox send/recv, group creation, and data-plane chunk waits
    # (was: collect honored RT_COLLECTIVE_TIMEOUT_S while send/recv and
    # create_collective_group hardcoded 300 s).
    collective_timeout_s = _def("collective_timeout_s", float, 3600.0)
    # Tensors at/above this ride the peer-to-peer transfer-plane path
    # (direct reduce-scatter/allgather chunks as raw blob frames /
    # same-host scratch memcpys); below it the coordinator reduces in
    # one round trip, which is cheaper for small tensors.
    collective_fastpath_min_bytes = _def("collective_fastpath_min_bytes",
                                         int, 256 * 1024)
    # Wire-path chunk size and scratch arena capacity for the
    # collective data plane.  The scratch file is sparse (/dev/shm);
    # pages materialize only when written.
    collective_chunk_bytes = _def("collective_chunk_bytes", int, 8 * 1024**2)
    collective_scratch_bytes = _def("collective_scratch_bytes", int, 1 << 30)
    # Bucket-fusion target: fuse_buckets coalesces small tensors into
    # flat buffers of about this many bytes so many tiny gradients ride
    # one rendezvous + one chunk exchange.
    collective_bucket_bytes = _def("collective_bucket_bytes",
                                   int, 32 * 1024**2)
    # Data-plane selection: "auto" (same-host one-sided reads /
    # scratch memcpy when the peer is reachable, raw blob frames
    # otherwise), "wire" (force blob frames even same-host), "store"
    # (the legacy object-store put/get ring — kept as the bench
    # baseline), "coord" (everything through the coordinator actor).
    collective_data_plane = _def("collective_data_plane", str, "auto")
    # Same-host one-sided reads (process_vm_readv straight out of the
    # sender's buffer — zero staging).  Probed at rendezvous and
    # auto-disabled where the kernel forbids it; set false to force
    # the scratch-arena memcpy path.
    collective_pvm_reads = _def("collective_pvm_reads", bool, True)

    # --- train (gang lifecycle + elastic recovery) ---
    # Gang RPC deadline: the start_training fan-out and
    # WorkerGroup.execute/execute_single (was hardcoded 600 s in
    # train/_internal/worker_group.py).
    train_start_timeout_s = _def("train_start_timeout_s", float, 600.0)
    # One report round: how long the driver waits for every rank's
    # next_result before declaring the round lost (was hardcoded
    # 3600 s in backend_executor.get_next_results).
    train_result_timeout_s = _def("train_result_timeout_s", float, 3600.0)
    # shutdown_training's join on the user loop thread (was hardcoded
    # 5 s).  The thread is a daemon; the join only bounds how long a
    # graceful stop waits for an unresponsive loop.
    train_worker_join_s = _def("train_worker_join_s", float, 5.0)
    # Elastic re-formation deadline: survivors (and joiners) must
    # report to the elastic coordinator AND finish the re-shard within
    # this bound or the driver falls back to a cold checkpoint
    # restart.  Jitter is added per recovery so many gangs recovering
    # at once don't stampede the control plane in lockstep.
    train_reform_timeout_s = _def("train_reform_timeout_s", float, 30.0)
    train_reform_jitter_s = _def("train_reform_jitter_s", float, 2.0)
    # Quorum: an elastic gang re-forms only while at least this many
    # members survive; below it the driver cold-restarts from the last
    # checkpoint (ScalingConfig.elastic_min_workers overrides).
    train_elastic_min_workers = _def("train_elastic_min_workers", int, 1)

    # --- control plane (GCS pubsub / snapshots / events) ---
    # Coalesced pubsub: every subscriber gets a bounded outbound queue
    # drained by a pump that batches same-channel messages into one
    # frame (KIND_BATCH), so an event burst costs O(events) enqueues
    # instead of O(events x subscribers) serialized awaits, and one
    # stalled subscriber can never head-of-line-block the broadcast.
    # Set false to restore the legacy per-event serialized push path
    # (kept as the bench baseline).
    gcs_pubsub_coalesce = _def("gcs_pubsub_coalesce", bool, True)
    # Per-subscriber outbound queue bound.  A subscriber that falls
    # this far behind starts losing its OLDEST queued events (drops are
    # counted and exported); pubsub is a best-effort notification
    # plane, so consumers must tolerate gaps (node views re-seed on
    # reconnect, actor waiters re-poll).
    gcs_pubsub_queue_max = _def("gcs_pubsub_queue_max", int, 10000)
    # Most messages one pump drain folds into a single batch frame.
    gcs_pubsub_batch_max = _def("gcs_pubsub_batch_max", int, 512)
    # Publish per-node resource/load deltas on the "nodes" channel when
    # a heartbeat payload changes them (raylets keep their spillback /
    # spread / hybrid views fresh instead of frozen at registration).
    gcs_publish_resource_updates = _def("gcs_publish_resource_updates",
                                        bool, True)
    # Durable-state snapshot cadence (when a persist path is set) and
    # how many trailing cluster events ride each snapshot, so a
    # restarted GCS keeps recent history instead of replaying the world.
    gcs_snapshot_period_s = _def("gcs_snapshot_period_s", float, 0.5)
    gcs_snapshot_events_tail = _def("gcs_snapshot_events_tail", int, 256)
    # Bounded cluster-event ring (drops are counted and exported).
    gcs_events_max = _def("gcs_events_max", int, 1000)

    # --- scheduling ---
    max_workers_per_node = _def("max_workers_per_node", int, 64)
    # Indexed cluster view for spillback/spread/hybrid picks: per-shape
    # candidate sets + score heaps updated incrementally from node
    # deltas, so a lease decision costs O(candidates-inspected) instead
    # of a full rescan of every node view.  Set false to force the
    # plain full-scan policy path (parity/debug escape hatch).
    sched_indexed_view = _def("sched_indexed_view", bool, True)
    # Fork-server worker spawn (zygote.py): pay the interpreter+import cost
    # once per node, fork workers in ~10ms after that.
    worker_zygote_enabled = _def("worker_zygote_enabled", bool, True)
    idle_worker_keep_s = _def("idle_worker_keep_s", float, 300.0)
    lease_spillback_threshold = _def("lease_spillback_threshold", float, 1.0)

    # --- tasks / actors ---
    max_task_retries_default = _def("max_task_retries_default", int, 3)
    # Lineage reconstruction attempts per lost object (reference:
    # ray_config_def.h task_max_retries semantics for object recovery).
    max_object_reconstructions = _def("max_object_reconstructions", int, 3)
    actor_max_restarts_default = _def("actor_max_restarts_default", int, 0)
    # How long a caller waits for a restarting actor to come back ALIVE
    # before treating it as dead (reference: the direct actor submitter
    # holds queued tasks while the GCS reports RESTARTING).  Generous on
    # purpose: a restart on a loaded 1-CPU host can take minutes.
    actor_restart_wait_s = _def("actor_restart_wait_s", float, 300.0)
    task_queue_warn_len = _def("task_queue_warn_len", int, 100000)

    # --- serve control plane (controller reconcile / autoscale ticks) ---
    # Reconcile-loop period (was the CONTROL_LOOP_PERIOD_S module
    # constant in serve/_private/controller.py) and the poll cadence of
    # the controller's wait loops (deployment-health wait, graceful
    # shutdown drain) — every controller tick interval now rides the
    # config table instead of hardcoded literals.
    serve_control_loop_period_s = _def("serve_control_loop_period_s",
                                       float, 0.1)
    serve_health_poll_period_s = _def("serve_health_poll_period_s",
                                      float, 0.1)

    # --- KV-aware serving (prefix-affinity routing + page migration) ---
    # Master switch for prefix-affinity routing: replicas publish radix
    # prefix digests through their autoscale gauges and the router
    # scores candidates by expected prefix-hit depth.  Off restores the
    # pure power-of-two-choices pick (kept as the bench baseline).
    serve_affinity = _def("serve_affinity", bool, True)
    # Most prefix fingerprints one replica publishes per digest (top-K
    # by recency) and the deepest page a fingerprint may describe.
    # Both bound digest size: a digest rides every autoscale poll and
    # every replica broadcast, so it must stay control-plane-sized.
    serve_affinity_digest_top_k = _def("serve_affinity_digest_top_k",
                                       int, 32)
    serve_affinity_digest_depth = _def("serve_affinity_digest_depth",
                                       int, 8)
    # Router score = blend * hit_depth_norm - (1 - blend) * load_norm:
    # 1.0 routes on affinity alone, 0.0 degenerates to load-only.
    serve_affinity_blend = _def("serve_affinity_blend", float, 0.7)
    # Hotspot bound: a replica whose occupancy (in-flight /
    # max_concurrent_queries) is at or past this fraction loses its
    # affinity claim — a viral prefix must not starve one replica, so
    # affinity always loses to overload.
    serve_affinity_hotspot_bound = _def("serve_affinity_hotspot_bound",
                                        float, 0.75)
    # How often a replica's digest may retrigger the controller's
    # replica broadcast (membership changes still broadcast at once);
    # bounds long-poll churn under hot caches.
    serve_affinity_refresh_s = _def("serve_affinity_refresh_s",
                                    float, 1.0)
    # --- KV page migration (serve/llm/kv_transfer.py) ---
    # Sliding window of in-flight page frames per migration pull (the
    # transfer plane's windowed-pump discipline).
    serve_kv_migration_window_chunks = _def(
        "serve_kv_migration_window_chunks", int, 4)
    # Below this many committed full pages, migration is skipped and
    # the destination re-prefills.  Crossover rationale: one migrated
    # page moves page_size * 2 * layers * kv_heads * head_dim * 4 bytes
    # over a ~GB/s link plus a fixed ~2 RPC rendezvous cost, while
    # re-prefilling the same page costs one chunked-prefill pass that
    # is amortized across the whole batch — for 1-page prefixes the
    # rendezvous alone usually exceeds the prefill FLOPs, so shipping
    # only wins once a few pages of K/V ride one rendezvous (measured
    # by bench.py --suite serve_scale's migration-vs-reprefill leg).
    serve_kv_min_migrate_pages = _def("serve_kv_min_migrate_pages",
                                      int, 2)
    # Same-host fast path: the origin stages export pages in a /dev/shm
    # file the destination mmap-reads (one memcpy, no socket); falls
    # back to wire frames when the file is not reachable.
    serve_kv_samehost = _def("serve_kv_samehost", bool, True)
    # An export a destination never sealed (puller died mid-pull) is
    # released after this TTL so its page refs cannot leak forever.
    serve_kv_export_ttl_s = _def("serve_kv_export_ttl_s", float, 60.0)
    # How long a router keeps trusting the pull address (kv_rdv) of a
    # replica that LEFT the membership broadcast.  Client-replayed
    # resume cursors name a kv_origin to migrate pages from; the router
    # only honors addresses it has itself observed in the broadcast —
    # never a client-invented endpoint (SSRF / cache poisoning) — and
    # the grace window covers the dead-replica resume case, where the
    # origin is gone from membership by the time the client retries.
    serve_kv_rdv_grace_s = _def("serve_kv_rdv_grace_s", float, 120.0)
    # --- KV memory hierarchy (cold-page tiering + durable sessions) ---
    # Master switch for the three-tier hierarchy: T0 decode pool, T1
    # host shared-memory arena, T2 file-backed page store.  Off keeps
    # the pure pool-bound behavior (the bench's tiering-off baseline).
    serve_kv_tiering = _def("serve_kv_tiering", bool, True)
    # A tree-only T0 page with no decode tick for this long is demoted
    # to the host arena by the engine's sweeper.  Short enough that an
    # idle conversation releases its pool pages well before a typical
    # human reply; long enough that an actively streaming request's
    # shared prefix never thrashes.
    serve_kv_demote_idle_s = _def("serve_kv_demote_idle_s", float, 30.0)
    # A T1 page idle this long past its demotion moves on to the store
    # tier (T2) — where it survives replica death and is pullable from
    # any replica on the host.
    serve_kv_t2_idle_s = _def("serve_kv_t2_idle_s", float, 120.0)
    # Sweeper cadence.  Also the retry hint submit() sends when the
    # demotable cold-page headroom could cover a rejected reservation:
    # one sweep from now the pages will be free.
    serve_kv_tier_sweep_s = _def("serve_kv_tier_sweep_s", float, 2.0)
    # Host-arena (T1) byte budget per engine.  Overflow demotes the
    # arena's coldest pages straight to the store tier, so T1 is a
    # cache over T2, never a second hard ceiling.
    serve_kv_t1_budget_bytes = _def("serve_kv_t1_budget_bytes",
                                    int, 256 * 1024**2)
    # Store-tier (T2) directory, shared by every replica on the host
    # (the spill-directory pattern); empty means
    # <tempdir>/rt_kv_store-<uid>.  Pages are content-addressed by
    # chained prefix fingerprint, so two replicas that never exchanged
    # state agree on the key of a shared prefix.
    serve_kv_store_dir = _def("serve_kv_store_dir", str, "")
    # Store entries (pages and session manifests) older than this are
    # garbage-collected by the sweeper; bounds disk growth at the cost
    # of how long a dormant session stays resurrectable.
    serve_kv_store_ttl_s = _def("serve_kv_store_ttl_s", float, 3600.0)
    # Retry-After for kv_exhausted rejections when no demotion headroom
    # applies (a KV pool drains at generation speed).  Sub-second values
    # are honored: the HTTP surface sends float seconds on the wire.
    serve_kv_retry_after_s = _def("serve_kv_retry_after_s", float, 5.0)
    # Router affinity: a digest hit whose deepest node sits in T1/T2 is
    # discounted by this factor versus a T0 hit — promoted pages cost a
    # host->device splice the decode-pool hit does not.
    serve_affinity_tier_discount = _def("serve_affinity_tier_discount",
                                        float, 0.5)

    # --- cluster autopilot (SLO-driven arbiter, _private/arbiter.py) ---
    # The GCS broker's arbitration tick: how often registered workload
    # declarations + smoothed signals are re-evaluated into grant /
    # revoke decisions.
    autopilot_period_s = _def("autopilot_period_s", float, 0.25)
    # Client-side report cadence (serve controller SLO attainment,
    # train gang agent, data soak lease) — each report doubles as the
    # grant fetch, so one RPC per period per workload.
    autopilot_report_period_s = _def("autopilot_report_period_s",
                                     float, 0.25)
    # A serve SLO breach must be SUSTAINED this long before the arbiter
    # reclaims capacity from lower-priority workloads (and the
    # recovery must be sustained equally long before capacity returns)
    # — the arbiter's half of the flap suppression.
    autopilot_slo_breach_window_s = _def("autopilot_slo_breach_window_s",
                                         float, 1.0)
    # Post-decision cooldown per workload: two budget changes for the
    # same workload are always at least this far apart.
    autopilot_cooldown_s = _def("autopilot_cooldown_s", float, 2.0)
    # EWMA smoothing over reported signals (TTFT p99) — 1.0 disables.
    autopilot_ewma_alpha = _def("autopilot_ewma_alpha", float, 0.5)
    # A revoked data soak lease stops admitting new tasks immediately;
    # in-flight tasks get this grace window to drain before the bench /
    # chaos harness calls the revocation late.
    autopilot_data_revoke_grace_s = _def("autopilot_data_revoke_grace_s",
                                         float, 2.0)
    # Nodes reserved for a reclaim beneficiary (so revoked capacity
    # drains instead of accepting new low-priority leases) un-reserve
    # after this TTL even if the arbiter never clears them.
    autopilot_reserve_ttl_s = _def("autopilot_reserve_ttl_s", float, 15.0)
    # A workload whose client stopped reporting (driver died without
    # unregistering) is dropped from arbitration after this long — its
    # budget returns to the pool instead of leaking forever.
    autopilot_stale_report_s = _def("autopilot_stale_report_s",
                                    float, 15.0)

    # --- tracing (the cross-plane span runtime, _private/tracing.py) ---
    # Always-on per-process span ring; set false to hard-disable every
    # record (the fast path is one bool check — measured by
    # `bench.py --suite trace` and gated <=5% in make bench-trace-quick).
    trace_enabled = _def("trace_enabled", bool, True)
    # Bounded ring capacity (drop-oldest; drops counted and exported as
    # tracing_events_dropped_total).
    trace_ring_capacity = _def("trace_ring_capacity", int, 8192)
    # Complete events WITHOUT span linkage shorter than this are not
    # recorded (perf-only noise gate); linked spans always record —
    # dropping them would hole the request tree.
    trace_min_dur_us = _def("trace_min_dur_us", float, 0.0)
    # RPC handlers slower than this record an rpc.slow span (0 disables).
    trace_rpc_slow_ms = _def("trace_rpc_slow_ms", float, 50.0)
    # Sample 1/N engine decode ticks as engine.decode_tick spans (the
    # tick runs thousands of times per second; 0 disables tick spans).
    trace_decode_tick_sample = _def("trace_decode_tick_sample", int, 64)
    # Byte cap on the pickled telemetry KV push (the stale convenience
    # view).  The push must stay control-plane-sized: anything
    # chunk-sized belongs on raw transfer frames, and the authoritative
    # trace path is the dump_trace pull, which has no such cap.
    trace_kv_push_budget = _def("trace_kv_push_budget", int, 48 * 1024)

    # --- logging ---
    log_to_driver = _def("log_to_driver", bool, True)

    def __init__(self, overrides: dict | None = None):
        for name, (typ, default) in _DEFS.items():
            env = os.environ.get(f"RT_{name.upper()}")
            if env is not None:
                if typ is bool:
                    val = env.lower() in ("1", "true", "yes")
                elif typ is int:
                    val = int(env)
                elif typ is float:
                    val = float(env)
                else:
                    val = env
                setattr(self, name, val)
            else:
                setattr(self, name, default)
        if overrides:
            for k, v in overrides.items():
                if k not in _DEFS:
                    raise ValueError(f"Unknown system config: {k}")
                setattr(self, k, v)

    def to_env(self) -> dict[str, str]:
        """Serialize current values as env vars for child processes."""
        out = {}
        for name in _DEFS:
            v = getattr(self, name)
            out[f"RT_{name.upper()}"] = json.dumps(v) if not isinstance(v, str) else v
        return out


GLOBAL_CONFIG = _Config()


def apply_system_config(overrides: dict):
    global GLOBAL_CONFIG
    GLOBAL_CONFIG = _Config(overrides)
    return GLOBAL_CONFIG
