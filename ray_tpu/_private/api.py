"""Public API: init/shutdown/remote/get/put/wait and cluster introspection.

Reference: python/ray/_private/worker.py — init :1024, connect :1846,
get :2188, remote decorator overloads :122-366.
"""

from __future__ import annotations

import asyncio
import atexit
import inspect
import os
import threading

from ray_tpu import exceptions as rexc
from ray_tpu._private import protocol
from ray_tpu._private import worker as worker_mod
from ray_tpu._private.config import apply_system_config
from ray_tpu._private.node import InProcessNode, new_session_dir
from ray_tpu._private.worker import CoreWorker, MODE_DRIVER

_state_lock = threading.RLock()
_head_node: InProcessNode | None = None
_loop = None
_loop_thread = None


def _ensure_loop():
    global _loop, _loop_thread
    if _loop is not None and _loop_thread.is_alive():
        return _loop
    ready = threading.Event()

    def _main():
        global _loop
        _loop = asyncio.new_event_loop()
        asyncio.set_event_loop(_loop)
        protocol.enable_eager_tasks(_loop)
        ready.set()
        _loop.run_forever()

    _loop_thread = threading.Thread(target=_main, name="ray_tpu-io",
                                    daemon=True)
    _loop_thread.start()
    ready.wait(30)
    return _loop


def init(address: str | None = None, *, num_cpus=None, num_tpus=None,
         num_gpus=None, resources=None, object_store_memory=None,
         namespace: str = "default", ignore_reinit_error: bool = False,
         local_mode: bool = False,
         _system_config: dict | None = None, log_to_driver: bool = True,
         runtime_env=None, **kwargs):
    """Start a cluster on this machine (address=None) or connect to one
    ("host:gcs_port").  local_mode=True runs everything inline in this
    process (reference: ray.init(local_mode=True)) — no workers, no
    store; for debugging and runtime-free unit tests."""
    global _head_node
    with _state_lock:
        if worker_mod.global_worker is not None and \
                worker_mod.global_worker.connected:
            if ignore_reinit_error:
                return worker_mod.global_worker
            raise RuntimeError("ray_tpu.init() called twice "
                               "(use ignore_reinit_error=True)")
        if _system_config:
            apply_system_config(_system_config)
        if local_mode:
            from ray_tpu._private.local_mode import LocalModeWorker
            w = LocalModeWorker(namespace=namespace)
            worker_mod.global_worker = w
            atexit.register(shutdown)
            return w
        if num_tpus is None:
            num_tpus = num_gpus
        loop = _ensure_loop()
        if address is None:
            _head_node = InProcessNode(
                loop, head=True, num_cpus=num_cpus, num_tpus=num_tpus,
                resources=resources, object_store_memory=object_store_memory,
                session_dir=new_session_dir()).start()
            gcs_addr = _head_node.gcs_addr
            raylet_addr = _head_node.raylet_addr
            store_path = _head_node.raylet.store_path
            store_cap = _head_node.raylet.store_capacity
            driver_host = "127.0.0.1"
        else:
            host, port = address.split(":")
            gcs_addr = (host, int(port))
            raylet_addr, store_path, store_cap = _discover_local_raylet(
                loop, gcs_addr)
            # Advertise the LOCAL RAYLET's address: it registered with
            # the cluster-reachable --node-ip, so peers can dial the
            # driver back on it (owner protocol).  Multi-NIC machines
            # may route to the GCS on a different interface than the
            # cluster data network, so the route-to-GCS guess is only
            # the fallback when the raylet is loopback-bound.
            if raylet_addr[0] not in ("127.0.0.1", "localhost"):
                driver_host = raylet_addr[0]
            else:
                driver_host = _routable_host(gcs_addr[0])
        cw = CoreWorker(MODE_DRIVER, gcs_addr, raylet_addr=raylet_addr,
                        store_path=store_path, store_cap=store_cap,
                        host=driver_host)
        cw.loop = loop
        fut = asyncio.run_coroutine_threadsafe(cw._connect(), loop)
        fut.result(60)
        cw.connected = True
        worker_mod.global_worker = cw
        from ray_tpu._private import usage
        try:
            usage.on_init(
                _head_node.session_dir if _head_node is not None else None,
                os.path.basename(
                    _head_node.session_dir) if _head_node is not None
                else f"client-{os.getpid()}")
        except Exception:
            pass  # usage stats must never block init
        atexit.register(shutdown)
        return cw


def _routable_host(peer_host: str) -> str:
    """The local interface address that routes to `peer_host` —
    what this process should ADVERTISE so that host can dial back."""
    if peer_host in ("127.0.0.1", "localhost"):
        return "127.0.0.1"
    import socket
    try:
        with socket.socket(socket.AF_INET, socket.SOCK_DGRAM) as s:
            s.connect((peer_host, 1))  # no packets; just picks a route
            return s.getsockname()[0]
    except OSError:
        return "127.0.0.1"


def _discover_local_raylet(loop, gcs_addr):
    """Connecting to an existing cluster: find this machine's raylet."""
    from ray_tpu._private import protocol

    async def _find():
        conn = await protocol.Connection.connect(gcs_addr[0], gcs_addr[1],
                                                 name="probe")
        nodes = await conn.request("get_nodes", {})
        await conn.close()
        return nodes

    nodes = asyncio.run_coroutine_threadsafe(_find(), loop).result(30)
    import socket

    def _is_local(host: str) -> bool:
        # An address is local iff this machine can BIND to it — covers
        # loopback, the hostname, AND routable interface addresses
        # (multi-host nodes advertise --node-ip, not 127.0.0.1).
        if host in ("0.0.0.0", "::"):
            # Wildcards bind anywhere; a node advertising one is
            # misconfigured, never "local".
            return False
        if host in ("127.0.0.1", "localhost", socket.gethostname()):
            return True
        try:
            with socket.socket() as s:
                s.bind((host, 0))
            return True
        except OSError:
            return False

    for n in nodes:
        if n["alive"] and _is_local(n["addr"][0]):
            # store path/capacity arrive in the raylet's register_worker
            # reply (see CoreWorker._connect)
            return tuple(n["addr"]), None, None
    raise RuntimeError("no alive raylet found on this machine")


def shutdown():
    global _head_node
    from ray_tpu._private import usage
    usage.on_shutdown()
    with _state_lock:
        cw = worker_mod.global_worker
        if cw is not None:
            cw.shutdown()
            worker_mod.global_worker = None
        if _head_node is not None:
            _head_node.kill()
            _head_node = None


def is_initialized() -> bool:
    return (worker_mod.global_worker is not None
            and worker_mod.global_worker.connected)


def remote(*args, **kwargs):
    """@ray_tpu.remote decorator for functions and classes (reference:
    python/ray/_private/worker.py:122-366)."""
    from ray_tpu.actor import ActorClass
    from ray_tpu.remote_function import RemoteFunction

    def _make(target, opts):
        if inspect.isclass(target):
            return ActorClass(target, **opts)
        return RemoteFunction(target, **opts)

    if len(args) == 1 and not kwargs and callable(args[0]):
        # Any callable works bare: python/builtin functions, classes,
        # functools.partial, callables with __call__.
        return _make(args[0], {})
    if args:
        raise TypeError("@remote takes keyword options only, e.g. "
                        "@remote(num_cpus=2)")

    def decorator(target):
        return _make(target, kwargs)
    return decorator


def _worker() -> CoreWorker:
    cw = worker_mod.global_worker
    if cw is None or not cw.connected:
        raise RuntimeError("ray_tpu.init() must be called first")
    return cw


def _gcs():
    """Typed GCS accessor facade for the connected driver (reference:
    gcs/gcs_client/accessor.h via global_state_accessor.h)."""
    from ray_tpu._private.gcs_client import global_gcs_client
    return global_gcs_client()


def get(refs, *, timeout=None):
    return _worker().get(refs, timeout=timeout)


def put(value) -> "ObjectRef":
    return _worker().put(value)


def wait(refs, *, num_returns=1, timeout=None, fetch_local=True):
    if not isinstance(refs, list):
        raise TypeError("ray_tpu.wait() expects a list of ObjectRefs")
    return _worker().wait(refs, num_returns=num_returns, timeout=timeout,
                          fetch_local=fetch_local)


def cancel(ref, *, force: bool = False) -> bool:
    """Cancel a task (reference: ray.cancel worker.py): True if the task
    was stopped (dequeued, or its worker killed with force=True)."""
    return _worker().cancel_task(ref, force=force)


def kill(actor, *, no_restart=True):
    from ray_tpu.actor import ActorHandle
    if not isinstance(actor, ActorHandle):
        raise TypeError("ray_tpu.kill() expects an actor handle")
    w = _worker()
    if getattr(w, "mode", None) == "local":
        w.kill_actor_local(actor._ray_actor_id)
        return
    _gcs().actors.kill(actor._ray_actor_id, no_restart=no_restart)


def get_actor(name: str, namespace: str = "default"):
    from ray_tpu.actor import ActorHandle
    w = _worker()
    if getattr(w, "mode", None) == "local":
        view = w.get_named_actor(name, namespace)
    else:
        view = _gcs().actors.get_by_name(name, namespace)
    if view is None:
        raise ValueError(f"no actor named '{name}'")
    return ActorHandle(view["actor_id"], view.get("class_name", ""),
                       addr=tuple(view["addr"]) if view.get("addr") else None)


def nodes():
    out = []
    for v in _gcs().nodes.get_all():
        out.append({
            "NodeID": v["node_id"].hex(),
            "Alive": v["alive"],
            "NodeManagerAddress": v["addr"][0],
            "NodeManagerPort": v["addr"][1],
            "Resources": v["resources"],
            "Available": v.get("available", {}),
            "Labels": v.get("labels", {}),
        })
    return out


def cluster_resources():
    return _gcs().nodes.cluster_resources()["total"]


def available_resources():
    return _gcs().nodes.cluster_resources()["available"]


def wait_placement_group_ready(pg, timeout: float = 60.0) -> bool:
    view = _gcs().placement_groups.wait_ready(pg.id, timeout=timeout)
    return view is not None and view["state"] == "CREATED"


class RuntimeContext:
    def __init__(self, worker: CoreWorker):
        self._worker = worker

    @property
    def job_id(self):
        return self._worker.job_id

    @property
    def node_id(self):
        return self._worker.node_id

    @property
    def actor_id(self):
        return self._worker.actor_id

    @property
    def task_id(self):
        return self._worker.exec_ctx.task_id

    def get_job_id(self):
        return self.job_id.hex()

    def get_node_id(self):
        return self.node_id.hex() if self.node_id else None

    def get_actor_id(self):
        return self.actor_id.hex() if self.actor_id else None

    def get_tpu_ids(self):
        return get_tpu_ids()


def get_runtime_context() -> RuntimeContext:
    return RuntimeContext(_worker())


def get_tpu_ids() -> list:
    """Chip indices the raylet granted to THIS task/actor's lease
    (reference: ray.get_gpu_ids over GPU resource instances).  Empty in
    the driver or for leases without a TPU resource."""
    w = _worker()
    ids = list(getattr(w.exec_ctx, "tpu_ids", []) or [])
    if ids:
        return ids
    return list(getattr(w, "_actor_tpu_ids", []) or [])


def get_gpu_ids() -> list:
    """Reference-compatible alias of get_tpu_ids (ray.get_gpu_ids):
    scripts written against the reference keep working; on this
    framework the accelerator resource is TPU chips."""
    return get_tpu_ids()


def timeline(filename: str | None = None):
    """Chrome-trace events for every process in the cluster (reference:
    `ray timeline`, python/ray/_private/state.py chrome_tracing_dump —
    events aggregated from the per-process telemetry pushed to the GCS
    KV).

    STALE CONVENIENCE VIEW: each process's KV push carries only the
    freshest ring tail and lags by the push period; the authoritative
    path is ``cluster_trace()`` (the ``dump_trace`` RPC pull, whole
    rings on demand).  Truncation is self-describing: every process
    contributes a ``trace.ring_meta`` instant event recording its drop
    count and ring coverage window."""
    import json
    import pickle

    from ray_tpu._private import tracing as _tracing
    w = _worker()
    keys = w._run(w._gcs_request("kv_keys",
                                 {"ns": "telemetry", "prefix": b""}))["keys"]
    events = []
    for key in keys:
        blob = w._run(w._gcs_request("kv_get",
                                     {"ns": "telemetry",
                                      "key": key}))["value"]
        if blob is None:
            continue
        try:
            payload = pickle.loads(blob)
            events.extend(payload.get("profile", []))
            stats = payload.get("trace_stats")
            if stats is not None:
                stats = dict(stats, pid=payload.get("pid"))
                events.append(_tracing.meta_event(stats))
        except Exception:
            continue
    # The driver's own events never round-trip through the KV push delay.
    events.extend(w._profile_events)
    events.append(_tracing.meta_event())
    events.sort(key=lambda e: e.get("ts", 0))
    if filename:
        with open(filename, "w") as f:
            json.dump(events, f)
    return events


def cluster_trace(stats_only: bool = False,
                  filename: str | None = None):
    """Pull every process's span ring NOW (the authoritative trace
    path): the driver's own ring, the GCS's, and — via one
    ``dump_trace`` RPC per raylet, fanned out to its registered
    workers — every node process.  Returns
    ``{"processes": [per-process dump], "events": merged chrome-trace
    list}`` (events omitted with stats_only); each process contributes
    a ``trace.ring_meta`` event so truncation is visible.  Backs
    ``rt timeline --cluster`` and ``rt trace <id>``."""
    import asyncio
    import json

    from ray_tpu._private import protocol
    from ray_tpu._private import tracing as _tracing
    w = _worker()

    async def _collect():
        procs = []
        try:
            d = await w._gcs_request("dump_trace",
                                     {"stats_only": stats_only})
            procs.append(d)
        except Exception as e:
            procs.append({"role": "gcs",
                          "error": f"{type(e).__name__}: {e}"})
        nodes = await w._gcs_request("get_nodes", {})

        async def _one(view):
            try:
                conn = await protocol.Connection.connect(
                    view["addr"][0], view["addr"][1],
                    name="trace-pull", timeout=10)
                try:
                    return await conn.request(
                        "dump_trace", {"stats_only": stats_only,
                                       "include_workers": True},
                        timeout=30.0)
                finally:
                    await conn.close()
            except Exception as e:
                return {"role": "raylet",
                        "node_id": view["node_id"].hex(),
                        "error": f"{type(e).__name__}: {e}"}

        replies = await asyncio.gather(
            *[_one(v) for v in nodes if v.get("alive")])
        for r in replies:
            if "processes" in r:
                procs.extend(r["processes"])
            else:
                procs.append(r)
        return procs

    procs = w._run(_collect())
    procs.append(dict(_tracing.dump(stats_only=stats_only),
                      role="driver"))
    # One ring can be reached through several doors (the GCS, every
    # in-process raylet, and the driver itself may SHARE a process in
    # test clusters): keep one dump per ring — the largest, so a
    # stats_only stub never shadows a full dump.  The key is the ring's
    # per-process random id, NOT the bare OS pid: two containerized
    # nodes routinely hold workers with the same pid, and deduping on
    # pid would silently discard one node's whole ring.
    by_ring: dict = {}
    for p in procs:
        # Error stubs carry no ring_id; their worker/node id is still
        # unique cluster-wide, unlike a containerized pid.
        key = (p.get("ring_id") or p.get("worker_id")
               or p.get("node_id") or p.get("pid"))
        if key is None:
            by_ring[object()] = p
            continue
        cur = by_ring.get(key)
        if cur is None or len(p.get("events", ())) > \
                len(cur.get("events", ())):
            by_ring[key] = p
    procs = list(by_ring.values())
    out = {"processes": [
        {k: v for k, v in p.items() if k != "events"} for p in procs]}
    if not stats_only:
        events = []
        for p in procs:
            events.extend(p.get("events", ()))
            if "depth" in p:
                events.append(_tracing.meta_event(p))
        events.sort(key=lambda e: e.get("ts", 0))
        out["events"] = events
        if filename:
            with open(filename, "w") as f:
                json.dump(events, f)
    return out


def get_trace(trace_id: str):
    """Assemble ONE request's span tree from a cluster-wide ring pull:
    ``cluster_trace()`` merged events filtered to ``trace_id``, linked
    parent→child (cross-process via the propagated span ids), with the
    derived per-stage latency breakdown (TTFT decomposition when the
    serve/engine taxonomy is present).  Backs ``rt trace <id>``."""
    from ray_tpu._private import tracing as _tracing
    events = cluster_trace()["events"]
    return _tracing.assemble(events, trace_id)
