"""Typed GCS client: one accessor per metadata table.

Reference: src/ray/gcs/gcs_client/accessor.h (Node/Actor/Job/PG/KV
accessors on GcsClient) and global_state_accessor.h (the synchronous
view backing `ray.nodes()` / state APIs).  Callers name operations
(`gcs.nodes.get_all()`) instead of assembling raw RPC method strings;
every call rides the worker's reconnect-once request path, so GCS
restarts stay transparent here too.
"""

from __future__ import annotations

from typing import Dict, List, Optional


class _Accessor:
    def __init__(self, worker):
        self._w = worker

    def _call(self, method: str, body: Optional[Dict] = None):
        return self._w._run(self._w._gcs_request(method, body or {}))


class NodeAccessor(_Accessor):
    def get_all(self) -> List[Dict]:
        return self._call("get_nodes")

    def wait_for(self, count: int, timeout: float = 30.0) -> bool:
        return self._call("wait_for_nodes",
                          {"count": count, "timeout": timeout}).get("ok",
                                                                    False)

    def drain(self, node_id) -> Dict:
        return self._call("drain_node", {"node_id": node_id})

    def resource_demands(self) -> Dict:
        return self._call("get_resource_demands")

    def cluster_resources(self) -> Dict:
        """{'total': {...}, 'available': {...}} aggregated over nodes."""
        return self._call("cluster_resources")


class ActorAccessor(_Accessor):
    def get(self, actor_id) -> Dict:
        return self._call("get_actor", {"actor_id": actor_id})

    def get_by_name(self, name: str,
                    namespace: str = "default") -> Dict:
        return self._call("get_named_actor",
                          {"name": name, "namespace": namespace})

    def list(self, **filters) -> List[Dict]:
        return self._call("list_actors", filters)

    def list_named(self, namespace: Optional[str] = None) -> List:
        return self._call("list_named_actors",
                          {"namespace": namespace})

    def kill(self, actor_id, no_restart: bool = True) -> Dict:
        return self._call("kill_actor", {"actor_id": actor_id,
                                         "no_restart": no_restart})

    def wait_alive(self, actor_id, timeout: float = 60.0) -> Dict:
        return self._call("wait_actor_alive",
                          {"actor_id": actor_id, "timeout": timeout})


class JobAccessor(_Accessor):
    def list(self) -> List[Dict]:
        return self._call("list_jobs")


class PlacementGroupAccessor(_Accessor):
    def get(self, pg_id) -> Dict:
        return self._call("get_placement_group", {"pg_id": pg_id})

    def list(self) -> List[Dict]:
        return self._call("list_placement_groups")

    def wait_ready(self, pg_id, timeout: float = 60.0) -> Dict:
        return self._call("wait_placement_group",
                          {"pg_id": pg_id, "timeout": timeout})

    def remove(self, pg_id) -> Dict:
        return self._call("remove_placement_group", {"pg_id": pg_id})


class KVAccessor(_Accessor):
    """Internal KV (reference: gcs_kv_manager.h InternalKVInterface)."""

    def put(self, ns: str, key, value) -> Dict:
        return self._call("kv_put", {"ns": ns, "key": key,
                                     "value": value})

    def get(self, ns: str, key):
        return self._call("kv_get", {"ns": ns, "key": key}).get("value")

    def delete(self, ns: str, key) -> Dict:
        return self._call("kv_del", {"ns": ns, "key": key})

    def keys(self, ns: str, prefix: bytes = b"") -> List:
        return self._call("kv_keys",
                          {"ns": ns, "prefix": prefix})["keys"]


class EventAccessor(_Accessor):
    def list(self, **filters) -> List[Dict]:
        return self._call("list_events", filters)

    def list_with_stats(self, limit: int = 200) -> Dict:
        """Events plus ring accounting: {"events", "dropped", "cap"}."""
        return self._call("list_events", {"limit": limit,
                                          "with_stats": True})

    def record(self, event: Dict) -> Dict:
        return self._call("record_event", event)


class GcsClient:
    """Typed synchronous facade over the GCS for in-process callers."""

    def __init__(self, worker):
        self.nodes = NodeAccessor(worker)
        self.actors = ActorAccessor(worker)
        self.jobs = JobAccessor(worker)
        self.placement_groups = PlacementGroupAccessor(worker)
        self.kv = KVAccessor(worker)
        self.events = EventAccessor(worker)
        self._w = worker

    def ping(self) -> Dict:
        return self._w._run(self._w._gcs_request("ping", {}))

    def control_plane_stats(self) -> Dict:
        """Pubsub queue/batch/drop counters, event-ring stats, snapshot
        age/size, node/demand table sizes (see GcsServer
        rpc_control_plane_stats)."""
        return self._w._run(self._w._gcs_request("control_plane_stats",
                                                 {}))


def global_gcs_client() -> GcsClient:
    """The connected driver/worker's GcsClient (reference:
    GlobalStateAccessor usage from the Python state APIs)."""
    from ray_tpu._private import worker as worker_mod
    w = worker_mod.global_worker
    if w is None or not w.connected:
        raise RuntimeError("ray_tpu.init() must be called first")
    return GcsClient(w)
