"""CoreWorker: the in-process runtime of every driver and worker.

TPU-native re-design of the reference core worker (reference:
src/ray/core_worker/core_worker.h:63 — SubmitTask core_worker.cc:1567, Put
:892, Get :1095, ExecuteTask :2181, HandlePushTask :2543;
CoreWorkerDirectTaskSubmitter transport/direct_task_transport.h:57 with
per-SchedulingKey lease pools; CoreWorkerDirectActorTaskSubmitter
direct_actor_task_submitter.h:67 with per-caller sequence numbers;
TaskManager task_manager.h:86 for retries; ReferenceCounter
reference_count.h:61 for ownership; memory store
store_provider/memory_store/memory_store.h:43).

Each process runs one CoreWorker: it owns the objects it creates (the owner
resolves status/location queries from borrowers), submits tasks via
raylet-granted worker leases and pushes them directly worker-to-worker, and
— in worker processes — executes pushed tasks/actor methods on an executor
pool while the asyncio loop stays responsive for the data plane.
"""

from __future__ import annotations

import asyncio
import functools
import heapq
import logging
import os
import threading
import time
import traceback
from collections import deque
from concurrent.futures import Future as CFuture, ThreadPoolExecutor
from concurrent.futures import TimeoutError as CFTimeoutError

from ray_tpu import exceptions as rexc
from ray_tpu._private import failpoints, protocol, retry, serialization
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu._private.ids import (ActorID, FunctionID, JobID, NodeID, ObjectID,
                                  TaskID, WorkerID)
from ray_tpu._private.object_ref import ObjectRef
from ray_tpu._private.shm_store import StoreMapping
from ray_tpu._private.task_spec import (ActorCreationSpec, ActorTaskSpec,
                                        TaskSpec)
from ray_tpu._private import tracing as _tracing

logger = logging.getLogger(__name__)

global_worker: "CoreWorker | None" = None

# Distributed trace context, propagated inside task specs (reference:
# util/tracing/tracing_helper.py — otel context rides the TaskSpec).
# The contextvar, id minting, and the per-process span ring all live in
# _private/tracing.py now that every plane records spans, not just
# task/actor execution here.
_TRACE = _tracing._TRACE
_trace_for_submit = _tracing.trace_for_submit


# Serializes cross-thread attachment of concurrent.futures waiters to
# owned entries against the loop-side ready flip (OwnedObject.set_ready):
# a sync get() attaches its waiter directly under this lock — no
# call_soon_threadsafe hop (and thus no self-pipe syscall) per get.
_CF_LOCK = threading.Lock()


class _Latch:
    """Countdown waiter attached (via per-entry _LatchRef wrappers) to
    SEVERAL owned entries' cf_waiters: trips a threading.Event when
    every entry has fired — or IMMEDIATELY when any entry completes
    ERRORED, preserving the fail-fast semantics of the asyncio.gather
    path this replaces.  Backs the list-get fast path: one wake for N
    objects."""

    __slots__ = ("_n", "event", "errored")

    def __init__(self, n: int):
        self._n = n
        self.event = threading.Event()
        self.errored = False


class _LatchRef:
    """One entry's stake in a _Latch; duck-types the CFuture surface
    set_ready() touches (done / set_result)."""

    __slots__ = ("latch", "entry")

    def __init__(self, latch: _Latch, entry: "OwnedObject"):
        self.latch = latch
        self.entry = entry

    def done(self) -> bool:
        return self.latch.event.is_set()

    def set_result(self, _value):  # loop thread only (set_ready)
        latch = self.latch
        if self.entry.state == ERRORED:
            latch.errored = True
            latch.event.set()  # fail fast: don't wait for the rest
            return
        latch._n -= 1
        if latch._n <= 0:
            latch.event.set()

MODE_DRIVER = "driver"
MODE_WORKER = "worker"

# Owned-object states.
PENDING = "PENDING"
INLINE = "INLINE"
IN_STORE = "IN_STORE"
ERRORED = "ERRORED"


class _PinView:
    """Buffer wrapper tying a raylet read-pin to the lifetime of the
    zero-copy views handed to user code (PEP 688 __buffer__): when the
    last derived memoryview/ndarray dies, the pin is released and the
    object becomes evictable/spillable again (reference: plasma client
    Release on buffer destruction)."""

    __slots__ = ("_mv", "_cb")

    def __init__(self, mv: memoryview, release_cb):
        self._mv = mv
        self._cb = release_cb

    def __buffer__(self, flags):
        return memoryview(self._mv)

    def __del__(self):
        cb, self._cb = self._cb, None
        if cb is not None:
            try:
                cb()
            except Exception:
                pass


class _RefArg:
    """Marker for a top-level ObjectRef argument: the executor substitutes
    the fetched value (nested refs are passed through as refs — reference
    semantics)."""
    __slots__ = ("ref",)

    def __init__(self, ref: ObjectRef):
        self.ref = ref


class OwnedObject:
    __slots__ = ("state", "blob", "location", "size", "event", "local_refs",
                 "submitted_task", "reconstructions", "cf_waiters",
                 "dynamic_children")

    def __init__(self):
        self.state = PENDING
        self.blob = None
        self.location: NodeID | None = None
        self.size = 0
        self.event = asyncio.Event()
        self.local_refs = 0
        # The submitting task's spec, kept for lineage reconstruction
        # (reference: TaskManager lineage, task_manager.h:86; recovery via
        # ObjectRecoveryManager::RecoverObject object_recovery_manager.h:90).
        self.submitted_task = None
        self.reconstructions = 0
        # Sub-object ids of a num_returns="dynamic" task's yields; freed
        # when this (main) entry is released.
        self.dynamic_children = None
        # concurrent.futures waiters from sync get() fast paths on other
        # threads; fired (on the loop thread) the moment the entry lands.
        self.cf_waiters = None

    def ready(self):
        return self.state != PENDING

    def set_ready(self):
        """Mark ready: wake loop-side awaiters and cross-thread waiters.
        Loop-thread only.  The waiter list is taken under _CF_LOCK so
        sync get()s on other threads can attach directly (lock-ordered
        against the ready flip) instead of paying a loop hop."""
        self.event.set()
        with _CF_LOCK:
            waiters = self.cf_waiters
            self.cf_waiters = None
        if waiters:
            for f in waiters:
                if not f.done():
                    f.set_result(None)


class LeasePool:
    """Per-SchedulingKey lease pool (reference: direct_task_transport.h:57 —
    worker_to_lease_entry / pipelining per scheduling key)."""

    def __init__(self):
        self.queue: list = []
        self.idle: list = []
        self.all: dict[bytes, dict] = {}
        self.requests_inflight = 0
        self.return_timers: dict[bytes, asyncio.TimerHandle] = {}
        # request_id -> raylet conn the request is queued at (for cancel)
        self.outstanding: dict[bytes, object] = {}


class _ActorSendQueue:
    """Per-actor submission queue drained by ONE long-lived pump task
    (reference: the direct actor submitter's per-actor send queue,
    direct_actor_task_submitter.h:67).  A submission costs one loop hop
    (the cross-thread enqueue); sequence numbers are assigned at
    DEQUEUE, on the loop, so the unacked-window/reconnect-replay
    semantics are identical to the per-call submitter this replaces —
    and bursts to one actor coalesce into a single KIND_BATCH frame."""

    __slots__ = ("pending", "waiter", "pump", "addr_hint")

    def __init__(self):
        self.pending: deque = deque()
        self.waiter: asyncio.Future | None = None
        self.pump: asyncio.Task | None = None
        self.addr_hint: tuple | None = None


class ExecutionContext(threading.local):
    def __init__(self):
        self.task_id = None
        self.actor_id = None
        self.lease_id = None
        self.blocked_depth = 0
        self.tpu_ids: list = []  # chip indices granted to this lease


class CoreWorker:
    def __init__(self, mode, gcs_addr, raylet_addr=None, store_path=None,
                 store_cap=None, worker_id=None, job_id=None,
                 host="127.0.0.1"):
        self.mode = mode
        self.host = host
        self.worker_id = worker_id or WorkerID.from_random()
        self.job_id = job_id or JobID.from_random()
        self.gcs_addr = gcs_addr
        self.raylet_addr = raylet_addr
        self.node_id: NodeID | None = None
        self.store_path = store_path
        self.store_cap = store_cap
        self.mapping: StoreMapping | None = None
        # Pluggable worker-to-worker RPC surface: subsystems living in
        # the worker process (the collective transport) register async
        # handlers and per-method blob sinks here instead of growing
        # rpc_* methods on CoreWorker.  blob_providers lets an inbound
        # KIND_BLOB body land straight in a subsystem-owned buffer.
        self.ext_rpc: dict[str, object] = {}
        self.blob_providers: dict[str, object] = {}
        self._collective_transport = None
        self.server = protocol.RpcServer(self._handle, host=host,
                                         name=f"cw-{mode}",
                                         blob_provider=self._blob_provider)
        self.addr: tuple[str, int] | None = None
        self.gcs: protocol.Connection | None = None
        self.raylet: protocol.Connection | None = None
        self.loop: asyncio.AbstractEventLoop | None = None
        self._loop_thread: threading.Thread | None = None
        self._loop_ready = threading.Event()
        # ownership tables
        self.owned: dict[ObjectID, OwnedObject] = {}
        self._pinned: set[bytes] = set()
        self._borrow_cache: dict[ObjectID, bytes] = {}
        # Argument ObjectRefs of in-flight tasks, pinned so the owner keeps
        # serving them until the dependent task finishes (reference:
        # TaskManager lineage pinning of task dependencies).  Keyed by the
        # task's first return ObjectID.
        self._arg_pins: dict[ObjectID, list] = {}
        # Lineage for reconstruction: task_id -> spec while any of the
        # task's returns is still owned; arg refs move to _lineage_pins on
        # completion so re-execution can still resolve them.
        self._lineage: dict[TaskID, dict] = {}
        self._lineage_pins: dict[TaskID, list] = {}
        self._recovering: dict[TaskID, asyncio.Future] = {}
        # task_id -> (lease, spec) while pushed to a worker (for cancel)
        self._inflight_tasks: dict[TaskID, tuple] = {}
        # submission state
        self.lease_pools: dict[tuple, LeasePool] = {}
        self._worker_conns: dict[tuple, protocol.Connection] = {}
        self._owner_conns: dict[tuple, protocol.Connection] = {}
        self._exported_fns: set[bytes] = set()
        self._fn_cache: dict[bytes, object] = {}
        # actor-caller state
        self._actor_seq: dict[ActorID, int] = {}
        self._actor_conns: dict[ActorID, protocol.Connection] = {}
        self._actor_addr_cache: dict[ActorID, tuple] = {}
        self._actor_locks: dict[ActorID, asyncio.Lock] = {}
        # Unacked submission window per actor: seq -> entry.  Held across
        # incarnations and resent IN ORDER on restart (reference:
        # direct_actor_task_submitter.h:67 resend of the unacked window).
        self._actor_unacked: dict[ActorID, dict[int, dict]] = {}
        self._actor_recovering: dict[ActorID, asyncio.Future] = {}
        # Pipelined submission state: one send queue + pump per actor,
        # return-oid -> queued entry (for cancel of unsent calls), and
        # the per-(actor, method) spec templates of the zero-alloc
        # dispatch fast path.
        self._actor_queues: dict[ActorID, _ActorSendQueue] = {}
        self._actor_queued_refs: dict[ObjectID, dict] = {}
        self._actor_spec_templates: dict[tuple, dict] = {}
        # actor-executor state
        self.actor_instance = None
        self.actor_id: ActorID | None = None
        self._actor_is_async = False
        self._actor_pools: dict[str, ThreadPoolExecutor] = {}
        self._actor_async_sems: dict[str, asyncio.Semaphore] = {}
        self._caller_seq: dict[bytes, int] = {}
        self._caller_buffer: dict[bytes, list] = {}
        # Wire-duplicate defense (chaos dup action / retransmits): seqs
        # whose dispatch is still running, and reply waiters parked by
        # duplicate frames of those seqs (see rpc_push_actor_task).
        self._caller_running: dict[bytes, set] = {}
        self._dup_waiters: dict = {}
        self._task_pool = ThreadPoolExecutor(max_workers=1,
                                             thread_name_prefix="exec")
        # Drain-batched dispatch state for single-thread executor pools
        # (see _exec_on_serial_pool), keyed by id(pool).
        self._exec_states: dict[int, dict] = {}
        self.exec_ctx = ExecutionContext()
        self.connected = False
        self._shutdown = False
        # MPSC thread->loop post queue (see _post).
        self._post_q: deque = deque()
        self._post_armed = False
        self._loop_ident: int | None = None
        self._pubsub_handlers: dict[str, object] = {}
        self._gcs_reconnect_lock: asyncio.Lock | None = None
        # Chrome-trace profile events for ray_tpu.timeline(): the
        # process-wide span ring (_private/tracing.py) — bounded,
        # drop-oldest, drained authoritatively by the dump_trace RPC.
        self._trace_ring = _tracing.ring()

    @property
    def _profile_events(self) -> list:
        """Snapshot view of this process's span ring (compat surface
        for ray_tpu.timeline()'s driver-side merge)."""
        return self._trace_ring.snapshot()

    # ------------------------------------------------------------ lifecycle
    def start_driver(self):
        """Driver mode: run the loop in a background thread."""
        self._loop_thread = threading.Thread(target=self._loop_main,
                                             name="ray_tpu-io", daemon=True)
        self._loop_thread.start()
        self._loop_ready.wait(30)
        self._call(self._connect()).result(cfg.connect_timeout_s)
        self.connected = True

    def _loop_main(self):
        self.loop = asyncio.new_event_loop()
        asyncio.set_event_loop(self.loop)
        protocol.enable_eager_tasks(self.loop)
        self._loop_ident = threading.get_ident()
        self._loop_ready.set()
        self.loop.run_forever()

    async def start_worker_async(self):
        """Worker mode: called from the worker process's own loop."""
        self.loop = asyncio.get_running_loop()
        protocol.enable_eager_tasks(self.loop)
        self._loop_ident = threading.get_ident()
        await self._connect()
        self.connected = True

    async def _gcs_request(self, method, body,
                           timeout=protocol._DEFAULT_TIMEOUT):
        """GCS RPC surviving a GCS restart/partition: bounded reconnect
        attempts with full-jitter backoff (reference: workers re-resolve
        the GCS after failover, NotifyGCSRestart node_manager.proto:343;
        was reconnect-exactly-once, which one flaky reconnect turned
        into a caller-visible error while the GCS was still coming
        back).  Reconnects are serialized so concurrent failures share
        one new connection rather than stampeding (and leaking the
        losers); when every attempt is exhausted the terminal error
        names the GCS address so the operator knows what was
        unreachable."""
        inject = None
        if failpoints.ACTIVE:
            act = failpoints.check("worker.gcs_request", peer=method)
            if act is not None:
                if act.kind == "delay":
                    await asyncio.sleep(act.delay_s)
                elif act.kind in ("error", "drop", "disconnect"):
                    # Raised INSIDE the try: an injected request fault
                    # must exercise the reconnect machinery, exactly
                    # like a real conn loss would.
                    inject = protocol.ConnectionLost(
                        f"failpoint: injected gcs_request {act.kind} "
                        f"({method})")
        attempts = max(1, cfg.gcs_reconnect_attempts)
        backoff = retry.ExpBackoff(cfg.gcs_reconnect_base_s,
                                   cfg.gcs_reconnect_cap_s)
        last_error: Exception | None = None
        failed = None
        # Attempt 0 is the request on the existing connection; attempts
        # 1..N reconnect first.  One loop, one classification of what
        # retries vs what surfaces.
        for attempt in range(attempts + 1):
            try:
                if attempt > 0:
                    if self._gcs_reconnect_lock is None:
                        self._gcs_reconnect_lock = asyncio.Lock()
                    async with self._gcs_reconnect_lock:
                        if self.gcs is failed or self.gcs.closed:
                            if failpoints.ACTIVE:
                                act = failpoints.check(
                                    "worker.gcs_reconnect")
                                if act is not None:
                                    if act.kind == "delay":
                                        await asyncio.sleep(act.delay_s)
                                    elif act.kind != "off":
                                        raise protocol.ConnectionLost(
                                            "failpoint: injected "
                                            f"gcs_reconnect {act.kind}")
                            old = self.gcs
                            try:
                                self.gcs = (
                                    await protocol.Connection.connect(
                                        self.gcs_addr[0],
                                        self.gcs_addr[1],
                                        handler=self._handle,
                                        name="cw->gcs",
                                        timeout=cfg.connect_timeout_s))
                            except asyncio.TimeoutError as e:
                                # Connect timeout = failed reconnect
                                # ATTEMPT (SYN black-holed partition) —
                                # classify as conn failure so the
                                # bounded retry keeps going.
                                raise ConnectionError(
                                    "connect timed out after "
                                    f"{cfg.connect_timeout_s}s") from e
                            if old is not None and not old.closed:
                                try:
                                    await old.close()
                                except Exception:
                                    pass
                if inject is not None:
                    e, inject = inject, None
                    raise e
                return await self.gcs.request(method, body,
                                              timeout=timeout)
            except asyncio.TimeoutError:
                # Request deadline with the connection still healthy
                # (the keepalive would have failed it otherwise): the
                # GCS may already be executing this RPC, so neither
                # tear down the shared connection nor re-send — surface
                # the deadline.  Caught before the conn-loss clause: on
                # py3.11+ TimeoutError is an OSError subclass.
                raise
            except (protocol.ConnectionLost, ConnectionError,
                    OSError) as e:
                if self._shutdown:
                    raise
                last_error = e
                failed = self.gcs
                if attempt < attempts:
                    await asyncio.sleep(backoff.next())
        raise ConnectionError(
            f"GCS at {self.gcs_addr[0]}:{self.gcs_addr[1]} unreachable "
            f"after {attempts} reconnect attempt(s); last error: "
            f"{last_error}") from last_error

    async def _connect(self):
        self.addr = (self.host, await self.server.start(0))
        self.gcs = await protocol.Connection.connect(
            self.gcs_addr[0], self.gcs_addr[1], handler=self._handle,
            name="cw->gcs", timeout=cfg.connect_timeout_s)
        if self.mode == MODE_DRIVER:
            await self.gcs.request("register_driver", {
                "job_id": self.job_id, "pid": os.getpid(),
                "entrypoint": " ".join(os.sys.argv)})
            if cfg.log_to_driver:
                import sys

                def _echo_logs(msg):
                    for line in (msg or {}).get("lines", []):
                        print(f"(worker {msg['worker']}, "
                              f"node {msg['node'][:8]}) {line}",
                              file=sys.stderr)

                self._pubsub_handlers["logs"] = _echo_logs
                await self.gcs.request("subscribe", {"channels": ["logs"]})
        self.loop.create_task(self._telemetry_loop())
        if self.raylet_addr is not None:
            on_close = None
            if self.mode == MODE_WORKER:
                # A worker whose raylet died must exit, or it leaks forever
                # (reference: workers die when the raylet socket closes,
                # src/ray/common/client_connection.h).
                def on_close(_conn):
                    if not self._shutdown:
                        logger.warning("raylet connection lost; worker exiting")
                        os._exit(1)
            self.raylet = await protocol.Connection.connect(
                self.raylet_addr[0], self.raylet_addr[1], handler=self._handle,
                name="cw->raylet", timeout=cfg.connect_timeout_s,
                on_close=on_close)
            reply = await self.raylet.request("register_worker", {
                "worker_id": self.worker_id.hex(),
                "addr": self.addr,
                "pid": os.getpid(),
            })
            self.node_id = reply["node_id"]
            if self.store_path is None:
                # External-driver connect path: the raylet tells us where
                # its arena lives so we can mmap the data plane.
                self.store_path = reply.get("store_path")
                self.store_cap = reply.get("store_capacity")
        if self.store_path:
            self.mapping = StoreMapping(self.store_path, self.store_cap)

    def _call(self, coro) -> CFuture:
        return asyncio.run_coroutine_threadsafe(coro, self.loop)

    # Coalesced thread->loop posting: call_soon_threadsafe writes to the
    # loop's self-pipe on EVERY call, so a burst of N submissions costs
    # N syscalls.  This MPSC queue arms at most one wake per drain: a
    # burst rides one self-pipe write, and posts from the loop thread
    # itself never pay a syscall at all.
    def _post(self, fn, *args):
        self._post_q.append((fn, args))
        if not self._post_armed:
            self._post_armed = True
            if threading.get_ident() == self._loop_ident:
                self.loop.call_soon(self._drain_posts)
            else:
                self.loop.call_soon_threadsafe(self._drain_posts)

    def _drain_posts(self):
        # Reset the arm flag FIRST: a producer appending after the reset
        # re-arms (worst case an extra no-op wake, never a lost item).
        self._post_armed = False
        q = self._post_q
        while q:
            try:
                fn, args = q.popleft()
            except IndexError:
                break
            try:
                fn(*args)
            except Exception:
                logger.exception("posted callback %s failed", fn)

    def _run(self, coro, timeout=None):
        """Run coro on the loop from a non-loop thread and wait."""
        return self._call(coro).result(timeout)

    def gcs_call(self, method, body, timeout=None):
        """Synchronous GCS RPC from any non-loop thread (serve
        controller executor threads, train gang agents, the CLI) —
        the same bounded-reconnect path as _gcs_request."""
        return self._run(self._gcs_request(method, body), timeout)

    def shutdown(self):
        if self._shutdown:
            return
        self._shutdown = True
        try:
            self._call(self._shutdown_async()).result(5)
        except Exception:
            pass
        if self._loop_thread is not None:
            self.loop.call_soon_threadsafe(self.loop.stop)
            self._loop_thread.join(5)
        self.connected = False

    async def _shutdown_async(self):
        for q in self._actor_queues.values():
            if q.pump is not None:
                q.pump.cancel()
        if self._collective_transport is not None:
            try:
                self._collective_transport.close()
            except Exception:
                pass
        await self.server.stop()
        for conn in list(self._worker_conns.values()) + \
                list(self._owner_conns.values()) + \
                list(self._actor_conns.values()):
            await conn.close()
        if self.raylet is not None:
            await self.raylet.close()
        if self.gcs is not None:
            await self.gcs.close()
        if self.mapping is not None:
            self.mapping.close()

    # ----------------------------------------------------------- rpc server
    async def _handle(self, conn, method, body):
        fn = getattr(self, "rpc_" + method, None)
        if fn is None:
            ext = self.ext_rpc.get(method)
            if ext is not None:
                return await ext(conn, body)
            raise protocol.RpcError(f"core worker: no method {method}")
        return await fn(conn, body)

    def _blob_provider(self, conn, method, header, nraw):
        """Route an inbound raw-payload frame to the subsystem that
        registered the method (returns a writable sink or None)."""
        p = self.blob_providers.get(method)
        if p is None:
            return None
        return p(conn, header, nraw)

    async def _telemetry_loop(self):
        """Push metric snapshots + profile events to the GCS KV every few
        seconds (reference: the per-node metrics agent relay,
        _private/metrics_agent.py:63; consumed by the dashboard head and
        ray_tpu.timeline()).  Also measures this process's event-loop lag
        (reference: the instrumented asio event loop, event_stats.h) —
        sustained lag means a handler is blocking the IO plane."""
        lag_gauge = None
        try:
            from ray_tpu.util.metrics import Gauge
            lag_gauge = Gauge(
                "rt_event_loop_lag_ms",
                "scheduling delay of the CoreWorker IO loop",
                tag_keys=("mode",))
        except Exception:
            pass
        while not self._shutdown:
            t0 = time.monotonic()
            # Jittered: thousands of workers pushing telemetry must not
            # beat against the GCS KV in phase.
            tick = retry.jittered(2.0)
            await asyncio.sleep(tick)
            if lag_gauge is not None:
                lag = max(0.0, (time.monotonic() - t0 - tick) * 1000)
                try:
                    lag_gauge.set(round(lag, 2), tags={"mode": self.mode})
                except Exception:
                    pass
            try:
                from ray_tpu.util import metrics as metrics_mod
                # Ring health rides the metrics push: the drop counter
                # (tracing_events_dropped_total) reaches prometheus, so
                # an overflowing ring is visible without a trace pull.
                _tracing.export_metrics()
                snaps = metrics_mod.registry_snapshot()
                # STALE CONVENIENCE VIEW: the KV push truncates to the
                # freshest ring tail and lags by the push period.  The
                # authoritative path is the dump_trace RPC pull
                # (ray_tpu.cluster_trace / rt timeline --cluster),
                # which drains the whole ring on demand.
                payload = self._telemetry_payload(snaps)
                if payload is None:
                    continue
                await self._gcs_request("kv_put", {
                    "ns": "telemetry", "key": self.worker_id.binary(),
                    "value": payload})
            except Exception:
                if self._shutdown:
                    return

    def _telemetry_payload(self, snaps):
        """Build one telemetry KV push, capped at
        cfg.trace_kv_push_budget bytes (the profile tail halves until it
        fits).  The push must stay control-plane-sized: a full ring tail
        pickles to hundreds of KiB, which belongs on the dump_trace
        pull, not the heartbeat.  Returns None when there is nothing to
        push."""
        import pickle
        events = self._trace_ring.tail(2000)
        if not snaps and not events:
            return None

        def _dumps(evs):
            return pickle.dumps({
                "snapshots": snaps, "profile": evs,
                # Ring coverage + drop counts: timeline() synthesizes a
                # trace.ring_meta event per process, so a truncated
                # trace says WHAT it could not retain.
                "trace_stats": self._trace_ring.stats(),
                "rpc_handlers": protocol.handler_stats_snapshot(),
                "pid": os.getpid(), "mode": self.mode})

        payload = _dumps(events)
        budget = cfg.trace_kv_push_budget
        while len(payload) > budget and events:
            events = events[-(len(events) // 2):] if len(events) > 1 else []
            payload = _dumps(events)
        # Degenerate guard: high-cardinality metric snapshots (per-tenant
        # counters etc.) can pickle past the budget with NO events at
        # all.  The push must never ship a chunk-sized pickle onto the
        # control plane, so halve the snapshot list too — prometheus is
        # a best-effort view; the next push re-snapshots everything.
        while len(payload) > budget and len(snaps) > 1:
            snaps = snaps[:len(snaps) // 2]
            payload = _dumps(events)
        return payload

    async def rpc_pubsub(self, conn, body):
        """GCS pubsub push (driver-side: mirrored worker logs, error
        events — reference: the driver's log/error subscriber threads in
        python/ray/_private/worker.py listen_error_messages etc.)."""
        handler = self._pubsub_handlers.get(body.get("channel"))
        if handler is not None:
            try:
                handler(body.get("message"))
            except Exception:
                pass
        return None

    async def rpc_pubsub_gap(self, conn, body):
        """The GCS shed some of this subscriber's events (slow-consumer
        bound).  Driver-side channels (logs, actor events) are
        best-effort streams with their own backstops, so the gap is
        tolerated silently."""
        return None

    async def rpc_pubsub_batch(self, conn, body):
        """Coalesced GCS pubsub: one frame carrying a same-channel run
        of messages (publish order preserved) — fanned out to the same
        per-channel handler as single pushes."""
        handler = self._pubsub_handlers.get(body.get("channel"))
        if handler is not None:
            for message in protocol.pubsub_batch_messages(body):
                try:
                    handler(message)
                except Exception:
                    pass
        return None

    # ======================================================= OWNER-SIDE API
    def put(self, value, _owner_ref=None) -> ObjectRef:
        blob, _nested = serialization.serialize(value)
        return self._run(self._put_blob(blob))

    async def _put_blob(self, blob, object_id=None) -> ObjectRef:
        oid = object_id or ObjectID.for_put()
        entry = OwnedObject()
        entry.local_refs = 1
        self.owned[oid] = entry
        size = blob.total_size()
        # state is written LAST: the sync-get fast path reads ready()
        # lock-free from other threads, so blob/location/size must be
        # visible before the state flip (GIL gives the ordering).
        if size <= cfg.max_direct_call_object_size or self.raylet is None:
            entry.blob = blob.to_bytes()
            entry.size = size
            entry.state = INLINE
        else:
            offset = await self._store_create(oid.binary(), size)
            if offset is not None:
                blob.write_into(self.mapping.slice(offset, size))
                await self.raylet.request("os_seal", {"oid": oid.binary()})
            entry.location = self.node_id
            entry.size = size
            entry.state = IN_STORE
        entry.set_ready()
        return ObjectRef(oid, owner_addr=self.addr, _track=True)

    async def _store_create(self, oid_bin: bytes, size: int):
        """Allocate ``oid`` in the local store; returns the arena offset,
        or None when a copy already exists there (idempotent create —
        reconstruction re-ran the producing task on a node that never
        lost the object; the caller skips its write+seal)."""
        reply = await self.raylet.request("os_create",
                                          {"oid": oid_bin, "size": size})
        if "error" in reply:
            raise rexc.ObjectLostError(oid_bin.hex(), reply["error"])
        if reply.get("exists"):
            return None
        return reply["offset"]

    def get(self, refs, timeout=None):
        if isinstance(refs, ObjectRef):
            return self._get_sync_single(refs, timeout)
        return self._get_sync_list(refs, timeout)

    def object_meta(self, refs) -> dict:
        """Driver-side metadata for OWNED, READY refs without touching
        the bytes: {ref.id: (size_bytes, NodeID_or_None, errored)}.
        Pending / borrowed refs are simply absent.  The data layer's
        streaming executor uses this for budget accounting and
        locality-aware placement — blocks must not ride through the
        driver just to learn their size or location."""
        out = {}
        for r in refs:
            entry = self.owned.get(r.id)
            if entry is None or not entry.ready():
                continue
            out[r.id] = (entry.size, entry.location,
                         entry.state == ERRORED)
        return out

    def object_locations(self, refs, timeout: float = 5.0) -> dict:
        """{ref.id: [NodeID, ...]} of believed sealed-copy holders:
        the owner-recorded primary location plus whatever the GCS
        object directory (rpc_get_object_locations — populated for
        stripe-size objects) knows of.  Best-effort: a missing or
        unreachable directory degrades to the primary copy only."""
        out = {}
        lookups = []
        for r in refs:
            entry = self.owned.get(r.id)
            locs = []
            if entry is not None and entry.location is not None:
                locs.append(entry.location)
            out[r.id] = locs
            lookups.append(r.id)

        async def _dir(oid):
            try:
                reply = await self._gcs_request(
                    "get_object_locations", {"oid": oid.binary()},
                    timeout=timeout)
                return oid, reply.get("locations", [])
            except Exception:
                return oid, []

        async def _all():
            return await asyncio.gather(*[_dir(o) for o in lookups])

        try:
            for oid, extra in self._run(_all(), timeout=timeout + 5.0):
                for nid in extra:
                    if nid not in out[oid]:
                        out[oid].append(nid)
        except Exception:
            pass
        return out

    @staticmethod
    def _attach_waiter(entry, waiter) -> bool:
        """Attach `waiter` to a pending entry under _CF_LOCK; False if
        the entry is already ready (nothing attached)."""
        with _CF_LOCK:
            if entry.ready():
                return False
            if entry.cf_waiters is None:
                entry.cf_waiters = []
            entry.cf_waiters.append(waiter)
            return True

    @staticmethod
    def _detach_waiter(entry, waiter):
        with _CF_LOCK:
            if entry.cf_waiters is not None:
                try:
                    entry.cf_waiters.remove(waiter)
                except ValueError:
                    pass

    def _get_sync_single(self, ref, timeout):
        """Sync-get fast path for one OWNED ref: attach a plain
        concurrent future directly (lock-ordered against set_ready — no
        loop hop, no self-pipe syscall), wait, then deserialize on the
        calling thread; the loop never spends time deserializing.
        Borrowed refs, in-store objects, and recovery fall back to the
        full async path with whatever remains of the ONE timeout
        budget."""
        deadline = None if timeout is None else time.monotonic() + timeout
        entry = self.owned.get(ref.id)
        if entry is not None and not entry.ready():
            waiter = CFuture()
            if self._attach_waiter(entry, waiter):
                self._notify_blocked()
                try:
                    waiter.result(timeout)
                except (TimeoutError, CFTimeoutError):
                    # CFTimeoutError: on py<3.11 concurrent.futures
                    # raises its OWN TimeoutError, which is NOT the
                    # builtin — the builtin-only clause let the timeout
                    # escape as a raw futures error instead of
                    # GetTimeoutError.  Prune the dead waiter: a caller
                    # polling with short timeouts must not grow
                    # entry.cf_waiters unboundedly.
                    self._detach_waiter(entry, waiter)
                    raise rexc.GetTimeoutError(
                        f"timed out waiting for object {ref.id.hex()}")
                finally:
                    self._notify_unblocked()
        if (entry is not None
                and (entry.state == INLINE or entry.state == ERRORED)):
            value = serialization.deserialize(entry.blob)
            if isinstance(value, _SerializedError):
                raise value.to_exception()
            return value
        # Borrowed / in-store / recovery: async path, remaining budget.
        remaining = self._remain(deadline)
        self._notify_blocked()
        try:
            return self._run(self._get_async_list(
                [ref], remaining, trace=_tracing.current_dict()))[0]
        finally:
            self._notify_unblocked()

    def _get_sync_list(self, refs, timeout):
        """List-get fast path for OWNED refs: ONE countdown latch rides
        every pending entry's waiter list, so a burst of N replies costs
        one thread wake, and all deserialization happens on the calling
        thread.  Any borrowed ref sends the whole call to the async
        path; in-store values resolve through it afterwards with the
        remaining budget."""
        deadline = None if timeout is None else time.monotonic() + timeout
        entries = [self.owned.get(r.id) for r in refs]
        if any(e is None for e in entries):
            self._notify_blocked()
            try:
                return self._run(self._get_async_list(
                    refs, timeout, trace=_tracing.current_dict()))
            finally:
                self._notify_unblocked()
        # Fail fast on errors already in hand, like the gather path did.
        for e in entries:
            if e.ready() and e.state == ERRORED:
                value = serialization.deserialize(e.blob)
                if isinstance(value, _SerializedError):
                    raise value.to_exception()
        latch = _Latch(0)
        wrappers = []
        with _CF_LOCK:
            # One lock region for the whole attach: set_ready can only
            # observe the latch after we release, so the count is final
            # before the first fire.
            for e in entries:
                if not e.ready():
                    if e.cf_waiters is None:
                        e.cf_waiters = []
                    w = _LatchRef(latch, e)
                    e.cf_waiters.append(w)
                    wrappers.append(w)
            latch._n = len(wrappers)
        if wrappers:
            self._notify_blocked()
            try:
                if not latch.event.wait(timeout):
                    for w in wrappers:
                        self._detach_waiter(w.entry, w)
                    raise rexc.GetTimeoutError(
                        f"timed out waiting for {len(refs)} objects")
            finally:
                self._notify_unblocked()
            if latch.errored:
                # A task failed while others may still be running: raise
                # its error NOW (fail-fast), detaching our stakes from
                # the stragglers first.
                for w in wrappers:
                    self._detach_waiter(w.entry, w)
                for e in entries:
                    if e.ready() and e.state == ERRORED:
                        value = serialization.deserialize(e.blob)
                        if isinstance(value, _SerializedError):
                            raise value.to_exception()
        values = []
        slow_idx = []
        for i, e in enumerate(entries):
            if e.state == INLINE or e.state == ERRORED:
                value = serialization.deserialize(e.blob)
                if isinstance(value, _SerializedError):
                    raise value.to_exception()
                values.append(value)
            else:
                values.append(None)
                slow_idx.append(i)
        if slow_idx:
            # In-store (or recovering) objects: async path, shared
            # remaining budget.
            remaining = self._remain(deadline)
            self._notify_blocked()
            try:
                slow_values = self._run(self._get_async_list(
                    [refs[i] for i in slow_idx], remaining,
                    trace=_tracing.current_dict()))
            finally:
                self._notify_unblocked()
            for i, v in zip(slow_idx, slow_values):
                values[i] = v
        return values

    def get_future(self, ref: ObjectRef) -> CFuture:
        return self._call(self._get_one(ref))

    def ready_future(self, ref: ObjectRef) -> CFuture:
        """Thread-safe future firing (with None) when an OWNED ref's
        entry becomes ready; fires immediately for already-ready and
        borrowed refs.  Pairs with try_take_local_value for the serve
        router's unary fast path: no coroutine is spawned per call and
        the value is deserialized on the CALLER's thread, keeping the
        CoreWorker IO loop out of the reply data path."""
        fut = CFuture()
        entry = self.owned.get(ref.id)
        if entry is None or entry.ready() \
                or not self._attach_waiter(entry, fut):
            fut.set_result(None)
        return fut

    def try_take_local_value(self, ref: ObjectRef):
        """(True, value) for a ready owned INLINE entry — deserialized
        on the calling thread (the carried exception is raised for
        ERRORED entries); (False, None) when the full get() path is
        needed (borrowed refs or in-store objects)."""
        entry = self.owned.get(ref.id)
        if entry is None or not entry.ready():
            return False, None
        state = entry.state
        if state != INLINE and state != ERRORED:
            return False, None
        value = serialization.deserialize(entry.blob)
        if isinstance(value, _SerializedError):
            raise value.to_exception()
        return True, value

    async def get_async(self, ref: ObjectRef):
        return await self._get_one(ref)

    async def _get_async_list(self, refs, timeout=None, trace=None):
        """``trace`` is the CALLER THREAD's span context: the sync get
        paths capture it before hopping to the IO loop (contextvars do
        not cross run_coroutine_threadsafe), so a store fetch that
        escalates into a transfer-plane pull stays in the task's
        trace."""
        deadline = None if timeout is None else time.monotonic() + timeout
        coros = [self._get_one(r, deadline, trace) for r in refs]
        return list(await asyncio.gather(*coros))

    async def _get_one(self, ref: ObjectRef, deadline=None, trace=None):
        blob = await self._resolve_blob(ref, deadline, trace)
        value = serialization.deserialize(blob)
        if isinstance(value, _SerializedError):
            raise value.to_exception()
        return value

    async def _resolve_blob(self, ref: ObjectRef, deadline=None,
                            trace=None):
        entry = self.owned.get(ref.id)
        if entry is not None:
            if not entry.ready():
                await self._wait_event(entry.event, deadline,
                                       f"object {ref.id.hex()}")
            if entry.state == INLINE:
                return entry.blob
            if entry.state == ERRORED:
                return entry.blob
            try:
                return await self._fetch_from_store(ref.id, entry.location,
                                                    deadline, trace)
            except rexc.ObjectLostError:
                # The node holding the primary copy died: reconstruct by
                # re-executing the creating task, then re-resolve.
                await self._recover_object(ref.id, entry)
                if entry.state in (INLINE, ERRORED):
                    return entry.blob
                return await self._fetch_from_store(ref.id, entry.location,
                                                    deadline, trace)
        # Borrowed ref: ask the owner.
        cached = self._borrow_cache.get(ref.id)
        if cached is not None:
            return cached
        if ref.owner_addr is None:
            raise rexc.ObjectLostError(ref.id.hex(), "no owner address")
        owner = await self._owner_conn(tuple(ref.owner_addr))
        status = await owner.request("get_object_status", {"oid": ref.id},
                                     timeout=self._remain(deadline))
        if status.get("error") is not None:
            return status["error"]  # serialized error blob
        if "blob" in status:
            self._borrow_cache[ref.id] = status["blob"]
            return status["blob"]
        try:
            return await self._fetch_from_store(ref.id, status["location"],
                                                deadline, trace)
        except rexc.ObjectLostError:
            # Report the loss to the owner, who recovers via lineage and
            # tells us where the object lives now.
            status = await owner.request("recover_object", {"oid": ref.id},
                                         timeout=self._remain(deadline))
            if status.get("error") is not None:
                return status["error"]
            if "blob" in status:
                self._borrow_cache[ref.id] = status["blob"]
                return status["blob"]
            return await self._fetch_from_store(
                ref.id, status["location"], deadline, trace)

    async def _fetch_from_store(self, oid: ObjectID, location,
                                deadline=None, trace=None):
        if self.raylet is None:
            raise rexc.ObjectLostError(oid.hex(), "no raylet (local mode)")
        # The remaining budget travels as ONE deadline: the raylet
        # charges every wait and every pulled chunk against it (a
        # stop-and-wait transfer used to re-grant the full timeout per
        # chunk).  The RPC timeout is slightly larger so the raylet's
        # own deadline error wins the race and keeps its detail.
        budget = self._remain(deadline) or 60.0
        body = {"oid": oid.binary(), "location": location,
                "timeout": budget}
        if trace is None:
            # Async callers (actor coroutines) still carry the context
            # in THIS task; sync callers captured it pre-hop.
            trace = _tracing.current_dict()
        if trace is not None and location is not None:
            # The trace crosses into the raylet only when a remote pull
            # may run (a local sealed copy records nothing): flow-start
            # here, flow-finish inside TransferManager.pull.
            trace = dict(trace, flow=_tracing.fresh_id())
            _tracing.flow_start(trace["flow"], "transfer")
            body["trace"] = trace
        reply = await self.raylet.request("os_get", body,
                                          timeout=budget + 5.0)
        if "error" in reply:
            if reply.get("timeout"):
                # The resolution ran out of the caller's budget — that
                # is a timeout, not a lost object: reconstruction would
                # re-execute the producing task for an object that still
                # exists on its node.
                raise rexc.GetTimeoutError(
                    f"object {oid.hex()}: {reply['error']}")
            raise rexc.ObjectLostError(oid.hex(), reply["error"])
        binary = oid.binary()
        self._pinned.add(binary)
        mv = self.mapping.slice(reply["offset"], reply["size"])

        def _release():
            if self._shutdown or self.loop is None or self.raylet is None:
                return
            self._pinned.discard(binary)
            try:
                asyncio.run_coroutine_threadsafe(
                    self.raylet.request("os_release", {"oid": binary}),
                    self.loop)
            except Exception:
                pass

        pv = _PinView(mv, _release)
        try:
            # Zero-copy: the returned view keeps the read-pin alive via
            # _PinView.__buffer__ (PEP 688, Python >= 3.12).
            return memoryview(pv)
        except TypeError:
            # Python < 3.12 ignores __buffer__ — memoryview() refuses
            # the wrapper.  Disarm pv FIRST (its __del__ must not
            # release the pin out from under the copy), copy under the
            # pin, then release exactly once; one copy per store fetch
            # beats every remote get() crashing.
            pv._cb = None
            data = bytes(mv)
            _release()
            return data

    @staticmethod
    def _remain(deadline):
        if deadline is None:
            return None
        return max(0.001, deadline - time.monotonic())

    async def _wait_event(self, event, deadline, what):
        if deadline is None:
            await event.wait()
        else:
            try:
                await asyncio.wait_for(event.wait(), self._remain(deadline))
            except asyncio.TimeoutError:
                raise rexc.GetTimeoutError(f"timed out waiting for {what}")

    def wait(self, refs, num_returns=1, timeout=None, fetch_local=True):
        self._notify_blocked()
        try:
            return self._run(self._wait_async(refs, num_returns, timeout,
                                              fetch_local))
        finally:
            self._notify_unblocked()

    async def _wait_async(self, refs, num_returns, timeout,
                          fetch_local=True):
        pending = list(refs)
        ready: list = []
        deadline = None if timeout is None else time.monotonic() + timeout

        async def _ready_one(r):
            if not fetch_local:
                # Readiness only, no byte movement: an OWNED ref is
                # ready when its entry lands (task finished / put
                # sealed) — resolving the blob here would PULL the
                # store copy to this node, which is exactly what the
                # streaming executor's handle plumbing must avoid
                # (fetch_local=True used to be silently forced).
                # Borrowed refs still resolve (the owner round trip is
                # what determines readiness for them).
                entry = self.owned.get(r.id)
                if entry is not None:
                    if not entry.ready():
                        await entry.event.wait()
                    return r
            await self._resolve_blob(r)
            return r

        tasks = {asyncio.ensure_future(_ready_one(r)): r for r in pending}
        try:
            while len(ready) < num_returns and tasks:
                done, _ = await asyncio.wait(
                    tasks.keys(), timeout=self._remain(deadline),
                    return_when=asyncio.FIRST_COMPLETED)
                if not done:
                    break
                for t in done:
                    r = tasks.pop(t)
                    if t.exception() is None:
                        ready.append(r)
                    else:
                        ready.append(r)  # errored objects count as ready
            not_ready = [tasks[t] for t in tasks]
        finally:
            for t in tasks:
                t.cancel()
        order = {id(r): i for i, r in enumerate(refs)}
        ready.sort(key=lambda r: order.get(id(r), 0))
        return ready, not_ready

    async def _owner_conn(self, addr: tuple) -> protocol.Connection:
        conn = self._owner_conns.get(addr)
        if conn is None or conn.closed:
            conn = await protocol.Connection.connect(
                addr[0], addr[1], handler=self._handle, name="cw->owner",
                timeout=cfg.connect_timeout_s)
            self._owner_conns[addr] = conn
        return conn

    async def rpc_get_object_status(self, conn, body):
        """Serve borrowers asking about an object we own (reference:
        CoreWorkerService GetObjectStatus)."""
        oid: ObjectID = body["oid"]
        entry = self.owned.get(oid)
        if entry is None:
            return {"error": _error_blob(
                rexc.ObjectLostError(oid.hex(), "owner has no record"))}
        if not entry.ready():
            await entry.event.wait()
        if entry.state == INLINE:
            return {"blob": entry.blob}
        if entry.state == ERRORED:
            return {"error": entry.blob}
        return {"location": entry.location, "size": entry.size}

    async def rpc_recover_object(self, conn, body):
        """A borrower failed to fetch an object we own: reconstruct it via
        lineage and reply with the fresh status (reference: owner-driven
        recovery, object_recovery_manager.h:41)."""
        oid: ObjectID = body["oid"]
        entry = self.owned.get(oid)
        if entry is None:
            return {"error": _error_blob(
                rexc.ObjectLostError(oid.hex(), "owner has no record"))}
        try:
            if entry.ready() and entry.state == IN_STORE:
                await self._recover_object(oid, entry)
        except rexc.ObjectLostError as e:
            return {"error": _error_blob(e)}
        if not entry.ready():
            await entry.event.wait()
        if entry.state == INLINE:
            return {"blob": entry.blob}
        if entry.state == ERRORED:
            return {"error": entry.blob}
        return {"location": entry.location, "size": entry.size}

    async def _recover_object(self, oid: ObjectID, entry: OwnedObject):
        """Re-execute the task that created `oid` (reference:
        TaskManager::ResubmitTask task_manager.h:135).  Deduped per task:
        concurrent losses of sibling returns re-execute once."""
        spec = entry.submitted_task
        if spec is None:
            raise rexc.ObjectLostError(
                oid.hex(), "object lost and not reconstructable "
                           "(ray_tpu.put objects have no lineage)")
        task_id = spec["task_id"]
        fut = self._recovering.get(task_id)
        if fut is not None:
            await asyncio.shield(fut)
            return
        fut = self._recovering[task_id] = self.loop.create_future()
        try:
            reexecutions = []
            for rid in spec["return_ids"]:
                e = self.owned.get(rid)
                if e is None:
                    continue
                if e.reconstructions >= cfg.max_object_reconstructions:
                    raise rexc.ObjectLostError(
                        oid.hex(),
                        f"exceeded {cfg.max_object_reconstructions} "
                        "reconstruction attempts")
                e.reconstructions += 1
                e.state = PENDING
                e.blob = None
                e.location = None
                e.event = asyncio.Event()
                reexecutions.append(rid)
            if oid not in spec["return_ids"]:
                # A dynamic-returns sub-object: not listed in the spec's
                # return ids, so reset it here — re-execution re-enters
                # the dynamic branch and fires THIS entry's fresh event.
                if entry.reconstructions >= \
                        cfg.max_object_reconstructions:
                    raise rexc.ObjectLostError(
                        oid.hex(),
                        f"exceeded {cfg.max_object_reconstructions} "
                        "reconstruction attempts")
                entry.reconstructions += 1
                entry.state = PENDING
                entry.blob = None
                entry.location = None
                entry.event = asyncio.Event()
                reexecutions.append(oid)
            logger.warning(
                "reconstructing %d object(s) by re-executing task %s",
                len(reexecutions), task_id.hex()[:8])
            self._pin_args_from_lineage(task_id)
            await self._submit(TaskSpec(spec))
            await entry.event.wait()
            if not fut.done():
                fut.set_result(True)
        except Exception as e:
            if not fut.done():
                fut.set_exception(e)
            raise
        finally:
            self._recovering.pop(task_id, None)
            # Consume fut's exception if nobody else awaited it.
            if fut.done() and fut.exception() is not None:
                fut.exception()

    def _pin_args_from_lineage(self, task_id):
        pins = self._lineage_pins.pop(task_id, None)
        if pins is not None:
            self._arg_pins[task_id] = pins

    # ----------------------------------------------------------- refcounting
    def add_local_ref(self, ref: ObjectRef):
        entry = self.owned.get(ref.id)
        if entry is not None:
            entry.local_refs += 1

    def remove_local_ref(self, ref: ObjectRef):
        if self._shutdown or not self.connected:
            return
        entry = self.owned.get(ref.id)
        if entry is None:
            return
        entry.local_refs -= 1
        if entry.local_refs <= 0 and entry.ready():
            self.owned.pop(ref.id, None)
            # A dynamic-returns main entry carries its yields' pins:
            # release them with it (their untracked refs in the
            # ObjectRefGenerator share the outer ref's lifetime).
            for child in entry.dynamic_children or ():
                self.remove_local_ref(ObjectRef(child,
                                                owner_addr=self.addr))
            if entry.state == IN_STORE and self.loop is not None:
                try:
                    self._call(self._delete_store_object(ref.id, entry))
                except Exception:
                    pass
            spec = entry.submitted_task
            if spec is not None and all(rid not in self.owned
                                        for rid in spec["return_ids"]):
                # Last live return gone: release the lineage + arg pins.
                self._lineage.pop(spec["task_id"], None)
                self._lineage_pins.pop(spec["task_id"], None)

    async def _delete_store_object(self, oid: ObjectID, entry):
        try:
            if entry.location == self.node_id and self.raylet is not None:
                await self.raylet.request("os_delete", {"oid": oid.binary()})
        except Exception:
            pass

    # ==================================================== TASK SUBMISSION
    def export_function(self, fn) -> bytes:
        blob = serialization.dumps_function(fn)
        import hashlib
        fn_id = hashlib.sha1(blob).digest()[:16]
        if fn_id not in self._exported_fns:
            self._run(self._gcs_request("kv_put", {
                "ns": "funcs", "key": fn_id, "value": blob}))
            self._exported_fns.add(fn_id)
            self._fn_cache[fn_id] = fn
        return fn_id

    def submit_task(self, fn_id: bytes, args, kwargs, opts: dict):
        task_id = TaskID.for_submit()
        num_returns = opts.get("num_returns", 1)
        # "dynamic": one visible return (the ObjectRefGenerator); the
        # per-yield objects get ids for_task_return(task_id, 1..N) on
        # the executing side and register with the owner on reply.
        dynamic = num_returns == "dynamic"
        if dynamic:
            num_returns = 1
        refs = []
        for i in range(num_returns):
            oid = ObjectID.for_task_return(task_id, i)
            entry = OwnedObject()
            entry.local_refs = 1
            self.owned[oid] = entry
            refs.append(ObjectRef(oid, owner_addr=self.addr, _track=True))
        args_blob = self._pack_args(args, kwargs)
        pg = opts.get("placement_group")
        trace = _trace_for_submit()
        # Submit-side flow start: the execution span (possibly another
        # process) closes the edge, connecting the waterfall.  No flow
        # id = un-spanned submit (nothing to connect from; keeps the
        # ambient per-call cost at one ring event).
        if "flow" in trace:
            _tracing.flow_start(trace["flow"])
        spec = TaskSpec.new(
            task_id=task_id,
            fn_id=fn_id,
            args_blob=args_blob,
            num_returns=-1 if dynamic else num_returns,
            owner_addr=self.addr,
            return_ids=[r.id for r in refs],
            resources=_normalize_resources(opts),
            strategy=_strategy_dict(opts.get("scheduling_strategy")),
            max_retries=opts.get("max_retries",
                                 cfg.max_task_retries_default),
            retry_exceptions=opts.get("retry_exceptions", False),
            name=opts.get("name", ""),
            trace=trace,
            runtime_env=(self._pack_runtime_env(opts["runtime_env"])
                         if opts.get("runtime_env") else None),
            pg_id=pg.id if pg is not None else None,
            bundle_index=opts.get("placement_group_bundle_index", -1),
        ).validate()
        # Lineage: keep the spec on every return so a lost object can be
        # reconstructed by re-executing the task (reference:
        # task_manager.h:86 lineage, object_recovery_manager.h:90).
        # num_returns=0 tasks have nothing to reconstruct — recording
        # lineage for them would leak specs+arg pins forever (cleanup runs
        # from remove_local_ref over return refs).
        if refs:
            for r in refs:
                self.owned[r.id].submitted_task = spec
            self._lineage[task_id] = spec
        self._pin_args(task_id, args, kwargs)
        if task_id in self._arg_pins:
            self._call(self._submit(spec))
        else:
            # No ObjectRef args -> nothing to await before dispatch; a
            # coalesced post skips run_coroutine_threadsafe's coroutine +
            # future-chaining overhead AND shares one loop wake across a
            # submission burst.
            self._post(self._enqueue_spec, spec)
        return refs

    def cancel_task(self, ref, force: bool = False) -> bool:
        """Cancel the task that produces `ref` (reference: ray.cancel,
        core_worker CancelTask): queued tasks are dequeued and their
        returns error with TaskCancelledError; running tasks are killed
        only with force=True (their worker is torn down)."""
        entry = self.owned.get(ref.id)
        spec = entry.submitted_task if entry is not None else None
        if spec is None:
            # Actor tasks: cancellable only while still queued in the
            # per-actor send queue (not yet on the wire).
            if self._run(self._cancel_queued_actor(ref.id)):
                return True
            raise ValueError(
                "ray_tpu.cancel only applies to normal-task returns "
                "and queued-but-unsent actor tasks: puts have no task, "
                "completed-and-released tasks are gone, and an actor "
                "task already on the wire cannot be cancelled (kill "
                "the actor instead)")
        return self._run(self._cancel(spec, force))

    async def _cancel(self, spec, force: bool) -> bool:
        task_id = spec["task_id"]
        key = self._scheduling_key(spec)
        pool = self.lease_pools.get(key)
        if pool is not None and any(s is spec for s in pool.queue):
            pool.queue[:] = [s for s in pool.queue if s is not spec]
            self._complete_with_error(spec, rexc.TaskCancelledError(
                f"task {task_id.hex()[:8]} cancelled before start"))
            # Re-pump: with the queue drained this cancels the stale
            # outstanding lease request, or a granted lease would park
            # in pool.idle forever holding its worker's resources.
            self._pump(key)
            return True
        inflight = self._inflight_tasks.get(task_id)
        if inflight is not None:
            lease, ispec = inflight
            if force:
                # Mark ONLY when actually stopping: a no-op cancel must
                # not poison later legitimate retries/reconstruction.
                ispec["cancelled"] = True
                self._drop_lease(key, lease)
                return True
            return False
        return False

    def _pack_runtime_env(self, runtime_env):
        from ray_tpu import runtime_env as renv

        def _kv_put(ns, key, value):
            self._run(self._gcs_request("kv_put", {
                "ns": ns, "key": key, "value": value}))

        return renv.pack(runtime_env, _kv_put)

    def _apply_runtime_env(self, runtime_env):
        """Executor side: materialize packages + env vars (reference:
        runtime-env creation before task execution).  Returns a restore
        callable: pooled workers are REUSED across tasks, so env vars /
        cwd / sys.path must not leak into the next task (the reference
        instead dedicates workers per runtime env)."""
        if not runtime_env:
            return None
        import sys
        from ray_tpu import runtime_env as renv

        def _kv_get(ns, key):
            return self._run(self._gcs_request(
                "kv_get", {"ns": ns, "key": key}))["value"]

        cache = os.path.join(
            os.environ.get("RT_SESSION_DIR", "/tmp/ray_tpu"),
            "runtime_envs")
        saved_env = dict(os.environ)
        saved_cwd = os.getcwd()
        saved_path = list(sys.path)

        def _restore():
            os.environ.clear()
            os.environ.update(saved_env)
            try:
                os.chdir(saved_cwd)
            except OSError:
                pass
            sys.path[:] = saved_path

        try:
            renv.apply(runtime_env, _kv_get, cache)
        except BaseException:
            # A half-applied env (vars set, package missing) must not
            # leak into the pooled worker.
            _restore()
            raise
        return _restore

    def _pin_args(self, task_id, args, kwargs):
        """Keep ObjectRef args alive until the task completes.  Keyed by
        task_id so num_returns=0 (fire-and-forget) tasks pin too."""
        pins = [a for a in args if isinstance(a, ObjectRef)]
        pins += [v for v in kwargs.values() if isinstance(v, ObjectRef)]
        if pins:
            self._arg_pins[task_id] = pins

    def _unpin_args(self, task_id):
        if task_id is None:
            return
        pins = self._arg_pins.pop(task_id, None)
        # While the task's lineage is retained (its returns may need
        # reconstruction), its args must stay fetchable: move the pins to
        # the lineage table instead of dropping them (reference: lineage
        # pinning of task dependencies, reference_count.h borrower docs).
        if pins is not None and task_id in self._lineage:
            self._lineage_pins[task_id] = pins

    _EMPTY_ARGS_BLOB: bytes | None = None

    def _pack_args(self, args, kwargs):
        if not args and not kwargs:
            blob = CoreWorker._EMPTY_ARGS_BLOB
            if blob is None:
                b, _ = serialization.serialize(([], {}))
                blob = CoreWorker._EMPTY_ARGS_BLOB = b.to_bytes()
            return blob
        new_args = [(_RefArg(a) if isinstance(a, ObjectRef) else a)
                    for a in args]
        new_kwargs = {k: (_RefArg(v) if isinstance(v, ObjectRef) else v)
                      for k, v in kwargs.items()}
        blob, _nested = serialization.serialize((new_args, new_kwargs))
        return blob.to_bytes()

    def _scheduling_key(self, spec):
        res = tuple(sorted(spec["resources"].items()))
        strat = spec.get("strategy")
        strat_key = tuple(sorted(strat.items())) if strat else None
        from ray_tpu.runtime_env import pip_env_key
        return (spec["fn_id"], res, strat_key, spec.get("pg_id"),
                spec.get("bundle_index"),
                pip_env_key(spec.get("runtime_env")))

    async def _submit(self, spec):
        await self._wait_args_ready(spec)
        self._enqueue_spec(spec)

    def _enqueue_spec(self, spec):
        key = self._scheduling_key(spec)
        pool = self.lease_pools.get(key)
        if pool is None:
            pool = self.lease_pools[key] = LeasePool()
        pool.queue.append(spec)
        self._pump(key)

    async def _wait_args_ready(self, spec):
        """Dependency resolution BEFORE dispatch (reference:
        DependencyResolver in direct_task_transport.h — a task is pushed
        only once its args exist).  Without this, dispatched tasks sit on
        workers blocking in the arg fetch; each blocked worker releases
        its CPU, the raylet admits yet another task, and an all-to-all
        under memory pressure amplifies into dozens of half-running tasks
        whose pinned args wedge the object store."""
        pins = self._arg_pins.get(spec["task_id"])
        if not pins:
            return
        for ref in pins:
            entry = self.owned.get(ref.id)
            if entry is not None and not entry.ready():
                await entry.event.wait()

    def _pump(self, key):
        pool = self.lease_pools[key]
        while pool.queue and pool.idle:
            lease = pool.idle.pop()
            timer = pool.return_timers.pop(lease["lease_id"], None)
            if timer is not None:
                timer.cancel()
            spec = pool.queue.pop(0)
            self.loop.create_task(self._push_on_lease(key, lease, spec))
        backlog = len(pool.queue)
        if backlog == 0 and pool.outstanding:
            self._cancel_outstanding(pool)
        # One lease wanted per queued task (capped): a busy lease must
        # NOT count as covering the backlog — its task may run for
        # hours, and parallelism must never depend on task duration.
        # (Regression: a lingering warm lease made the pool dispatch
        # task A onto it and then request nothing for task B, fully
        # serializing two same-key tasks — caught by the dask-on-ray
        # rendezvous test.)
        want = min(backlog, 8) - pool.requests_inflight
        for _ in range(max(0, want)):
            pool.requests_inflight += 1
            self.loop.create_task(self._request_lease(key))

    def _cancel_outstanding(self, pool):
        by_conn: dict[int, tuple] = {}
        for rid, conn in pool.outstanding.items():
            by_conn.setdefault(id(conn), (conn, []))[1].append(rid)
        pool.outstanding.clear()
        for conn, rids in by_conn.values():
            if not conn.closed:
                self.loop.create_task(self._send_cancel(conn, rids))

    async def _send_cancel(self, conn, rids):
        try:
            await conn.request("cancel_lease_requests", {"request_ids": rids})
        except Exception:
            pass

    async def _request_lease(self, key):
        pool = self.lease_pools[key]
        spec_probe = pool.queue[0] if pool.queue else None
        request_id = os.urandom(8)
        try:
            if spec_probe is None:
                return
            body = {
                "resources": spec_probe["resources"],
                "strategy": spec_probe.get("strategy"),
                "pg_id": spec_probe.get("pg_id"),
                "bundle_index": spec_probe.get("bundle_index"),
                "request_id": request_id,
            }
            renv = spec_probe.get("runtime_env") or {}
            from ray_tpu.runtime_env import env_spec, worker_env_key
            espec = env_spec(renv)
            if espec:
                body["env_key"] = worker_env_key(renv)
                body["env_spec"] = espec
            conn = self.raylet
            if spec_probe.get("pg_id") is not None:
                conn = await self._raylet_for_bundle(
                    spec_probe["pg_id"], spec_probe.get("bundle_index"))
            for _hop in range(4):
                pool.outstanding[request_id] = conn
                # Explicit timeout=None (NOT the config default
                # deadline): a cluster-wide-infeasible request stays
                # queued at the raylet as autoscaler demand (reference:
                # infeasible tasks wait for scale-up, they don't error).
                # Conn loss / keepalive / cancellation still wake this.
                reply = await conn.request("request_worker_lease", body,
                                           timeout=None)
                pool.outstanding.pop(request_id, None)
                if "spillback" in reply:
                    addr = tuple(reply["spillback"])
                    conn = await self._raylet_conn(addr)
                    body = dict(body)
                    body["strategy"] = None  # don't re-spread at the target
                    # A spilled request must not bounce again on the
                    # target's (possibly stale) view of us — it queues
                    # there instead (reference: spillback counts in the
                    # lease protocol prevent ping-pong).
                    body["hops"] = body.get("hops", 0) + 1
                    continue
                break
            if reply.get("cancelled"):
                # A task enqueued during the cancel round trip saw
                # requests_inflight > 0 and issued no request of its
                # own — re-pump so it gets one (this exit path must
                # behave like every other one).
                self.loop.call_soon(self._pump, key)
                return
            if "error" in reply:
                self._fail_queued(key, rexc.RayTpuError(reply["error"]))
                return
            if "worker_addr" not in reply:
                self._fail_queued(key, rexc.RayTpuError(
                    f"lease not granted after spillback hops: {reply}"))
                return
            worker_addr = tuple(reply["worker_addr"])
            wconn = await self._worker_conn(worker_addr)
            lease = {
                "lease_id": reply["lease_id"],
                "conn": wconn,
                "raylet": conn,
                "node_id": reply["node_id"],
                "worker_addr": worker_addr,
                "busy": False,
                "tpu_ids": reply.get("tpu_ids") or [],
            }
            pool.all[lease["lease_id"]] = lease
            pool.idle.append(lease)
        except Exception as e:
            logger.warning("lease request failed: %s", e)
            self._fail_queued(key, e)
            return
        finally:
            pool.requests_inflight -= 1
        self._pump(key)
        # Granted after the backlog drained (a finishing task absorbed
        # the queue): without a linger timer this lease would park its
        # worker forever.
        if (lease in pool.idle
                and lease["lease_id"] not in pool.return_timers):
            self._schedule_lease_return(key, lease)

    def _fail_queued(self, key, exc):
        pool = self.lease_pools.get(key)
        if pool is None:
            return
        while pool.queue:
            spec = pool.queue.pop(0)
            self._complete_with_error(spec, exc)

    def _complete_with_error(self, spec, exc):
        self._unpin_args(spec.get("task_id"))
        blob = _error_blob(exc if isinstance(exc, Exception)
                           else rexc.RayTpuError(str(exc)))
        for oid in spec["return_ids"]:
            entry = self.owned.get(oid)
            if entry is not None:
                entry.blob = blob
                entry.state = ERRORED  # last: lock-free readers order on it
                entry.set_ready()

    async def _raylet_for_bundle(self, pg_id, bundle_index):
        """Route a placement-group lease to the raylet holding the bundle
        (reference: PG-aware lease targeting via the bundle's node)."""
        view = await self._gcs_request(
            "wait_placement_group", {"pg_id": pg_id, "timeout": 60.0})
        if view is None or view.get("state") != "CREATED":
            raise rexc.RayTpuError(
                f"placement group {pg_id.hex()[:8]} not ready "
                f"(state={view and view.get('state')})")
        bundle_nodes = view["bundle_nodes"]
        if bundle_index is not None and bundle_index >= 0:
            node_ids = [bundle_nodes[bundle_index]]
        else:
            node_ids = list(dict.fromkeys(bundle_nodes))
        nodes = await self._gcs_request("get_nodes", {})
        by_id = {n["node_id"]: n for n in nodes}
        for nid in node_ids:
            nview = by_id.get(nid)
            if nview is not None and nview.get("alive"):
                if nid == self.node_id:
                    return self.raylet
                return await self._raylet_conn(tuple(nview["addr"]))
        raise rexc.RayTpuError(
            f"no alive node holds bundles of pg {pg_id.hex()[:8]}")

    async def _raylet_conn(self, addr):
        key = ("raylet",) + tuple(addr)
        conn = self._worker_conns.get(key)
        if conn is None or conn.closed:
            conn = await protocol.Connection.connect(
                addr[0], addr[1], handler=self._handle, name="cw->raylet2",
                timeout=cfg.connect_timeout_s)
            self._worker_conns[key] = conn
        return conn

    async def _worker_conn(self, addr):
        conn = self._worker_conns.get(tuple(addr))
        if conn is None or conn.closed:
            conn = await protocol.Connection.connect(
                addr[0], addr[1], handler=self._handle, name="cw->worker",
                timeout=cfg.connect_timeout_s)
            self._worker_conns[tuple(addr)] = conn
        return conn

    async def _push_on_lease(self, key, lease, spec):
        pool = self.lease_pools[key]
        lease["busy"] = True
        self._inflight_tasks[spec["task_id"]] = (lease, spec)
        try:
            reply = await lease["conn"].request("push_task", {
                "spec": spec, "lease_id": lease["lease_id"],
                "tpu_ids": lease.get("tpu_ids") or []}, timeout=None)
            self._record_results(spec, reply)
        except Exception as e:
            if spec.get("cancelled"):
                # _cancel already dropped this lease; don't double-kill.
                self._complete_with_error(spec, rexc.TaskCancelledError(
                    f"task {spec['task_id'].hex()[:8]} cancelled"))
                self._pump(key)
                return
            self._drop_lease(key, lease)
            retries = spec.get("max_retries", 0)
            if retries != 0 and _is_system_error(e):
                spec["max_retries"] = retries - 1 if retries > 0 else retries
                logger.info("retrying task %s after worker failure: %s",
                            spec["name"] or spec["task_id"].hex()[:8], e)
                pool.queue.append(spec)
            else:
                self._complete_with_error(spec, e)
            self._pump(key)
            return
        finally:
            self._inflight_tasks.pop(spec["task_id"], None)
        lease["busy"] = False
        if pool.queue:
            pool.idle.append(lease)
            self._pump(key)
        else:
            self._schedule_lease_return(key, lease)
            pool.idle.append(lease)

    def _schedule_lease_return(self, key, lease):
        """Linger briefly before returning the lease: a tight
        submit/get loop re-uses it without a fresh lease round trip."""
        pool = self.lease_pools[key]
        handle = self.loop.call_later(
            0.02, lambda: self.loop.create_task(
                self._return_lease(key, lease)))
        pool.return_timers[lease["lease_id"]] = handle

    async def _return_lease(self, key, lease):
        pool = self.lease_pools.get(key)
        if pool is None:
            return
        # The timer may have FIRED before _pump claimed the lease for a
        # new task (cancel() on a fired handle is a no-op).  _pump pops
        # return_timers when it claims — if our entry is gone, the lease
        # is busy again: returning it now would reclaim the worker
        # mid-push.
        if lease["lease_id"] not in pool.return_timers:
            return
        if lease in pool.idle:
            pool.idle.remove(lease)
        pool.all.pop(lease["lease_id"], None)
        pool.return_timers.pop(lease["lease_id"], None)
        try:
            await lease["raylet"].request("return_worker",
                                          {"lease_id": lease["lease_id"]})
        except Exception:
            pass

    def _drop_lease(self, key, lease):
        pool = self.lease_pools.get(key)
        if pool is None:
            return
        if lease in pool.idle:
            pool.idle.remove(lease)
        pool.all.pop(lease["lease_id"], None)
        try:
            self.loop.create_task(
                lease["raylet"].request("return_worker",
                                        {"lease_id": lease["lease_id"],
                                         "kill": True}))
        except Exception:
            pass

    def _record_results(self, spec, reply):
        self._unpin_args(spec.get("task_id"))
        if "error" in reply:
            blob = reply["error"]
            for oid in spec["return_ids"]:
                entry = self.owned.get(oid)
                if entry is not None:
                    entry.blob = blob
                    entry.state = ERRORED  # last: lock-free readers
                    entry.set_ready()
            return
        for oid, result in zip(spec["return_ids"], reply["results"]):
            entry = self.owned.get(oid)
            kind = result[0]
            if entry is None:
                if kind == "dynamic":
                    # The visible generator ref was released but
                    # deserialized sub-refs keep their own stakes: a
                    # reconstruction get() may be parked on one of
                    # them.  Refresh the surviving sub entries so those
                    # waiters unblock (skipping this was a permanent
                    # hang: the re-executed generator's results were
                    # dropped here and the PENDING subs never fired).
                    self._record_dynamic_children(result[1], entry=None)
                continue
            if kind == "inline":
                entry.blob = result[1]
                entry.size = len(result[1])
                entry.state = INLINE  # last: lock-free readers order on it
            elif kind == "dynamic":
                # Generator task: register each yielded object as owned
                # HERE (the caller is the owner, as for static returns),
                # then resolve the visible ref to an ObjectRefGenerator.
                # Lineage: subs carry the creating task's spec, so a
                # lost store-resident yield re-executes the generator
                # (recovery re-enters this branch and updates the SAME
                # entry objects in place — waiters' events fire).
                sub_refs, children = self._record_dynamic_children(
                    result[1], entry=entry)
                entry.dynamic_children = children
                from ray_tpu._private.object_ref import ObjectRefGenerator
                blob, _ = serialization.serialize(
                    ObjectRefGenerator(sub_refs))
                entry.blob = blob.to_bytes()
                entry.size = len(entry.blob)
                entry.state = INLINE
            else:  # ("store", node_id, size)
                entry.location = result[1]
                entry.size = result[2]
                entry.state = IN_STORE
            entry.set_ready()

    def _record_dynamic_children(self, records, entry):
        """Register/refresh the per-yield objects of a dynamic-returns
        task.  With `entry` (the task's main owned entry) present this
        is first registration: unknown subs are created and pinned for
        the main entry's lifetime.  With `entry=None` (re-execution
        after the outer ref was released) only subs somebody still owns
        are updated in place — their fresh events fire and parked
        recovery get()s resume."""
        sub_refs = []
        children = []
        for rec in records:
            sub_oid = ObjectID(rec[0])
            sub = self.owned.get(sub_oid)
            if sub is None:
                if entry is None:
                    continue  # released sub of a released generator
                sub = OwnedObject()
            if entry is not None:
                if sub.local_refs == 0:
                    # First registration: the pin lives until the
                    # MAIN entry is released (dynamic_children).
                    sub.local_refs = 1
                sub.submitted_task = entry.submitted_task
            if rec[1] == "inline":
                sub.blob = rec[2]
                sub.size = len(rec[2])
                sub.location = None
                sub.state = INLINE
            else:  # (oid, "store", node_id, size)
                sub.location = rec[2]
                sub.size = rec[3]
                sub.state = IN_STORE
            self.owned[sub_oid] = sub
            sub.set_ready()
            children.append(sub_oid)
            # _track=False: the pin above IS the ownership
            # stake — a tracked temp here would decrement it to
            # zero on GC and drop the entry.
            sub_refs.append(ObjectRef(sub_oid, owner_addr=self.addr))
        return sub_refs, children

    # ------------------------------------------------- blocked notifications
    def _notify_blocked(self):
        ctx = self.exec_ctx
        ctx.blocked_depth += 1
        if (self.mode == MODE_WORKER and ctx.blocked_depth == 1
                and ctx.lease_id is not None and self.raylet is not None):
            try:
                self._call(self.raylet.request("worker_blocked",
                                               {"lease_id": ctx.lease_id}))
            except Exception:
                pass

    def _notify_unblocked(self):
        ctx = self.exec_ctx
        ctx.blocked_depth -= 1
        if (self.mode == MODE_WORKER and ctx.blocked_depth == 0
                and ctx.lease_id is not None and self.raylet is not None):
            try:
                self._call(self.raylet.request("worker_unblocked",
                                               {"lease_id": ctx.lease_id}))
            except Exception:
                pass

    # ======================================================== EXECUTION SIDE
    async def _exec_on_serial_pool(self, pool, fn, *args):
        """run_in_executor replacement for SINGLE-thread pools: a burst
        of queued calls is drained by ONE pool submission (one futex
        wake instead of one per call), and results return to the loop
        through the coalesced _post queue (one self-pipe wake per
        drain).  Execution order on the pool thread == dispatch order —
        the property the serial pools exist for."""
        st = self._exec_states.get(id(pool))
        if st is None:
            st = self._exec_states[id(pool)] = {
                "q": deque(), "armed": False, "pool": pool}
        fut = self.loop.create_future()
        st["q"].append((fn, args, fut))
        if not st["armed"]:
            st["armed"] = True
            pool.submit(self._exec_drain, st)
        return await fut

    def _exec_drain(self, st):  # pool thread
        q = st["q"]
        while True:
            try:
                fn, args, fut = q.popleft()
            except IndexError:
                # Disarm FIRST, then re-check: an append racing the
                # disarm either sees armed and leaves the item to us, or
                # arms a fresh drain — never a stranded item.
                st["armed"] = False
                if q and not st["armed"]:
                    st["armed"] = True
                    continue
                return
            try:
                result, err = fn(*args), None
            except BaseException as e:
                # BaseException: SystemExit/_ActorExit must reach the
                # loop-side awaiter exactly as run_in_executor delivered
                # them (they terminate the worker there).
                result, err = None, e
            self._post(self._finish_serial_exec, fut, result, err)

    @staticmethod
    def _finish_serial_exec(fut, result, err):  # loop thread
        if fut.done():
            return
        if err is not None:
            fut.set_exception(err)
        else:
            fut.set_result(result)

    async def rpc_push_task(self, conn, body):
        spec = body["spec"]
        lease_id = body.get("lease_id")
        return await self._exec_on_serial_pool(
            self._task_pool, self._execute_task_sync, spec, lease_id,
            body.get("tpu_ids") or [])

    def _execute_task_sync(self, spec, lease_id, tpu_ids=()):
        ctx = self.exec_ctx
        ctx.task_id = spec["task_id"]
        ctx.lease_id = lease_id
        ctx.tpu_ids = list(tpu_ids)
        t0 = time.time()
        restore_env = None
        span = self._enter_span(spec.get("trace"))
        try:
            restore_env = self._apply_runtime_env(spec.get("runtime_env"))
            fn = self._load_function(spec["fn_id"])
            args, kwargs = self._unpack_args(spec["args"])
            result = fn(*args, **kwargs)
            return self._pack_results(result, spec)
        except Exception as e:
            return {"error": _error_blob(e, traceback.format_exc())}
        finally:
            if restore_env is not None:
                restore_env()
            self._record_profile_event(
                "task", spec.get("name") or getattr(
                    self._fn_cache.get(spec["fn_id"]), "__name__", "task"),
                t0, trace=span)
            ctx.task_id = None
            ctx.lease_id = None
            ctx.tpu_ids = []

    @staticmethod
    def _enter_span(trace, cat: str = "task"):
        """Adopt the submitter's trace context with a fresh span id so
        tasks submitted from here link as children; closes the
        submit-side flow edge (chrome ph "s"/"f" pair)."""
        return _tracing.adopt(trace, cat)

    def _record_profile_event(self, cat: str, name: str, t0: float,
                              trace=None):
        """Chrome-trace complete event (reference: core worker profiling
        events, src/ray/core_worker/profiling.h) into the bounded
        process ring — drop-oldest with a counted, exported drop total
        (was: a bare list that silently deleted half its buffer at
        10k).  Trace args link spans across processes."""
        _tracing.record(cat, name, t0, time.time() - t0, trace=trace)

    async def rpc_dump_trace(self, conn, body):
        """Pull-path trace dump: drain (or stat) this process's span
        ring on demand — the authoritative source for rt timeline
        --cluster / rt trace (the telemetry KV push is a truncated,
        lagging convenience view)."""
        body = body or {}
        return _tracing.dump(stats_only=bool(body.get("stats_only")),
                             clear=bool(body.get("clear")))

    def _load_function(self, fn_id: bytes):
        fn = self._fn_cache.get(fn_id)
        if fn is None:
            reply = self._run(self._gcs_request(
                "kv_get", {"ns": "funcs", "key": fn_id}))
            if reply["value"] is None:
                raise rexc.RayTpuError(f"function {fn_id.hex()} not found")
            fn = serialization.loads_function(reply["value"])
            self._fn_cache[fn_id] = fn
        return fn

    def _get_arg(self, ref):
        """Fetch a task argument without IMMEDIATELY taking the
        blocked-worker CPU release.

        The CPU release exists so user code calling get() on a
        not-yet-scheduled task can't deadlock the pool — but releasing
        it for every arg fetch lets the raylet admit another task whose
        pinned args deepen the very memory pressure stalling the fetch
        (observed: 7 concurrent tasks on a 2-CPU node, the arena 100%
        pinned by their args, every create wedged).  Submitter-owned
        args are dispatch-gated on readiness (_wait_args_ready), so the
        short first attempt covers them; borrowed refs and actor-task
        args are NOT gated, so after the grace window this falls back to
        the releasing path — a fetch truly waiting on an unscheduled
        producer still frees its CPU and the pool keeps moving."""
        try:
            return self._run(self._get_async_list(
                [ref], 2.0, trace=_tracing.current_dict()))[0]
        except Exception:
            pass
        return self.get(ref)

    def _unpack_args(self, args_blob):
        args, kwargs = serialization.deserialize(args_blob)
        args = [self._get_arg(a.ref) if isinstance(a, _RefArg) else a
                for a in args]
        kwargs = {k: (self._get_arg(v.ref) if isinstance(v, _RefArg) else v)
                  for k, v in kwargs.items()}
        return args, kwargs

    def _pack_results(self, result, spec):
        num_returns = spec["num_returns"]
        if num_returns == 0:
            return {"results": []}
        if num_returns == -1:  # num_returns="dynamic": generator task
            import inspect as _inspect
            # Require an actual generator/iterator — a returned str or
            # ndarray is iterable but exploding it into per-element
            # refs is never what the caller meant.
            if not (_inspect.isgenerator(result)
                    or hasattr(result, "__next__")):
                raise TypeError(
                    'num_returns="dynamic" tasks must return a '
                    f"generator/iterator, got {type(result).__name__}")
            task_id = spec["task_id"]
            dyn = []
            for i, value in enumerate(result):
                oid = ObjectID.for_task_return(task_id, i + 1)
                dyn.append((oid.binary(),) + self._pack_one(oid, value))
            return {"results": [("dynamic", dyn)]}
        if num_returns == 1:
            values = [result]
        else:
            values = list(result)
            if len(values) != num_returns:
                raise ValueError(
                    f"task declared num_returns={num_returns} but returned "
                    f"{len(values)} values")
        out = []
        for oid, value in zip(spec["return_ids"], values):
            out.append(self._pack_one(oid, value))
        return {"results": out}

    def _pack_one(self, oid, value):
        """Serialize one return: inline for small values, sealed into
        the local store otherwise."""
        blob, _ = serialization.serialize(value)
        size = blob.total_size()
        if size <= cfg.max_direct_call_object_size or self.raylet is None:
            return ("inline", blob.to_bytes())
        offset = self._run(self._store_create(oid.binary(), size))
        if offset is not None:
            blob.write_into(self.mapping.slice(offset, size))
            self._run(self.raylet.request("os_seal",
                                          {"oid": oid.binary()}))
        return ("store", self.node_id, size)

    # --------------------------------------------------------------- actors
    async def rpc_create_actor(self, conn, body):
        spec = body["spec"]
        self.actor_id = body["actor_id"]
        # Actor-lifetime device grant: every method call of this actor
        # sees the same chip indices (reference: actors keep their GPU
        # ids for their whole lifetime).
        self._actor_tpu_ids = list(body.get("tpu_ids") or [])
        try:
            result = await self.loop.run_in_executor(
                self._task_pool, self._create_actor_sync, spec)
            return result
        except Exception as e:
            return {"ok": False, "error": repr(e),
                    "error_blob": _error_blob(e, traceback.format_exc())}

    def _create_actor_sync(self, spec):
        try:
            self._apply_runtime_env(spec.get("runtime_env"))
            cls = self._load_function(spec["class_id"])
            args, kwargs = self._unpack_args(spec["init_args"])
            import inspect
            self.actor_instance = cls(*args, **kwargs)
            self._actor_is_async = any(
                inspect.iscoroutinefunction(m)
                for _, m in inspect.getmembers(
                    cls, predicate=inspect.isfunction))
            self._max_concurrency = spec.get("max_concurrency") or (
                1000 if self._actor_is_async else 1)
            groups = dict(spec.get("concurrency_groups") or {})
            # Sync methods always need a thread pool — an "async" actor can
            # still define plain def methods (async sems are made lazily).
            self._actor_pools["_default"] = ThreadPoolExecutor(
                max_workers=(1 if self._actor_is_async
                             else self._max_concurrency),
                thread_name_prefix="actor")
            for name, n in groups.items():
                self._actor_pools[name] = ThreadPoolExecutor(
                    max_workers=n, thread_name_prefix=f"actor-{name}")
            self._concurrency_groups = groups
            return {"ok": True}
        except Exception as e:
            return {"ok": False, "error": repr(e),
                    "error_blob": _error_blob(e, traceback.format_exc())}

    async def rpc_push_actor_task(self, conn, body):
        """Ordered actor-task execution (reference: ActorSchedulingQueue —
        per-caller sequence numbers ensure submission order)."""
        caller = body["caller_id"]
        seq = body["seq"]
        expected = self._caller_seq.get(caller, 0)
        if seq < expected:
            # Wire-level duplicate of a frame this stream already
            # consumed (dup'd frame, retransmit): NEVER re-execute.
            # Replays after an actor restart are not this case —
            # recovery re-mints fresh seqs for the unacked window, so
            # they arrive in-stream and run normally.  If the original
            # dispatch is still running we must ride its result: both
            # replies share the duplicated frame's msg_id, so a bare
            # ack could reach the caller FIRST and the real reply
            # (carrying the task's results) would then be dropped as a
            # stale msg_id — the results would be lost, not just the
            # frame deduped.  Once the original has completed, its
            # reply is already on the wire ahead of ours (same conn,
            # FIFO), so a generic ack is safe.
            w = self._dup_waiter(caller, seq)
            if w is not None:
                return await w
            return {"ok": True, "duplicate": True}
        if seq != expected:
            fut = self.loop.create_future()
            heapq.heappush(self._caller_buffer.setdefault(caller, []),
                           (seq, id(fut), fut, body))
            return await fut
        return await self._run_actor_task_in_order(caller, body)

    def _dup_waiter(self, caller, seq):
        """A future riding the still-running original dispatch of
        ``seq``, or None when that dispatch already completed (its
        reply is then already ahead of any ack on the wire)."""
        running = self._caller_running.get(caller)
        if not running or seq not in running:
            return None
        w = self.loop.create_future()
        self._dup_waiters.setdefault((caller, seq), []).append(w)
        return w

    def _finish_caller_task(self, caller, seq, result, exc):
        """Retire a tracked dispatch and resolve any duplicate-frame
        waiters with the same outcome.  The hot path (no duplicates
        anywhere) pays one set.discard and one empty-dict truth test."""
        running = self._caller_running.get(caller)
        if running is not None:
            running.discard(seq)
            if not running:
                self._caller_running.pop(caller, None)
        if self._dup_waiters:
            for w in self._dup_waiters.pop((caller, seq), ()):
                if w.cancelled():
                    continue
                if exc is not None:
                    w.set_exception(exc)
                else:
                    w.set_result(result)

    async def _run_tracked(self, caller, body):
        """_dispatch_actor_task plus duplicate-frame bookkeeping (the
        seq must already be in _caller_running)."""
        seq = body["seq"]
        try:
            result = await self._dispatch_actor_task(body)
        except BaseException as e:
            self._finish_caller_task(caller, seq, None, e)
            raise
        self._finish_caller_task(caller, seq, result, None)
        return result

    async def _run_actor_task_in_order(self, caller, body):
        seq = body["seq"]
        self._caller_seq[caller] = seq + 1
        self._caller_running.setdefault(caller, set()).add(seq)
        # Release any buffered next-in-line tasks.
        buf = self._caller_buffer.get(caller)
        if not buf:
            # Nothing buffered (the overwhelmingly common case): await
            # the dispatch directly — no Task allocation.  A successor
            # arriving mid-dispatch sees the advanced seq and dispatches
            # itself; only out-of-order arrivals need the buffer path.
            # (Tracking is inlined too: no wrapper coroutine here.)
            try:
                result = await self._dispatch_actor_task(body)
            except BaseException as e:
                self._finish_caller_task(caller, seq, None, e)
                raise
            self._finish_caller_task(caller, seq, result, None)
            return result
        task = self.loop.create_task(self._run_tracked(caller, body))
        # ONE release loop for both cases, because they interleave: a
        # buffered duplicate of a seq released *by this very loop*
        # surfaces at the heap front between releases, and two split
        # loops would neither ack it nor reach the entries behind it
        # (stranding the caller's whole stream).  Duplicates (< seq)
        # are never dispatched: they ride the original's still-running
        # result or get a generic ack; next-in-line entries dispatch
        # and advance the stream.
        while buf:
            expected = self._caller_seq[caller]
            if buf[0][0] < expected:
                _seq, _tie, fut, _dup = heapq.heappop(buf)
                if fut.cancelled():
                    continue
                w = self._dup_waiter(caller, _seq)
                if w is None:
                    fut.set_result({"ok": True, "duplicate": True})
                else:
                    def _ride(t, f=fut):
                        if f.cancelled():
                            return
                        if t.exception() is not None:
                            f.set_exception(t.exception())
                        else:
                            f.set_result(t.result())
                    w.add_done_callback(_ride)
                continue
            if buf[0][0] != expected:
                break
            _seq, _tie, fut, nxt = heapq.heappop(buf)
            self._caller_seq[caller] = nxt["seq"] + 1
            self._caller_running.setdefault(caller, set()).add(nxt["seq"])
            nxt_task = self.loop.create_task(self._run_tracked(caller, nxt))

            def _transfer(t, f=fut):
                if f.cancelled():
                    return
                if t.exception() is not None:
                    f.set_exception(t.exception())
                else:
                    f.set_result(t.result())
            nxt_task.add_done_callback(_transfer)
        return await task

    async def _dispatch_actor_task(self, body):
        method_name = body["method"]
        group = body.get("concurrency_group") or "_default"
        if self.actor_instance is None:
            return {"error": _error_blob(
                rexc.ActorDiedError(self.actor_id, "actor not initialized"))}
        method = getattr(self.actor_instance, method_name, None)
        if method is None:
            return {"error": _error_blob(AttributeError(
                f"actor has no method {method_name}"))}
        import inspect
        spec = {"task_id": body["task_id"], "num_returns": body["num_returns"],
                "return_ids": body["return_ids"]}
        if inspect.iscoroutinefunction(method):
            sem = self._actor_async_sems.get(group)
            if sem is None:
                n = (self._concurrency_groups.get(group)
                     if group != "_default" else None) or self._max_concurrency
                sem = self._actor_async_sems[group] = asyncio.Semaphore(n)
            async with sem:
                # Async actor methods adopt the caller's trace context
                # too (was: only the sync-pool paths recorded spans, so
                # every async actor call — serve replicas included —
                # was a tracing hole and broke trace continuity).
                t0 = time.time()
                # Default "task" cat: the submit-side flow_start used it,
                # and chrome matches flow pairs by (cat, name, id).
                span = self._enter_span(body.get("trace"))
                try:
                    args, kwargs = await self.loop.run_in_executor(
                        None, self._unpack_args, body["args"])
                    result = await method(*args, **kwargs)
                    return await self.loop.run_in_executor(
                        None, self._pack_results, result, spec)
                except Exception as e:
                    return {"error": _error_blob(e, traceback.format_exc())}
                finally:
                    self._record_profile_event(
                        "actor_task", body["method"], t0, trace=span)
        pool = self._actor_pools.get(group) or self._actor_pools["_default"]
        if pool._max_workers == 1:
            # The common sync-actor shape: drain-batched serial dispatch
            # (order-preserving; see _exec_on_serial_pool).
            return await self._exec_on_serial_pool(
                pool, self._execute_actor_method_sync, method, body, spec)
        return await self.loop.run_in_executor(
            pool, self._execute_actor_method_sync, method, body, spec)

    def _execute_actor_method_sync(self, method, body, spec):
        t0 = time.time()
        span = self._enter_span(body.get("trace"))
        try:
            args, kwargs = self._unpack_args(body["args"])
            result = method(*args, **kwargs)
            return self._pack_results(result, spec)
        except Exception as e:
            if isinstance(e, SystemExit) or isinstance(e, _ActorExit):
                raise
            return {"error": _error_blob(e, traceback.format_exc())}
        finally:
            self._record_profile_event("actor_task", body["method"], t0,
                                       trace=span)

    # --------------------------------------------------- actor-caller side
    def submit_actor_task(self, actor_id: ActorID, actor_addr, method: str,
                          args, kwargs, num_returns=1, opts=None):
        """Hot path: build the spec from a cached per-(actor, method)
        template — only task id / args / return ids / trace / seq vary
        per call — and hand it to the actor's send queue with ONE loop
        hop.  Sequencing, wire writes, and reply handling all live on
        the loop side (_actor_pump / _on_actor_reply)."""
        opts = opts or {}
        task_id = TaskID.for_submit()
        refs = []
        return_ids = []
        for i in range(num_returns):
            oid = ObjectID.for_task_return(task_id, i)
            entry = OwnedObject()
            entry.local_refs = 1
            self.owned[oid] = entry
            return_ids.append(oid)
            refs.append(ObjectRef(oid, owner_addr=self.addr, _track=True))
        args_blob = self._pack_args(args, kwargs)
        self._pin_args(task_id, args, kwargs)
        tkey = (actor_id, method, num_returns, opts.get("concurrency_group"))
        tmpl = self._actor_spec_templates.get(tkey)
        if tmpl is None:
            tmpl = self._actor_spec_templates[tkey] = ActorTaskSpec.new(
                task_id=None,
                method=method,
                args_blob=None,
                trace=None,
                num_returns=num_returns,
                return_ids=None,
                caller_id=self.worker_id.binary(),
                concurrency_group=opts.get("concurrency_group"),
                owner_addr=self.addr,
            )
        body = ActorTaskSpec(tmpl)
        body["task_id"] = task_id
        body["args"] = args_blob
        body["return_ids"] = return_ids
        body["trace"] = _trace_for_submit()
        if "flow" in body["trace"]:
            _tracing.flow_start(body["trace"]["flow"])
        entry = {"body": body, "retries": opts.get("max_task_retries", 0),
                 "attempts": 0, "fut": None, "seq": None, "conn": None,
                 "failed": None, "cancelled": False, "driver": False}
        self._post(self._actor_enqueue, actor_id, actor_addr, entry)
        return refs

    def _actor_enqueue(self, actor_id, actor_addr, entry):
        """Loop side of submit_actor_task: append to the actor's send
        queue (creating queue + pump on first use) and wake the pump."""
        q = self._actor_queues.get(actor_id)
        if q is None:
            q = self._actor_queues[actor_id] = _ActorSendQueue()
            q.pump = self.loop.create_task(self._actor_pump(actor_id, q))
            q.pump.add_done_callback(lambda t: t.cancelled() or t.exception())
        if actor_addr is not None and q.addr_hint is None:
            q.addr_hint = actor_addr
        q.pending.append(entry)
        for oid in entry["body"]["return_ids"]:
            self._actor_queued_refs[oid] = entry
        w = q.waiter
        if w is not None and not w.done():
            w.set_result(None)

    _ACTOR_SEND_BURST = 32

    async def _actor_pump(self, actor_id, q: _ActorSendQueue):
        """The one sender for this actor: drains the queue FIFO, assigns
        sequence numbers at dequeue, and writes bursts as one KIND_BATCH
        frame.  Between the seq assignment and the wire write nothing
        yields, so wire order always equals sequence order — the
        per-call lock of the old submitter is unnecessary here."""
        while not self._shutdown:
            if not q.pending:
                q.waiter = self.loop.create_future()
                try:
                    await q.waiter
                finally:
                    q.waiter = None
                continue
            # Never interleave fresh sends with an in-flight window
            # replay: replayed entries were submitted first and must
            # keep their place in the sequence stream.
            rec = self._actor_recovering.get(actor_id)
            if rec is not None:
                try:
                    await asyncio.shield(rec)
                except Exception:
                    pass
                continue
            conn = self._actor_conns.get(actor_id)
            if conn is None or conn.closed:
                # A (re)connect means a possibly new incarnation: replay
                # the unacked window FIRST so newer queued calls keep
                # their place behind it (submission order across
                # restart).  Entries stay IN the queue — and therefore
                # cancellable — until a live connection is in hand.
                if self._actor_unacked.get(actor_id):
                    try:
                        await self._actor_recover(actor_id, conn)
                    except Exception:
                        pass
                try:
                    conn = await self._actor_conn(actor_id, q.addr_hint)
                except Exception as e:
                    # No reachable incarnation: hand every queued entry
                    # to the retry/recovery slow path (each applies its
                    # own budget and terminal-death handling).
                    while q.pending:
                        entry = q.pending.popleft()
                        for oid in entry["body"]["return_ids"]:
                            self._actor_queued_refs.pop(oid, None)
                        if not entry["cancelled"]:
                            self._spawn_actor_entry_driver(actor_id,
                                                           entry, e)
                    continue
                continue  # re-check recovery state before sending
            batch = []
            while q.pending and len(batch) < self._ACTOR_SEND_BURST:
                entry = q.pending.popleft()
                for oid in entry["body"]["return_ids"]:
                    self._actor_queued_refs.pop(oid, None)
                if entry["cancelled"]:
                    continue  # returns already completed by cancel
                batch.append(entry)
            if not batch:
                continue
            try:
                una = self._actor_unacked.setdefault(actor_id, {})
                base = self._actor_seq.get(actor_id, 0)
                if len(batch) == 1:
                    batch[0]["body"]["seq"] = base
                    futs = [conn.request_send_nowait("push_actor_task",
                                                     batch[0]["body"])]
                else:
                    for i, entry in enumerate(batch):
                        entry["body"]["seq"] = base + i
                    futs = conn.request_send_many_nowait(
                        "push_actor_task", [e["body"] for e in batch])
                self._actor_seq[actor_id] = base + len(batch)
                for entry, fut in zip(batch, futs):
                    entry["seq"] = entry["body"]["seq"]
                    entry["conn"] = conn
                    entry["fut"] = fut
                    una[entry["seq"]] = entry
                    fut.add_done_callback(functools.partial(
                        self._on_actor_reply, actor_id, entry))
            except Exception as e:
                # The write never hit the wire (the nowait senders are
                # all-or-nothing) and the seq stream was not committed:
                # run each entry through the retry/recovery slow path.
                for entry in batch:
                    entry["fut"] = None
                    entry["conn"] = None
                    entry["seq"] = None
                    entry["body"].pop("seq", None)
                    self._spawn_actor_entry_driver(actor_id, entry, e)
                continue
            try:
                # Throttle at the transport's high-water mark: a stalled
                # actor must not let this queue buffer frames unbounded.
                # (The batch is already on the wire/window — a failure
                # here surfaces through the reply futures, not by
                # re-driving the entries.)
                await conn.backpressure()
            except Exception:
                pass

    def _maybe_evict_actor_queue(self, actor_id):
        """Drop the actor's send machinery (parked pump task + queue +
        spec templates) once nothing is queued or unacked — an
        actor-churn workload (launch/kill loops) must not park one task
        per dead actor forever.  Safe for live actors: the next call
        recreates the queue, and the seq stream / unacked window live in
        their own tables, which this does NOT touch."""
        if self._shutdown:
            return
        if self._actor_unacked.get(actor_id):
            return
        q = self._actor_queues.get(actor_id)
        if q is not None:
            if q.pending:
                return
            self._actor_queues.pop(actor_id, None)
            if q.pump is not None:
                q.pump.cancel()
        for key in [k for k in self._actor_spec_templates
                    if k[0] == actor_id]:
            self._actor_spec_templates.pop(key, None)

    def _on_actor_conn_close(self, actor_id, conn):
        self._maybe_evict_actor_queue(actor_id)

    def _on_actor_reply(self, actor_id, entry, fut):
        """Reply-future callback for queue-sent actor tasks (loop
        thread).  Success is recorded inline — no per-call task ever
        existed; any failure hands the entry to a driver task that owns
        the retry/recovery loop."""
        if entry["driver"] or entry["fut"] is not fut:
            return  # a driver task or a recovery resend owns this entry
        if not fut.cancelled() and fut.exception() is None:
            self._actor_unacked.get(actor_id, {}).pop(entry["seq"], None)
            body = entry["body"]
            self._record_results({"task_id": body["task_id"],
                                  "return_ids": body["return_ids"]},
                                 fut.result())
            return
        self._spawn_actor_entry_driver(actor_id, entry, None)

    def _spawn_actor_entry_driver(self, actor_id, entry, pre_error):
        entry["driver"] = True
        t = self.loop.create_task(
            self._drive_actor_entry(actor_id, entry, pre_error))
        # Failures surface through the return entries; retrieve any stray
        # exception so task GC doesn't log it.
        t.add_done_callback(lambda t: t.cancelled() or t.exception())

    async def _cancel_queued_actor(self, oid) -> bool:
        """Cancel an actor task still waiting in its send queue: the
        entry is marked (the pump skips it at dequeue) and its returns
        complete with TaskCancelledError immediately.  Returns False if
        the call already reached the wire."""
        entry = self._actor_queued_refs.get(oid)
        if entry is None:
            return False
        if entry["cancelled"]:
            return True
        entry["cancelled"] = True
        body = entry["body"]
        self._unpin_args(body["task_id"])
        blob = _error_blob(rexc.TaskCancelledError(
            f"actor task {body['task_id'].hex()[:8]} cancelled before "
            "it was sent"))
        for roid in body["return_ids"]:
            self._actor_queued_refs.pop(roid, None)
            oentry = self.owned.get(roid)
            if oentry is not None:
                oentry.blob = blob
                oentry.state = ERRORED  # last: lock-free readers
                oentry.set_ready()
        return True

    async def _actor_send(self, actor_id, actor_addr, entry):
        """Connect (or reuse), assign the next sequence number, put the
        request on the wire, and register the entry in the actor's unacked
        window — all under the per-actor lock so wire order always matches
        sequence order (reference: the direct actor submitter's send queue
        preserves submission order per caller)."""
        lock = self._actor_locks.get(actor_id)
        if lock is None:
            lock = self._actor_locks[actor_id] = asyncio.Lock()
        async with lock:
            conn = await self._actor_conn(actor_id, actor_addr)
            seq = self._actor_seq.get(actor_id, 0)
            self._actor_seq[actor_id] = seq + 1
            body = entry["body"]
            body["seq"] = seq
            entry["seq"] = seq
            entry["conn"] = conn
            try:
                entry["fut"] = await conn.request_send("push_actor_task",
                                                       body)
            except Exception:
                # The send never hit the wire: roll the sequence number
                # back (we still hold the lock, so nobody interleaved) —
                # a burned seq would wedge the actor's in-order queue.
                self._actor_seq[actor_id] = seq
                raise
            self._actor_unacked.setdefault(actor_id, {})[seq] = entry

    async def _drive_actor_entry(self, actor_id, entry, pre_error=None):
        """Slow-path driver for one entry after a failure: retry through
        the per-actor unacked window.  On a connection loss the whole
        window is held, the next incarnation is awaited (patiently — a
        restart under load may take minutes), and every entry with retry
        budget left is resent IN ORIGINAL ORDER by one shared recovery
        pass; entries out of budget fail with ActorDiedError.  -1
        retries = unbounded while the actor keeps restarting.
        Reference: direct_actor_task_submitter.h:67.

        Entered with entry["fut"] set to the failed reply future (a sent
        call whose connection died), or None (the pump could not reach
        the actor at all, `pre_error` carries why)."""
        body = entry["body"]
        retries = entry["retries"]
        first_error = pre_error
        addr = None
        while True:
            if entry["fut"] is None and entry["failed"] is None:
                # Not on a wire (pump send failed, or a resend is due):
                # send on the current incarnation.
                if retries != -1 and entry["attempts"] > max(retries, 0):
                    break
                try:
                    await self._actor_send(actor_id, addr, entry)
                except Exception as e:
                    if first_error is None:
                        first_error = e
                    entry["attempts"] += 1
                    if retries != -1 and entry["attempts"] > max(retries, 0):
                        break
                    try:
                        await self._actor_recover(actor_id, None)
                    except rexc.ActorDiedError as e2:
                        if first_error is None:
                            first_error = e2
                        break
                    except Exception:
                        pass  # transient; the budget check above bounds us
                    addr = None  # re-resolve from the GCS on the resend
                    continue
            if entry["failed"] is not None:
                break  # recovery exhausted this entry's retry budget
            fut = entry["fut"]
            try:
                reply = await fut
                self._actor_unacked.get(actor_id, {}).pop(entry["seq"], None)
                self._record_results({"task_id": body["task_id"],
                                      "return_ids": body["return_ids"]},
                                     reply)
                return
            except Exception as e:
                if first_error is None:
                    first_error = e
            if entry["fut"] is not fut or entry["failed"] is not None:
                # A concurrent recovery already resent (or failed) this
                # entry while we were waking up: act on its decision.
                continue
            if retries != -1 and entry["attempts"] >= max(retries, 0):
                break
            try:
                await self._actor_recover(actor_id, entry.get("conn"))
            except rexc.ActorDiedError as e:
                # Terminal: the GCS reported DEAD (or gave up entirely).
                if first_error is None:
                    first_error = e
                break
            except Exception as e:
                # Transient: the next incarnation crashed between the GCS
                # reporting ALIVE and our reconnect.  Consume a retry and
                # go around (the wait inside recovery throttles the loop).
                if first_error is None:
                    first_error = e
                entry["attempts"] += 1
                continue
            if entry["fut"] is fut and entry["failed"] is None:
                # Recovery declined (live connection already in place —
                # e.g. an earlier recovery crashed mid-window and lost this
                # entry): resend it ourselves on the live connection.
                self._actor_unacked.get(actor_id, {}).pop(entry["seq"], None)
                entry["fut"] = None
                entry["attempts"] += 1
        await self._finalize_actor_entry(actor_id, entry, first_error)

    async def _finalize_actor_entry(self, actor_id, entry, first_error):
        """Terminal failure: complete the entry's returns with
        ActorDiedError carrying the best-known cause."""
        body = entry["body"]
        self._actor_unacked.get(actor_id, {}).pop(entry.get("seq"), None)
        view = await self._wait_actor_alive(actor_id, overall_timeout=1.0)
        cause = (entry["failed"]
                 or (_death_cause_from_view(view)
                     if isinstance(first_error, protocol.ConnectionLost)
                     else None)
                 or str(first_error))
        err = rexc.ActorDiedError(actor_id, cause)
        blob = _error_blob(err)
        self._unpin_args(body["task_id"])
        for oid in body["return_ids"]:
            oentry = self.owned.get(oid)
            if oentry is not None:
                oentry.blob = blob
                oentry.state = ERRORED  # last: lock-free readers
                oentry.set_ready()
        # Terminal failures usually mean a dead actor: reap its parked
        # send machinery once the last entry settles.
        self._maybe_evict_actor_queue(actor_id)

    async def _actor_recover(self, actor_id, failed_conn):
        """Single-flight per actor: wait for the next ALIVE incarnation,
        reconnect, and resend the entire unacked window in original-seq
        order.  Entries whose retry budget is exhausted are marked failed
        instead of resent.  Raises if the actor is terminally DEAD.

        `failed_conn` is the connection the caller observed failing; if
        the current connection is already a LIVE different one, another
        recovery has run and this call is a no-op (resending the window
        over a live connection would double-execute tasks)."""
        rec = self._actor_recovering.get(actor_id)
        if rec is not None:
            await asyncio.shield(rec)
            return
        cur = self._actor_conns.get(actor_id)
        if (cur is not None and not cur.closed
                and (failed_conn is None or cur is not failed_conn)):
            return
        rec = self.loop.create_future()
        self._actor_recovering[actor_id] = rec
        try:
            stale = self._actor_conns.get(actor_id)
            view = await self._wait_actor_alive(actor_id)
            if (view is None or view.get("state") != "ALIVE"
                    or view.get("addr") is None):
                raise rexc.ActorDiedError(
                    actor_id, _death_cause_from_view(view) or "not found")
            lock = self._actor_locks.get(actor_id)
            if lock is None:
                lock = self._actor_locks[actor_id] = asyncio.Lock()
            async with lock:
                conn = self._actor_conns.get(actor_id)
                if conn is stale or (conn is not None and conn.closed):
                    self._actor_conns.pop(actor_id, None)
                # _actor_conn resets the seq stream on address change.
                conn = await self._actor_conn(actor_id, tuple(view["addr"]))
                unacked = self._actor_unacked.get(actor_id) or {}
                entries = [unacked[s] for s in sorted(unacked)]
                unacked.clear()
                for ent in entries:
                    ent["attempts"] += 1
                    r = ent["retries"]
                    if r != -1 and ent["attempts"] > max(r, 0):
                        ent["failed"] = ("task was submitted to a previous "
                                         "incarnation and is out of retries")
                        ent["fut"] = None
                        if not ent.get("driver"):
                            # No driver task is watching this entry (it
                            # was queue-sent and its reply callback
                            # already fired): complete its returns here.
                            t = self.loop.create_task(
                                self._finalize_actor_entry(
                                    actor_id, ent, None))
                            t.add_done_callback(
                                lambda t: t.cancelled() or t.exception())
                        continue
                    seq = self._actor_seq.get(actor_id, 0)
                    self._actor_seq[actor_id] = seq + 1
                    ent["body"]["seq"] = seq
                    ent["seq"] = seq
                    ent["fut"] = await conn.request_send("push_actor_task",
                                                         ent["body"])
                    unacked[seq] = ent
                    if not ent.get("driver"):
                        ent["fut"].add_done_callback(functools.partial(
                            self._on_actor_reply, actor_id, ent))
            rec.set_result(None)
        except Exception as e:
            rec.set_exception(e)
            raise
        finally:
            self._actor_recovering.pop(actor_id, None)
            if not rec.done():
                rec.set_result(None)

    async def _wait_actor_alive(self, actor_id, overall_timeout=None):
        """Wait until the actor is in a TERMINAL-for-us state: ALIVE or
        DEAD.  A restart in progress (RESTARTING/PENDING) keeps waiting up
        to `overall_timeout` (default cfg.actor_restart_wait_s) instead of
        being misread as death — a restart on a loaded host can take far
        longer than one RPC's patience."""
        overall = (overall_timeout if overall_timeout is not None
                   else cfg.actor_restart_wait_s)
        deadline = time.monotonic() + overall
        view = None
        while True:
            remain = deadline - time.monotonic()
            if remain <= 0:
                return view
            try:
                view = await self._gcs_request(
                    "wait_actor_alive",
                    {"actor_id": actor_id, "timeout": min(30.0, remain)})
            except Exception:
                return view
            if view is None or view.get("state") in ("ALIVE", "DEAD"):
                return view

    async def _actor_conn(self, actor_id, actor_addr):
        """Resolve a live connection to the actor.  Only call while holding
        the per-actor lock.  A reconnect to a *different* address means a new
        actor incarnation: the sequence stream restarts at 0."""
        conn = self._actor_conns.get(actor_id)
        if conn is not None and not conn.closed:
            return conn
        if actor_addr is None or (conn is not None and conn.closed):
            view = await self._wait_actor_alive(actor_id)
            if view is None or view.get("addr") is None or \
                    view.get("state") != "ALIVE":
                raise rexc.ActorDiedError(
                    actor_id, _death_cause_from_view(view) or "not found")
            actor_addr = tuple(view["addr"])
        if self._actor_addr_cache.get(actor_id) not in (None, tuple(actor_addr)):
            self._actor_seq[actor_id] = 0  # new incarnation, new stream
        conn = await protocol.Connection.connect(
            actor_addr[0], actor_addr[1], handler=self._handle,
            name="cw->actor", timeout=cfg.connect_timeout_s,
            on_close=functools.partial(self._on_actor_conn_close,
                                       actor_id))
        self._actor_conns[actor_id] = conn
        self._actor_addr_cache[actor_id] = tuple(actor_addr)
        return conn

    def create_actor(self, class_id: bytes, init_args, init_kwargs,
                     opts: dict) -> ActorID:
        actor_id = ActorID.from_random()
        init_blob = self._pack_args(init_args, init_kwargs)
        pg = opts.get("placement_group")
        spec = ActorCreationSpec.new(
            class_id=class_id,
            class_name=opts.get("class_name", ""),
            init_blob=init_blob,
            resources=_normalize_resources(opts, actor=True),
            max_restarts=opts.get("max_restarts",
                                  cfg.actor_max_restarts_default),
            max_concurrency=opts.get("max_concurrency"),
            concurrency_groups=opts.get("concurrency_groups"),
            name=opts.get("name"),
            namespace=opts.get("namespace", "default"),
            detached=opts.get("lifetime") == "detached",
            scheduling_strategy=_strategy_dict(
                opts.get("scheduling_strategy")),
            runtime_env=(self._pack_runtime_env(opts["runtime_env"])
                         if opts.get("runtime_env") else None),
            placement_group_id=pg.id if pg is not None else None,
            bundle_index=(opts.get("placement_group_bundle_index")
                          if pg is not None else None),
        )
        reply = self._run(self._gcs_request("create_actor", {
            "actor_id": actor_id, "spec": spec, "job_id": self.job_id}))
        if not reply.get("ok"):
            raise ValueError(reply.get("reason", "actor creation failed"))
        return actor_id

    # ------------------------------------------------------------ misc rpc
    async def rpc_ping(self, conn, body):
        return {"ok": True, "mode": self.mode}

    async def rpc_set_failpoints(self, conn, body):
        """Runtime fault-plane toggle: tests flip failpoints / partition
        rules on a live worker mid-run (see failpoints.apply_rpc)."""
        return failpoints.apply_rpc(body)

    async def rpc_exit(self, conn, body):
        asyncio.get_running_loop().call_later(0.05, os._exit, 0)
        return {"ok": True}


class _ActorExit(SystemExit):
    pass


class _SerializedError:
    """Wrapper stored as the value of errored objects; raising happens at
    get() (reference: RayTaskError stored as the object value)."""

    def __init__(self, exc: Exception | None, repr_str: str, tb: str):
        self.exc = exc
        self.repr_str = repr_str
        self.tb = tb

    def to_exception(self) -> Exception:
        if isinstance(self.exc, (rexc.ActorError, rexc.ObjectLostError,
                                 rexc.RayTpuError)):
            return self.exc
        if isinstance(self.exc, Exception):
            return rexc._wrap_cause(self.exc, self.tb)
        return rexc.TaskError(self.repr_str, self.tb)


def _error_blob(exc: Exception, tb: str = "") -> bytes:
    try:
        blob, _ = serialization.serialize(_SerializedError(exc, repr(exc), tb))
    except Exception:
        blob, _ = serialization.serialize(
            _SerializedError(None, repr(exc), tb))
    return blob.to_bytes()


def _death_cause_from_view(view) -> str | None:
    """Human-readable death cause; appends the actor-init traceback shipped
    by the executing worker (gcs ActorInfo.init_error_blob) when present."""
    if not view:
        return None
    cause = view.get("death_cause")
    blob = view.get("init_error")
    if blob:
        try:
            se = serialization.deserialize(blob)
            tb = getattr(se, "tb", "")
            if tb:
                cause = f"{cause or 'actor init failed'}\n{tb}"
        except Exception:
            pass
    return cause


def _is_system_error(e: Exception) -> bool:
    return isinstance(e, (protocol.ConnectionLost, ConnectionError, OSError,
                          asyncio.TimeoutError))


def _normalize_resources(opts: dict, actor=False) -> dict:
    res = dict(opts.get("resources") or {})
    num_cpus = opts.get("num_cpus")
    if num_cpus is None:
        num_cpus = 0 if actor else 1
    if num_cpus:
        res["CPU"] = float(num_cpus)
    num_tpus = opts.get("num_tpus", opts.get("num_gpus"))
    if num_tpus:
        res["TPU"] = float(num_tpus)
    if opts.get("memory"):
        res["memory"] = float(opts["memory"])
    return res


def _strategy_dict(strategy):
    if strategy is None:
        return None
    if isinstance(strategy, str):
        if strategy == "SPREAD":
            return {"type": "spread"}
        if strategy == "DEFAULT":
            return None
        return None
    # NodeAffinitySchedulingStrategy / PlacementGroupSchedulingStrategy
    from ray_tpu.util.scheduling_strategies import (
        NodeAffinitySchedulingStrategy)
    if isinstance(strategy, NodeAffinitySchedulingStrategy):
        return {"type": "node_affinity", "node_id": strategy.node_id,
                "soft": strategy.soft}
    return None
