"""Raylet: the per-node daemon — scheduler, worker pool, object-store authority.

TPU-native re-design of the reference raylet (reference:
src/ray/raylet/node_manager.h:143 — HandleRequestWorkerLease
node_manager.cc:1822, HandleReturnWorker :1965; WorkerPool worker_pool.h:153
PopWorker :337; LocalTaskManager local_task_manager.h:58;
PlacementGroupResourceManager placement_group_resource_manager.h; the plasma
store runs in-process, object_manager/plasma/store_runner.cc).

Responsibilities:
  * grants worker *leases* to core workers (lease = a worker process +
    reserved resources; the submitter then pushes tasks directly to the
    worker, amortizing scheduling — same protocol shape as the reference)
  * worker pool: spawn/reuse/kill python worker processes
  * local resource accounting incl. placement-group bundle accounts with
    2-phase prepare/commit (reference: node_manager.proto:365-372)
  * shared-memory object store authority (metadata RPC; data plane is the
    clients' own mmap — see shm_store.py) + inter-node object pulls
    (reference: object_manager/pull_manager.h:47 chunked pulls)
  * blocked-worker CPU release so nested ray.get can't deadlock the pool
    (reference: worker blocked/unblocked resource release in node_manager)
"""

from __future__ import annotations

import asyncio
import logging
import os
import subprocess
import sys
import time

from ray_tpu._private import failpoints, protocol, retry
from ray_tpu._private import tracing as _tracing
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu._private.ids import NodeID, WorkerID
from ray_tpu._private.shm_store import StoreServer, StoreMapping, default_store_path
from ray_tpu._private.transfer import TransferManager, _remain

logger = logging.getLogger(__name__)


class WorkerHandle:
    def __init__(self, worker_id, proc, conn=None, kind="cpu",
                 env_key: str = ""):
        self.kind = kind
        self.env_key = env_key  # content address of the pip venv ("" = base)
        self.worker_id: WorkerID = worker_id
        self.proc: subprocess.Popen | None = proc
        self.conn: protocol.Connection | None = conn
        self.addr: tuple[str, int] | None = None
        self.pid: int | None = proc.pid if proc else None
        self.lease_id = None
        self.actor_id = None
        self.registered = asyncio.Event()
        self.last_idle = time.monotonic()


class _ContainerProcHandle:
    """Popen facade for a container worker.  Signals must reach the
    CONTAINER (`runtime rm -f <name>`), not just the podman/docker
    client process — SIGKILLing the client detaches the engine-managed
    container, which keeps running (and `--rm` never fires), leaking
    the worker and its lease."""

    # Every in-flight remove-then-kill thread, including those whose
    # worker was already popped from the raylet's table — shutdown must
    # join ALL of them or the engine-managed containers leak.
    _live_kill_threads: "set" = set()

    def __init__(self, proc: subprocess.Popen, runtime: str, name: str):
        self._proc = proc
        self._runtime = runtime
        self._name = name
        self.pid = proc.pid
        self._kill_thread = None

    def poll(self):
        return self._proc.poll()

    def wait(self, timeout=None):
        return self._proc.wait(timeout)

    def kill(self):
        # kill() is invoked from async raylet paths (worker reaping,
        # shutdown); a blocking `rm -f` with a 10s timeout would stall
        # lease scheduling and GCS heartbeats, and several serial kills
        # during drain could exceed the heartbeat timeout and turn an
        # orderly drain into a NODE_DEAD.  But the ORDER still matters:
        # the container must be removed before the client is SIGKILLed
        # (killing the client first detaches the engine-managed
        # container — see class docstring).  So the wait/retry/kill
        # sequence runs on a short-lived daemon thread.  Idempotent:
        # kill() is reached twice on a deliberate kill (rpc_kill_worker
        # then _on_worker_dead's poll()-is-alive check) — a second
        # thread would just race the first's `rm -f` and log spurious
        # failures.
        import threading
        if self._kill_thread is not None:
            return

        def _remove_then_kill():
            try:
                for attempt in (1, 2):
                    try:
                        rc = subprocess.run(
                            [self._runtime, "rm", "-f", self._name],
                            stdout=subprocess.DEVNULL,
                            stderr=subprocess.DEVNULL,
                            timeout=10).returncode
                    except Exception:
                        rc = -1
                    if rc == 0:
                        break
                    logger.warning(
                        "container rm -f %s failed (rc=%s, attempt %d)",
                        self._name, rc, attempt)
                try:
                    self._proc.kill()
                except Exception:
                    pass
            finally:
                type(self)._live_kill_threads.discard(
                    threading.current_thread())

        self._kill_thread = threading.Thread(
            target=_remove_then_kill, daemon=True,
            name=f"container-kill-{self._name}")
        type(self)._live_kill_threads.add(self._kill_thread)
        self._kill_thread.start()

    def join_kill(self, timeout: float):
        """Block until the remove-then-kill sequence finishes (raylet
        shutdown must not exit before `rm -f` runs — daemon threads die
        with the interpreter and the containers would leak)."""
        if self._kill_thread is not None:
            self._kill_thread.join(timeout)

    terminate = kill


_PENDING_GAUGE = None


def _pending_leases_gauge():
    global _PENDING_GAUGE
    if _PENDING_GAUGE is None:
        from ray_tpu.util.metrics import Gauge
        _PENDING_GAUGE = Gauge(
            "raylet_pending_leases",
            "queued (ungranted) worker-lease requests",
            tag_keys=("node",))
    return _PENDING_GAUGE


class Lease:
    def __init__(self, lease_id, worker, resources, pg_key):
        self.lease_id = lease_id
        self.worker: WorkerHandle = worker
        self.resources: dict = resources
        self.pg_key = pg_key  # (pg_id, bundle_index) or None
        self.blocked = False
        self.tpu_ids: list = []  # device indices granted to this lease


class Raylet:
    def __init__(self, gcs_addr, resources, labels=None, host="127.0.0.1",
                 session_dir="/tmp/ray_tpu", store_capacity=None,
                 node_name=None):
        self.node_id = NodeID.from_random()
        self.gcs_addr = gcs_addr
        self.host = host
        self.session_dir = session_dir
        self.node_name = node_name
        self.total_resources = dict(resources)
        self.available = dict(resources)
        # Per-device TPU accounting: chip index -> fraction in use
        # (reference: the raylet's GPU-id resource instances backing
        # ray.get_gpu_ids; fractional leases share one chip).
        self._tpu_slots: dict[int, float] = {
            i: 0.0 for i in range(int(resources.get("TPU", 0)))}
        self.labels = labels or {}
        self.server = protocol.RpcServer(self._handle, host=host, name="raylet",
                                         on_disconnect=self._on_conn_lost,
                                         blob_provider=self._blob_sink)
        self.gcs: protocol.Connection | None = None
        self.port = None
        store_capacity = store_capacity or cfg.object_store_memory_bytes
        self.store_path = default_store_path(session_dir, self.node_id.hex())
        self.store = StoreServer(self.store_path, store_capacity)
        self.store_capacity = store_capacity
        self.mapping = StoreMapping(self.store_path, store_capacity)
        # workers, pooled by (kind, env_key): a pip-venv task only ever
        # reuses a worker whose venv matches (reference: worker_pool.h
        # matching runtime_env hashes on PopWorker)
        self.workers: dict[WorkerID, WorkerHandle] = {}
        self.idle_workers: dict[tuple, list[WorkerHandle]] = {}
        self._spawn_sem = None  # created lazily on the loop
        self.leases: dict[bytes, Lease] = {}
        self.pending_leases: list[dict] = []  # queued lease requests
        self._lease_waiters: list = []
        # placement group bundle accounts: (pg_id, idx) -> {"reserved", "avail"}
        self.bundles: dict[tuple, dict] = {}
        # object store waiters: oid -> [futures] waiting for seal
        self.seal_waiters: dict[bytes, list[asyncio.Future]] = {}
        # Spilling (reference: raylet LocalObjectManager::SpillObjects
        # local_object_manager.h:99 + external_storage.py): primary copies
        # move to disk under memory pressure and restore on access.
        self.spill_dir = os.path.join(session_dir, "spill",
                                      self.node_id.hex()[:8])
        self._created_sizes: dict[bytes, int] = {}
        self.primary_objects: dict[bytes, int] = {}  # sealed, creator-pinned
        self.spilled: dict[bytes, tuple[str, int]] = {}  # oid -> (path, size)
        self._spilling: set[bytes] = set()
        self._restores_inflight: dict[bytes, asyncio.Future] = {}
        # cached cluster node table (from GCS pubsub), plus the indexed
        # scheduling view: per-shape candidate sets / score heaps
        # updated incrementally from "nodes" added/removed/updated
        # events, so spillback/spread/hybrid picks don't rescan every
        # node view per lease decision (see sched_policy.ClusterIndex).
        self.cluster_nodes: dict[NodeID, dict] = {}
        from ray_tpu._private.sched_policy import SchedulingPolicies
        self.sched = SchedulingPolicies()
        # Monotonic counter of applied "nodes" pubsub events + the
        # counter value at which each node was last touched by one:
        # _sync_node_views must not let a STALE snapshot override
        # events applied inline while the snapshot was in flight.
        self._node_event_seq = 0
        self._node_touched: dict = {}
        self.peer_conns: dict[NodeID, protocol.Connection] = {}
        self._next_lease = 0
        self._shutdown = False
        self._subproc_env = None
        self._zygote = None  # ZygoteClient once warm (fast fork spawn)
        self._spawn_sem_cap = None
        # per-instance pull dedup (a class attribute would be shared across
        # the in-process multi-raylet test Cluster)
        self._pulls_inflight: dict = {}
        # In-flight push receives: oid -> {"off": arena offset, "size",
        # "sender": id(sender conn), "gen": transfer generation,
        # "last": last-chunk ts, "received": bytes}
        self._push_recv: dict = {}
        self._push_gen = 0  # generation minted per os_push_begin
        # Windowed pull/push engine (admission, striping, retries).
        self.transfers = TransferManager(self)
        # Spill-file read fds kept open across a transfer's chunks:
        # oid -> [fd, last_used, inflight_reads, eof_seen]
        self._spill_read_fds: dict[bytes, list] = {}
        # Oids this node has reported to the GCS object directory, so
        # removal reports fire only for entries that actually exist
        # there (sub-stripe objects are never reported at all).
        self._reported_locs: set[bytes] = set()
        # pins held on behalf of each client conn: id(conn) -> {oid: count}
        self._client_pins: dict[int, dict[bytes, int]] = {}
        # unsealed creates per client conn (freed if the client dies
        # before sealing): id(conn) -> {oid}
        self._creating: dict[int, set[bytes]] = {}
        # resource shapes already warned about as infeasible (event dedup)
        self._infeasible_warned: set[tuple] = set()
        # Pending-lease queue depth gauge (updated from the heartbeat
        # loop; one process-wide metric, one series per node so the
        # in-process multi-raylet cluster doesn't shadow itself).
        try:
            self._pending_gauge = _pending_leases_gauge().series(
                {"node": self.node_id.hex()[:8]})
        except Exception:
            self._pending_gauge = None

    # -------------------------------------------------------------- startup
    async def start(self, port=0):
        self.port = await self.server.start(port)
        # The node tag in the connection name is what the fault plane's
        # partition/slow-link rules match on (test_utils.partition).
        self.gcs = await protocol.Connection.connect(
            self.gcs_addr[0], self.gcs_addr[1], handler=self._handle_gcs_push,
            name=f"raylet:{self.node_id.hex()[:8]}->gcs",
            timeout=cfg.connect_timeout_s)
        reply = await self.gcs.request("register_node", {
            "node_id": self.node_id,
            "addr": (self.host, self.port),
            "resources": self.total_resources,
            "labels": self.labels,
        })
        for view in reply.get("cluster_nodes", []):
            self._observe_node_view(view)
        await self.gcs.request("subscribe", {"channels": ["nodes"]})
        loop = asyncio.get_running_loop()
        loop.create_task(self._heartbeat_loop())
        loop.create_task(self._reap_loop())
        if cfg.worker_zygote_enabled:
            loop.create_task(self._start_zygote())
        if cfg.log_to_driver:
            from ray_tpu._private.log_monitor import LogMonitor

            async def _pub(channel, message):
                await self.gcs.request("publish", {"channel": channel,
                                                   "message": message})

            # Per-raylet log subdir: in the in-process multi-raylet test
            # Cluster all nodes share one session dir, and each monitor
            # must tail only its own workers.
            self._log_monitor = LogMonitor(
                os.path.join(self.session_dir, "logs",
                             self.node_id.hex()[:8]), _pub,
                self.node_id.hex())
            loop.create_task(self._log_monitor.run())
        logger.info("raylet %s on %s:%s resources=%s", self.node_id.hex()[:8],
                    self.host, self.port, self.total_resources)
        return self.port

    def _worker_env(self):
        if self._subproc_env is None:
            env = dict(os.environ)
            env.update(cfg.to_env())
            env.update({
                "RT_RAYLET_HOST": self.host,
                "RT_RAYLET_PORT": str(self.port),
                "RT_GCS_HOST": self.gcs_addr[0],
                "RT_GCS_PORT": str(self.gcs_addr[1]),
                "RT_NODE_ID": self.node_id.hex(),
                "RT_STORE_PATH": self.store_path,
                "RT_STORE_CAP": str(self.store_capacity),
                "RT_SESSION_DIR": self.session_dir,
                # Workers must not grab the TPU chip by default; tasks that
                # need it are leased TPU resources and may init jax then.
                "JAX_PLATFORMS": os.environ.get("RT_WORKER_JAX_PLATFORMS", "cpu"),
            })
            # The spawned `python -m ray_tpu...` must find the package even
            # when this process imported it via a sys.path entry (script dir,
            # editable layout) that subprocesses don't inherit.
            import ray_tpu
            pkg_root = os.path.dirname(os.path.dirname(
                os.path.abspath(ray_tpu.__file__)))
            parts = [p for p in env.get("PYTHONPATH", "").split(os.pathsep) if p]
            if pkg_root not in parts:
                env["PYTHONPATH"] = os.pathsep.join([pkg_root] + parts)
            self._subproc_env = env
        return self._subproc_env

    # ------------------------------------------------------------ rpc entry
    async def _handle(self, conn, method, body):
        fn = getattr(self, "rpc_" + method, None)
        if fn is None:
            raise protocol.RpcError(f"raylet: no method {method}")
        return await fn(conn, body)

    async def _handle_gcs_push(self, conn, method, body):
        """The GCS talks back over the raylet's own registration connection
        (duplex): pubsub pushes AND control RPCs (actor leases, bundle
        prepare/commit) arrive here."""
        if method == "pubsub":
            if body["channel"] == "nodes":
                await self._on_node_event(body["message"])
            return None
        if method == "pubsub_batch":
            # Coalesced broadcast: one frame carrying a same-channel
            # run of messages, delivered in publish order.
            if body["channel"] == "nodes":
                for msg in protocol.pubsub_batch_messages(body):
                    await self._on_node_event(msg)
            return None
        if method == "pubsub_gap":
            # The GCS shed events we never saw (slow-subscriber
            # bound): the node view may now have silent holes — heal
            # by re-seeding authoritatively instead of waiting for a
            # reconnect that may never come.
            if "nodes" in body.get("channels", ()):
                asyncio.get_running_loop().create_task(
                    self._reseed_node_views())
            return None
        return await self._handle(conn, method, body)

    async def _sync_node_views(self, views, hard_prune: bool,
                               cutoff: int):
        """Resync cluster_nodes + the scheduling index against an
        authoritative view list.  ``hard_prune`` additionally tears
        down data-plane state (peer conns, transfers) for absent nodes
        — only safe when the list is known COMPLETE (get_nodes over
        the full table).  A register reply after a non-persistent GCS
        restart is NOT complete (it holds only nodes re-registered so
        far), so that path soft-prunes: absent nodes stop being
        scheduling targets, but live peer connections and in-flight
        transfers — which don't depend on the GCS — survive until the
        peers re-register and their views return.

        ``cutoff`` is the local node-event counter captured BEFORE the
        snapshot was requested: any node touched by a pubsub event
        applied after that point has NEWER state than the snapshot
        (e.g. an 'added' dispatched inline while the reply was in
        flight) and is left alone entirely — the snapshot must never
        prune or overwrite it."""
        fresh = {v["node_id"] for v in views if v.get("alive", True)}
        for nid in [n for n in self.cluster_nodes
                    if n not in fresh and n != self.node_id
                    and self._node_touched.get(n, 0) <= cutoff]:
            if hard_prune:
                await self._on_node_event({"event": "removed",
                                           "node_id": nid})
            else:
                self.cluster_nodes.pop(nid, None)
                self.sched.index.remove(nid)
        for v in views:
            if self._node_touched.get(v["node_id"], 0) <= cutoff:
                self._observe_node_view(v)
        # Entries at/below the cutoff have served their purpose.
        self._node_touched = {k: s for k, s in self._node_touched.items()
                              if s > cutoff}

    async def _reseed_node_views(self):
        """Authoritative node-view refresh (gap heal / post-shed):
        fetch the FULL table, prune cached nodes no longer alive in
        it, re-observe the rest."""
        if self.gcs is None or self.gcs.closed:
            return
        cutoff = self._node_event_seq
        try:
            views = await self.gcs.request("get_nodes", {}, timeout=30.0)
        except Exception:
            return
        await self._sync_node_views(views, hard_prune=True,
                                    cutoff=cutoff)

    def _observe_node_view(self, view: dict):
        """Seed/replace one full node view (registration reply, added
        event, post-reconnect re-seed) in both the legacy dict and the
        indexed scheduling view (which never tracks this node itself).
        Non-alive views are rejected outright: a dead node never emits
        the "removed" event that would prune it later, so admitting it
        would make it a permanent phantom scheduling target."""
        if not view.get("alive", True):
            self.cluster_nodes.pop(view["node_id"], None)
            self.sched.index.remove(view["node_id"])
            return
        self.cluster_nodes[view["node_id"]] = view
        if view["node_id"] != self.node_id:
            self.sched.index.upsert(view)

    async def _on_node_event(self, msg: dict):
        event = msg["event"]
        nid = msg["node"]["node_id"] if event == "added" \
            else msg["node_id"]
        self._node_event_seq += 1
        self._node_touched[nid] = self._node_event_seq
        if event == "added":
            view = msg["node"]
            self._observe_node_view(view)
            self._respill_pending(view)
        elif event == "removed":
            self.cluster_nodes.pop(msg["node_id"], None)
            self.sched.index.remove(msg["node_id"])
            self.transfers.drop_peer(msg["node_id"])
            conn2 = self.peer_conns.pop(msg["node_id"], None)
            if conn2 is not None:
                await conn2.close()
        elif event == "updated":
            # Heartbeat-delta broadcast: refresh availability/load (and
            # the draining flag) incrementally — this is what keeps
            # spillback/spread/hybrid decisions off stale registration
            # snapshots without any rescan.
            nid = msg["node_id"]
            view = self.cluster_nodes.get(nid)
            if view is not None:
                if "available" in msg:
                    view["available"] = msg["available"]
                if "load" in msg:
                    view["load"] = msg["load"]
                if "draining" in msg:
                    view["draining"] = msg["draining"]
                if "reserved" in msg:
                    view["reserved"] = msg["reserved"]
            if nid != self.node_id:
                from ray_tpu._private import sched_policy
                self.sched.index.update(
                    nid, available=msg.get("available"),
                    load=msg.get("load"),
                    draining=msg.get("draining"),
                    # None clears a reservation, so absent-vs-None must
                    # survive the hop: forward the sentinel when the
                    # delta didn't carry the field.
                    reserved=msg.get("reserved", sched_policy._UNSET))

    def _respill_pending(self, new_node_view):
        """A node joined: queued requests this node can NEVER satisfy but
        the new node can are answered with a spillback to it (the path
        that un-wedges infeasible-queued demand after a scale-up)."""
        total = new_node_view.get("resources", {})
        addr = tuple(new_node_view["addr"])
        # Shapes the new node satisfies are feasible again: forget the
        # warn-dedup so a LATER scale-down + new infeasible demand of the
        # same shape warns operators again.
        for shape in list(self._infeasible_warned):
            if all(total.get(k, 0) >= v for k, v in shape):
                self._infeasible_warned.discard(shape)
        for req in list(self.pending_leases):
            if req["future"].done():
                continue
            res = req["resources"]
            if self._fits_total(res):
                continue  # locally feasible: the scheduler will grant it
            if all(total.get(k, 0) >= v for k, v in res.items()):
                req["future"].set_result({"spillback": addr})
                self.pending_leases.remove(req)

    async def _on_conn_lost(self, conn):
        self._release_client_pins(conn)
        self._abort_pushes_from(conn)
        for oid in self._creating.pop(id(conn), ()):
            got = self.store.get(oid)
            if got is not None and not got[2]:
                # Client died mid-create: free the unsealed allocation.
                self._created_sizes.pop(oid, None)
                self._discard_unsealed(oid)
            elif got is not None and got[2]:
                self.store.release(oid)  # drop the probe pin
        for w in list(self.workers.values()):
            if w.conn is conn:
                await self._on_worker_dead(w, "worker connection lost")

    def _discard_unsealed(self, oid: bytes):
        """Free an unsealed allocation made by a transfer that died —
        abort() drops the alloc-time creator pin (shm_store.cc Alloc:
        refcount=1) and frees the extent atomically; release() refuses
        unsealed entries so a stray release can't free memory under a
        still-writing creator."""
        self.store.abort(oid)

    def _abort_pushes_from(self, conn):
        """Sender connection died: drop its in-flight push transfers so the
        unsealed allocations don't sit in the arena until the stale sweep,
        and so an immediate re-push (new connection) isn't answered {skip}.
        Waiters are woken to re-check the store / fall back to a pull."""
        sender = id(conn)
        for oid, ent in list(self._push_recv.items()):
            if ent["sender"] == sender:
                self._push_recv.pop(oid, None)
                self._discard_unsealed(oid)
                for fut in self.seal_waiters.pop(oid, []):
                    if not fut.done():
                        fut.set_result(None)

    # ------------------------------------------------------- worker lifecycle
    def _idle(self, kind: str, env_key: str = "") -> list:
        return self.idle_workers.setdefault((kind, env_key), [])

    def _ensure_venv(self, env_key: str, pip_specs: list) -> str:
        """Create (once) the content-addressed virtualenv for a pip
        runtime env and return its interpreter path (reference:
        _private/runtime_env/pip.py — spec-hash-keyed cached envs).
        Blocking; call from an executor thread."""
        import subprocess as sp
        root = os.path.join(self.session_dir, "venvs", env_key)
        py = os.path.join(root, "bin", "python")
        done_marker = os.path.join(root, ".ready")
        if os.path.exists(done_marker):
            return py
        lock = root + ".lock"
        os.makedirs(os.path.dirname(root), exist_ok=True)
        import time as _time

        def _lock_stale() -> bool:
            # The builder writes its pid into the lock; a SIGKILLed builder
            # (the chaos-test fault mode) orphans it. Dead pid or an
            # untouched lock older than the build bound means stale.
            try:
                with open(lock) as f:
                    pid = int(f.read().strip() or "0")
            except (OSError, ValueError):
                pid = 0
            if pid:
                try:
                    os.kill(pid, 0)
                    return False  # builder is alive: never stale
                except ProcessLookupError:
                    return True
                except PermissionError:
                    return False  # alive, different uid
            # No readable pid (partial write / legacy lock): fall back to
            # age — an untouched lock older than any plausible build.
            try:
                return _time.time() - os.path.getmtime(lock) > 600.0
            except OSError:
                return False

        deadline = _time.monotonic() + 900.0
        while True:
            try:
                fd = os.open(lock, os.O_CREAT | os.O_EXCL | os.O_WRONLY)
                os.write(fd, str(os.getpid()).encode())
                os.close(fd)
                break
            except FileExistsError:
                if os.path.exists(done_marker):
                    return py
                if _lock_stale():
                    # Clear an orphaned lock.  rename() is atomic, so at
                    # most one waiter unlinks it; everyone then races on
                    # O_EXCL as usual, and ONLY the lock holder touches
                    # the half-built root (below) — no rmtree here, so a
                    # concurrent winner's build can't be deleted.
                    try:
                        os.rename(lock, lock + f".claimed.{os.getpid()}")
                        os.unlink(lock + f".claimed.{os.getpid()}")
                    except OSError:
                        pass
                    continue
                if _time.monotonic() > deadline:
                    raise RuntimeError(
                        f"timed out waiting for venv build lock {lock}")
                # Jittered so a gang of workers racing one build lock
                # don't all re-poll (and re-stat the marker) in phase.
                _time.sleep(retry.jittered(0.5))
        try:
            if not os.path.exists(done_marker):
                # We hold the lock: safe to clear any half-built root left
                # by a SIGKILLed predecessor before building fresh.
                if os.path.isdir(root):
                    import shutil
                    shutil.rmtree(root, ignore_errors=True)
                sp.check_call([sys.executable, "-m", "venv",
                               "--system-site-packages", root],
                              stdout=sp.DEVNULL, stderr=sp.STDOUT)
                # The venv overlays the BASE interpreter's site-packages;
                # when this process itself runs inside a venv (common:
                # /opt/venv), the parent's packages (jax, setuptools...)
                # live one level up and --system-site-packages misses
                # them.  A .pth appends the parent's site dirs AFTER the
                # venv's own, so pip installs still shadow the overlay.
                import site
                parents = [p for p in site.getsitepackages()
                           if os.path.isdir(p)]
                vsite = sp.check_output(
                    [py, "-c", "import site;"
                     "print(site.getsitepackages()[-1])"]).decode().strip()
                with open(os.path.join(vsite, "_parent_overlay.pth"),
                          "w") as f:
                    f.write("\n".join(parents) + "\n")
                sp.check_call([py, "-m", "pip", "install", "--quiet",
                               "--no-build-isolation"] + list(pip_specs),
                              stdout=sp.DEVNULL)
                with open(done_marker, "w") as f:
                    f.write("\n".join(pip_specs))
            return py
        finally:
            try:
                os.unlink(lock)
            except OSError:
                pass

    def prestart_workers(self, n: int, kind: str = "cpu"):
        """Spawn warm workers ahead of demand (reference: WorkerPool
        PrestartWorkers — python startup is expensive, ~2s with jax in the
        interpreter, so cold-start per lease would dominate small tasks)."""
        for _ in range(n):
            w = self._spawn_worker(kind)
            asyncio.get_running_loop().create_task(self._await_prestart(w))

    async def _await_prestart(self, w: WorkerHandle):
        if not await self._wait_registered(w):
            return
        pool = self._idle(w.kind, w.env_key)
        if w.lease_id is None and w not in pool:
            w.last_idle = time.monotonic()
            pool.append(w)
            self._kick_scheduler()

    async def _wait_registered(self, w: WorkerHandle) -> bool:
        """Wait for a spawned worker to register, fast-failing if its
        process dies during startup (bad env, import error) instead of
        sitting out the full register timeout.  Venv workers get triple
        patience: pip may be building their environment first."""
        deadline = time.monotonic() + cfg.worker_register_timeout_s * (
            3 if w.env_key else 1)
        while not w.registered.is_set():
            if getattr(w, "dead", False):
                return False
            if w.proc is not None and w.proc.poll() is not None:
                await self._on_worker_dead(
                    w, f"worker process exited rc={w.proc.returncode} "
                       f"before registering")
                return False
            if time.monotonic() >= deadline:
                await self._on_worker_dead(w, "worker failed to register")
                return False
            try:
                await asyncio.wait_for(w.registered.wait(), 0.1)
            except asyncio.TimeoutError:
                pass
        # The event is also set by _on_worker_dead to break this wait.
        return not getattr(w, "dead", False)

    async def _start_zygote(self):
        """Spawn the warm fork-server (zygote.py): one ~2s interpreter +
        import cost per node, after which workers fork in ~10ms instead of
        cold-starting.  Until it's ready, _spawn_worker falls back to
        Popen cold starts."""
        from ray_tpu._private.zygote import ZygoteClient
        sock_path = os.path.join(self.session_dir,
                                 f"zygote_{self.node_id.hex()[:8]}.sock")
        env = dict(self._worker_env())
        env.pop("RT_WORKER_ID", None)
        logfile = os.path.join(self.session_dir, "logs",
                               self.node_id.hex()[:8], "zygote.log")
        os.makedirs(os.path.dirname(logfile), exist_ok=True)
        out = open(logfile, "ab")
        proc = subprocess.Popen(
            [sys.executable, "-m", "ray_tpu._private.zygote", sock_path],
            env=env, stdout=out, stderr=subprocess.STDOUT,
            start_new_session=True)
        out.close()
        zy = ZygoteClient(sock_path, proc)
        if await zy.wait_ready():
            self._zygote = zy
            logger.info("zygote ready on %s", self.node_id.hex()[:8])
        else:
            logger.warning("zygote failed to start; using cold spawns")
            zy.kill()

    def _worker_env_for(self, worker_id, kind: str):
        env = dict(self._worker_env())
        env["RT_WORKER_ID"] = worker_id.hex()
        unset = []
        if kind == "tpu":
            # TPU workers get the real backend (axon/tpu); cpu workers are
            # pinned to the host platform so they never grab the chip.
            env.pop("JAX_PLATFORMS", None)
            unset.append("JAX_PLATFORMS")
            if "RT_WORKER_JAX_PLATFORMS_TPU" in os.environ:
                env["JAX_PLATFORMS"] = os.environ["RT_WORKER_JAX_PLATFORMS_TPU"]
                unset = []
        return env, unset

    def _worker_logfile(self, worker_id):
        return os.path.join(self.session_dir, "logs",
                            self.node_id.hex()[:8],
                            f"worker-{worker_id.hex()[:8]}.log")

    def _spawn_worker(self, kind: str = "cpu", env_key: str = "",
                      env_spec: dict | None = None) -> WorkerHandle:
        worker_id = WorkerID.from_random()
        env, unset = self._worker_env_for(worker_id, kind)
        logfile = self._worker_logfile(worker_id)
        if env_key:
            # Interpreter-environment runtime env (pip venv / conda env /
            # container image): dedicated worker built asynchronously;
            # the zygote can't serve these — its warm image is the base
            # interpreter.
            spec = env_spec or {}
            w = WorkerHandle(worker_id, None, kind=kind, env_key=env_key)
            self.workers[worker_id] = w
            if spec.get("container"):
                coro = self._spawn_container_worker(
                    w, env, spec["container"], logfile)
            elif spec.get("conda"):
                coro = self._spawn_conda_worker(
                    w, env, spec["conda"], logfile)
            else:
                coro = self._spawn_venv_worker(
                    w, env, env_key, list(spec.get("pip") or []), logfile)
            asyncio.get_running_loop().create_task(coro)
            return w
        if self._zygote is not None and self._zygote.ready:
            # proc is attached asynchronously when the fork reply lands;
            # _wait_registered tolerates proc=None meanwhile.
            w = WorkerHandle(worker_id, None, kind=kind)
            self.workers[worker_id] = w
            asyncio.get_running_loop().create_task(
                self._fork_worker(w, env, unset, logfile))
            return w
        proc = self._popen_worker(
            [sys.executable, "-m", "ray_tpu._private.worker_main"],
            env, logfile)
        w = WorkerHandle(worker_id, proc, kind=kind)
        self.workers[worker_id] = w
        return w

    @staticmethod
    def _popen_worker(argv: list, env: dict, logfile: str):
        """One place for the worker-process launch boilerplate shared by
        the base, venv, conda, and container spawn paths."""
        os.makedirs(os.path.dirname(logfile), exist_ok=True)
        out = open(logfile, "ab")
        try:
            return subprocess.Popen(
                argv, env=env, stdout=out, stderr=subprocess.STDOUT,
                start_new_session=True)
        finally:
            out.close()

    async def _spawn_venv_worker(self, w: WorkerHandle, env, env_key,
                                 pip_specs, logfile):
        try:
            py = await asyncio.get_running_loop().run_in_executor(
                None, self._ensure_venv, env_key, pip_specs)
            w.proc = self._popen_worker(
                [py, "-m", "ray_tpu._private.worker_main"], env, logfile)
            w.pid = w.proc.pid
        except Exception as e:
            logger.warning("venv worker spawn failed: %s", e)
            await self._on_worker_dead(
                w, f"pip runtime_env creation failed: {e}")

    async def _spawn_conda_worker(self, w: WorkerHandle, env, conda_spec,
                                  logfile):
        """Worker under an EXISTING conda env's interpreter (reference:
        _private/runtime_env/conda.py get_conda_env_dir — envs are
        prebuilt; we resolve name -> prefix -> bin/python)."""
        try:
            prefix = conda_spec
            if not os.path.isdir(prefix):
                prefix = os.path.join(self._conda_root(), "envs",
                                      conda_spec)
            py = os.path.join(prefix, "bin", "python")
            if not os.path.exists(py):
                raise FileNotFoundError(
                    f"conda env {conda_spec!r}: no interpreter at {py}")
            w.proc = self._popen_worker(
                [py, "-m", "ray_tpu._private.worker_main"], env, logfile)
            w.pid = w.proc.pid
        except Exception as e:
            logger.warning("conda worker spawn failed: %s", e)
            await self._on_worker_dead(
                w, f"conda runtime_env creation failed: {e}")

    def _local_env_key(self, env_key: str, env_spec: dict | None) -> str:
        """Pool key for conda envs is resolved LOCALLY, not trusted from
        the submitter: the same interpreter must map to one pool no
        matter how the submitter spelled it (name vs prefix), and two
        distinct envs sharing a basename must not share a pool.  Only
        this raylet knows its filesystem, so the driver-computed key is
        replaced by a hash of the realpath'd prefix (the same
        resolution _spawn_conda_worker applies)."""
        if not env_spec or not env_spec.get("conda"):
            return env_key
        spec = str(env_spec["conda"])
        prefix = spec
        if not os.path.isdir(prefix):
            prefix = os.path.join(self._conda_root(), "envs", spec)
        import hashlib
        return hashlib.sha1(
            ("conda-local:" + os.path.realpath(prefix)).encode()
        ).hexdigest()[:16]

    @staticmethod
    def _conda_root() -> str:
        """The conda INSTALL root (holding envs/), not the active env:
        CONDA_ROOT wins; else derive from CONDA_EXE (<root>/bin/conda);
        else walk an activated env's CONDA_PREFIX (<root>/envs/<name>)
        up to the root; else /opt/conda."""
        root = os.environ.get("CONDA_ROOT")
        if root:
            return root
        exe = os.environ.get("CONDA_EXE")
        if exe:
            return os.path.dirname(os.path.dirname(exe))
        prefix = os.environ.get("CONDA_PREFIX")
        if prefix:
            parent = os.path.dirname(prefix)
            if os.path.basename(parent) == "envs":
                return os.path.dirname(parent)
            return prefix  # base env IS the root
        return "/opt/conda"

    _CONTAINER_ENV_PREFIXES = ("RT_", "JAX_", "XLA_", "PYTHON", "TPU_")

    def _container_command(self, image: str, run_options: list, env: dict,
                           inner: list) -> list:
        """Assemble the `podman/docker run` invocation (reference:
        _private/runtime_env/container.py worker command rewrite).
        --network=host keeps the raylet RPC loopback reachable; the
        session dir bind-mount carries the shm-store arena file, so
        in-container workers mmap the SAME pages (zero-copy object
        reads survive containerization); the repo mount provides the
        framework source when the image doesn't bake it in."""
        import shutil as _shutil
        runtime = os.environ.get("RT_CONTAINER_RUNTIME")             or _shutil.which("podman") or _shutil.which("docker")
        if not runtime:
            raise RuntimeError(
                "container runtime_env needs podman or docker on PATH "
                "(or RT_CONTAINER_RUNTIME)")
        repo_root = os.path.dirname(os.path.dirname(
            os.path.dirname(os.path.abspath(__file__))))
        cmd = [runtime, "run", "--rm", "--network=host",
               "-v", f"{self.session_dir}:{self.session_dir}",
               "-v", f"{repo_root}:{repo_root}:ro"]
        # The store arena usually lives OUTSIDE the session dir (in
        # /dev/shm when writable) — bind-mount the file itself or the
        # worker's mmap of the shared pages fails at startup.
        if self.store_path and not self.store_path.startswith(
                self.session_dir + os.sep):
            cmd += ["-v", f"{self.store_path}:{self.store_path}"]
        keep = {k: v for k, v in env.items()
                if k.startswith(self._CONTAINER_ENV_PREFIXES)}
        keep["PYTHONPATH"] = repo_root + (
            ":" + env["PYTHONPATH"] if env.get("PYTHONPATH") else "")
        for k, v in sorted(keep.items()):
            cmd += ["-e", f"{k}={v}"]
        cmd += list(run_options)
        cmd.append(image)
        cmd += inner
        return cmd

    async def _spawn_container_worker(self, w: WorkerHandle, env,
                                      container_spec, logfile):
        try:
            name = f"rt-worker-{w.worker_id.hex()[:12]}"
            cmd = self._container_command(
                container_spec["image"],
                ["--name", name]
                + list(container_spec.get("run_options", [])), env,
                ["python", "-m", "ray_tpu._private.worker_main"])
            proc = self._popen_worker(cmd, env, logfile)
            w.proc = _ContainerProcHandle(proc, cmd[0], name)
            w.pid = proc.pid
        except Exception as e:
            logger.warning("container worker spawn failed: %s", e)
            await self._on_worker_dead(
                w, f"container runtime_env creation failed: {e}")

    async def _fork_worker(self, w: WorkerHandle, env, unset, logfile):
        from ray_tpu._private.zygote import PidHandle
        try:
            pid = await self._zygote.fork(env, logfile, unset_env=unset)
            w.proc = PidHandle(pid)
            w.pid = pid
        except Exception as e:
            logger.warning("zygote fork failed (%s); cold-starting", e)
            if w.worker_id not in self.workers:
                return  # already reaped
            os.makedirs(os.path.dirname(logfile), exist_ok=True)
            out = open(logfile, "ab")
            w.proc = subprocess.Popen(
                [sys.executable, "-m", "ray_tpu._private.worker_main"],
                env=env, stdout=out, stderr=subprocess.STDOUT,
                start_new_session=True)
            out.close()
            w.pid = w.proc.pid

    async def rpc_register_worker(self, conn, body):
        worker_id = WorkerID.from_hex(body["worker_id"])
        w = self.workers.get(worker_id)
        if w is None:  # e.g. driver-managed process; adopt it
            w = WorkerHandle(worker_id, None)
            self.workers[worker_id] = w
        w.conn = conn
        w.addr = tuple(body["addr"])
        w.pid = body["pid"]
        w.registered.set()
        self._kick_scheduler()
        return {"ok": True, "node_id": self.node_id,
                "store_path": self.store_path,
                "store_capacity": self.store_capacity}

    def _spawn_cap(self) -> int:
        """Concurrent-spawn bound: wide for ~10ms zygote forks, narrow for
        ~2s interpreter cold starts."""
        if self._zygote is not None and self._zygote.ready:
            return 16
        return max(2, int(self.total_resources.get("CPU", 2)))

    async def _get_ready_worker(self, kind: str = "cpu",
                                env_key: str = "",
                                env_spec: dict | None = None
                                ) -> WorkerHandle | None:
        idle = self._idle(kind, env_key)
        while idle:
            w = idle.pop()
            if w.conn is not None and not w.conn.closed:
                return w
        if len(self.workers) >= cfg.max_workers_per_node:
            return None
        # Bound concurrent cold starts: on a small host an unbounded
        # spawn storm (each ~2s of CPU) starves the running tasks.
        # Zygote forks are ~10ms, so they get a much wider bound; the
        # semaphore is rebuilt whenever the cap changes (zygote warming
        # up or dying) rather than frozen at first use.
        cap = self._spawn_cap()
        if self._spawn_sem is None or self._spawn_sem_cap != cap:
            self._spawn_sem = asyncio.Semaphore(cap)
            self._spawn_sem_cap = cap
        async with self._spawn_sem:
            idle = self._idle(kind, env_key)
            if idle:
                w = idle.pop()
                if w.conn is not None and not w.conn.closed:
                    return w
            w = self._spawn_worker(kind, env_key=env_key,
                                   env_spec=env_spec)
            if not await self._wait_registered(w):
                return None
            return w

    async def _on_worker_dead(self, w: WorkerHandle, reason: str):
        if getattr(w, "dead", False):
            return  # already reaped (e.g. spawn failure + register timeout)
        w.dead = True
        w.registered.set()  # wake _wait_registered immediately, not at
        # its deadline — it checks w.dead and reports the spawn failure
        self.workers.pop(w.worker_id, None)
        pool = self._idle(w.kind, w.env_key)
        if w in pool:
            pool.remove(w)
        if w.actor_id is not None and self.gcs is not None:
            try:
                await self.gcs.request("report_actor_death", {
                    "actor_id": w.actor_id, "reason": reason})
            except Exception:
                pass
        if w.proc is not None and w.proc.poll() is None:
            try:
                w.proc.kill()
            except Exception:
                pass
        # Container workers: engine removal runs on a background
        # thread; hold the dead worker's lease resources until removal
        # completes so a replacement isn't granted the same TPU /
        # host-network ports while the old container still holds them.
        join = getattr(w.proc, "join_kill", None)
        if join is not None:
            await asyncio.get_running_loop().run_in_executor(
                None, join, 25.0)
        if w.lease_id is not None:
            lease = self.leases.pop(w.lease_id, None)
            if lease is not None:
                self._release_resources(lease)
        self._kick_scheduler()

    async def rpc_kill_worker(self, conn, body):
        w = self.workers.get(body["worker_id"])
        if w is None:
            return {"ok": False}
        w.actor_id = None  # killed deliberately; no death report
        if w.proc is not None:
            try:
                w.proc.kill()
            except Exception:
                pass
        await self._on_worker_dead(w, "killed")
        return {"ok": True}

    async def _reap_loop(self):
        while not self._shutdown:
            # Jittered: N raylets in one test process (or container)
            # must not wake and sweep their worker tables in phase.
            await asyncio.sleep(retry.jittered(0.2))
            for w in list(self.workers.values()):
                if w.proc is not None and w.proc.poll() is not None:
                    await self._on_worker_dead(
                        w, f"worker exited with code {w.proc.returncode}")
            # trim long-idle workers
            now = time.monotonic()
            for key, idle in self.idle_workers.items():
                keep = []
                for w in idle:
                    if now - w.last_idle > cfg.idle_worker_keep_s:
                        if w.proc is not None:
                            try:
                                w.proc.terminate()
                            except Exception:
                                pass
                    else:
                        keep.append(w)
                self.idle_workers[key] = keep

    # ------------------------------------------------------------ resources
    def _fits(self, resources: dict, pg_key=None) -> bool:
        pool = self.bundles[pg_key]["avail"] if pg_key else self.available
        return all(pool.get(k, 0) >= v - 1e-9 for k, v in resources.items())

    def _fits_total(self, resources: dict) -> bool:
        return all(self.total_resources.get(k, 0) >= v - 1e-9
                   for k, v in resources.items())

    def _acquire(self, resources: dict, pg_key=None):
        pool = self.bundles[pg_key]["avail"] if pg_key else self.available
        for k, v in resources.items():
            pool[k] = pool.get(k, 0) - v

    def _release(self, resources: dict, pg_key=None):
        pool = self.available if pg_key is None else None
        if pg_key is not None:
            bundle = self.bundles.get(pg_key)
            if bundle is None:
                return
            pool = bundle["avail"]
        for k, v in resources.items():
            pool[k] = pool.get(k, 0) + v

    def _release_resources(self, lease: Lease):
        if not lease.blocked:
            self._release(lease.resources, lease.pg_key)
        else:
            non_cpu = {k: v for k, v in lease.resources.items() if k != "CPU"}
            self._release(non_cpu, lease.pg_key)
        self._free_tpu_ids(lease)

    # ----------------------------------------------------- TPU device ids
    def _alloc_tpu_ids(self, lease: Lease) -> list:
        """Pin specific chip indices to a lease.  Whole-chip requests
        take exclusively-free slots; fractional requests bin-pack onto
        the fullest slot that still fits (so two 0.5 leases share one
        chip and whole chips stay free for whole-chip leases).  Ids are
        advisory — allocation failure (fragmentation) grants the lease
        with no pinned ids rather than blocking it."""
        amount = float(lease.resources.get("TPU", 0) or 0)
        if amount <= 0 or not self._tpu_slots:
            return []
        ids: list = []
        if amount >= 1.0 - 1e-9:
            free = [i for i, used in self._tpu_slots.items()
                    if used <= 1e-9]
            k = int(round(amount))
            if len(free) < k:
                return []
            ids = free[:k]
            for i in ids:
                self._tpu_slots[i] = 1.0
        else:
            cands = [(used, i) for i, used in self._tpu_slots.items()
                     if used + amount <= 1.0 + 1e-9]
            if not cands:
                return []
            _, best = max(cands)
            self._tpu_slots[best] += amount
            ids = [best]
        lease.tpu_ids = ids
        return ids

    def _free_tpu_ids(self, lease: Lease):
        amount = float(lease.resources.get("TPU", 0) or 0)
        if not lease.tpu_ids:
            return
        if amount >= 1.0 - 1e-9:
            for i in lease.tpu_ids:
                self._tpu_slots[i] = 0.0
        else:
            for i in lease.tpu_ids:
                left = self._tpu_slots[i] - amount
                # Snap float residue to exactly 0.0: non-binary
                # fractions (three 0.3 leases, say) otherwise leave
                # ~1e-17 occupancy that blocks whole-chip grants on
                # this slot forever.
                self._tpu_slots[i] = 0.0 if left < 1e-9 else left
        lease.tpu_ids = []

    # --------------------------------------------------------------- leases
    async def rpc_request_worker_lease(self, conn, body):
        """Lease protocol (reference: NodeManager::HandleRequestWorkerLease
        node_manager.cc:1822 — grant locally, queue, or reply with a
        spillback node for the submitter to retry on)."""
        resources = body.get("resources") or {}
        pg_id = body.get("pg_id")
        bundle_index = body.get("bundle_index")
        hopped = body.get("hops", 0) > 0
        pg_key = None
        strat = body.get("strategy") or {}
        affinity_local = False
        if pg_id is None and strat.get("type") == "node_affinity":
            # Locality-routed task (e.g. the data layer's streaming
            # executor placing a map task where its input block lives):
            # redirect the lease to the target raylet when it is a
            # live, non-draining peer; a SOFT miss (dead/unknown
            # target) falls through to the ordinary policy chain,
            # a hard miss errors.  The spillback reply nulls the
            # strategy on the worker side, so the target just grants
            # or queues locally.
            target = self._affinity_node(strat.get("node_id"))
            soft = bool(strat.get("soft", False))
            if target is not None and target != self.node_id \
                    and not hopped:
                view = self.cluster_nodes.get(target)
                if view is not None and view.get("alive", True) \
                        and view.get("addr") \
                        and not view.get("draining"):
                    return {"spillback": tuple(view["addr"])}
                target = None  # known-dead / not-yet-known target
            if target == self.node_id:
                # Affinity to THIS node — soft or hard — must not be
                # re-spilled by the busy-shed hybrid policy below:
                # "busy right now" is exactly when a locality-placed
                # task should QUEUE here rather than run somewhere it
                # has to pull its input from (warm idle leases hold
                # CPUs, so a shed would fire on every loaded node).
                # Soft only governs the dead/unknown-target fallback;
                # an infeasible-forever shape still spills via the
                # fits-total branch above.
                affinity_local = True
            elif target is None and not soft:
                return {"error": "node affinity target is not "
                                 "schedulable (dead or unknown)"}
        if pg_id is not None:
            pg_key = self._bundle_key_for(pg_id, bundle_index, resources)
            if pg_key is None:
                return {"error": f"placement group {pg_id} bundle "
                                 f"{bundle_index} not on this node"}
        elif not self._fits_total(resources):
            # Infeasible here — spill to a node where it can ever fit.
            target = self._pick_spillback(resources)
            if target is not None:
                return {"spillback": target}
            # Infeasible CLUSTER-WIDE: queue, don't error (reference: the
            # raylet's infeasible task queue — the request becomes
            # autoscaler demand via pending_shapes, and _respill_pending
            # redirects it when a capable node joins).  Surface the wait
            # as a cluster event ONCE PER SHAPE (a fan-out of identical
            # requests must not flood the bounded event ring).
            shape = tuple(sorted(resources.items()))
            if shape not in self._infeasible_warned:
                self._infeasible_warned.add(shape)
                try:
                    await self.gcs.request("publish", {
                        "channel": "events",
                        "message": {"severity": "WARNING",
                                    "source": "raylet",
                                    "message": f"task demand {resources} "
                                               f"is infeasible on the "
                                               f"current cluster; waiting "
                                               f"for scale-up"}})
                except Exception:
                    pass
        elif (body.get("strategy") or {}).get("type") == "spread":
            target = self._pick_spread_target(resources)
            if target is not None:
                return {"spillback": target}
        elif hopped or affinity_local:
            # Already spilled here once (or hard-affinity-pinned here):
            # queue locally — re-spilling on a stale resource view of
            # the sender ping-pongs the request until its hop budget
            # dies (reference: the lease protocol's spillback count).
            pass
        elif not self._fits(resources):
            # Feasible here but busy: shed to a node that can run it NOW,
            # scored by post-placement critical-resource utilization
            # (reference: hybrid pack/spread scoring,
            # raylet/scheduling/policy/hybrid_scheduling_policy.h:48 +
            # scorer.h — local-first, spill at saturation).
            target = self._pick_hybrid_target(resources)
            if target is not None:
                return {"spillback": target}
        fut = asyncio.get_running_loop().create_future()
        self.pending_leases.append({"resources": resources, "pg_key": pg_key,
                                    "future": fut,
                                    "env_key": self._local_env_key(
                                        body.get("env_key", ""),
                                        body.get("env_spec")),
                                    "env_spec": body.get("env_spec"),
                                    "request_id": body.get("request_id")})
        self._kick_scheduler()
        granted = await fut
        return granted

    async def rpc_cancel_lease_requests(self, conn, body):
        """Cancel queued (not yet granted) lease requests (reference:
        node_manager.proto CancelWorkerLease — submitters cancel speculative
        leases when their task queue drains)."""
        ids = set(body["request_ids"])
        cancelled = 0
        for req in list(self.pending_leases):
            if req.get("request_id") in ids and not req["future"].done():
                req["future"].set_result({"cancelled": True})
                self.pending_leases.remove(req)
                cancelled += 1
        return {"cancelled": cancelled}

    def _affinity_node(self, nid):
        """Resolve a node_affinity target to a known NodeID.  Callers
        commonly pass the hex string from ray_tpu.nodes(); the data
        layer passes owner-recorded NodeIDs directly."""
        if nid is None:
            return None
        if nid == self.node_id or nid in self.cluster_nodes:
            return nid
        if isinstance(nid, str):
            if nid == self.node_id.hex():
                return self.node_id
            for k in self.cluster_nodes:
                if getattr(k, "hex", None) and k.hex() == nid:
                    return k
        return None

    def _bundle_key_for(self, pg_id, bundle_index, resources):
        if bundle_index is not None and bundle_index >= 0:
            key = (pg_id, bundle_index)
            return key if key in self.bundles else None
        for key, acct in self.bundles.items():
            if key[0] == pg_id and all(acct["avail"].get(k, 0) >= v
                                       for k, v in resources.items()):
                return key
        for key in self.bundles:
            if key[0] == pg_id:
                return key
        return None

    # Spillback / spread / hybrid targeting now rides the composable
    # policy chain over the incrementally-indexed cluster view
    # (sched_policy.py): same scoring semantics as the old inline scans
    # (parity-tested in tests/test_sched_policy.py), but a decision
    # costs O(candidates-inspected) instead of a rescan of every node
    # view, and spillback rotates among eligible targets instead of
    # pile-driving the first total-fit node in view order.

    def _pick_spillback(self, resources):
        return self.sched.pick_spillback(resources, exclude=self.node_id)

    def _pick_hybrid_target(self, resources):
        """Least-utilized node with the request's resources AVAILABLE
        right now; None keeps the task queued locally."""
        return self.sched.pick_hybrid(resources, exclude=self.node_id)

    def _pick_spread_target(self, resources):
        """SPREAD strategy: redirect to the least-loaded feasible node
        (reference: scheduling/policy/spread_scheduling_policy)."""
        return self.sched.pick_spread(resources, self._load(),
                                      exclude=self.node_id)

    def _load(self):
        return len(self.pending_leases)

    def _kick_scheduler(self):
        self._kick_pending = True
        asyncio.get_running_loop().call_soon(
            lambda: asyncio.get_running_loop().create_task(
                self._schedule_leases()))

    _scheduling = False
    _kick_pending = False

    async def _schedule_leases(self):
        """Grant pending lease requests from the idle pool; never block on a
        worker cold-start (spawns run as background tasks and re-kick)."""
        if self._shutdown:
            return  # the store handle is gone; a late kick must not touch it
        if self._scheduling:
            self._kick_pending = True
            return
        self._scheduling = True
        try:
            need_spawn: dict = {}
            # Object-store backpressure (reference: memory-aware admission
            # in the raylet): admitting more tasks while the arena is
            # nearly all PINNED only adds more pinned args — the running
            # tasks must finish (and release pins) first.  Gate on
            # pinned+unsealed, not used(): unpinned secondary copies are
            # evictable on demand and must not throttle admission.  One
            # lease always proceeds so the node can't wedge.  Sampled
            # once per pass (it scans the object table under the store
            # mutex).
            store_pressured = False
            if len(self.leases) >= 1 and self.pending_leases:
                st = self.store.stats()
                store_pressured = (st["pinned_bytes"] + st["unsealed_bytes"]
                                   > 0.85 * self.store_capacity)
            for req in list(self.pending_leases):
                if req["future"].done():
                    self.pending_leases.remove(req)
                    continue
                if not self._fits(req["resources"], req["pg_key"]):
                    continue
                if store_pressured and len(self.leases) >= 1:
                    break
                kind = "tpu" if req["resources"].get("TPU") else "cpu"
                env_key = req.get("env_key", "")
                w = None
                idle = self._idle(kind, env_key)
                while idle:
                    cand = idle.pop()
                    if cand.conn is not None and not cand.conn.closed:
                        w = cand
                        break
                if w is None:
                    cur = need_spawn.setdefault(
                        (kind, env_key), [0, req.get("env_spec")])
                    cur[0] += 1
                    continue
                self._acquire(req["resources"], req["pg_key"])
                self.pending_leases.remove(req)
                lease_id = os.urandom(8)
                lease = Lease(lease_id, w, req["resources"], req["pg_key"])
                self.leases[lease_id] = lease
                w.lease_id = lease_id
                req["future"].set_result({
                    "lease_id": lease_id,
                    "worker_addr": w.addr,
                    "worker_id": w.worker_id,
                    "node_id": self.node_id,
                    "tpu_ids": self._alloc_tpu_ids(lease),
                })
            for (kind, env_key), (n, env_spec) in need_spawn.items():
                self._ensure_spawning(kind, n, env_key=env_key,
                                      env_spec=env_spec)
        finally:
            self._scheduling = False
            if self._kick_pending and self.pending_leases:
                self._kick_pending = False
                asyncio.get_running_loop().create_task(
                    self._schedule_leases())

    _spawns_outstanding = 0

    def _ensure_spawning(self, kind: str, demand: int,
                         env_key: str = "", env_spec: dict | None = None):
        """Keep at most `demand` additional cold starts in flight, bounded by
        the node CPU count and the pool cap (reference: WorkerPool
        maximum_startup_concurrency).  Zygote forks are cheap, so the
        bound widens once the fork server is warm."""
        cap = self._spawn_cap()
        can_spawn = min(
            demand - self._spawns_outstanding,
            cap - self._spawns_outstanding,
            cfg.max_workers_per_node - len(self.workers),
        )
        for _ in range(max(0, can_spawn)):
            self._spawns_outstanding += 1
            w = self._spawn_worker(kind, env_key=env_key,
                                   env_spec=env_spec)
            asyncio.get_running_loop().create_task(self._finish_spawn(w))

    async def _finish_spawn(self, w: WorkerHandle):
        try:
            if not await self._wait_registered(w):
                return
        finally:
            self._spawns_outstanding -= 1
        pool = self._idle(w.kind, w.env_key)
        if w.lease_id is None and w not in pool:
            w.last_idle = time.monotonic()
            pool.append(w)
        self._kick_scheduler()

    async def rpc_return_worker(self, conn, body):
        lease = self.leases.pop(body["lease_id"], None)
        if lease is None:
            return {"ok": False}
        self._release_resources(lease)
        w = lease.worker
        w.lease_id = None
        if body.get("kill"):
            await self._on_worker_dead(w, "lease returned with kill")
        elif w.conn is not None and not w.conn.closed:
            w.last_idle = time.monotonic()
            self._idle(w.kind, w.env_key).append(w)
        self._kick_scheduler()
        return {"ok": True}

    async def rpc_worker_blocked(self, conn, body):
        """Worker is blocked in get(); temporarily release its CPUs so the
        pool can make progress (reference: node_manager blocked-worker
        resource release — prevents nested-get deadlock)."""
        lease = self.leases.get(body["lease_id"])
        if lease is None or lease.blocked:
            return {"ok": False}
        lease.blocked = True
        cpus = {k: v for k, v in lease.resources.items() if k == "CPU"}
        if cpus:
            self._release(cpus, lease.pg_key)
            self._kick_scheduler()
        return {"ok": True}

    async def rpc_worker_unblocked(self, conn, body):
        lease = self.leases.get(body["lease_id"])
        if lease is None or not lease.blocked:
            return {"ok": False}
        lease.blocked = False
        cpus = {k: v for k, v in lease.resources.items() if k == "CPU"}
        if cpus:
            self._acquire(cpus, lease.pg_key)  # may overcommit briefly
        return {"ok": True}

    # -------------------------------------------------------- actor leasing
    async def rpc_lease_worker_for_actor(self, conn, body):
        resources = body.get("resources") or {}
        pg_id = body.get("pg_id")
        pg_key = None
        if pg_id is not None:
            pg_key = self._bundle_key_for(pg_id, body.get("bundle_index"),
                                          resources)
            if pg_key is None:
                return {"ok": False, "reason": "bundle not here"}
        if not self._fits(resources, pg_key):
            return {"ok": False, "reason": "resources busy"}
        self._acquire(resources, pg_key)
        kind = "tpu" if resources.get("TPU") else "cpu"
        renv = (body.get("spec") or {}).get("runtime_env") or {}
        from ray_tpu.runtime_env import env_spec as _env_spec
        from ray_tpu.runtime_env import worker_env_key
        espec = _env_spec(renv)
        w = await self._get_ready_worker(
            kind,
            env_key=self._local_env_key(worker_env_key(renv), espec),
            env_spec=espec)
        if w is None:
            self._release(resources, pg_key)
            return {"ok": False, "reason": "no worker"}
        lease_id = os.urandom(8)
        lease = Lease(lease_id, w, resources, pg_key)
        self.leases[lease_id] = lease
        w.lease_id = lease_id
        w.actor_id = body["actor_id"]
        tpu_ids = self._alloc_tpu_ids(lease)
        try:
            reply = await w.conn.request("create_actor", {
                "actor_id": body["actor_id"],
                "spec": body["spec"],
                "lease_id": lease_id,
                "tpu_ids": tpu_ids,
            }, timeout=120.0)
        except Exception as e:
            await self._on_worker_dead(w, f"actor creation failed: {e}")
            return {"ok": False, "reason": f"create_actor failed: {e}"}
        if not reply.get("ok"):
            w.actor_id = None
            self.leases.pop(lease_id, None)
            self._free_tpu_ids(lease)
            self._release(resources, pg_key)
            w.last_idle = time.monotonic()
            self._idle(w.kind, w.env_key).append(w)
            return {"ok": False, "reason": reply.get("error", "init failed"),
                    "init_error": reply.get("error_blob")}
        return {"ok": True, "worker_addr": w.addr, "worker_id": w.worker_id,
                "pid": w.pid}

    # ------------------------------------------------------ placement groups
    async def rpc_prepare_bundle(self, conn, body):
        resources = body["resources"]
        if not self._fits(resources):
            return {"ok": False}
        self._acquire(resources)
        key = (body["pg_id"], body["bundle_index"])
        self.bundles[key] = {"reserved": dict(resources),
                             "avail": dict(resources), "committed": False}
        return {"ok": True}

    async def rpc_commit_bundle(self, conn, body):
        key = (body["pg_id"], body["bundle_index"])
        if key in self.bundles:
            self.bundles[key]["committed"] = True
            return {"ok": True}
        return {"ok": False}

    async def rpc_return_bundle(self, conn, body):
        key = (body["pg_id"], body["bundle_index"])
        acct = self.bundles.pop(key, None)
        if acct is not None:
            self._release(acct["reserved"])
            self._kick_scheduler()
        return {"ok": True}

    # ---------------------------------------------------------- object store
    async def rpc_os_create(self, conn, body):
        oid: bytes = body["oid"]
        size: int = body["size"]
        if size > self.store_capacity:
            # Can never fit — fail NOW, not after the full retry window.
            return {"error": f"object of {size} bytes exceeds the "
                             f"object store capacity "
                             f"({self.store_capacity} bytes)"}
        # One bounded converge loop for BOTH transient obstacles:
        #  - memory pinned by running tasks' zero-copy args: QUEUE the
        #    create instead of failing (reference: the plasma store's
        #    create-request queue blocks until eviction frees room) —
        #    pins drop as tasks finish, backoff re-probes ever more
        #    gently after a nearly-free first retry;
        #  - an UNSEALED in-flight creation of the same oid (inbound
        #    pull/push, another worker): alloc raises KeyError but
        #    contains() is sealed-only, so {exists} would make the
        #    client skip its write while trusting a transfer that may
        #    yet abort (leaving the object permanently unsealed).  Wait
        #    it out: the seal turns the NEXT iteration's contains()
        #    into {exists}; an abort frees the entry and our alloc
        #    wins.
        deadline = (asyncio.get_running_loop().time()
                    + cfg.create_retry_timeout_s)
        backoff = retry.ExpBackoff(0.02, 0.5)
        off = None
        inflight = False
        while True:
            if self.store.contains(oid):
                # Idempotent create: a reconstruction re-executing the
                # producing task on a node that still holds a SEALED
                # copy must not error — the client skips its
                # write+seal and the existing copy stands.
                return {"exists": True}
            try:
                off = await self._alloc_with_spill(oid, size)
                inflight = False
            except KeyError:
                off, inflight = None, True
            if off is not None or self._shutdown or \
                    asyncio.get_running_loop().time() >= deadline:
                break
            await asyncio.sleep(backoff.next())
        if self._shutdown:
            return {"error": "raylet shutting down"}
        if inflight:
            return {"error": f"creation of {oid.hex()} raced an "
                             f"in-flight transfer that neither sealed "
                             f"nor aborted within "
                             f"{cfg.create_retry_timeout_s:.0f}s"}
        if off is None:
            try:
                holders = {}
                for conn_id, pins in self._client_pins.items():
                    who = "?"
                    for w in self.workers.values():
                        if w.conn is not None and id(w.conn) == conn_id:
                            who = f"worker:{w.pid}"
                            break
                    holders[f"{who}#{conn_id % 9973}"] = sum(pins.values())
                logger.warning(
                    "create of %d bytes timed out; stats=%s primaries=%d "
                    "holders=%s", size, self.store.stats(),
                    len(self.primary_objects), holders)
            except Exception:
                pass
            return {"error": f"object store OOM allocating {size} bytes "
                             f"(after spilling)"}
        self._created_sizes[oid] = size
        # Remember who is mid-create: if the client dies before sealing,
        # its unsealed allocation must be discarded (conn-loss handler).
        self._creating.setdefault(id(conn), set()).add(oid)
        return {"offset": off}

    async def _alloc_with_spill(self, oid: bytes, size: int):
        """alloc, spilling primary copies to disk on memory pressure (the
        C++ store already LRU-evicts unpinned secondary copies).  Spills
        escalate: a fragmented arena may need several times `size` freed
        before first-fit finds a contiguous hole, so keep spilling until
        the alloc lands or nothing spillable remains."""
        off = self.store.alloc(oid, size)
        attempt = 0
        while off is None and attempt < 6:
            freed = await self._spill_bytes(size * (1 + attempt))
            off = self.store.alloc(oid, size)
            if freed == 0 and off is None:
                break
            attempt += 1
        return off

    async def _spill_bytes(self, need: int) -> int:
        """Move primary copies to disk, oldest first, until ~need bytes of
        pinned space have been released.  Returns bytes freed."""
        os.makedirs(self.spill_dir, exist_ok=True)
        freed = 0
        loop = asyncio.get_running_loop()
        for oid in list(self.primary_objects):
            if freed >= need:
                break
            size = self.primary_objects.get(oid)
            if size is None or oid in self.spilled \
                    or oid in self._spilling:
                # _spilling guard: concurrent OOM allocs must not spill the
                # same object twice (double file write + pin over-release).
                continue
            self._spilling.add(oid)
            got = self.store.get(oid)
            try:
                if got is None:
                    self.primary_objects.pop(oid, None)
                    continue
                offset, sz, sealed = got
                if not sealed:
                    # Get() takes no pin on unsealed objects — nothing
                    # to release (a release here would have stolen the
                    # creator's pin and freed the extent under its
                    # in-progress write; the store now rejects it).
                    continue
                path = os.path.join(self.spill_dir, oid.hex())
                data = bytes(self.mapping.slice(offset, sz))
                await loop.run_in_executor(None, self._write_spill_file,
                                           path, data)
                self.store.release(oid)        # our read pin
                self.spilled[oid] = (path, sz)
                self.primary_objects.pop(oid, None)
                # Deferred delete + drop the creator pin: the arena region
                # is reclaimed once concurrent readers release.
                self.store.delete(oid)
                self.store.release(oid)
                freed += sz
                logger.info("spilled %s (%d bytes) to %s",
                            oid.hex()[:8], sz, path)
            finally:
                self._spilling.discard(oid)
        return freed

    @staticmethod
    def _write_spill_file(path: str, data: bytes):
        tmp = path + ".tmp"
        with open(tmp, "wb") as f:
            f.write(data)
        os.replace(tmp, path)

    async def _restore_spilled(self, oid: bytes) -> bool:
        """Bring a spilled object back into the arena (reference:
        SpilledObjectReader)."""
        ent = self.spilled.get(oid)
        if ent is None:
            return False
        fut = self._restores_inflight.get(oid)
        if fut is not None:
            return await asyncio.shield(fut)
        fut = asyncio.get_running_loop().create_future()
        self._restores_inflight[oid] = fut
        off = None
        try:
            path, size = ent
            off = await self._alloc_with_spill(oid, size)
            if off is None:
                fut.set_result(False)
                return False
            data = await asyncio.get_running_loop().run_in_executor(
                None, lambda: open(path, "rb").read())
            self.mapping.slice(off, size)[:] = data
            # Restored copy is evictable (the disk copy remains the
            # primary until os_delete).
            self._seal_release_notify(oid)
            fut.set_result(True)
            return True
        except Exception as e:
            logger.warning("restore of %s failed: %s", oid.hex()[:8], e)
            if off is not None:
                self._discard_unsealed(oid)
            if not fut.done():
                fut.set_result(False)
            return False
        finally:
            self._restores_inflight.pop(oid, None)

    async def rpc_os_seal(self, conn, body):
        oid = body["oid"]
        creating = self._creating.get(id(conn))
        if creating is not None:
            creating.discard(oid)
        self.store.seal(oid)
        size = self._created_sizes.pop(oid, None)
        if size is not None:
            # Client-created (not pulled): this node holds the primary copy.
            self.primary_objects[oid] = size
        for fut in self.seal_waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(None)
        self._report_sealed(oid)
        return {"ok": True}

    def _report_sealed(self, oid: bytes):
        """Report a fresh sealed copy to the GCS object directory —
        only when it is big enough to ever stripe: the directory's sole
        consumer is multi-source pull selection, and sub-threshold
        objects would just accrete entries the C store can LRU-evict
        without telling anyone."""
        got = self.store.get(oid)
        if got is None:
            return
        self.store.release(oid)
        if got[1] >= cfg.transfer_stripe_min_bytes:
            self._reported_locs.add(oid)
            self._report_locations([oid], added=True)

    def _report_locations(self, oids, added: bool):
        """Fire-and-forget report of sealed copies appearing/vanishing
        on this node to the GCS object directory (the striped-pull
        source list).  Best-effort: a lost report only costs a pull its
        extra sources, and stat-at-pull filters stale entries."""
        if self.gcs is None or self.gcs.closed or self._shutdown:
            return
        method = ("object_locations_added" if added
                  else "object_locations_removed")
        try:
            task = asyncio.get_running_loop().create_task(
                self.gcs.push(method, {"node_id": self.node_id,
                                       "oids": list(oids)}))
            task.add_done_callback(
                lambda t: t.cancelled() or t.exception())
        except Exception:
            pass

    async def rpc_os_get(self, conn, body):
        """Resolve objects to (offset, size) in the local arena, pulling from
        remote nodes when needed (locations provided by owners).  The
        client's timeout becomes ONE deadline for the whole resolution —
        every wait and every pulled chunk draws from the same budget
        (previously each chunk request was re-granted the full timeout,
        so a transfer could legally take timeout x n_chunks)."""
        oid = body["oid"]
        timeout = body.get("timeout", 60.0)
        deadline = time.monotonic() + timeout
        location = body.get("location")  # NodeID where the object lives
        # Caller's span context (worker-side get): a pull recorded here
        # links into the task's trace, crossing worker -> raylet.  The
        # flow edge closes HERE, not inside TransferManager.pull: the
        # resolution may be served without a fresh pull (already local,
        # joined an in-flight pull or push), and the worker's flow-start
        # must not dangle in those cases.
        trace = body.get("trace")
        if trace and trace.get("flow"):
            _tracing.flow_end(trace["flow"], "transfer")
        if oid in self.spilled and not self.store.contains(oid):
            await self._restore_spilled(oid)
        got = self.store.get(oid)
        if got is not None:
            offset, size, sealed = got
            if sealed:
                self._track_pin(conn, oid)
                return {"offset": offset, "size": size}
            await self._wait_sealed(oid, self._remaining(deadline))
            got = self.store.get(oid)
            if got and got[2]:
                # Keep the re-get's pin and track it: the client's later
                # os_release must find a pin of its own to drop, not steal
                # the creator's.
                self._track_pin(conn, oid)
                return {"offset": got[0], "size": got[1]}
            # "timeout": the caller's budget ran out, the object still
            # exists — the worker maps this to GetTimeoutError, never to
            # an ObjectLostError that would trigger reconstruction.
            return {"error": "timeout waiting for object seal",
                    "timeout": True}
        if location is not None and location != self.node_id:
            # A failed pull is only "lost" if the control plane agrees no
            # copy-holding node is alive; an unreachable-but-alive source
            # (partition, restart, half-open link) is transient, so the
            # pull retries under the caller's budget.  Reporting a merely
            # partitioned object as lost would re-execute its creating
            # task even though the copy still exists.
            backoff = retry.ExpBackoff(0.05, 1.0)
            ok = False
            while True:
                ok = await self._pull_object(oid, location, deadline,
                                             trace)
                if ok:
                    break
                if time.monotonic() >= deadline:
                    return {"error": f"pull deadline exceeded fetching "
                                     f"{oid.hex()}", "timeout": True}
                if not await self._object_source_alive(oid, location):
                    return {"error": f"failed to pull {oid.hex()} from "
                                     f"{location.hex()[:8]}: no live "
                                     f"source"}
                rem = self._remaining(deadline)
                await asyncio.sleep(min(backoff.next(), rem or 0.001))
            got = self.store.get(oid)
            if got and got[2]:
                self._track_pin(conn, oid)
                return {"offset": got[0], "size": got[1]}
        await self._wait_sealed(oid, self._remaining(deadline))
        got = self.store.get(oid)
        if got and got[2]:
            self._track_pin(conn, oid)
            return {"offset": got[0], "size": got[1]}
        # NOT flagged as a timeout even when the budget is spent: with no
        # pullable location and nothing sealed locally the object may be
        # genuinely gone, and ObjectLostError is what lets the owner fall
        # back to lineage reconstruction.
        return {"error": f"object {oid.hex()} not found"}

    # One deadline clamp for the whole transfer plane (shared with
    # TransferManager so the floor/None semantics can't diverge).
    _remaining = staticmethod(_remain)

    async def _object_source_alive(self, oid, location) -> bool:
        """Is ANY node believed to hold a copy of ``oid`` still alive
        per the control plane?  Decides pull-retry (alive: the failure
        is transient) vs ObjectLost/reconstruction (dead).  Liveness
        is answered from the pubsub-synced local node view — this runs
        once per failed pull attempt, and re-dumping the whole node
        table from the GCS on every retry across many degraded pulls
        would stampede the very service the jittered retries protect —
        with one cheap directory RPC for extra copy-holders.  An
        unreachable GCS cannot prove death, so it answers alive."""
        candidates = {location}
        if self.gcs is not None and not self.gcs.closed:
            try:
                reply = await self.gcs.request(
                    "get_object_locations", {"oid": oid}, timeout=5.0)
                candidates.update(reply.get("locations", []))
            except Exception:
                return True  # partitioned from the GCS: inconclusive
        for nid in candidates:
            view = self.cluster_nodes.get(nid)
            if view is not None and view.get("alive", True):
                return True
        return False

    async def _wait_sealed(self, oid, timeout):
        fut = asyncio.get_running_loop().create_future()
        self.seal_waiters.setdefault(oid, []).append(fut)
        try:
            await asyncio.wait_for(fut, timeout)
        except asyncio.TimeoutError:
            pass

    async def _peer(self, node_id) -> protocol.Connection | None:
        conn = self.peer_conns.get(node_id)
        if conn is not None and not conn.closed:
            return conn
        view = self.cluster_nodes.get(node_id)
        if view is None and self.gcs is not None:
            # Routed through _observe_node_view: the scheduling index
            # must learn anything this fallback discovers, and dead
            # (alive=False) views must stay rejected — get_nodes
            # returns the full table including the departed.
            for v in await self.gcs.request("get_nodes", {}):
                self._observe_node_view(v)
            view = self.cluster_nodes.get(node_id)
        if view is None:
            return None
        try:
            conn = await protocol.Connection.connect(
                view["addr"][0], view["addr"][1], handler=self._handle,
                name=f"raylet:{self.node_id.hex()[:8]}"
                     f"->raylet:{node_id.hex()[:8]}",
                timeout=cfg.connect_timeout_s,
                blob_provider=self._blob_sink)
        except Exception:
            return None
        self.peer_conns[node_id] = conn
        return conn

    async def _pull_object(self, oid, location, deadline,
                           trace=None) -> bool:
        if oid in self._pulls_inflight:
            try:
                return await asyncio.wait_for(
                    asyncio.shield(self._pulls_inflight[oid]),
                    self._remaining(deadline))
            except asyncio.TimeoutError:
                return False
        fut = asyncio.get_running_loop().create_future()
        self._pulls_inflight[oid] = fut
        try:
            ok = await self._do_pull(oid, location, deadline, trace)
            if not fut.done():
                fut.set_result(ok)
            return ok
        except Exception as e:
            if not fut.done():
                fut.set_result(False)
            logger.warning("pull %s failed: %s", oid.hex()[:8], e)
            return False
        finally:
            self._pulls_inflight.pop(oid, None)

    async def _do_pull(self, oid, location, deadline, trace=None) -> bool:
        if oid in self._push_recv:
            # A push of this object is already streaming in: wait for its
            # seal instead of double-allocating.  If the pushing sender
            # dies, _abort_pushes_from (conn loss) or the stale sweep
            # cleans the transfer and wakes us to fall through to a pull.
            await self._wait_sealed(oid, self._remaining(deadline))
            got = self.store.get(oid)
            if got is not None and got[2]:
                self.store.release(oid)  # get() pinned the sealed copy
                return True
            if oid in self._push_recv:
                # Push stream still live after the full deadline: it owns
                # the allocation, so a pull can't proceed.
                return False
        # Windowed, possibly striped transfer (TransferManager resolves
        # extra sealed sources via the GCS object directory).
        return await self.transfers.pull(oid, location, deadline,
                                         trace=trace)

    async def rpc_os_stat(self, conn, body):
        oid = body["oid"]
        got = self.store.get(oid)
        if got is None or not got[2]:
            spilled = self.spilled.get(oid)
            if spilled is not None:
                return {"size": spilled[1]}
            if oid in self._reported_locs:
                # Directory self-heal: this node once advertised a copy
                # the C store has since LRU-evicted (eviction has no
                # Python hook).  The first wasted stat prunes the stale
                # entry so later pulls stop selecting this node.
                self._reported_locs.discard(oid)
                self._report_locations([oid], added=False)
            return {"error": "not here"}
        self.store.release(oid)
        return {"size": got[1]}

    async def rpc_os_map(self, conn, body):
        """Same-host zero-copy pull support: pin the sealed object and
        expose its arena location so a co-located raylet can mmap this
        node's arena file read-only and memcpy the bytes directly
        (reference: plasma clients share the store mmap; here each
        raylet owns an arena, so cross-raylet same-host reads map the
        peer's file).  The caller MUST os_release when the copy is done
        (conn loss releases tracked pins as usual)."""
        oid = body["oid"]
        got = self.store.get(oid)
        if got is None or not got[2]:
            return {"error": "not here"}  # spilled/unsealed: wire path
        offset, size, _ = got
        self._track_pin(conn, oid)
        return {"offset": offset, "size": size,
                "store_path": self.store_path,
                "capacity": self.store_capacity}

    async def rpc_os_read_chunk(self, conn, body):
        """Serve one chunk of a sealed (or spilled) object.  The reply
        rides a raw KIND_BLOB_REP frame: the arena slice goes to the
        transport as ONE memoryview (the read pin is dropped once the
        transport no longer references it) — chunk bytes never touch
        pickle.  ``body["pickle"]`` selects the legacy pickled-dict
        reply for old-style sequential readers (and the bench's
        stop-and-wait baseline)."""
        oid = body["oid"]
        legacy = body.get("pickle", False)
        if failpoints.ACTIVE:
            act = failpoints.check("raylet.serve_chunk",
                                   peer=self.node_id.hex()[:8])
            if act is not None:
                if act.kind == "error":
                    return {"error": "failpoint: injected serve error"}
                if act.kind == "delay":
                    await asyncio.sleep(act.delay_s)
                elif act.kind == "drop":
                    # A lost reply: stall past any sane chunk deadline
                    # so the puller times out / reroutes, exactly as if
                    # the frame had vanished on the wire.
                    await asyncio.sleep(act.delay_s or 60.0)
                    return {"error": "failpoint: chunk reply dropped"}
        got = self.store.get(oid)
        if got is None or not got[2]:
            spilled = self.spilled.get(oid)
            if spilled is not None:
                # Serve peer pulls straight from the spill file — no need
                # to churn the arena for a pass-through transfer.  One fd
                # per in-progress transfer, positional reads (pread), so
                # concurrent windowed chunks don't reopen the file or
                # race a shared seek offset.
                path, size = spilled
                start = body["offset"]
                n = min(body["len"], size - start)
                ent = self._spill_fd_acquire(oid, path)
                if ent is None:
                    return {"error": "spill file unavailable"}
                try:
                    data = await asyncio.get_running_loop().run_in_executor(
                        None, os.pread, ent[0], n, start)
                except OSError as e:
                    return {"error": f"spill read failed: {e}"}
                finally:
                    self._spill_fd_release(oid, ent,
                                           eof=start + n >= size)
                if legacy:
                    return {"data": data}
                return protocol.Blob({"len": len(data)}, data)
            return {"error": "not here"}
        offset, size, _ = got
        start = body["offset"]
        n = min(body["len"], size - start)
        if legacy:
            data = bytes(self.mapping.slice(offset + start, n))
            self.store.release(oid)
            return {"data": data}
        return protocol.Blob(
            {"len": n}, self.mapping.slice(offset + start, n),
            on_sent=lambda: self.store.release(oid))

    # One open fd serves every chunk of an in-progress spilled-object
    # transfer (the old path reopened the file PER CHUNK); closed when
    # the last chunk has been read out or by the stale sweep.
    def _spill_fd_acquire(self, oid: bytes, path: str):
        ent = self._spill_read_fds.get(oid)
        if ent is None:
            try:
                fd = os.open(path, os.O_RDONLY)
            except OSError:
                return None
            ent = self._spill_read_fds[oid] = \
                [fd, time.monotonic(), 0, False]
        ent[1] = time.monotonic()
        ent[2] += 1
        return ent

    def _spill_fd_release(self, oid: bytes, ent, eof: bool):
        ent[2] -= 1
        if eof:
            ent[3] = True
        if ent[3] and ent[2] <= 0 \
                and self._spill_read_fds.get(oid) is ent:
            self._close_spill_fd(oid)

    def _retire_spill_fd(self, oid: bytes):
        """Close the cached spill fd — unless executor-thread preads are
        still in flight, in which case mark it close-on-last-read:
        closing under a reader would let a reused fd number serve bytes
        of some unrelated file as chunk data."""
        ent = self._spill_read_fds.get(oid)
        if ent is not None and ent[2] > 0:
            ent[3] = True  # the final _spill_fd_release closes it
        else:
            self._close_spill_fd(oid)

    def _close_spill_fd(self, oid: bytes):
        ent = self._spill_read_fds.pop(oid, None)
        if ent is not None:
            try:
                os.close(ent[0])
            except OSError:
                pass

    def _track_pin(self, conn, oid: bytes):
        pins = self._client_pins.setdefault(id(conn), {})
        pins[oid] = pins.get(oid, 0) + 1

    def _release_client_pins(self, conn):
        """Client (worker/driver) went away: drop every pin it held so its
        objects become evictable again (reference: plasma releases a
        client's objects when its socket closes)."""
        pins = self._client_pins.pop(id(conn), None)
        if not pins:
            return
        for oid, count in pins.items():
            for _ in range(count):
                self.store.release(oid)

    async def rpc_os_release(self, conn, body):
        oid = body["oid"]
        pins = self._client_pins.get(id(conn))
        if pins and pins.get(oid):
            pins[oid] -= 1
            if pins[oid] <= 0:
                del pins[oid]
        self.store.release(oid)
        if self.pending_leases:
            # Freed pins may clear the store-pressure admission gate.
            self._kick_scheduler()
        return {"ok": True}

    async def rpc_os_delete(self, conn, body):
        oid = body["oid"]
        was_primary = self.primary_objects.pop(oid, None) is not None
        self.store.delete(oid)
        if was_primary:
            # Drop the creator pin (held since alloc so the primary copy
            # could never be LRU-evicted).  Without this the delete stays
            # deferred forever and a put/delete loop leaks the arena dry.
            self.store.release(oid)
        self._created_sizes.pop(oid, None)
        self._retire_spill_fd(oid)
        spilled = self.spilled.pop(oid, None)
        if spilled is not None:
            try:
                os.remove(spilled[0])
            except OSError:
                pass
        # Only objects actually in the directory need a removal report —
        # the common sub-stripe object was never added, and a push per
        # GC'd oid would tax the hot release path for nothing.
        if oid in self._reported_locs:
            self._reported_locs.discard(oid)
            self._report_locations([oid], added=False)
        return {"ok": True}

    async def rpc_os_contains(self, conn, body):
        return {"contains": self.store.contains(body["oid"])}

    # ---------------------------------------------------------- push path
    # Reference: the PushManager half of the object manager
    # (src/ray/object_manager/push_manager.h) — the owner side streams
    # chunks unsolicited so broadcast-shaped flows (weight sync, large
    # shared args) pre-position copies instead of N cold pulls.

    def _seal_release_notify(self, oid):
        """Seal a transferred-in copy, drop the creator pin, and wake
        seal waiters (shared by the pull, restore, and push receive
        paths).  The new sealed copy is reported to the GCS object
        directory so later pulls can stripe across it."""
        self.store.seal(oid)
        self.store.release(oid)
        for fut in self.seal_waiters.pop(oid, []):
            if not fut.done():
                fut.set_result(None)
        self._report_sealed(oid)

    async def rpc_os_push_to(self, conn, body):
        """Replicate a local sealed object to peer raylets (targets are
        node ids).  Transfers run concurrently — one slow peer doesn't
        serialize the broadcast."""
        oid = body["oid"]
        results = await asyncio.gather(
            *(self.transfers.push(oid, node_id)
              for node_id in body["targets"]))
        pushed, failed = [], []
        for node_id, ok in zip(body["targets"], results):
            (pushed if ok else failed).append(node_id.hex())
        return {"pushed": pushed, "failed": failed}

    def _sweep_stale_pushes(self, now):
        """Drop transfers with no chunk activity for more than
        cfg.push_stale_sweep_s (sender died mid-stream) so their
        unsealed allocations don't leak the arena, and close spill-read
        fds idle past the same threshold.  Staleness is measured from
        the LAST chunk, so a legitimately slow large push is never swept
        while it is still making progress.  Waiters are woken (they
        re-check the store and fall back to a pull or a timeout error
        instead of hanging out their full timeout)."""
        stale_s = cfg.push_stale_sweep_s
        for stale, ent in list(self._push_recv.items()):
            if now - ent["last"] > stale_s:
                conn = ent.get("conn")
                if conn is not None and conn._sink_reads:
                    # A chunk body is mid-read into this extent right
                    # now: not stale, and freeing it would corrupt the
                    # write.  Fresh grace period.
                    ent["last"] = now
                    continue
                self._push_recv.pop(stale, None)
                self._discard_unsealed(stale)
                for fut in self.seal_waiters.pop(stale, []):
                    if not fut.done():
                        fut.set_result(None)
        for oid, fent in list(self._spill_read_fds.items()):
            if fent[2] <= 0 and now - fent[1] > stale_s:
                self._close_spill_fd(oid)

    async def rpc_os_push_begin(self, conn, body):
        """Open one inbound push transfer: allocate the destination
        extent and register the transfer under the sender connection.
        Subsequent os_push chunk frames from that connection land
        straight in the allocation (see _blob_sink).  A concurrent push
        of the same oid from a second sender is answered {skip} rather
        than clobbering the live transfer (reference: PushManager dedups
        pushes per (object, node))."""
        oid, size = body["oid"], body["size"]
        now = time.monotonic()
        sender = id(conn)
        self._sweep_stale_pushes(now)
        ent = self._push_recv.get(oid)
        if ent is not None:
            if ent["sender"] != sender:
                # A live transfer from another sender owns this oid.
                return {"skip": True}
            # Same sender restarting its own stream: start clean.
            self._push_recv.pop(oid, None)
            self._discard_unsealed(oid)
        elif self.store.contains(oid) or oid in self._pulls_inflight:
            return {"skip": True}
        try:
            off = await self._alloc_with_spill(oid, size)
        except KeyError:
            return {"skip": True}  # concurrent pull/push won
        if off is None:
            return {"error": "object store OOM receiving push"}
        # Each transfer gets its own generation, echoed back in every
        # chunk header: a same-sender restart pops the old entry, but
        # its already-in-flight chunks must NOT count toward the new
        # transfer's "received" (they may duplicate offsets the new
        # stream will resend, sealing an object with unwritten holes).
        self._push_gen += 1
        gen = self._push_gen
        # "chunks" records the starting offset of every chunk already
        # counted: a duplicated frame (retry, network dup, chaos dup
        # action) must be idempotent, never double-counted — a byte
        # counter alone would seal the object early with holes.
        self._push_recv[oid] = {"off": off, "size": size, "sender": sender,
                                "gen": gen, "conn": conn, "last": now,
                                "received": 0, "chunks": set()}
        return {"ok": True, "gen": gen}

    def _blob_sink(self, conn, method, header, nbytes):
        """Blob-frame sink resolution (runs synchronously on the read
        loop BEFORE the payload is consumed): inbound os_push chunk
        bytes are written straight into the arena extent their transfer
        allocated in os_push_begin — no staging buffer, no pickle.
        Returns None (frame buffered normally) for anything that isn't
        a live, in-range chunk of a transfer owned by this sender."""
        if method != "os_push" or not isinstance(header, dict):
            return None
        ent = self._push_recv.get(header.get("oid"))
        if ent is None or ent["sender"] != id(conn) \
                or ent["gen"] != header.get("gen"):
            return None
        pos, n = header.get("offset", -1), header.get("len", -1)
        if n != nbytes or pos < 0 or pos + n > ent["size"]:
            return None
        return self.mapping.writable(ent["off"] + pos, n)

    async def rpc_os_push(self, conn, body):
        """Account one pushed chunk (its bytes were already routed into
        the arena by _blob_sink while the frame was being read); seal
        once every byte has arrived.  ``body`` is a protocol.BlobFrame —
        body.data is None on the fast path, or carries the raw bytes
        when the sink was declined (transfer swept/superseded between
        frames, or an out-of-range header)."""
        hdr = body.header
        oid = hdr["oid"]
        ent = self._push_recv.get(oid)
        if ent is None or ent["sender"] != id(conn) \
                or ent["gen"] != hdr.get("gen"):
            # Transfer swept as stale, superseded by a restart, or never
            # opened: these bytes were NOT kept.  An explicit error (not
            # a silent ok/skip) so the sender doesn't report a replica
            # on a node that discarded the data.
            return {"error": "push transfer not live"}
        ent["last"] = time.monotonic()
        if body.data is not None:
            # Declined sink with a live entry: validate and fall back to
            # an explicit copy into the extent.
            pos, n = hdr.get("offset", -1), hdr.get("len", -1)
            if n != len(body.data) or pos < 0 or pos + n > ent["size"]:
                return {"error": "push chunk out of range"}
            dest = self.mapping.writable(ent["off"], ent["size"])
            dest[pos:pos + n] = body.data
        if hdr["offset"] in ent["chunks"]:
            # Duplicate delivery of a chunk this transfer already
            # counted: the (re)write above was byte-identical, so just
            # ack without advancing "received".
            return {"ok": True, "duplicate": True}
        ent["chunks"].add(hdr["offset"])
        ent["received"] += hdr["len"]
        if ent["received"] >= ent["size"]:
            self._push_recv.pop(oid, None)
            self._seal_release_notify(oid)
        return {"ok": True}

    async def rpc_os_used(self, conn, body):
        return {"used": self.store.used(), "capacity": self.store_capacity}

    async def rpc_transfer_stats(self, conn, body):
        """Transfer-plane counters (pull/push volumes, striping,
        retries) for tests and observability."""
        return dict(self.transfers.stats)

    async def rpc_dump_trace(self, conn, body):
        """Pull-path trace dump for this node: the raylet's own span
        ring plus — with include_workers (default on) — every
        registered worker's ring, fanned out concurrently.  Returns
        {"processes": [per-process dump...]}; a worker that fails to
        answer contributes an {"error": ...} stub instead of failing
        the node dump."""
        body = body or {}
        stats_only = bool(body.get("stats_only"))
        clear = bool(body.get("clear"))
        procs = [dict(_tracing.dump(stats_only=stats_only, clear=clear),
                      role="raylet", node_id=self.node_id.hex())]
        if body.get("include_workers", True):
            targets = [w for w in list(self.workers.values())
                       if w.conn is not None and not w.conn.closed]

            async def _one(w):
                try:
                    d = await w.conn.request(
                        "dump_trace", {"stats_only": stats_only,
                                       "clear": clear}, timeout=10.0)
                    d["role"] = "worker"
                    d["worker_id"] = w.worker_id.hex()
                    return d
                except Exception as e:
                    return {"role": "worker", "pid": w.pid,
                            "worker_id": w.worker_id.hex(),
                            "error": f"{type(e).__name__}: {e}"}

            procs.extend(await asyncio.gather(*[_one(w)
                                                for w in targets]))
        return {"processes": procs, "node_id": self.node_id.hex()}

    # ------------------------------------------------------ state API feeds
    async def rpc_pool_stats(self, conn, body):
        """Worker-pool quiescence probe: spawned-but-unregistered workers
        are still paying interpreter startup (~2s of CPU each with jax in
        the image) — benchmarks and tests wait for zero before timing."""
        unregistered = sum(1 for w in self.workers.values()
                           if not w.registered.is_set())
        return {"workers": len(self.workers), "starting": unregistered,
                "leases": len(self.leases)}

    async def rpc_list_leases(self, conn, body):
        """Running + queued work on this node (reference: per-worker task
        state feeding python/ray/experimental/state/api.py list_tasks)."""
        running = []
        for lease in self.leases.values():
            running.append({
                "lease_id": lease.lease_id.hex(),
                "worker_id": lease.worker.worker_id.hex(),
                "pid": lease.worker.pid,
                "resources": lease.resources,
                "actor_id": (lease.worker.actor_id.hex()
                             if lease.worker.actor_id else None),
                "blocked": lease.blocked,
                "state": "RUNNING",
            })
        queued = [{"resources": p.get("resources", {}),
                   "state": "PENDING_NODE_ASSIGNMENT"}
                  for p in self.pending_leases]
        return {"running": running, "queued": queued,
                "node_id": self.node_id.hex()}

    async def rpc_list_local_objects(self, conn, body):
        objs = []
        for oid, size in self.primary_objects.items():
            objs.append({"object_id": oid.hex(), "size": size,
                         "where": "memory", "primary": True})
        for oid, (_path, size) in self.spilled.items():
            objs.append({"object_id": oid.hex(), "size": size,
                         "where": "spilled", "primary": True})
        return {"objects": objs, "node_id": self.node_id.hex(),
                "store_used": self.store.used(),
                "store_capacity": self.store_capacity}

    # ------------------------------------------------------------- lifecycle
    async def _heartbeat_loop(self):
        """Versioned-snapshot resource sync (reference: RaySyncer,
        common/ray_syncer/ray_syncer.h:88 — reporters version their
        snapshots; only versions the receiver hasn't acked travel).

        Every tick sends a liveness beat carrying just (node_id,
        version); the resource payload is attached only while the GCS's
        acked version lags the local one.  A restarted GCS acks 0, so
        the next beat automatically carries a full snapshot."""
        report_period = cfg.resource_report_period_ms / 1000.0
        beat_period = cfg.heartbeat_period_ms / 1000.0
        last_report = None
        last_beat = 0.0
        self._last_hw_report = 0.0
        self._sync_version = 0
        self._gcs_acked_version = -1
        last_sweep = 0.0
        while not self._shutdown:
            await asyncio.sleep(report_period)
            try:
                # Periodic transfer-plane sweep: a node that only SERVES
                # pulls never receives os_push_begin (the other sweep
                # trigger), so without this tick its aborted transfers'
                # cached spill-read fds and stale push extents would
                # leak until shutdown.
                tick = time.monotonic()
                if tick - last_sweep >= min(30.0, cfg.push_stale_sweep_s):
                    last_sweep = tick
                    self._sweep_stale_pushes(tick)
                report = (dict(self.available), self._load(),
                          [dict(p["resources"])
                           for p in self.pending_leases[:32]])
                if self._pending_gauge is not None:
                    self._pending_gauge.set(len(self.pending_leases))
                if report != last_report:
                    self._sync_version += 1
                    last_report = report
                need_payload = \
                    self._gcs_acked_version < self._sync_version
                now = time.monotonic()
                # Payload deltas ride the fast tick; liveness-only beats
                # ride the slow heartbeat period (an idle node costs one
                # tiny RPC per heartbeat_period_ms).
                if not need_payload and now - last_beat < beat_period:
                    continue
                last_beat = now
                body = {"node_id": self.node_id,
                        "version": self._sync_version}
                # Hardware report rides the slow beat (reference:
                # reporter_agent.py relaying psutil stats; here the
                # per-node raylet process samples directly).
                if now - self._last_hw_report >= beat_period:
                    self._last_hw_report = now
                    from ray_tpu._private.reporter import sample_node_stats
                    body["node_stats"] = sample_node_stats(
                        session_dir=self.session_dir, store=self.store,
                        store_capacity=self.store_capacity,
                        n_workers=len(self.workers))
                if need_payload:
                    body.update({
                        "available": report[0],
                        "load": report[1],
                        # Resource shapes of queued leases: the
                        # autoscaler's demand signal (reference:
                        # ResourceLoad feeding LoadMetrics).
                        "pending_shapes": report[2],
                    })
                if failpoints.ACTIVE:
                    act = failpoints.check("raylet.heartbeat",
                                           peer=self.node_id.hex()[:8])
                    if act is not None:
                        if act.kind == "drop":
                            continue  # this beat never leaves the node
                        if act.kind == "delay":
                            await asyncio.sleep(act.delay_s)
                        elif act.kind in ("error", "disconnect"):
                            raise protocol.ConnectionLost(
                                "failpoint: injected heartbeat "
                                f"{act.kind}")
                # Bounded wait: during a partition this request must
                # fail fast enough that the loop keeps beating through
                # the reconnect path instead of wedging on one RPC.
                reply = await self.gcs.request(
                    "heartbeat", body,
                    timeout=max(2.0, cfg.heartbeat_period_ms / 250.0))
                if reply.get("ok"):
                    self._gcs_acked_version = reply.get(
                        "acked_version", self._gcs_acked_version)
                elif "unknown node" in reply.get("reason", ""):
                    # GCS restarted and lost the node table: re-register
                    # (reference: NotifyGCSRestart node_manager.proto:343).
                    self._gcs_acked_version = -1
                    await self._reconnect_gcs()
            except Exception:
                if self._shutdown:
                    return
                self._gcs_acked_version = -1
                await self._reconnect_gcs()

    def _register_body(self):
        return {
            "node_id": self.node_id,
            "addr": (self.host, self.port),
            "resources": self.total_resources,
            "labels": self.labels,
            "node_name": self.node_name,
        }

    async def _reconnect_gcs(self):
        """Reconnect + re-register after a GCS restart/partition.  A
        raylet retries forever (it is useless without a control plane)
        but with full-jitter backoff, so a thousand raylets losing one
        GCS don't stampede its recovery in lockstep.  Bounded per-RPC
        timeouts keep a half-open link from wedging an attempt."""
        backoff = retry.ExpBackoff(cfg.gcs_reconnect_base_s,
                                   cfg.gcs_reconnect_cap_s)
        while not self._shutdown:
            try:
                conn = await protocol.Connection.connect(
                    self.gcs_addr[0], self.gcs_addr[1],
                    handler=self._handle_gcs_push,
                    name=f"raylet:{self.node_id.hex()[:8]}->gcs",
                    timeout=5.0)
                try:
                    # Events applied after this point are newer than
                    # the register reply's snapshot (the implicit
                    # subscription starts with registration) — the
                    # sync below must not override them.
                    cutoff = self._node_event_seq
                    reply = await conn.request("register_node",
                                               self._register_body(),
                                               timeout=10.0)
                    old, self.gcs = self.gcs, conn
                    if old is not None and not old.closed:
                        try:
                            await old.close()
                        except Exception:
                            pass
                    # Events missed while disconnected are gone, so
                    # cached nodes absent from the reply must stop
                    # being scheduling targets (soft prune: the reply
                    # may be INCOMPLETE after a non-persistent GCS
                    # restart, so live peer conns are not torn down —
                    # see _sync_node_views).
                    await self._sync_node_views(
                        reply.get("cluster_nodes", []),
                        hard_prune=False, cutoff=cutoff)
                    await self.gcs.request("subscribe",
                                           {"channels": ["nodes"]},
                                           timeout=10.0)
                except BaseException:
                    if self.gcs is not conn:
                        await conn.close()
                    raise
                logger.info("raylet %s re-registered with GCS",
                            self.node_id.hex()[:8])
                return
            except Exception:
                await asyncio.sleep(backoff.next())

    async def rpc_shutdown(self, conn, body):
        asyncio.get_running_loop().create_task(self.shutdown())
        return {"ok": True}

    async def rpc_ping(self, conn, body):
        return {"ok": True, "node_id": self.node_id}

    async def rpc_set_failpoints(self, conn, body):
        """Runtime fault-plane toggle: tests flip failpoints / partition
        rules on a live raylet mid-run (see failpoints.apply_rpc)."""
        return failpoints.apply_rpc(body)

    async def shutdown(self):
        self._shutdown = True
        # Announce planned exit BEFORE dropping the GCS connection, so
        # the control plane records an orderly drain instead of a node
        # death (which would log errors and churn actor restarts during
        # every clean shutdown).
        if self.gcs is not None:
            try:
                await self.gcs.request("node_draining",
                                       {"node_id": self.node_id},
                                       timeout=2.0)
            except Exception:
                pass  # GCS already gone: its disconnect path handles it
        for w in list(self.workers.values()):
            if w.proc is not None:
                try:
                    w.proc.kill()
                except Exception:
                    pass
        # Container workers: their kill() runs `rm -f` on a daemon
        # thread — wait for removal before the process exits, or the
        # engine-managed containers outlive the node.  One shared
        # deadline >= the thread's 2x10s retry budget, covering threads
        # whose worker was already popped from self.workers.
        deadline = time.monotonic() + 22.0
        for t in list(_ContainerProcHandle._live_kill_threads):
            t.join(max(0.0, deadline - time.monotonic()))
            if t.is_alive():
                logger.warning(
                    "container removal %s still running at raylet "
                    "exit; the container may leak", t.name)
        if self._zygote is not None:
            self._zygote.kill()
            self._zygote = None
        await self.server.stop()
        if self.gcs is not None:
            await self.gcs.close()
        for oid in list(self._spill_read_fds):
            self._close_spill_fd(oid)
        self.transfers.close()
        self.mapping.close()
        self.store.close()


def main():
    import argparse
    import json
    parser = argparse.ArgumentParser()
    parser.add_argument("--gcs-host", required=True)
    parser.add_argument("--gcs-port", type=int, required=True)
    parser.add_argument("--port", type=int, default=0)
    parser.add_argument("--host", default="127.0.0.1")
    parser.add_argument("--resources", default="{}")
    parser.add_argument("--labels", default="{}")
    parser.add_argument("--session-dir", default="/tmp/ray_tpu")
    parser.add_argument("--store-capacity", type=int, default=0)
    parser.add_argument("--node-name", default=None)
    parser.add_argument("--prestart-workers", type=int, default=-1)
    args = parser.parse_args()
    logging.basicConfig(level=logging.INFO,
                        format="[raylet] %(levelname)s %(message)s")
    resources = json.loads(args.resources)
    labels = json.loads(args.labels)
    if not resources:
        from ray_tpu._private.resources import detect_node_resources
        resources, auto_labels = detect_node_resources()
        labels = {**auto_labels, **labels}

    async def run():
        raylet = Raylet((args.gcs_host, args.gcs_port), resources,
                        labels=labels, host=args.host,
                        session_dir=args.session_dir,
                        store_capacity=args.store_capacity or None,
                        node_name=args.node_name)
        port = await raylet.start(args.port)
        print(f"RAYLET_PORT={port}", flush=True)
        # Consumed by NodeProcesses so provider-launched nodes can be
        # matched to GCS node views (autoscaler idle drain).
        print(f"RAYLET_NODE_ID={raylet.node_id.hex()}", flush=True)
        n_warm = args.prestart_workers
        if n_warm < 0:
            n_warm = min(2, max(1, int(resources.get("CPU", 1))))
        if n_warm:
            raylet.prestart_workers(n_warm)
        # Graceful SIGTERM (rt stop): close the store so the RAM-backed
        # /dev/shm arena is unlinked instead of leaking until reboot.
        import signal as _signal
        stop = asyncio.Event()
        loop = asyncio.get_running_loop()
        protocol.enable_eager_tasks(loop)
        loop.add_signal_handler(_signal.SIGTERM, stop.set)
        await stop.wait()
        await raylet.shutdown()

    asyncio.run(run())


if __name__ == "__main__":
    main()
