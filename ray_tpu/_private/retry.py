"""Shared retry/backoff policies: exponential backoff with full jitter.

Reference: the reference scatters fixed-interval retry sleeps through
its node/worker paths; at cluster scale those synchronize — every
raylet that lost the GCS retries on the same cadence and the recovered
GCS absorbs a thundering herd.  The fix is the standard full-jitter
exponential backoff (delay #n drawn uniformly from (0, min(cap,
base*mult^n))), which both spreads the herd and caps the tail.

Two primitives, used by worker.py / raylet.py in place of their old
fixed sleeps:

* :class:`ExpBackoff` — per-retry-loop policy object; ``next()`` yields
  the next jittered delay, ``reset()`` rewinds after a success.
* :func:`jittered` — one-shot +/-``frac`` jitter for *periodic* loops
  (telemetry pushes, reap ticks, lock polls) so identical loops across
  a large cluster drift apart instead of beating in phase.

Determinism: when ``RT_CHAOS_SEED`` is set (the chaos battery), the
module RNG is seeded from it so a replayed run sleeps the same
schedule; without it, delays are process-random as production wants.
"""

from __future__ import annotations

import os
import random

_seed_env = os.environ.get("RT_CHAOS_SEED")
_rng = random.Random(int(_seed_env)) if _seed_env else random.Random()


def jittered(period: float, frac: float = 0.25, rng=None) -> float:
    """``period`` +/- ``frac`` uniform jitter — for periodic loops."""
    r = rng if rng is not None else _rng
    return period * (1.0 - frac + 2.0 * frac * r.random())


class ExpBackoff:
    """Full-jitter exponential backoff.

    ``next()`` returns a delay drawn uniformly from (0, ceiling] where
    the ceiling doubles (by ``mult``) each attempt up to ``cap``.  A
    1 ms floor keeps a zero draw from turning a retry loop into a hot
    spin.
    """

    __slots__ = ("base", "cap", "mult", "attempt", "_rng")

    def __init__(self, base: float, cap: float, mult: float = 2.0,
                 rng=None):
        self.base = base
        self.cap = cap
        self.mult = mult
        self.attempt = 0
        self._rng = rng if rng is not None else _rng

    def next(self) -> float:
        ceiling = min(self.cap, self.base * (self.mult ** self.attempt))
        self.attempt += 1
        return max(0.001, self._rng.uniform(0.0, ceiling))

    def reset(self):
        self.attempt = 0
