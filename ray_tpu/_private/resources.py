"""Node resource detection, with TPU chips/topology as first-class resources.

Reference: src/ray/common/task/scheduling_resources.h models CPU/GPU/custom
resources as fixed-point quantities; GPUs are opaque fungible units.  The
TPU-era model here instead detects chips via jax and records ICI topology
(slice name + mesh coordinates) as node labels so the placement layer
(placement.py) can allocate contiguous sub-meshes — the scheduling-visible
difference between a TPU pod and a bag of GPUs.
"""

from __future__ import annotations

import os


def detect_node_resources(num_cpus=None, num_tpus=None, resources=None,
                          object_store_memory=None):
    res = dict(resources or {})
    if num_cpus is None:
        num_cpus = float(os.environ.get("RT_NUM_CPUS", os.cpu_count() or 1))
    res["CPU"] = float(num_cpus)
    labels = {}
    if num_tpus is None:
        env = os.environ.get("RT_NUM_TPUS")
        if env is not None:
            num_tpus = float(env)
        else:
            num_tpus, labels = _detect_tpus()
    if num_tpus:
        res["TPU"] = float(num_tpus)
    res.setdefault("memory", float(_detect_memory()))
    return res, labels


_DETECT_CACHE = None


def _detect_tpus():
    """Probe jax for local TPU chips.  The probe is cached process-wide and
    guarded by a timeout: backend bring-up goes through a device tunnel that
    can take arbitrarily long when the chip is busy, and resource detection
    must never block cluster bring-up (reference analogue: GPU autodetect in
    python/ray/_private/resource_spec.py, which trusts nvml and never
    blocks)."""
    global _DETECT_CACHE
    if _DETECT_CACHE is not None:
        return _DETECT_CACHE
    if os.environ.get("RT_DISABLE_TPU_DETECTION") or \
            os.environ.get("JAX_PLATFORMS", "").strip() in ("cpu",):
        _DETECT_CACHE = (0, {})
        return _DETECT_CACHE
    result = {}

    def _probe():
        try:
            import jax
            result["devices"] = [d for d in jax.local_devices()
                                 if d.platform not in ("cpu",)]
        except Exception:
            result["devices"] = []

    import threading
    t = threading.Thread(target=_probe, daemon=True)
    t.start()
    t.join(timeout=float(os.environ.get("RT_TPU_DETECT_TIMEOUT_S", "20")))
    devices = result.get("devices") or []
    if not devices:
        _DETECT_CACHE = (0, {})
        return _DETECT_CACHE
    labels = {"tpu_platform": devices[0].platform}
    coords = getattr(devices[0], "coords", None)
    if coords is not None:
        labels["tpu_coords"] = tuple(coords)
    slice_index = getattr(devices[0], "slice_index", None)
    if slice_index is not None:
        labels["tpu_slice"] = str(slice_index)
    _DETECT_CACHE = (len(devices), labels)
    return _DETECT_CACHE


def _detect_memory():
    try:
        with open("/proc/meminfo") as f:
            for line in f:
                if line.startswith("MemTotal:"):
                    return int(line.split()[1]) * 1024
    except OSError:
        pass
    return 8 * 1024**3
