"""Runtime lock-order sanitizer: the dynamic half of the RTC pass.

``ray_tpu/lint/concurrency.py`` derives the acquired-while-held graph
statically (RTC102).  This module is its runtime complement: lock
hotspots are created through :func:`make_lock` / :func:`make_rlock` /
:func:`make_condition` with the SAME ``Class.attr`` / ``module.NAME``
key the analyzer uses, and under the chaos/failpoint battery
(``RT_LOCK_SANITIZER=1``) every wrapped acquisition is recorded:

* a thread-local held stack tracks what each thread holds;
* acquiring B while holding A records the edge ``A -> B``;
* an acquisition whose REVERSE edge was already observed is a
  lock-order **violation** — the interleaving that deadlocks exists,
  whether or not this run hit it;
* :func:`check_against_static` diffs the dynamic edges against the
  analyzer's graph (``python -m ray_tpu.lint --emit-lock-graph``):
  dynamic edges the analyzer missed are *analyzer gaps*, worth a bug
  report against the lint pass itself.

Cost model: when the sanitizer is disabled (the default), the
factories return the raw ``threading`` primitive — zero wrapper, zero
overhead, decided once at lock creation.  Enabling it
(:func:`enable` or the env var) therefore only affects locks created
AFTER the switch; module-level locks wrap only when the env var is set
before import, which is how the chaos targets run
(``RT_LOCK_SANITIZER=1 make chaos``) — child processes inherit the env
and wrap theirs too.

Reentrant holds of the same key (RLock, or two instances of one class)
are skipped: per-key identity is the class attribute, matching the
static graph's nodes.
"""

from __future__ import annotations

import os
import sys
import threading
from typing import Dict, List, Optional, Tuple

__all__ = [
    "enabled", "enable", "disable", "make_lock", "make_rlock",
    "make_condition", "edges", "violations", "reset",
    "load_static_graph", "check_against_static", "report",
]

_state_lock = threading.Lock()  # raw on purpose: guards the recorder
_tls = threading.local()

_enabled = bool(os.environ.get("RT_LOCK_SANITIZER", "")
                not in ("", "0", "off", "false"))
# (a, b) -> first-witness provenance
_edges: Dict[Tuple[str, str], dict] = {}
_violations: List[dict] = []


def enabled() -> bool:
    return _enabled


def enable() -> None:
    """Wrap locks created from now on (idempotent)."""
    global _enabled
    _enabled = True


def disable() -> None:
    global _enabled
    _enabled = False


def _held_stack() -> list:
    stack = getattr(_tls, "stack", None)
    if stack is None:
        stack = _tls.stack = []
    return stack


def _call_site() -> str:
    """file:line of the first frame outside this module and threading."""
    f = sys._getframe(2)
    here = __file__
    while f is not None:
        fn = f.f_code.co_filename
        if fn != here and not fn.endswith("threading.py"):
            return f"{os.path.basename(fn)}:{f.f_lineno}"
        f = f.f_back
    return "?"


def _record_acquire(name: str) -> None:
    stack = _held_stack()
    if not stack or stack[-1] == name or name in stack:
        return  # first lock, or reentrancy on the same key
    held = stack[-1]
    site = _call_site()
    with _state_lock:
        edge = (held, name)
        if edge not in _edges:
            _edges[edge] = {"thread": threading.current_thread().name,
                            "site": site}
        rev = _edges.get((name, held))
        if rev is not None:
            _violations.append({
                "edge": edge, "site": site,
                "thread": threading.current_thread().name,
                "reverse_site": rev["site"],
                "reverse_thread": rev["thread"],
                "message": (
                    f"lock-order violation: {held} -> {name} at {site} "
                    f"({threading.current_thread().name}) but "
                    f"{name} -> {held} was taken at {rev['site']} "
                    f"({rev['thread']}) — the opposite interleaving "
                    "deadlocks")})


class _SanLock:
    """Order-recording wrapper around a threading lock.  Supports the
    context-manager and acquire/release protocols, so it drops into
    ``threading.Condition(lock=...)`` too."""

    def __init__(self, name: str, inner):
        self.name = name
        self._inner = inner

    def acquire(self, blocking: bool = True, timeout: float = -1):
        if blocking:
            # Record at ATTEMPT time: if this acquisition is the one
            # that deadlocks, the violation must already be on file.
            _record_acquire(self.name)
        got = self._inner.acquire(blocking, timeout)
        if got:
            if not blocking:
                _record_acquire(self.name)
            _held_stack().append(self.name)
        return got

    def release(self):
        self._inner.release()
        stack = _held_stack()
        # Remove the most recent hold of this key (Condition.wait
        # releases out of top-of-stack order when other wrapped locks
        # interleave).
        for i in range(len(stack) - 1, -1, -1):
            if stack[i] == self.name:
                del stack[i]
                break

    def locked(self):
        return self._inner.locked()

    def __enter__(self):
        self.acquire()
        return self

    def __exit__(self, *exc):
        self.release()
        return False

    def __repr__(self):
        return f"<locksan {self.name} {self._inner!r}>"


def make_lock(name: str):
    """A ``threading.Lock`` (raw when the sanitizer is off)."""
    if not _enabled:
        return threading.Lock()
    return _SanLock(name, threading.Lock())


def make_rlock(name: str):
    if not _enabled:
        return threading.RLock()
    return _SanLock(name, threading.RLock())


def make_condition(name: str):
    """A ``threading.Condition`` over a (possibly wrapped) lock: with
    the sanitizer on, waiting/reacquiring shows up as release/acquire
    on the condition's key, exactly like the analyzer models it."""
    return threading.Condition(make_lock(name))


# ------------------------------------------------------------ inspection

def edges() -> Dict[Tuple[str, str], dict]:
    with _state_lock:
        return dict(_edges)


def violations() -> List[dict]:
    with _state_lock:
        return list(_violations)


def reset() -> None:
    """Clear recorded edges and violations (not the enabled flag)."""
    with _state_lock:
        _edges.clear()
        del _violations[:]


def load_static_graph(data) -> set:
    """``{"edges": [[a, b], ...]}`` (the ``--emit-lock-graph`` shape,
    or a path to a JSON file of it) -> a set of (a, b) tuples."""
    if isinstance(data, (str, os.PathLike)):
        import json
        with open(data) as f:
            data = json.load(f)
    return {tuple(e) for e in data.get("edges", [])}


def check_against_static(static_edges: set) -> dict:
    """Diff dynamic reality against the analyzer's graph.

    ``gaps``  — edges the runtime observed that static analysis missed
    (report these against ray_tpu/lint/concurrency.py: a manual
    acquire(), an attribute the ctor-scan didn't see, ...).
    ``unexercised`` — static edges no test drove; coverage, not bugs.
    """
    dyn = set(edges())
    return {
        "gaps": sorted(dyn - static_edges),
        "unexercised": sorted(static_edges - dyn),
    }


def report() -> str:
    """Human-readable summary (used by the chaos battery on failure)."""
    vio = violations()
    eds = edges()
    lines = [f"locksan: {len(eds)} edge(s), {len(vio)} violation(s)"]
    for (a, b), prov in sorted(eds.items()):
        lines.append(f"  edge {a} -> {b}  [{prov['site']} "
                     f"{prov['thread']}]")
    for v in vio:
        lines.append(f"  VIOLATION {v['message']}")
    return "\n".join(lines)
