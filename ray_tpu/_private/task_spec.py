"""Typed task/actor specifications.

Reference: src/ray/common/task/task_spec.h — TaskSpecification wraps the
wire message (protobuf there) with typed accessors, so every layer names
fields instead of poking at raw maps.  Here the wire format is the
pickled dict that rides the RPC plane; the spec classes subclass dict so
the wire format, the in-memory lineage entry, and the typed view are the
same object (no conversion on the hot path), while construction is
centralized and validated in one place.
"""

from __future__ import annotations

from typing import Any, Dict, List, Optional

from ray_tpu._private.ids import ActorID, TaskID, ObjectID


class TaskSpec(dict):
    """A normal (stateless) task submission.

    Dict-compatible for the wire; typed accessors for the runtime
    (reference: task_spec.h TaskSpecification::TaskId/GetRequiredResources
    /GetSchedulingStrategy/...).
    """

    REQUIRED = ("task_id", "fn_id", "args", "num_returns", "owner_addr",
                "return_ids", "resources")

    @classmethod
    def new(cls, *, task_id: TaskID, fn_id: bytes, args_blob,
            num_returns: int, owner_addr, return_ids: List[ObjectID],
            resources: Dict[str, float], strategy: Optional[Dict],
            max_retries: int, retry_exceptions: bool, name: str,
            trace, runtime_env: Optional[Dict] = None,
            pg_id=None, bundle_index: int = -1) -> "TaskSpec":
        spec = cls(
            task_id=task_id,
            fn_id=fn_id,
            args=args_blob,
            num_returns=num_returns,
            owner_addr=owner_addr,
            return_ids=return_ids,
            resources=resources,
            strategy=strategy,
            max_retries=max_retries,
            retry_exceptions=retry_exceptions,
            name=name,
            trace=trace,
        )
        if runtime_env:
            spec["runtime_env"] = runtime_env
        if pg_id is not None:
            spec["pg_id"] = pg_id
            spec["bundle_index"] = bundle_index
        return spec

    def validate(self) -> "TaskSpec":
        missing = [k for k in self.REQUIRED if k not in self]
        if missing:
            raise ValueError(f"TaskSpec missing fields {missing}")
        expected = self["num_returns"]
        if expected == -1:
            expected = 1  # dynamic: one visible ObjectRefGenerator ref
        if len(self["return_ids"]) != expected:
            raise ValueError("return_ids/num_returns mismatch")
        return self

    # ------------------------------------------------------------ fields
    @property
    def task_id(self) -> TaskID:
        return self["task_id"]

    @property
    def fn_id(self) -> bytes:
        return self["fn_id"]

    @property
    def num_returns(self) -> int:
        return self["num_returns"]

    @property
    def return_ids(self) -> List[ObjectID]:
        return self["return_ids"]

    @property
    def owner_addr(self):
        return self["owner_addr"]

    @property
    def resources(self) -> Dict[str, float]:
        return self["resources"]

    @property
    def strategy(self) -> Optional[Dict]:
        return self.get("strategy")

    @property
    def max_retries(self) -> int:
        return self.get("max_retries", 0)

    @property
    def name(self) -> str:
        return self.get("name", "")

    @property
    def pg_id(self):
        return self.get("pg_id")

    @property
    def bundle_index(self) -> int:
        return self.get("bundle_index", -1)

    @property
    def runtime_env(self) -> Optional[Dict]:
        return self.get("runtime_env")


class ActorTaskSpec(dict):
    """A method invocation pushed directly to an actor process
    (reference: task_spec.h actor-task fields + the direct actor
    submitter's per-caller sequence numbers)."""

    @classmethod
    def new(cls, *, task_id: TaskID, method: str, args_blob,
            num_returns: int, return_ids: List[ObjectID], caller_id: bytes,
            owner_addr, trace,
            concurrency_group: Optional[str] = None) -> "ActorTaskSpec":
        return cls(
            task_id=task_id,
            method=method,
            args=args_blob,
            num_returns=num_returns,
            return_ids=return_ids,
            caller_id=caller_id,
            owner_addr=owner_addr,
            trace=trace,
            concurrency_group=concurrency_group,
        )

    @property
    def task_id(self) -> TaskID:
        return self["task_id"]

    @property
    def method(self) -> str:
        return self["method"]

    @property
    def num_returns(self) -> int:
        return self["num_returns"]

    @property
    def return_ids(self) -> List[ObjectID]:
        return self["return_ids"]

    @property
    def seq(self) -> Optional[int]:
        return self.get("seq")


class ActorCreationSpec(dict):
    """An actor-creation request registered with the GCS (reference:
    task_spec.h actor-creation fields / gcs_actor_manager.h RegisterActor
    payload)."""

    @classmethod
    def new(cls, *, class_id: bytes, class_name: str, init_blob,
            resources: Dict[str, float], max_restarts: int,
            max_concurrency: Optional[int],
            concurrency_groups: Optional[Dict], name: Optional[str],
            namespace: str, detached: bool,
            scheduling_strategy: Optional[Dict],
            runtime_env: Optional[Dict] = None,
            placement_group_id=None,
            bundle_index: Optional[int] = None) -> "ActorCreationSpec":
        spec = cls(
            class_id=class_id,
            class_name=class_name,
            init_args=init_blob,
            resources=resources,
            max_restarts=max_restarts,
            max_concurrency=max_concurrency,
            concurrency_groups=concurrency_groups,
            name=name,
            namespace=namespace,
            detached=detached,
            scheduling_strategy=scheduling_strategy,
        )
        if runtime_env:
            spec["runtime_env"] = runtime_env
        if placement_group_id is not None:
            spec["placement_group_id"] = placement_group_id
            spec["bundle_index"] = bundle_index
        return spec

    @property
    def class_name(self) -> str:
        return self.get("class_name", "")

    @property
    def resources(self) -> Dict[str, float]:
        return self["resources"]

    @property
    def max_restarts(self) -> int:
        return self.get("max_restarts", 0)

    @property
    def detached(self) -> bool:
        return self.get("detached", False)

    @property
    def name(self) -> Optional[str]:
        return self.get("name")

    @property
    def namespace(self) -> str:
        return self.get("namespace", "default")
