"""Binary unique identifiers for all runtime entities.

TPU-native re-design of the reference's ID layer (reference:
src/ray/common/id.h — JobID/TaskID/ObjectID/ActorID/NodeID as fixed-width
binary ids with embedded structure).  We keep the same entity set but use
flat 16-byte random ids; object ids embed the owner task id + return index
so lineage can be recovered from the id alone.
"""

from __future__ import annotations

import itertools
import os
import threading

from ray_tpu._private import locksan

_ID_SIZE = 16


class BaseID:
    __slots__ = ("_bin",)

    def __init__(self, binary: bytes):
        if not isinstance(binary, bytes) or len(binary) != _ID_SIZE:
            raise ValueError(f"{type(self).__name__} requires {_ID_SIZE} bytes, got {binary!r}")
        self._bin = binary

    @classmethod
    def from_random(cls):
        return cls(os.urandom(_ID_SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * _ID_SIZE)

    def is_nil(self) -> bool:
        return self._bin == b"\x00" * _ID_SIZE

    def binary(self) -> bytes:
        return self._bin

    def hex(self) -> str:
        return self._bin.hex()

    def __hash__(self):
        return hash(self._bin)

    def __eq__(self, other):
        return type(other) is type(self) and other._bin == self._bin

    def __repr__(self):
        return f"{type(self).__name__}({self._bin.hex()})"

    def __reduce__(self):
        return (type(self), (self._bin,))


class JobID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class ActorID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class FunctionID(BaseID):
    pass


class TaskID(BaseID):
    _counter_lock = locksan.make_lock("TaskID._counter_lock")
    _counter = 0
    # Submission fast path: one urandom syscall per PROCESS, not per
    # task (urandom is expensive on syscall-filtered hosts).  The
    # 12-byte prefix ObjectID.for_task_return keeps must stay unique
    # per task: 6 random base bytes + 6-byte counter fill it exactly.
    _submit_base = os.urandom(6)
    _submit_next = itertools.count(1).__next__

    @classmethod
    def for_submit(cls) -> "TaskID":
        return cls(cls._submit_base
                   + cls._submit_next().to_bytes(6, "little")
                   + b"\x00\x00\x00\x00")

    @classmethod
    def for_fake_task(cls):
        return cls.from_random()


class ObjectID(BaseID):
    """Object id = 12 random bytes (task id prefix) + 4-byte return index."""

    _put_base = os.urandom(10)
    _put_next = itertools.count(1).__next__

    @classmethod
    def for_task_return(cls, task_id: "TaskID", index: int):
        return cls(task_id.binary()[:12] + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls):
        # Same per-process base + counter scheme as TaskID.for_submit.
        return cls(cls._put_base + cls._put_next().to_bytes(6, "little"))

    def return_index(self) -> int:
        return int.from_bytes(self._bin[12:], "little")


def _reseed_id_bases():
    """Fresh per-process bases + counters.  Registered as an at-fork
    hook: zygote-forked workers must NOT share the parent's id stream —
    a shared base + counter would mint colliding task/object ids in
    different processes."""
    TaskID._submit_base = os.urandom(6)
    TaskID._submit_next = itertools.count(1).__next__
    ObjectID._put_base = os.urandom(10)
    ObjectID._put_next = itertools.count(1).__next__


os.register_at_fork(after_in_child=_reseed_id_bases)

ObjectRefID = ObjectID
