"""Binary unique identifiers for all runtime entities.

TPU-native re-design of the reference's ID layer (reference:
src/ray/common/id.h — JobID/TaskID/ObjectID/ActorID/NodeID as fixed-width
binary ids with embedded structure).  We keep the same entity set but use
flat 16-byte random ids; object ids embed the owner task id + return index
so lineage can be recovered from the id alone.
"""

from __future__ import annotations

import os
import threading

_ID_SIZE = 16


class BaseID:
    __slots__ = ("_bin",)

    def __init__(self, binary: bytes):
        if not isinstance(binary, bytes) or len(binary) != _ID_SIZE:
            raise ValueError(f"{type(self).__name__} requires {_ID_SIZE} bytes, got {binary!r}")
        self._bin = binary

    @classmethod
    def from_random(cls):
        return cls(os.urandom(_ID_SIZE))

    @classmethod
    def from_hex(cls, hex_str: str):
        return cls(bytes.fromhex(hex_str))

    @classmethod
    def nil(cls):
        return cls(b"\x00" * _ID_SIZE)

    def is_nil(self) -> bool:
        return self._bin == b"\x00" * _ID_SIZE

    def binary(self) -> bytes:
        return self._bin

    def hex(self) -> str:
        return self._bin.hex()

    def __hash__(self):
        return hash(self._bin)

    def __eq__(self, other):
        return type(other) is type(self) and other._bin == self._bin

    def __repr__(self):
        return f"{type(self).__name__}({self._bin.hex()})"

    def __reduce__(self):
        return (type(self), (self._bin,))


class JobID(BaseID):
    pass


class NodeID(BaseID):
    pass


class WorkerID(BaseID):
    pass


class ActorID(BaseID):
    pass


class PlacementGroupID(BaseID):
    pass


class FunctionID(BaseID):
    pass


class TaskID(BaseID):
    _counter_lock = threading.Lock()
    _counter = 0

    @classmethod
    def for_fake_task(cls):
        return cls.from_random()


class ObjectID(BaseID):
    """Object id = 12 random bytes (task id prefix) + 4-byte return index."""

    @classmethod
    def for_task_return(cls, task_id: "TaskID", index: int):
        return cls(task_id.binary()[:12] + index.to_bytes(4, "little"))

    @classmethod
    def for_put(cls):
        return cls.from_random()

    def return_index(self) -> int:
        return int.from_bytes(self._bin[12:], "little")


ObjectRefID = ObjectID
