"""rt — the command-line surface of the framework.

Reference: python/ray/scripts/scripts.py (`ray start/stop/status/...`)
and experimental/state/state_cli.py (`ray list actors/tasks/...`).
Usage: python -m ray_tpu.scripts.cli <command> [...] --address host:port

Commands:
  status                      cluster resources + nodes + trace rings
  list {nodes,actors,tasks,objects,placement-groups,jobs,events}
  summary {tasks,objects}
  timeline [--output FILE]    chrome-trace dump (KV-push convenience
                              view; lags by the push period)
  timeline --cluster          authoritative pull: drain every process's
                              span ring NOW via the dump_trace RPC
  trace [TRACE_ID]            assemble one request's span tree across
                              processes, with per-stage latency
                              breakdown (TTFT decomposition for serve
                              requests); without an id, list recent
                              trace ids
  job submit -- <entrypoint>  supervised job; streams status
  job logs <submission_id>
  job stop <submission_id>
  resize <gang> <n>           elastic gang resize via the autopilot
                              broker (structured errors when the gang
                              is unknown / not elastic / below quorum)
  autopilot                   broker workload table: grants, SLO
                              breach state, reserved nodes
  dashboard [--port N]        start the dashboard head, print its URL
  lint <paths>                static distributed-correctness linter
"""

from __future__ import annotations

import argparse
import json
import sys


def _connect(address):
    import ray_tpu
    ray_tpu.init(address=address, ignore_reinit_error=True,
                 log_to_driver=False)


def _print_rows(rows):
    if not rows:
        print("(none)")
        return
    cols = list(rows[0].keys())
    widths = {c: max(len(str(c)), *(len(str(r.get(c, ""))) for r in rows))
              for c in cols}
    print("  ".join(str(c).ljust(widths[c]) for c in cols))
    for r in rows:
        print("  ".join(str(r.get(c, "")).ljust(widths[c]) for c in cols))


_STATE_DIR = "/tmp/ray_tpu"
_STATE_FILE = f"{_STATE_DIR}/started_nodes.json"


def _load_started():
    import os
    if not os.path.exists(_STATE_FILE):
        return []
    try:
        with open(_STATE_FILE) as f:
            return json.load(f)
    except Exception:
        return []


def _save_started(entries):
    import os
    os.makedirs(_STATE_DIR, exist_ok=True)
    with open(_STATE_FILE, "w") as f:
        json.dump(entries, f, indent=2)


def cmd_start(args):
    """Bring up this machine's node processes and leave them running
    (reference: `ray start --head` / `ray start --address`,
    python/ray/scripts/scripts.py:532).  The head runs GCS + raylet; a
    joining node runs just a raylet registered to --address."""
    from ray_tpu._private.node import NodeProcesses, new_session_dir

    if not args.head and args.address is None:
        p_err = ("rt start needs --head (start a new cluster) or "
                 "--address host:port (join one)")
        print(p_err, file=sys.stderr)
        sys.exit(2)
    if args.node_ip in ("0.0.0.0", "::"):
        print("--node-ip must be a routable ADVERTISED address, not a "
              "wildcard bind address (peers would dial themselves)",
              file=sys.stderr)
        sys.exit(2)
    head = args.address is None
    gcs_addr = None
    if not head:
        host, port = args.address.rsplit(":", 1)
        gcs_addr = (host, int(port))
    resources = json.loads(args.resources) if args.resources else None
    node = NodeProcesses(
        session_dir=new_session_dir(),
        head=head, gcs_addr=gcs_addr,
        host=args.node_ip, gcs_port=args.port,
        num_cpus=args.num_cpus, num_tpus=args.num_tpus,
        resources=resources, node_name=args.node_name,
        register_atexit=False,  # processes outlive this CLI invocation
    ).start()
    entries = _load_started()
    entries.append({
        "head": head,
        "gcs_address": f"{node.gcs_addr[0]}:{node.gcs_addr[1]}",
        "raylet_address": f"{node.raylet_addr[0]}:{node.raylet_addr[1]}",
        "session_dir": node.session_dir,
        "pids": node.pids(),
    })
    _save_started(entries)
    if head:
        print(f"started head node")
        print(f"  GCS address: {node.gcs_addr[0]}:{node.gcs_addr[1]}")
        print(f"  connect a driver:   ray_tpu.init(address="
              f"\"{node.gcs_addr[0]}:{node.gcs_addr[1]}\")")
        print(f"  join another node:  rt start --address "
              f"{node.gcs_addr[0]}:{node.gcs_addr[1]} "
              f"--node-ip <that machine's IP>")
    else:
        print(f"started worker node, joined {args.address}")
    print(f"  raylet: {node.raylet_addr[0]}:{node.raylet_addr[1]}"
          f"  session: {node.session_dir}")
    print(f"  stop with: rt stop")


def _terminate_ray_pids(all_pids, deadline_s: float = 10.0) -> int:
    """Shared teardown for rt stop / rt down: SIGTERM pids whose cmdline
    still looks like ours (pid recycling guard), wait only on the ones
    actually signalled, SIGKILL stragglers, then sweep /dev/shm arenas
    for EVERY recorded pid (dead raylets leave arenas too).  Returns the
    number of processes signalled."""
    import glob
    import os
    import signal
    import time

    def _is_ours(pid: int) -> bool:
        try:
            with open(f"/proc/{pid}/cmdline", "rb") as f:
                cmd = f.read().replace(b"\0", b" ").decode(
                    errors="replace")
        except OSError:
            return False
        return "ray_tpu" in cmd

    all_pids = [int(p) for p in all_pids if p]
    ours = [p for p in all_pids if _is_ours(p)]
    stopped = 0
    for pid in ours:
        try:
            os.kill(pid, signal.SIGTERM)
            stopped += 1
        except (ProcessLookupError, PermissionError):
            pass
    deadline = time.monotonic() + deadline_s
    live = set(ours)
    while live and time.monotonic() < deadline:
        for pid in list(live):
            try:
                os.kill(pid, 0)
            except ProcessLookupError:
                live.discard(pid)
        time.sleep(0.2)
    for pid in live:
        try:
            os.kill(pid, signal.SIGKILL)
        except ProcessLookupError:
            pass
    for pid in all_pids:
        for path in glob.glob(f"/dev/shm/rt_store_*_{pid}"):
            try:
                os.unlink(path)
            except OSError:
                pass
    return stopped


def cmd_stop(args):
    """Kill every node process started by `rt start` on this machine.
    SIGTERM first (the raylet closes its store gracefully, unlinking the
    /dev/shm arena), SIGKILL stragglers, then sweep any arena files the
    killed raylets left behind."""
    entries = _load_started()
    if not entries:
        print("no started nodes recorded")
        return
    all_pids = [pid for e in entries
                for pid in e.get("pids", {}).values()]
    stopped = _terminate_ray_pids(all_pids)
    _save_started([])
    print(f"stopped {stopped} processes")


def cmd_status(args):
    import ray_tpu
    from ray_tpu._private.reporter import format_utilization
    from ray_tpu.experimental import state
    _connect(args.address)
    print("cluster:", json.dumps(ray_tpu.cluster_resources()))
    print("available:", json.dumps(ray_tpu.available_resources()))
    rows = []
    for n in state.list_nodes():
        stats = n.pop("node_stats", {})
        n["utilization"] = format_utilization(stats) or "(pending)"
        rows.append(n)
    _print_rows(rows)
    # Trace-ring health per process: depth/capacity and — the signal —
    # the drop counter (nonzero = the ring overflowed; pull traces more
    # often or raise RT_TRACE_RING_CAPACITY).
    print("trace rings:")
    trows = []
    for p in ray_tpu.cluster_trace(stats_only=True)["processes"]:
        trows.append({
            "role": p.get("role", "?"), "pid": p.get("pid", ""),
            "depth": p.get("depth", ""),
            "capacity": p.get("capacity", ""),
            "dropped": p.get("dropped", ""),
            "error": p.get("error", "")})
    _print_rows(trows)


def cmd_list(args):
    from ray_tpu.experimental import state
    _connect(args.address)
    fn = {
        "nodes": state.list_nodes,
        "actors": state.list_actors,
        "tasks": state.list_tasks,
        "objects": state.list_objects,
        "placement-groups": state.list_placement_groups,
        "jobs": state.list_jobs,
        "events": state.list_cluster_events,
    }[args.entity]
    rows = fn()
    if args.format == "json":
        print(json.dumps(rows, default=str, indent=2))
    else:
        _print_rows(rows)


def cmd_summary(args):
    from ray_tpu.experimental import state
    _connect(args.address)
    fn = {"tasks": state.summarize_tasks,
          "objects": state.summarize_objects}[args.entity]
    print(json.dumps(fn(), default=str, indent=2))


def cmd_timeline(args):
    import ray_tpu
    _connect(args.address)
    if args.cluster:
        # Authoritative pull: one dump_trace RPC per process, whole
        # rings, NOW — vs the default KV-push view that truncates to
        # each ring's tail and lags by the push period.
        out = ray_tpu.cluster_trace(filename=args.output)
        events = out["events"]
        dropped = sum(p.get("dropped", 0) or 0
                      for p in out["processes"])
        print(f"pulled {len(events)} events from "
              f"{len(out['processes'])} process(es); "
              f"{dropped} dropped ring-side")
    else:
        events = ray_tpu.timeline(filename=args.output)
    if args.output:
        print(f"wrote {len(events)} events to {args.output}")
    elif not args.cluster:
        print(json.dumps(events[:50], indent=2))
        if len(events) > 50:
            print(f"... {len(events) - 50} more (use --output FILE)")


def cmd_trace(args):
    """Assemble one request's span tree (serve request or task graph)
    across every process it touched; without an id, list the trace ids
    seen in the cluster's rings, newest first."""
    import ray_tpu
    from ray_tpu._private import tracing
    _connect(args.address)
    if not args.trace_id:
        events = ray_tpu.cluster_trace()["events"]
        ids = tracing.trace_ids(events)
        rows = [{"trace_id": tid, "events": n,
                 "root": name or "?",
                 "age_s": round(max(0.0, (events[-1].get("ts", 0)
                                          - (ts or 0)) / 1e6), 1)
                 if events else ""}
                for tid, (n, ts, name) in sorted(
                    ids.items(), key=lambda kv: -(kv[1][1] or 0))[:25]]
        _print_rows(rows)
        print("rt trace <trace_id> for the span tree")
        return
    tree = ray_tpu.get_trace(args.trace_id)
    if not tree["spans"] and not tree["annotations"]:
        print(f"no events for trace {args.trace_id!r} (already "
              "rotated out of the rings, or wrong id)")
        return
    if args.format == "json":
        print(json.dumps(tree, indent=2, default=repr))
    else:
        print(tracing.format_trace(tree))


def cmd_job(args):
    from ray_tpu.job_submission import JobSubmissionClient
    if args.address and args.address.startswith("http"):
        # Remote REST submission against the dashboard head — works
        # from machines that are NOT cluster members (reference:
        # `ray job submit --address http://head:8265`).
        client = JobSubmissionClient(args.address)
    else:
        _connect(args.address)
        client = JobSubmissionClient()
    if args.job_cmd == "submit":
        sid = client.submit_job(entrypoint=" ".join(args.entrypoint))
        print(f"submitted {sid}")
        if not args.no_wait:
            status = client.wait_until_finished(sid, timeout=args.timeout)
            print(f"status: {status}")
            print(client.get_job_logs(sid), end="")
            sys.exit(0 if status == "SUCCEEDED" else 1)
    elif args.job_cmd == "logs":
        print(client.get_job_logs(args.submission_id), end="")
    elif args.job_cmd == "stop":
        print("stopped" if client.stop_job(args.submission_id)
              else "stop failed")
    elif args.job_cmd == "list":
        _print_rows([{k: v for k, v in j.items() if k != "logs"}
                     for j in client.list_jobs()])


def _cluster_state_path(name: str) -> str:
    import os
    os.makedirs(_STATE_DIR, exist_ok=True)
    return os.path.join(_STATE_DIR, f"cluster_{name}.json")


def cmd_up(args):
    """Launch a cluster from a YAML config (reference: `ray up`,
    scripts.py:980 — bootstrap the head, then run the autoscaler
    monitor against the config's node types)."""
    import os
    import subprocess
    import time

    from ray_tpu._private.node import NodeProcesses, new_session_dir
    from ray_tpu.autoscaler.command_runner import (NodeUpdater,
                                                   SubprocessCommandRunner)
    from ray_tpu.autoscaler.config import load_cluster_config

    config = load_cluster_config(args.config_file)
    name = config["cluster_name"]
    if config["provider"]["type"] not in ("local_process", "tpu_pod"):
        print(f"rt up supports provider types local_process/tpu_pod; "
              f"{config['provider']['type']!r} is a test-harness "
              "provider", file=sys.stderr)
        sys.exit(2)
    state_path = _cluster_state_path(name)
    if os.path.exists(state_path):
        print(f"cluster {name!r} already recorded at {state_path}; "
              "run `rt down` first")
        sys.exit(1)

    # 1. Head-node bootstrap commands (reference: updater running
    # setup_commands then the start command).  The head's node
    # processes are spawned directly below; head_start_command is an
    # EXTRA user hook run after they are up.
    runner = SubprocessCommandRunner()
    NodeUpdater(runner, config["setup_commands"]
                + config.get("head_setup_commands", []),
                start_command="").update()

    head_res = dict(config["head_node"].get("resources", {"CPU": 1}))
    head = NodeProcesses(
        session_dir=new_session_dir(), head=True, host=args.node_ip,
        num_cpus=head_res.pop("CPU", 1), resources=head_res,
        register_atexit=False).start()
    gcs = f"{head.gcs_addr[0]}:{head.gcs_addr[1]}"
    if config.get("head_start_command"):
        runner = SubprocessCommandRunner(
            env={"RT_GCS_ADDRESS": gcs})
        runner.run(config["head_start_command"])

    # 2. Autoscaler monitor (detached): owns the provider, launches
    # min_workers, scales on demand, persists worker pids for rt down.
    state = {"cluster_name": name, "gcs_address": gcs,
             "head_pids": head.pids(),
             "session_dir": head.session_dir, "worker_pids": []}
    with open(state_path, "w") as f:
        json.dump(state, f, indent=2)
    monitor = subprocess.Popen(
        [sys.executable, "-m",
         "ray_tpu.autoscaler._private.monitor_main",
         os.path.abspath(args.config_file), "--gcs", gcs,
         "--state-file", state_path],
        stdout=open(os.path.join(head.session_dir, "logs",
                                 "monitor.out"), "ab"),
        stderr=subprocess.STDOUT, start_new_session=True)
    state["monitor_pid"] = monitor.pid
    with open(state_path, "w") as f:
        json.dump(state, f, indent=2)
    # Give min_workers a moment to register before reporting.
    time.sleep(1.0)
    print(f"cluster {name!r} up")
    print(f"  GCS address: {gcs}")
    print(f"  connect: ray_tpu.init(address=\"{gcs}\")")
    print(f"  tear down: rt down {args.config_file}")


def cmd_down(args):
    """Tear down a cluster started by `rt up` (reference: `ray down`,
    scripts.py:1167)."""
    import os

    from ray_tpu.autoscaler.config import load_cluster_config

    config = load_cluster_config(args.config_file)
    state_path = _cluster_state_path(config["cluster_name"])
    try:
        with open(state_path) as f:
            state = json.load(f)
    except OSError:
        print(f"no recorded cluster {config['cluster_name']!r}")
        return
    except ValueError:
        # Corrupt/half-written state (rt up killed mid-write): remove
        # it so the cluster isn't permanently wedged; processes must be
        # cleaned by rt stop / manually.
        os.unlink(state_path)
        print(f"removed corrupt state file {state_path}; use `rt stop` "
              "to sweep any surviving node processes")
        return

    # The monitor goes FIRST so it can't relaunch workers mid-teardown.
    pids = ([state.get("monitor_pid")] if state.get("monitor_pid")
            else []) + list(state.get("worker_pids", [])) \
        + list(state.get("head_pids", {}).values())
    killed = _terminate_ray_pids(pids)
    os.unlink(state_path)
    print(f"cluster {config['cluster_name']!r} down "
          f"({killed} processes signalled)")


def cmd_serve(args):
    """Declarative serve verbs (reference: `serve deploy/build/status`
    over the schema-validated config YAML)."""
    from ray_tpu.serve import schema as serve_schema
    if args.serve_cmd != "build":
        # build is purely local (imports + YAML emit) — no cluster.
        _connect(args.address)
    if args.serve_cmd == "deploy":
        config = serve_schema.load_config_file(args.config_file)
        deployed = serve_schema.apply_config(config)
        print(f"deployed: {', '.join(deployed)}")
    elif args.serve_cmd == "build":
        config = serve_schema.build_config(args.import_paths)
        text = serve_schema.dump_config_file(config, args.output)
        if args.output:
            print(f"wrote {args.output}")
        else:
            print(text, end="")
    elif args.serve_cmd == "status":
        from ray_tpu import serve as serve_mod
        print(json.dumps(serve_mod.status(), indent=2, default=str))


def cmd_resize(args):
    """Elastic gang resize from the CLI: routes through the GCS broker
    (rpc_resize_gang), which validates elasticity/quorum/capacity and
    hands the target to the gang's autopilot agent as a directive.  The
    driver-side Trainer keeps running — the gang re-forms in place."""
    from ray_tpu._private.worker import global_worker
    _connect(args.address)
    reply = global_worker.gcs_call(
        "resize_gang", {"gang": args.gang, "target": args.target},
        timeout=10)
    if isinstance(reply, dict) and reply.get("ok"):
        print(f"resize accepted: gang {reply.get('gang', args.gang)!r} "
              f"-> {args.target} workers (applied by the gang's "
              "autopilot agent at its next report)")
        return
    err = (reply or {}).get("error", {})
    code = err.get("code", "ERROR")
    msg = err.get("message", json.dumps(reply, default=str))
    print(f"resize rejected [{code}]: {msg}", file=sys.stderr)
    sys.exit(1)


def cmd_autopilot(args):
    """Broker introspection: registered workloads, grants, SLO state,
    and reserved nodes (rpc_arbiter_status)."""
    from ray_tpu._private.worker import global_worker
    _connect(args.address)
    reply = global_worker.gcs_call("arbiter_status", {}, timeout=10)
    if args.format == "json":
        print(json.dumps(reply, indent=2, default=str))
        return
    wls = (reply or {}).get("workloads", [])
    rows = [{"wid": w.get("wid"), "kind": w.get("kind"),
             "prio": w.get("priority"), "min": w.get("min_units"),
             "want": w.get("want"), "granted": w.get("granted"),
             "now": w.get("units_now"),
             "breached": w.get("breached", False)} for w in wls]
    print(f"capacity: {reply.get('capacity')} units, "
          f"reserved nodes: {len(reply.get('reserved_nodes', {}))}")
    _print_rows(rows)


def cmd_dashboard(args):
    import time

    from ray_tpu.dashboard import start_dashboard
    _connect(args.address)
    addr = start_dashboard(port=args.port)
    print(f"dashboard: http://{addr['host']}:{addr['port']}")
    if args.block:
        while True:
            time.sleep(3600)


def cmd_usage(args):
    from ray_tpu._private import usage
    if args.usage_cmd == "status":
        mode = usage.usage_stats_enabledness().name.lower()
        print(f"usage stats: {mode} "
              f"(config: {usage._config_path()})")
        return
    enabled = args.usage_cmd == "enable"
    usage.set_usage_stats_enabled_via_config(enabled)
    print(f"usage stats {'enabled' if enabled else 'disabled'} "
          f"(written to {usage._config_path()})")


def main(argv=None):
    argv = sys.argv[1:] if argv is None else list(argv)
    # Delegate `rt [--address X] lint ...` wholesale to
    # `python -m ray_tpu.lint` (shared flags + exit codes); bypasses
    # argparse.REMAINDER's refusal to capture leading --flags.
    # --address is rt's only global flag and lint needs no cluster.
    rest = argv
    if rest and rest[0].startswith("--address"):
        rest = rest[1:] if "=" in rest[0] else rest[2:]
    if rest[:1] == ["lint"]:
        from ray_tpu.lint.__main__ import main as lint_main
        try:
            sys.exit(lint_main(rest[1:]))
        except BrokenPipeError:  # piped into head/a pager that exited
            sys.exit(0)
    p = argparse.ArgumentParser(prog="rt", description=__doc__)
    p.add_argument("--address", default=None,
                   help="GCS address host:port (default: local cluster)")
    sub = p.add_subparsers(dest="cmd", required=True)

    st = sub.add_parser("start", help="start node processes on this machine")
    st.add_argument("--head", action="store_true",
                    help="start a head node (GCS + raylet)")
    st.add_argument("--address", default=None,
                    help="GCS host:port of the cluster to join")
    st.add_argument("--port", type=int, default=0,
                    help="GCS port for --head (default: any free port)")
    st.add_argument("--node-ip", default="127.0.0.1",
                    help="bind/advertise address — set to this machine's "
                         "routable IP for multi-host clusters")
    st.add_argument("--num-cpus", type=int, default=None)
    st.add_argument("--num-tpus", type=int, default=None)
    st.add_argument("--resources", default=None,
                    help='extra resources as JSON, e.g. \'{"A": 2}\'')
    st.add_argument("--node-name", default=None)
    st.set_defaults(fn=cmd_start)

    sub.add_parser("stop", help="stop node processes started by rt start") \
        .set_defaults(fn=cmd_stop)

    sub.add_parser("status").set_defaults(fn=cmd_status)

    lp = sub.add_parser("list")
    lp.add_argument("entity", choices=["nodes", "actors", "tasks",
                                       "objects", "placement-groups",
                                       "jobs", "events"])
    lp.add_argument("--format", choices=["table", "json"],
                    default="table")
    lp.set_defaults(fn=cmd_list)

    sp = sub.add_parser("summary")
    sp.add_argument("entity", choices=["tasks", "objects"])
    sp.set_defaults(fn=cmd_summary)

    tp = sub.add_parser(
        "timeline", help="chrome-trace dump (default: KV-push view; "
        "--cluster drains every process's span ring now)")
    tp.add_argument("--output", default=None)
    tp.add_argument("--cluster", action="store_true",
                    help="authoritative pull via the dump_trace RPC "
                         "(merges GCS, raylets, and every worker)")
    tp.set_defaults(fn=cmd_timeline)

    trp = sub.add_parser(
        "trace", help="assemble one request's cross-process span tree "
        "with a per-stage latency breakdown (TTFT decomposition for "
        "serve requests); no id lists recent traces")
    trp.add_argument("trace_id", nargs="?", default=None)
    trp.add_argument("--format", choices=["tree", "json"],
                     default="tree")
    trp.set_defaults(fn=cmd_trace)

    jp = sub.add_parser("job")
    jsub = jp.add_subparsers(dest="job_cmd", required=True)
    js = jsub.add_parser("submit")
    js.add_argument("entrypoint", nargs=argparse.REMAINDER)
    js.add_argument("--no-wait", action="store_true")
    js.add_argument("--timeout", type=float, default=3600.0)
    jl = jsub.add_parser("logs")
    jl.add_argument("submission_id")
    jst = jsub.add_parser("stop")
    jst.add_argument("submission_id")
    jsub.add_parser("list")
    jp.set_defaults(fn=cmd_job)

    rz = sub.add_parser(
        "resize", help="resize an elastic train gang via the autopilot "
        "broker (structured errors: UNKNOWN_GANG, NOT_ELASTIC, "
        "BELOW_QUORUM, ABOVE_CAPACITY)")
    rz.add_argument("gang", help="gang name (ScalingConfig.name)")
    rz.add_argument("target", type=int, help="target worker count")
    rz.set_defaults(fn=cmd_resize)

    ap = sub.add_parser(
        "autopilot", help="show the autopilot broker's workload table "
        "(grants, SLO breach state, reserved nodes)")
    ap.add_argument("--format", choices=["table", "json"],
                    default="table")
    ap.set_defaults(fn=cmd_autopilot)

    dp = sub.add_parser("dashboard")
    dp.add_argument("--port", type=int, default=0)
    dp.add_argument("--block", action="store_true")
    dp.set_defaults(fn=cmd_dashboard)

    up = sub.add_parser("up", help="launch a cluster from a YAML config")
    up.add_argument("config_file")
    up.add_argument("--node-ip", default="127.0.0.1")
    up.set_defaults(fn=cmd_up)

    down = sub.add_parser("down", help="tear down an rt up cluster")
    down.add_argument("config_file")
    down.set_defaults(fn=cmd_down)

    svp = sub.add_parser("serve", help="declarative serve config verbs")
    svsub = svp.add_subparsers(dest="serve_cmd", required=True)
    svd = svsub.add_parser("deploy", help="apply a serve config YAML")
    svd.add_argument("config_file")
    svb = svsub.add_parser("build",
                           help="emit config YAML for deployments")
    svb.add_argument("import_paths", nargs="+",
                     help="module:deployment import paths")
    svb.add_argument("-o", "--output", default=None)
    svsub.add_parser("status")
    svp.set_defaults(fn=cmd_serve)

    lintp = sub.add_parser(
        "lint", help="AST-based distributed-correctness linter "
        "(RTL001-RTL008); same flags as python -m ray_tpu.lint")
    # Normally short-circuited by the delegation above; kept complete
    # so any argparse-reached path still lints with the user's args.
    lintp.add_argument("lint_args", nargs=argparse.REMAINDER)

    def _run_lint(args):
        from ray_tpu.lint.__main__ import main as lint_main
        sys.exit(lint_main(args.lint_args))

    lintp.set_defaults(fn=_run_lint)

    usp = sub.add_parser(
        "usage", help="usage-stats opt in/out (reference: ray "
        "disable-usage-stats / enable-usage-stats)")
    usp.add_argument("usage_cmd",
                     choices=["status", "enable", "disable"])
    usp.set_defaults(fn=cmd_usage)

    args = p.parse_args(argv)
    args.fn(args)


if __name__ == "__main__":
    main()
