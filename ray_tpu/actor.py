"""Actor classes and handles.

Reference: python/ray/actor.py — ActorClass (:377) with _remote (:657)
registering with the GCS, ActorHandle (:1020) submitting ordered method
calls directly to the actor process.
"""

from __future__ import annotations

from ray_tpu._private import worker as worker_mod
from ray_tpu._private.ids import ActorID


class ActorMethod:
    def __init__(self, handle: "ActorHandle", name: str, num_returns=1):
        self._handle = handle
        self._name = name
        self._num_returns = num_returns

    def remote(self, *args, **kwargs):
        return self._handle._invoke(self._name, args, kwargs,
                                    self._num_returns, {})

    def options(self, **opts):
        handle, name = self._handle, self._name
        default_num_returns = self._num_returns

        class _Optioned:
            def remote(self, *args, **kwargs):
                num_returns = opts.get("num_returns", default_num_returns)
                return handle._invoke(name, args, kwargs, num_returns, opts)

        return _Optioned()


class ActorHandle:
    def __init__(self, actor_id: ActorID, class_name: str = "",
                 method_meta: dict | None = None, addr=None,
                 max_task_retries: int = 0):
        self._actor_id = actor_id
        self._class_name = class_name
        self._method_meta = method_meta or {}
        self._addr = addr
        self._max_task_retries = max_task_retries

    def __getattr__(self, name):
        # Underscored names fail fast (pickle/copy/display protocol probes
        # must not see phantom methods) — except the framework's own "_rt_"
        # actor-method namespace (e.g. CollectiveMixin._rt_init_collective).
        if name.startswith("_") and not name.startswith("_rt_"):
            raise AttributeError(name)
        num_returns = self._method_meta.get(name, {}).get("num_returns", 1)
        return ActorMethod(self, name, num_returns)

    def _invoke(self, method, args, kwargs, num_returns, opts):
        if num_returns == "dynamic":
            # Keep this loud: without the check it surfaces as an
            # obscure TypeError from range() deep in the submitter.
            raise ValueError(
                'num_returns="dynamic" is only supported for task '
                "returns, not actor methods")
        w = worker_mod.global_worker
        opts = dict(opts)
        opts.setdefault("max_task_retries", self._max_task_retries)
        refs = w.submit_actor_task(self._actor_id, self._addr, method, args,
                                   kwargs, num_returns=num_returns, opts=opts)
        if num_returns == 1:
            return refs[0]
        return refs

    @property
    def _ray_actor_id(self):
        return self._actor_id

    def __reduce__(self):
        return (ActorHandle, (self._actor_id, self._class_name,
                              self._method_meta, None))

    def __repr__(self):
        return f"ActorHandle({self._class_name}, {self._actor_id.hex()[:16]})"


class ActorClass:
    def __init__(self, cls, **default_opts):
        self._cls = cls
        self._default_opts = default_opts
        self._class_id = None
        self._exported_by = None

    def __call__(self, *args, **kwargs):
        raise TypeError(
            f"Actor class '{self._cls.__name__}' cannot be instantiated "
            f"directly. Use '{self._cls.__name__}.remote()'.")

    def remote(self, *args, **kwargs):
        return self._remote(args, kwargs, self._default_opts)

    def options(self, **opts):
        merged = {**self._default_opts, **opts}
        parent = self

        class _Optioned:
            def remote(self, *args, **kwargs):
                return parent._remote(args, kwargs, merged)

        return _Optioned()

    def _remote(self, args, kwargs, opts):
        w = worker_mod.global_worker
        if w is None or not w.connected:
            raise RuntimeError("ray_tpu.init() must be called first")
        if self._class_id is None or self._exported_by is not w:
            self._class_id = w.export_function(self._cls)
            self._exported_by = w
        opts = dict(opts)
        opts.setdefault("class_name", self._cls.__name__)
        actor_id = w.create_actor(self._class_id, args, kwargs, opts)
        meta = {}
        for name in dir(self._cls):
            m = getattr(self._cls, name, None)
            if callable(m) and hasattr(m, "_num_returns"):
                meta[name] = {"num_returns": m._num_returns}
        return ActorHandle(actor_id, self._cls.__name__, meta,
                           max_task_retries=opts.get("max_task_retries", 0))

    def __getstate__(self):
        # Same contract as RemoteFunction: drop per-process export caches
        # so actor classes can cross process boundaries.
        state = self.__dict__.copy()
        state["_class_id"] = None
        state["_exported_by"] = None
        return state

    @property
    def bind(self):
        from ray_tpu.dag import ClassNode

        def _bind(*args, **kwargs):
            return ClassNode(self._cls, args, kwargs, self._default_opts)
        return _bind


def method(num_returns=1):
    """Decorator for actor methods declaring multiple returns (reference:
    python/ray/actor.py ray.method)."""
    def decorator(m):
        m._num_returns = num_returns
        return m
    return decorator
