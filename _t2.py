import logging, time, glob
logging.basicConfig(level=logging.INFO)
import ray_tpu
ray_tpu.init(num_cpus=4)


@ray_tpu.remote
class Counter:
    def __init__(self, start=0):
        self.n = start

    def incr(self, k=1):
        self.n += k
        return self.n


c = Counter.remote(10)
import ray_tpu._private.worker as wm
import ray_tpu._private.api as api
w = wm.global_worker
gcs = api._head_node.gcs_server
raylet = api._head_node.raylet
r = c.incr.remote()
for tick in range(20):
    time.sleep(1)
    actors = [(a.state, a.addr, a.death_cause) for a in gcs.actors.values()]
    e = w.owned.get(r.id)
    print(f"t={tick} actors={actors} obj={e.state if e else 'GONE'} "
          f"workers={len(raylet.workers)}", flush=True)
    if e and e.state != "PENDING":
        print("result:", ray_tpu.get(r), flush=True)
        break

for f in glob.glob(api._head_node.session_dir + "/logs/*"):
    txt = open(f).read()
    if txt.strip():
        print("===", f, flush=True)
        print(txt[-2000:], flush=True)
import os
os._exit(0)
