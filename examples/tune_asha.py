"""Hyperparameter sweep with ASHA early stopping + a TimeoutStopper
safety net.

Run: RT_DISABLE_TPU_DETECTION=1 python examples/tune_asha.py
"""

import ray_tpu
from ray_tpu import tune
from ray_tpu.air import RunConfig
from ray_tpu.tune import Tuner, TuneConfig
from ray_tpu.tune.schedulers import ASHAScheduler


def objective(config):
    acc = 0.0
    for step in range(30):
        acc += config["lr"] * (1.0 - acc)  # toy convergence curve
        tune.report({"accuracy": acc})


def main():
    ray_tpu.init(num_cpus=4)
    results = Tuner(
        objective,
        param_space={"lr": tune.loguniform(1e-3, 0.5)},
        tune_config=TuneConfig(
            metric="accuracy", mode="max", num_samples=8,
            scheduler=ASHAScheduler(metric="accuracy", mode="max",
                                    max_t=30, grace_period=3)),
        run_config=RunConfig(stop=tune.TimeoutStopper(300)),
    ).fit()
    best = results.get_best_result()
    print("best lr: %.4f  accuracy: %.3f"
          % (best.config["lr"], best.metrics["accuracy"]))
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
