"""Train the flagship GPT with JaxTrainer: gang of workers, mesh from
ScalingConfig axes, AIR checkpoints.

Run: RT_DISABLE_TPU_DETECTION=1 python examples/train_gpt.py
(sizes are CPU-safe; on a TPU host drop RT_DISABLE_TPU_DETECTION and
raise d_model/seq — the same script drives the chip)
"""

import ray_tpu
from ray_tpu.air import Checkpoint, ScalingConfig, session
from ray_tpu.train.jax import JaxConfig, JaxTrainer


def train_loop(config):
    import jax
    import jax.numpy as jnp
    import optax

    from ray_tpu.models import gpt

    cfg = gpt.GPTConfig(vocab_size=256, d_model=64, n_heads=4,
                        n_layers=2, d_ff=128, max_seq=64,
                        dtype=jnp.float32, remat=False)
    mesh = session.get_mesh()  # built from ScalingConfig axes
    opt = optax.adamw(1e-3)
    key = jax.random.PRNGKey(0)
    state, _ = gpt.make_train_state(cfg, key, mesh=mesh, optimizer=opt)
    step = gpt.make_train_step(cfg, mesh=mesh, optimizer=opt,
                               donate=False)
    tokens = jax.random.randint(key, (8, 33), 0, cfg.vocab_size)
    for epoch in range(config["epochs"]):
        state, metrics = step(state, tokens)
        session.report(
            {"loss": float(metrics["loss"]), "epoch": epoch},
            checkpoint=Checkpoint.from_pytree({"params": state["params"]})
            if epoch == config["epochs"] - 1 else None)


def main():
    ray_tpu.init(num_cpus=4)
    trainer = JaxTrainer(
        train_loop,
        train_loop_config={"epochs": 10},
        jax_config=JaxConfig(use_distributed=False, virtual_cpu_devices=8),
        scaling_config=ScalingConfig(num_workers=1, dp=2, tp=2, fsdp=2),
    )
    result = trainer.fit()
    print("final loss:", result.metrics["loss"])
    print("checkpoint keys:", list(result.checkpoint.to_pytree()))
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
