"""Continuous-batching LLM serving: one engine, many concurrent
requests, tokens streamed as they are generated.

Run: RT_DISABLE_TPU_DETECTION=1 python examples/llm_serving.py

Contrast with serve_llm.py (request-level @serve.batch): here requests
are batched at ITERATION level — a request joins the running decode
batch the moment a KV slot frees, streams each token immediately, and
leaves without waiting for anyone else (ray_tpu.serve.llm).  Toy-sized
weights; the same deployment shape serves a real GPT (replicas that
request num_tpus=1 keep params + the KV slot pool resident in HBM).
"""

import json
import time
import urllib.request

import ray_tpu
from ray_tpu import serve
from ray_tpu.serve.llm import llm_deployment


def load_model():
    """Zero-arg loader, run INSIDE the replica (weights never ride the
    deployment pickle)."""
    import jax
    import jax.numpy as jnp

    from ray_tpu.models import gpt

    cfg = gpt.GPTConfig(vocab_size=256, d_model=64, n_heads=4,
                        n_layers=2, d_ff=128, max_seq=128,
                        dtype=jnp.float32, remat=False)
    return gpt.init_params(cfg, jax.random.PRNGKey(0)), cfg


def main():
    ray_tpu.init(num_cpus=4)
    serve.start()
    handle = llm_deployment(
        load_model,
        # KV memory is a PAGED pool: admission is bounded by free pages
        # (kv_pages * page_size tokens), identical prompt prefixes share
        # pages through the radix cache, and speculate_k fuses
        # prompt-lookup speculation into the batched decode tick.
        engine_config={"num_slots": 4, "max_seq": 64, "page_size": 8,
                       "kv_pages": 32, "speculate_k": 3,
                       "prefill_chunk": 16, "max_queue_len": 32},
        default_generation={"max_new_tokens": 12},
    ).deploy()

    # Unary: several concurrent calls share the decode batch.
    prompts = [[1, 2, 3, 4], [9, 8, 7], [5, 5, 5, 5, 5]]
    resps = [handle.generate.remote(p) for p in prompts]
    for p, r in zip(prompts, resps):
        print("generate", p, "->", r.result(timeout=120))

    # Streaming: tokens arrive one by one, long before the request
    # finishes (the method is named "stream", which shadows
    # DeploymentHandle.stream — hence options()).
    t0 = time.monotonic()
    for tok in handle.options("stream").stream([1, 2, 3, 4],
                                               max_new_tokens=12):
        print(f"  streamed token {tok} at +{time.monotonic() - t0:.3f}s")

    # HTTP: plain JSON and SSE on the same route.
    serve.run(serve.get_deployment("llm"), _start_proxy=True)
    addr = serve.get_proxy_address()
    url = f"http://{addr['host']}:{addr['port']}/llm"
    req = urllib.request.Request(
        url, data=json.dumps({"tokens": [1, 2, 3, 4]}).encode(),
        method="POST", headers={"content-type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        print("HTTP JSON:", json.loads(resp.read()))

    req = urllib.request.Request(
        url, data=json.dumps({"tokens": [1, 2, 3, 4]}).encode(),
        method="POST", headers={"content-type": "application/json",
                                "accept": "text/event-stream"})
    events = []
    with urllib.request.urlopen(req, timeout=60) as resp:
        print("HTTP SSE:", resp.headers["Content-Type"])
        for line in resp:
            line = line.strip()
            if line.startswith(b"data: "):
                events.append(line[6:].decode())
    print("SSE events:", events)
    assert events[-1] == "[DONE]" and len(events) == 13

    print("engine stats:", handle.stats.remote().result(timeout=60))
    serve.shutdown()
    ray_tpu.shutdown()
    print("llm serving example done")


if __name__ == "__main__":
    main()
