"""PPO on CartPole with the fluent AlgorithmConfig builder.

Run: RT_DISABLE_TPU_DETECTION=1 python examples/rllib_ppo.py
"""

import ray_tpu
from ray_tpu.rllib.algorithms.ppo import PPOConfig


def main():
    ray_tpu.init(num_cpus=4)
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=200)
            .training(train_batch_size=800, lr=3e-4,
                      num_sgd_iter=6)).build()
    for i in range(5):
        result = algo.train()
        print(f"iter {i}: episode_reward_mean="
              f"{result['episode_reward_mean']:.1f}")
    algo.stop()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
