"""Data pipeline -> Train ingest: read files, preprocess, shard to a
training gang (reference: the AIR "data + train" quickstart shape).

Run: RT_DISABLE_TPU_DETECTION=1 python examples/data_to_train.py
"""

import os
import tempfile

import numpy as np
import pandas as pd

import ray_tpu
from ray_tpu import data
from ray_tpu.air import ScalingConfig, session
from ray_tpu.data.preprocessors import StandardScaler
from ray_tpu.train.jax import JaxConfig, JaxTrainer


def train_loop(config):
    import jax
    import jax.numpy as jnp

    shard = session.get_dataset_shard("train")
    w = jnp.zeros((2,))

    @jax.jit
    def sgd(w, x, y):
        def loss(w):
            return jnp.mean((x @ w - y) ** 2)
        l, g = jax.value_and_grad(loss)(w)
        return w - 0.1 * g, l

    for epoch in range(config["epochs"]):
        for batch in shard.iter_batches(batch_size=32,
                                        batch_format="numpy"):
            x = jnp.stack([jnp.asarray(batch["a"], jnp.float32),
                           jnp.asarray(batch["b"], jnp.float32)], axis=1)
            y = jnp.asarray(batch["y"], jnp.float32)
            w, l = sgd(w, x, y)
        session.report({"loss": float(l), "epoch": epoch})


def main():
    ray_tpu.init(num_cpus=4)

    # 1. Write some CSV shards, read them back as a Dataset.
    tmp = tempfile.mkdtemp()
    rng = np.random.default_rng(0)
    for i in range(4):
        a, b = rng.normal(size=100), rng.normal(size=100)
        pd.DataFrame({"a": a, "b": b, "y": 3 * a - 2 * b}).to_csv(
            os.path.join(tmp, f"part{i}.csv"), index=False)
    ds = data.read_csv(tmp)
    print("read", ds.count(), "rows from", len(ds.input_files()), "files")

    # 2. Train with a fitted preprocessor; "train" auto-splits per rank.
    trainer = JaxTrainer(
        train_loop,
        train_loop_config={"epochs": 3},
        datasets={"train": ds},
        preprocessor=StandardScaler(columns=["a", "b"]),
        jax_config=JaxConfig(use_distributed=False),
        scaling_config=ScalingConfig(num_workers=2),
    )
    result = trainer.fit()
    print("final loss:", result.metrics["loss"])
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
