"""Serve a jax model over HTTP with batching and an ASGI ingress.

Run: RT_DISABLE_TPU_DETECTION=1 python examples/serve_model.py
"""

import json
import urllib.request

import ray_tpu
from ray_tpu import serve


def main():
    ray_tpu.init(num_cpus=4)

    @serve.deployment(name="scorer", num_replicas=1)
    class Scorer:
        def __init__(self):
            import jax
            import jax.numpy as jnp
            k = jax.random.PRNGKey(0)
            self.w = jax.random.normal(k, (4, 2))
            self.fwd = jax.jit(lambda w, x: jnp.argmax(x @ w, -1))

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.02)
        async def score_batch(self, xs):
            import jax.numpy as jnp
            batch = jnp.stack([jnp.asarray(x, jnp.float32) for x in xs])
            return [int(v) for v in self.fwd(self.w, batch)]

        async def __call__(self, request):
            x = request.json()["x"]
            return {"class": await self.score_batch(x)}

    handle = serve.run(Scorer, _start_proxy=True)
    addr = serve.get_proxy_address()
    url = f"http://{addr['host']}:{addr['port']}/scorer"
    req = urllib.request.Request(
        url, data=json.dumps({"x": [1.0, 0.0, -1.0, 0.5]}).encode(),
        method="POST", headers={"content-type": "application/json"})
    with urllib.request.urlopen(req, timeout=30) as resp:
        print("HTTP:", json.loads(resp.read()))

    # Same deployment through a Python handle (no HTTP hop):
    from ray_tpu.serve import Request
    out = handle.remote(Request(
        method="POST", body=json.dumps({"x": [0.0, 1.0, 0.0, 0.0]})
        .encode())).result(timeout=30)
    print("handle:", out)

    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
