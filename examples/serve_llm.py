"""Serve an LLM with KV-cache generation: batched decode on the
replica's chip, HTTP in front.

Run: RT_DISABLE_TPU_DETECTION=1 python examples/serve_llm.py
(toy-sized weights; the same deployment shape serves a real GPT —
replicas that request num_tpus=1 keep the params resident in HBM)
"""

import json
import urllib.request

import ray_tpu
from ray_tpu import serve


@serve.deployment(name="llm", num_replicas=1)
class LLM:
    def __init__(self):
        import jax
        import jax.numpy as jnp

        from ray_tpu.models import decode, gpt

        self.cfg = gpt.GPTConfig(vocab_size=256, d_model=64, n_heads=4,
                                 n_layers=2, d_ff=128, max_seq=128,
                                 dtype=jnp.float32, remat=False)
        self.params = gpt.init_params(self.cfg, jax.random.PRNGKey(0))
        self.decode = decode

    @serve.batch(max_batch_size=4, batch_wait_timeout_s=0.05)
    async def generate_batch(self, prompts):
        """Queries arriving together decode as ONE batched lax.scan —
        the MXU sees [batch, ...] matmuls instead of vector products.
        Mixed lengths left-pad to a common width; prompt_lens makes the
        pad columns invisible to attention, so batched results equal
        per-query results."""
        import jax.numpy as jnp
        width = max(len(p) for p in prompts)
        batch = jnp.asarray([[0] * (width - len(p)) + p
                             for p in prompts], jnp.int32)
        lens = jnp.asarray([len(p) for p in prompts], jnp.int32)
        out = self.decode.generate(self.params, batch, self.cfg,
                                   max_new_tokens=8, temperature=0.7,
                                   top_k=20, prompt_lens=lens)
        return [list(map(int, row)) for row in out]

    async def __call__(self, request):
        prompt = request.json()["tokens"]
        return {"generated": await self.generate_batch(prompt)}


def main():
    ray_tpu.init(num_cpus=4)
    serve.run(LLM, _start_proxy=True)
    addr = serve.get_proxy_address()
    url = f"http://{addr['host']}:{addr['port']}/llm"
    req = urllib.request.Request(
        url, data=json.dumps({"tokens": [1, 2, 3, 4]}).encode(),
        method="POST", headers={"content-type": "application/json"})
    with urllib.request.urlopen(req, timeout=60) as resp:
        out = json.loads(resp.read())
    print("generated:", out["generated"])
    assert len(out["generated"]) == 8
    serve.shutdown()
    ray_tpu.shutdown()


if __name__ == "__main__":
    main()
