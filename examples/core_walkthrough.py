"""Core API walkthrough: tasks, actors, objects, placement groups.

Run: RT_DISABLE_TPU_DETECTION=1 python examples/core_walkthrough.py
(reference analogue: the ray-core walkthrough examples)
"""

import numpy as np

import ray_tpu


def main():
    ray_tpu.init(num_cpus=4)

    # --- tasks
    @ray_tpu.remote
    def square(x):
        return x * x

    print("squares:", ray_tpu.get([square.remote(i) for i in range(5)]))

    # --- objects through the shared-memory store (zero-copy numpy)
    big = np.random.rand(1000, 1000)
    ref = ray_tpu.put(big)
    assert ray_tpu.get(ref).shape == (1000, 1000)
    print("put/get of %.1f MB ok" % (big.nbytes / 1e6))

    # --- actors
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self, k=1):
            self.n += k
            return self.n

    c = Counter.remote()
    # Submit all three first — actor tasks run in submission order, so
    # one batched get returns [1, 2, 3] without three round trips.
    print("counter:", ray_tpu.get([c.incr.remote() for _ in range(3)]))

    # --- placement group: reserve a resource bundle, run inside it
    from ray_tpu.util.placement_group import (placement_group,
                                              remove_placement_group)
    from ray_tpu.util.scheduling_strategies import (
        PlacementGroupSchedulingStrategy)

    pg = placement_group([{"CPU": 1}], strategy="PACK")
    ray_tpu.wait_placement_group_ready(pg)
    strat = PlacementGroupSchedulingStrategy(placement_group=pg)
    print("in-pg task:",
          ray_tpu.get(square.options(scheduling_strategy=strat).remote(7)))
    remove_placement_group(pg)

    ray_tpu.shutdown()
    print("core walkthrough done")


if __name__ == "__main__":
    main()
