"""Every example in examples/ runs end-to-end (reference: the doc/example
smoke suites in CI — examples are user surface, so they must not rot)."""

import os
import subprocess
import sys

import pytest

EXAMPLES = os.path.join(os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))), "examples")


def _run(name, timeout=240):
    repo = os.path.dirname(EXAMPLES)
    env = dict(os.environ, RT_DISABLE_TPU_DETECTION="1",
               JAX_PLATFORMS="cpu",
               PYTHONPATH=repo + os.pathsep + os.environ.get(
                   "PYTHONPATH", ""))
    proc = subprocess.run(
        [sys.executable, os.path.join(EXAMPLES, name)],
        env=env, timeout=timeout, capture_output=True, text=True,
        cwd=os.path.dirname(EXAMPLES))
    assert proc.returncode == 0, \
        f"{name} failed:\n{proc.stdout[-2000:]}\n{proc.stderr[-2000:]}"
    return proc.stdout


def test_core_walkthrough():
    out = _run("core_walkthrough.py")
    assert "core walkthrough done" in out
    assert "in-pg task: 49" in out


@pytest.mark.slow
def test_train_gpt():
    out = _run("train_gpt.py")
    assert "final loss:" in out and "params" in out


@pytest.mark.slow
def test_tune_asha():
    out = _run("tune_asha.py", timeout=360)
    assert "best lr:" in out


@pytest.mark.slow
def test_serve_model():
    out = _run("serve_model.py")
    assert "HTTP: {'class':" in out and "handle: {'class':" in out


@pytest.mark.slow
def test_data_to_train():
    out = _run("data_to_train.py")
    assert "read 400 rows from 4 files" in out
    assert "final loss:" in out


@pytest.mark.slow
def test_rllib_ppo():
    out = _run("rllib_ppo.py", timeout=480)
    assert "episode_reward_mean" in out


@pytest.mark.slow
def test_serve_llm():
    out = _run("serve_llm.py", timeout=360)
    assert "generated:" in out


@pytest.mark.slow
def test_llm_serving_continuous_batching():
    out = _run("llm_serving.py", timeout=360)
    assert "llm serving example done" in out
    assert "[DONE]" in out  # SSE stream reached its terminator
