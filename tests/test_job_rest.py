"""Remote job submission over HTTP (reference:
dashboard/modules/job/job_head.py REST + sdk.py JobSubmissionClient):
submit/poll/logs from a client that holds ONLY the dashboard URL."""

import json
import urllib.request

import pytest

import ray_tpu
from ray_tpu.job_submission import JobStatus, JobSubmissionClient


@pytest.fixture
def dashboard(tmp_path):
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    from ray_tpu.dashboard import start_dashboard
    addr = start_dashboard()
    yield f"http://{addr['host']}:{addr['port']}"
    ray_tpu.shutdown()


def test_submit_poll_logs_over_http_only(dashboard):
    # The client touches nothing but HTTP: no driver connection.
    client = JobSubmissionClient(dashboard)
    assert client._http  # REST mode, not driver mode
    sid = client.submit_job(
        entrypoint="python -c \"print('hello-from-job'); print(6*7)\"")
    status = client.wait_until_finished(sid, timeout=180)
    assert status == JobStatus.SUCCEEDED
    logs = client.get_job_logs(sid)
    assert "hello-from-job" in logs and "42" in logs
    info = client.get_job_info(sid)
    assert info["submission_id"] == sid
    assert any(j.get("submission_id") == sid
               for j in client.list_jobs())


@pytest.mark.slow
def test_streaming_logs_and_stop(dashboard):
    client = JobSubmissionClient(dashboard)
    sid = client.submit_job(
        entrypoint="python -u -c \""
                   "import time\n"
                   "for i in range(40):\n"
                   "    print('tick', i, flush=True)\n"
                   "    time.sleep(0.3)\"")
    # Stream the follow endpoint while the job runs.
    chunks = []
    for chunk in client.tail_job_logs(sid):
        chunks.append(chunk)
        if sum(c.count("tick") for c in chunks) >= 3:
            break
    assert sum(c.count("tick") for c in chunks) >= 3
    assert client.stop_job(sid)
    status = client.wait_until_finished(sid, timeout=60)
    assert status == JobStatus.STOPPED


def test_rest_error_paths(dashboard):
    client = JobSubmissionClient(dashboard)
    with pytest.raises(KeyError):
        client.get_job_info("raysubmit_doesnotexist")
    # Missing entrypoint -> 400 surfaced as RuntimeError.
    req = urllib.request.Request(
        f"{dashboard}/api/jobs", data=json.dumps({}).encode(),
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError):
        urllib.request.urlopen(req, timeout=30)
