"""Apex-DQN (distributed replay), vector envs, connectors.

Reference: rllib/algorithms/apex_dqn/apex_dqn.py, rllib/env/vector_env.py,
rllib/connectors/."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import ApexDQNConfig, PPOConfig


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


@pytest.mark.slow
def test_apex_dqn_distributed_replay_learns(ray_init):
    algo = (ApexDQNConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=200)
            .training(train_batch_size=800, num_replay_shards=2,
                      num_sgd_steps=100, sgd_batch_size=64, lr=1e-3,
                      learning_starts=400, epsilon_anneal_iters=5)
            .debugging(seed=3)
            .build())
    best = 0.0
    trained = 0
    routed = 0
    # Generous iteration budget: suite load on the 1-CPU host slows
    # the async routing (stragglers carry over), costing sample volume.
    for _ in range(30):
        r = algo.train()
        best = max(best, r.get("episode_reward_mean") or 0.0)
        trained += r.get("num_env_steps_trained", 0)
        routed += r.get("fragments_routed", 0)
        if best >= 50:
            break
    stats = ray_tpu.get(
        [ra.stats.remote() for ra in algo.replay_actors], timeout=60)
    algo.stop()
    # Replay shards really received experience, the learner really
    # trained from them, and the policy improved over random (~22) —
    # an improvement bar like the plain DQN test's (not PPO's >=150);
    # kept modest because suite load on a 1-CPU host adds variance.
    assert all(s["added"] > 0 for s in stats), stats
    assert trained > 0
    assert routed > 0
    assert best >= 45, f"Apex-DQN failed to learn (best={best})"


@pytest.mark.slow
def test_vector_env_sampling_ppo(ray_init):
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=0, rollout_fragment_length=400)
            .training(train_batch_size=800, num_sgd_iter=12,
                      sgd_minibatch_size=128, lr=2e-3,
                      num_envs_per_worker=4)
            .debugging(seed=11)
            .build())
    # The local worker steps 4 envs per policy forward; fragments from
    # all envs still train correctly (same improvement bar as A2C's).
    best = 0.0
    for _ in range(20):
        r = algo.train()
        best = max(best, r.get("episode_reward_mean") or 0.0)
        if best >= 70:
            break
    algo.stop()
    assert best >= 70, f"vector-env PPO failed to learn (best={best})"


def test_meanstd_obs_connector():
    from ray_tpu.rllib.connectors import MeanStdObsFilter
    f = MeanStdObsFilter()
    rng = np.random.RandomState(0)
    outs = [f(rng.normal(5.0, 2.0, size=3)) for _ in range(500)]
    tail = np.stack(outs[-200:])
    # Normalized stream: near zero-mean unit-variance.
    assert abs(tail.mean()) < 0.3
    assert 0.6 < tail.std() < 1.4
    # State round-trips (synced alongside weights).
    state = f.get_state()
    g = MeanStdObsFilter()
    g.set_state(state)
    x = rng.normal(5.0, 2.0, size=3)
    np.testing.assert_allclose(f.get_state()["mean"], g.get_state()["mean"])


def test_clip_actions_connector():
    from ray_tpu.rllib.connectors import ClipActionsConnector
    c = ClipActionsConnector(low=[-1.0, -1.0], high=[1.0, 1.0])
    out = c(np.array([3.0, -0.5]))
    np.testing.assert_allclose(out, [1.0, -0.5])
