"""DDPG/TD3 continuous control (reference: rllib/algorithms/ddpg,
rllib/algorithms/td3 — mechanics + learning checks)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.examples.env import ReachEnv
from ray_tpu.rllib import DDPGConfig, TD3Config


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_ddpg_pendulum_mechanics(ray_init):
    algo = (DDPGConfig()
            .environment("Pendulum-v1")
            .rollouts(num_rollout_workers=0, rollout_fragment_length=200)
            .training(train_batch_size=400, learning_starts=400,
                      num_sgd_steps=40)
            .debugging(seed=3)
            .build())
    worker = algo.workers.local_worker
    assert not worker._discrete
    batch = worker.sample(64)
    acts = batch["actions"]
    assert acts.dtype == np.float32 and acts.shape[1] == 1
    assert np.all(acts >= -2.0 - 1e-5) and np.all(acts <= 2.0 + 1e-5)
    for _ in range(3):
        r = algo.train()
    stats = r["info"]["learner"]
    assert stats, "learner never ran"
    assert np.isfinite(stats["critic_loss"])
    assert np.isfinite(stats["actor_loss"])
    assert r["episode_reward_mean"] > -1650  # not degenerate
    algo.stop()


@pytest.mark.slow
def test_ddpg_learns_reach_task(ray_init):
    algo = (DDPGConfig()
            .environment(lambda cfg: ReachEnv())
            .rollouts(num_rollout_workers=0, rollout_fragment_length=120)
            .training(train_batch_size=240, learning_starts=240,
                      num_sgd_steps=120, sgd_batch_size=64,
                      gamma=0.9, exploration_noise=0.2)
            .debugging(seed=5)
            .build())
    best = -1e9
    for _ in range(25):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
        if best > -4.0:
            break
    algo.stop()
    # Random walk scores ~-15 per 40-step episode; a trained reacher
    # pins x near 0.
    assert best > -6.0, f"DDPG failed the reach task (best={best})"


@pytest.mark.slow
def test_td3_learns_reach_and_uses_td3_mechanics(ray_init):
    algo = (TD3Config()
            .environment(lambda cfg: ReachEnv())
            .rollouts(num_rollout_workers=0, rollout_fragment_length=120)
            .training(train_batch_size=240, learning_starts=240,
                      num_sgd_steps=120, sgd_batch_size=64,
                      gamma=0.9, exploration_noise=0.2)
            .debugging(seed=6)
            .build())
    policy = algo.workers.local_worker.policy
    assert policy.twin_q and policy.policy_delay == 2
    assert policy.target_noise > 0
    # Twin critics really exist: two heads in the critic pytree.
    import jax
    n_dense = len([k for k in jax.tree_util.tree_leaves(
        policy.critic_params)])
    assert n_dense >= 12  # 2 heads x 3 layers x (kernel, bias)
    best = -1e9
    for _ in range(25):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
        if best > -4.0:
            break
    algo.stop()
    assert best > -6.0, f"TD3 failed the reach task (best={best})"


@pytest.mark.slow
def test_td3_pendulum_improves(ray_init):
    """TD3 climbs the Pendulum learning curve (slow tier: ~25k env
    steps; matches public TD3 baselines' pace on this env)."""
    algo = (TD3Config()
            .environment("Pendulum-v1")
            .rollouts(num_rollout_workers=0, rollout_fragment_length=200)
            .training(train_batch_size=400, learning_starts=400,
                      num_sgd_steps=300, sgd_batch_size=128,
                      actor_lr=1e-3, critic_lr=1e-3, gamma=0.9,
                      exploration_noise=0.15)
            .debugging(seed=7)
            .build())
    best = -1e9
    for _ in range(60):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
        if best > -600:
            break
    algo.stop()
    assert best > -800, f"TD3 failed to improve on Pendulum (best={best})"
