"""Dataset API-surface parity: aggregate/export/split/random-access
(reference: python/ray/data/dataset.py — aggregate :1341, size_bytes,
input_files, randomize_block_order :773, split_proportionately :1110,
to_*_refs, to_torch, to_random_access_dataset :3044)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data


@pytest.fixture(scope="module")
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_aggregate_fns(ray_init):
    from ray_tpu.data import Count, Max, Mean, Min, Std, Sum

    ds = data.from_items([{"x": float(i)} for i in range(10)],
                         parallelism=3)
    out = ds.aggregate(Count(), Sum("x"), Min("x"), Max("x"), Mean("x"),
                       Std("x"))
    assert out["count()"] == 10
    assert out["sum(x)"] == 45.0
    assert out["min(x)"] == 0.0 and out["max(x)"] == 9.0
    assert out["mean(x)"] == 4.5
    assert abs(out["std(x)"] - np.std(np.arange(10.0), ddof=1)) < 1e-9


def test_scalar_aggregates_distributed(ray_init):
    ds = data.range(100, parallelism=5)
    assert ds.sum() == 4950
    assert ds.min() == 0 and ds.max() == 99
    assert ds.mean() == 49.5
    assert abs(ds.std() - np.std(np.arange(100), ddof=1)) < 1e-9


def test_groupby_aggregate_and_std(ray_init):
    from ray_tpu.data import Mean, Sum

    rows = [{"k": i % 3, "v": float(i)} for i in range(12)]
    ds = data.from_items(rows, parallelism=4)
    out = ds.groupby("k").aggregate(Sum("v"), Mean("v")).to_pandas()
    out = out.sort_values("k").reset_index(drop=True)
    for k in range(3):
        vals = [r["v"] for r in rows if r["k"] == k]
        assert out.loc[k, "sum(v)"] == sum(vals)
        assert out.loc[k, "mean(v)"] == sum(vals) / len(vals)
    std = ds.groupby("k").std("v").to_pandas().sort_values("k")
    assert len(std) == 3


def test_size_bytes_and_block_refs(ray_init):
    ds = data.from_numpy(np.ones((64, 8), np.float64), parallelism=4)
    assert ds.size_bytes() >= 64 * 8 * 8
    refs = ds.get_internal_block_refs()
    assert len(refs) == ds.num_blocks()
    total = sum(len(ray_tpu.get(r)["data"]) for r in refs)
    assert total == 64


def test_input_files_tracked(ray_init, tmp_path):
    import pandas as pd
    for i in range(3):
        pd.DataFrame({"a": [i]}).to_csv(tmp_path / f"f{i}.csv",
                                        index=False)
    ds = data.read_csv(str(tmp_path))
    files = ds.input_files()
    assert len(files) == 3 and all(f.endswith(".csv") for f in files)
    # survives transforms
    assert ds.map_batches(lambda b: b).input_files() == files


def test_randomize_block_order(ray_init):
    ds = data.range(40, parallelism=8).randomize_block_order(seed=7)
    assert sorted(ds.take_all()) == list(range(40))
    first = ds.take(5)
    assert first != list(range(5))  # order actually changed


def test_split_proportionately(ray_init):
    ds = data.range(100, parallelism=4)
    a, b, c = ds.split_proportionately([0.2, 0.3])
    assert a.count() == 20 and b.count() == 30 and c.count() == 50
    assert sorted(a.take_all() + b.take_all() + c.take_all()) == \
        list(range(100))
    with pytest.raises(ValueError):
        ds.split_proportionately([0.5, 0.6])


def test_to_refs_exports(ray_init):
    import pandas as pd
    import pyarrow as pa

    ds = data.from_pandas(pd.DataFrame({"a": range(10)}))
    nps = ray_tpu.get(ds.to_numpy_refs(column="a"))
    assert np.concatenate([np.asarray(x) for x in nps]).tolist() == \
        list(range(10))
    dfs = ray_tpu.get(ds.to_pandas_refs())
    assert all(isinstance(d, pd.DataFrame) for d in dfs)
    tbls = ray_tpu.get(ds.to_arrow_refs())
    assert all(isinstance(t, pa.Table) for t in tbls)


def test_to_torch(ray_init):
    import torch

    rows = [{"x": float(i), "y": 2.0 * i, "label": i % 2}
            for i in range(32)]
    ds = data.from_items(rows, parallelism=2)
    it = ds.to_torch(label_column="label", batch_size=8)
    feats, labels, n = None, [], 0
    for f, l in it:  # noqa: E741
        assert isinstance(f, torch.Tensor) and f.shape[1] == 2
        n += f.shape[0]
        labels.append(l)
    assert n == 32
    assert torch.cat(labels).sum().item() == 16


def test_tf_paths_gated(ray_init):
    ds = data.range(4)
    try:
        import tensorflow  # noqa: F401
        has_tf = True
    except ImportError:
        has_tf = False
    if not has_tf:
        with pytest.raises(ImportError):
            list(ds.iter_tf_batches())


def test_lazy_execution_flags(ray_init):
    ds = data.range(10).map(lambda x: x + 1)
    assert not ds.is_fully_executed()
    assert ds.lazy() is ds
    out = ds.fully_executed()
    assert out.is_fully_executed()
    cp = ds.copy()
    assert cp.take_all() == ds.take_all()


def test_write_datasource(ray_init):
    from ray_tpu.data import Datasource

    captured = []

    class CaptureSink(Datasource):
        def do_write(self, blocks, **kw):
            captured.extend(blocks)

    data.range(10, parallelism=2).write_datasource(CaptureSink())
    assert sum(len(b) for b in captured) == 10


def test_random_access_dataset(ray_init):
    rows = [{"key": i, "val": i * 10} for i in range(50)]
    ds = data.from_items(rows, parallelism=5)
    rad = ds.to_random_access_dataset("key", num_workers=2)
    assert ray_tpu.get(rad.get_async(7))["val"] == 70
    assert ray_tpu.get(rad.get_async(999)) is None
    got = rad.multiget([3, 17, 41, 999])
    assert [None if g is None else g["val"] for g in got] == \
        [30, 170, 410, None]
    assert "worker" in rad.stats()


def test_random_access_block_assignment_is_contiguous(ray_init):
    """Each worker must own a CONTIGUOUS chunk of the sorted block
    list (the docstring's key-locality claim): round-robin would
    interleave adjacent keys across workers."""
    rows = [{"key": i} for i in range(60)]
    ds = data.from_items(rows, parallelism=6)
    rad = ds.to_random_access_dataset("key", num_workers=3)
    by_worker = {}
    for block_idx, w in rad._block_to_worker.items():
        by_worker.setdefault(w, []).append(block_idx)
    assert sum(len(v) for v in by_worker.values()) == 6
    for w, idxs in by_worker.items():
        idxs = sorted(idxs)
        assert idxs == list(range(idxs[0], idxs[-1] + 1)), \
            f"worker {w} got non-contiguous blocks {idxs}"
    # Workers cover increasing, non-overlapping ranges in order.
    spans = sorted((min(v), max(v)) for v in by_worker.values())
    for (_, hi), (lo, _) in zip(spans, spans[1:]):
        assert lo == hi + 1
    # Lookups still resolve correctly under the new assignment.
    got = rad.multiget(list(range(0, 60, 7)) + [999])
    assert [None if g is None else g["key"] for g in got] == \
        list(range(0, 60, 7)) + [None]


def test_stats_reports_stages(ray_init):
    ds = data.range(10, parallelism=2).map(lambda x: x * 2)
    ds.take_all()
    s = ds.stats()
    assert "blocks" in s and "Stage" in s
