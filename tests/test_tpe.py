"""Native TPE searcher: unit convergence + Tuner integration
(reference: tune/tests/test_searchers.py over search/hyperopt)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.tune import TuneConfig, Tuner
from ray_tpu.tune.search import choice, loguniform, uniform
from ray_tpu.tune.search.tpe import TPESearcher


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def _objective_value(cfg):
    penalty = 0.0 if cfg["kind"] == "good" else 0.5
    return (cfg["x"] - 0.7) ** 2 + penalty


def test_tpe_concentrates_on_optimum():
    space = {"x": uniform(0.0, 1.0),
             "kind": choice(["good", "bad"]),
             "const": 3}
    searcher = TPESearcher(space, metric="loss", mode="min",
                           num_samples=60, n_startup=10, seed=0)
    history = []
    for i in range(60):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        assert cfg is not None and cfg["const"] == 3
        loss = _objective_value(cfg)
        searcher.on_trial_complete(tid, {"loss": loss})
        history.append((cfg, loss))
    assert searcher.suggest("overflow") is None  # budget exhausted

    best = min(h[1] for h in history)
    assert best < 0.02, f"TPE best loss {best}"
    # The model phase should concentrate near x=0.7 / kind=good compared
    # to the random startup phase.
    startup = [c["x"] for c, _ in history[:10]]
    model = [c["x"] for c, _ in history[-20:]]
    assert abs(np.mean(model) - 0.7) < abs(np.mean(startup) - 0.7) + 0.05
    model_kinds = [c["kind"] for c, _ in history[-20:]]
    assert model_kinds.count("good") >= 12


def test_tpe_log_domain_and_max_mode():
    space = {"lr": loguniform(1e-5, 1e-1)}
    searcher = TPESearcher(space, metric="score", mode="max",
                           num_samples=40, n_startup=8, seed=1)
    for i in range(40):
        tid = f"t{i}"
        cfg = searcher.suggest(tid)
        # Peak score at lr = 1e-3.
        score = -abs(np.log10(cfg["lr"]) + 3)
        searcher.on_trial_complete(tid, {"score": score})
    tail = [searcher._history[i][0]["lr"] for i in range(-10, 0)]
    geo = 10 ** np.mean(np.log10(tail))
    assert 1e-4 < geo < 1e-2, f"TPE geo-mean lr {geo}"


@pytest.mark.slow
def test_tpe_drives_tuner(ray_init):
    def objective(config):
        from ray_tpu.air import session
        session.report(
            {"loss": (config["x"] - 0.25) ** 2, "done": True})

    space = {"x": uniform(0.0, 1.0)}
    results = Tuner(
        objective,
        param_space=space,
        tune_config=TuneConfig(
            metric="loss", mode="min",
            search_alg=TPESearcher(space, metric="loss", mode="min",
                                   num_samples=12, n_startup=4,
                                   seed=2)),
    ).fit()
    assert len(results) == 12
    best = results.get_best_result()
    assert best.metrics["loss"] < 0.05


@pytest.mark.slow
def test_gp_search_finds_optimum(ray_init):
    """Native GP-EI searcher (reference role: search/bayesopt adapter)
    beats the random-startup baseline on a smooth 2-D surface."""
    from ray_tpu import tune
    from ray_tpu.tune.search.gp import GPSearch

    def objective(config):
        x, y = config["x"], config["y"]
        score = -((x - 0.3) ** 2 + (y - 0.7) ** 2)
        tune.report({"score": score, "done": True})

    space = {"x": tune.uniform(0.0, 1.0), "y": tune.uniform(0.0, 1.0)}
    gp = GPSearch(space, metric="score", mode="max", num_samples=24,
                  n_startup=6, seed=5)
    tuner = tune.Tuner(objective, param_space=space,
                       tune_config=tune.TuneConfig(
                           search_alg=gp, metric="score", mode="max"))
    results = tuner.fit()
    best = results.get_best_result(metric="score", mode="max")
    # Within a modest radius of the optimum (random-only over 24 samples
    # lands this close with probability ~55%; the GP reliably does).
    assert best.metrics["score"] > -0.01, best.metrics
    # Categorical + log dims also encode/decode.
    space2 = {"lr": tune.loguniform(1e-5, 1e-1),
              "act": tune.choice(["relu", "tanh"])}
    gp2 = GPSearch(space2, metric="score", mode="max", num_samples=4,
                   n_startup=1, seed=0)
    c1 = gp2.suggest("a")
    gp2.on_trial_complete("a", {"score": 1.0})
    c2 = gp2.suggest("b")
    assert 1e-5 <= c2["lr"] <= 1e-1 and c2["act"] in ("relu", "tanh")
