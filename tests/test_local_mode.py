"""local_mode=True: the inline runtime-free execution seam (reference:
ray.init(local_mode=True); the mock layer role of src/mock/ray)."""

import numpy as np
import pytest

import ray_tpu


@pytest.fixture
def local():
    ray_tpu.init(local_mode=True)
    yield
    ray_tpu.shutdown()


def test_tasks_run_inline(local):
    calls = []

    @ray_tpu.remote
    def f(x):
        calls.append(x)  # visible: same process, no pickling round-trip
        return x + 1

    ref = f.remote(1)
    assert calls == [1]  # executed at submission
    assert ray_tpu.get(ref) == 2
    nested = f.remote(ref)
    assert ray_tpu.get(nested) == 3  # refs resolve as args


def test_put_get_wait(local):
    r = ray_tpu.put(np.arange(4))
    np.testing.assert_array_equal(ray_tpu.get(r), np.arange(4))
    ready, pending = ray_tpu.wait([r], num_returns=1)
    assert ready == [r] and not pending


def test_actor_lifecycle_and_named(local):
    @ray_tpu.remote
    class Counter:
        def __init__(self, n0):
            self.n = n0

        def add(self, k):
            self.n += k
            return self.n

    c = Counter.options(name="ctr").remote(10)
    assert ray_tpu.get(c.add.remote(5)) == 15
    again = ray_tpu.get_actor("ctr")
    assert ray_tpu.get(again.add.remote(1)) == 16
    ray_tpu.kill(c)
    with pytest.raises(ray_tpu.exceptions.ActorDiedError):
        ray_tpu.get(c.add.remote(1))


def test_task_errors_surface_at_get(local):
    @ray_tpu.remote
    def boom():
        raise ValueError("inline failure")

    ref = boom.remote()
    with pytest.raises(ValueError, match="inline failure"):
        ray_tpu.get(ref)


def test_multiple_returns(local):
    @ray_tpu.remote
    def pair():
        return 1, 2

    a, b = pair.options(num_returns=2).remote()
    assert ray_tpu.get([a, b]) == [1, 2]


def test_cluster_verbs_raise_clearly(local):
    with pytest.raises(RuntimeError, match="local mode"):
        ray_tpu.nodes()


def test_runtime_context_and_await(local):
    ctx = ray_tpu.get_runtime_context()
    assert len(ctx.get_job_id()) > 0
    assert ctx.get_node_id()
    assert ctx.get_actor_id() is None

    @ray_tpu.remote
    class Awaiter:
        async def pull(self, refs):
            # Nested refs stay refs (real-runtime semantics) and
            # resolve via await.
            return await refs[0] + 1

    a = Awaiter.remote()
    ref = ray_tpu.put(41)
    assert ray_tpu.get(a.pull.remote([ref])) == 42
