"""ray_tpu.dag: lazy bind/execute IR (reference: python/ray/dag tests)."""

import pytest

import ray_tpu
from ray_tpu.dag import InputNode


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_function_dag(ray_init):
    @ray_tpu.remote
    def a(x):
        return x + 1

    @ray_tpu.remote
    def b(x):
        return x * 2

    @ray_tpu.remote
    def combine(x, y):
        return x + y

    with InputNode() as inp:
        dag = combine.bind(a.bind(inp), b.bind(inp))
    ref = dag.execute(10)
    assert ray_tpu.get(ref, timeout=60) == (10 + 1) + (10 * 2)


def test_actor_dag(ray_init):
    @ray_tpu.remote
    class Adder:
        def __init__(self, base):
            self.base = base

        def add(self, x):
            return self.base + x

    with InputNode() as inp:
        actor = Adder.bind(100)
        dag = actor.add.bind(inp)
    assert ray_tpu.get(dag.execute(7), timeout=60) == 107
