"""Round-4 algorithm additions, part 3: MAML, MB-MPO, Dreamer,
AlphaStar league (reference: rllib/algorithms/{maml,mbmpo,dreamer,
alpha_star}/tests)."""

import numpy as np
import pytest

from ray_tpu.rllib import (AlphaStarConfig, DreamerConfig, MAMLConfig,
                           MBMPOConfig)


def _holdout_tasks(n=8, seed=123):
    rng = np.random.RandomState(seed)
    tasks = []
    for _ in range(n):
        th = rng.uniform(0, 2 * np.pi)
        tasks.append({"goal": (0.5 * np.cos(th), 0.5 * np.sin(th))})
    return tasks


@pytest.mark.slow
def test_maml_adaptation_on_held_out_tasks():
    """After meta-training, ONE inner policy-gradient step on a
    held-out task improves deterministic performance on average — the
    property MAML optimizes (exact grad-through-grad meta-gradient)."""
    algo = (MAMLConfig()
            .training(meta_batch_size=8, episodes_per_task=16,
                      inner_lr=0.5, outer_lr=3e-3)
            .debugging(seed=0)
            .build())
    for _ in range(12):
        r = algo.step()
    assert np.isfinite(r["post_adaptation_reward"])
    pres, posts = [], []
    for task in _holdout_tasks():
        pres.append(algo.evaluate(algo.params, task))
        adapted = algo.adapt_to(task)
        posts.append(algo.evaluate(adapted, task))
    gain = float(np.mean(posts) - np.mean(pres))
    assert gain > 0.7, (
        f"one-step adaptation should improve held-out tasks "
        f"(mean pre={np.mean(pres):.2f}, post={np.mean(posts):.2f}, "
        f"gain={gain:.2f})")


@pytest.mark.slow
def test_mbmpo_learns_models_and_adapts_inside_them():
    """MB-MPO: the dynamics ensemble fits the real transitions (point
    dynamics are linear — loss goes to ~0) and the meta-policy's
    IMAGINED post-adaptation return beats its real pre-adaptation
    return (adaptation happens inside the learned models, which is the
    algorithm's point)."""
    algo = (MBMPOConfig()
            .training(ensemble_size=4, episodes_per_task=12,
                      inner_lr=0.5, outer_lr=3e-3,
                      model_train_steps=150, real_episodes_per_iter=8)
            .debugging(seed=0)
            .build())
    reals, imagined, mloss = [], [], np.inf
    for _ in range(8):
        r = algo.step()
        reals.append(r["episode_reward_mean"])
        imagined.append(r["imagined_post_adaptation_reward"])
        mloss = r["model_loss"]
    assert mloss < 1e-3, f"dynamics ensemble did not fit ({mloss})"
    assert r["buffer_size"] > 500
    assert np.mean(imagined[-6:]) > np.mean(reals[-6:]) + 1.0, (
        f"imagined post-adaptation ({np.mean(imagined[-6:]):.2f}) "
        f"should beat real pre-adaptation ({np.mean(reals[-6:]):.2f})")


@pytest.mark.slow
def test_dreamer_latent_imagination_improves_pendulum():
    """Dreamer: the world model fits (loss falls an order of
    magnitude) and behavior learned purely in latent imagination
    improves real Pendulum return well past random."""
    algo = (DreamerConfig()
            .environment("Pendulum-v1")
            .training(max_episode_steps=100, episodes_per_iter=4,
                      model_train_steps=60, behavior_train_steps=60)
            .debugging(seed=0)
            .build())
    first = None
    best = -np.inf
    wm_losses = []
    for _ in range(25):
        r = algo.step()
        if first is None:
            first = r["episode_reward_this_iter"]
        best = max(best, r["episode_reward_this_iter"])
        wm_losses.append(r["world_model_loss"])
        if best >= -400 and wm_losses[-1] < 3.0:
            break
    algo.stop()
    assert wm_losses[-1] < 3.0, (
        f"world model did not fit (loss={wm_losses[-1]:.2f})")
    assert best >= first + 120, (
        f"imagination-trained behavior should improve on the random "
        f"start (first={first:.0f}, best={best:.0f})")


@pytest.mark.slow
def test_alpha_star_league_beats_self_play_on_rps():
    """The league's reason to exist: on rock-paper-scissors, naive
    self-play CYCLES (its mixture stays exploitable); the league's
    fictitious-self-play mixture approaches the Nash mixture."""
    def run(**kw):
        algo = (AlphaStarConfig()
                .training(init_scale=1.5, games_per_step=512, **kw)
                .debugging(seed=1)
                .build())
        mix = []
        for _ in range(200):
            r = algo.step()
            mix.append(r["mixture_exploitability"])
        return float(np.mean(mix[-20:])), r

    league_expl, r = run()
    assert r["league_size"] > 50            # snapshots accumulated
    self_play_expl, _ = run(num_main_exploiters=0,
                            num_league_exploiters=0,
                            snapshot_every=10**9)
    assert league_expl < 0.3, (
        f"league mixture should approach Nash (expl={league_expl:.3f})")
    assert self_play_expl > 0.6, (
        f"self-play should stay cycling/exploitable "
        f"(expl={self_play_expl:.3f})")
    assert league_expl < self_play_expl - 0.25
