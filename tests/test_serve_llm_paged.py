"""Paged KV cache, radix prefix reuse, and in-engine speculation.

Three layers under test:

  * paging.py bookkeeping — refcounted BlockAllocator + RadixPrefixCache
    (the load-bearing invariant: evicting one sharer of a prefix page
    must never free a page another request still gathers through);
  * decode.paged_chunk_step — block-table attention must match the
    contiguous cache kernels for any page permutation;
  * the engine — THE acceptance property is parity: random arrival
    schedules x {prefix full hit, partial hit, miss} x {speculation
    on/off} must all stream tokens bit-identical to per-prompt greedy
    decode.generate(), plus free-page-bounded admission and the
    structured queue_full / kv_exhausted backpressure split.
"""

import asyncio
import random
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import decode, gpt, llama
from ray_tpu.serve.llm import (BlockAllocator, EngineOverloadedError,
                               GenerationEngine, RadixPrefixCache)

GPT_CFG = gpt.GPTConfig(vocab_size=97, d_model=32, n_heads=4,
                        n_layers=2, d_ff=64, max_seq=64,
                        dtype=jnp.float32, remat=False, use_flash=False)
LLAMA_CFG = llama.LlamaConfig(vocab_size=97, d_model=32, n_heads=4,
                              n_kv_heads=2, n_layers=2, d_ff=48,
                              max_seq=64, dtype=jnp.float32,
                              remat=False, use_flash=False)


def _params(cfg):
    mod = llama if isinstance(cfg, llama.LlamaConfig) else gpt
    return mod.init_params(cfg, jax.random.PRNGKey(0))


GPT_PARAMS = _params(GPT_CFG)

# One shared shape vocabulary so jit compilations are reused across
# tests: 3 rows, page 4, max_seq 48, chunk-5 prefill.
PAGED_KW = dict(num_slots=3, max_seq=48, prefill_chunk=5, page_size=4,
                kv_pages=40)


def _prompt(seed, n, cfg=GPT_CFG):
    return [int(t) for t in np.asarray(jax.random.randint(
        jax.random.PRNGKey(seed), (n,), 1, cfg.vocab_size))]


def _oracle(params, cfg, prompt, max_new, eos_token=None):
    out = decode.generate(params, jnp.asarray([prompt]), cfg,
                          max_new_tokens=max_new, eos_token=eos_token)
    return np.asarray(out[0])


# ---------------------------------------------------------------------------
# BlockAllocator


def test_block_allocator_refcounted_alloc_free():
    a = BlockAllocator(4, first_page=1)
    assert a.free_pages == 4
    pages = a.alloc(3)
    assert sorted(pages) == [1, 2, 3] and a.free_pages == 1
    # all-or-nothing: a too-big request leaves the free list untouched
    assert a.alloc(2) is None
    assert a.free_pages == 1
    # shared page: the second holder keeps it alive
    a.incref(pages[0])
    assert not a.decref(pages[0])          # one ref left
    assert a.refcount(pages[0]) == 1
    assert a.free_pages == 1
    assert a.decref(pages[0])              # last ref frees
    assert a.free_pages == 2
    with pytest.raises(ValueError):
        a.decref(pages[0])                 # double free is loud
    for p in pages[1:]:
        a.decref(p)
    assert a.free_pages == 4


def test_radix_cache_match_insert_evict():
    a = BlockAllocator(8, first_page=1)
    rc = RadixPrefixCache(2, a)
    toks = [5, 6, 7, 8, 9, 10]
    pages = a.alloc(3)
    rc.insert(toks, pages)                 # tree now holds 3 refs
    assert rc.nodes == 3
    # full-page match only; max_tokens caps the walk
    got, n = rc.match(toks)
    assert got == pages and n == 6
    got, n = rc.match(toks, max_tokens=5)  # cap at 5 -> 2 full pages
    assert got == pages[:2] and n == 4
    got, n = rc.match([5, 6, 7, 99])       # diverges in page 2
    assert got == pages[:1] and n == 2
    assert rc.match([1, 2]) == ([], 0)
    # releasing the requester's own refs leaves pages tree-held
    for p in pages:
        a.decref(p)
    assert a.free_pages == 5
    # evicting one sharer's node must not free a page a live holder
    # still reads: hold page[2] as a "request", then evict everything
    a.incref(pages[2])
    rc.evict(8)                            # wants all 8 free
    assert rc.nodes == 0
    assert a.free_pages == 7               # pages[2] survives its node
    assert a.refcount(pages[2]) == 1
    a.decref(pages[2])
    assert a.free_pages == 8


def test_radix_releasable_counts_tree_only_pages():
    """releasable() is the engine's evict-worthiness pre-check: pages a
    full wipe could actually free (tree-only holders).  A reservation
    that even a full wipe cannot cover must not destroy the cache."""
    a = BlockAllocator(6, first_page=1)
    rc = RadixPrefixCache(2, a)
    pages = a.alloc(3)
    rc.insert([1, 2, 3, 4, 5, 6], pages)
    # requester still holds all 3 -> nothing is releasable yet
    assert rc.releasable() == 0
    a.decref(pages[0])
    a.decref(pages[1])
    assert rc.releasable() == 2              # two tree-only pages now
    # free=3, releasable=2: a 6-page ask is unsatisfiable — the engine
    # skips evict() in that case; a 5-page ask is coverable
    assert a.free_pages + rc.releasable() < 6
    assert a.free_pages + rc.releasable() >= 5
    rc.evict(5)
    assert a.free_pages == 5
    # the shared leaf's NODE went (it blocked the interior pages) but
    # its page survives on the requester's ref
    assert rc.nodes == 0
    assert a.refcount(pages[2]) == 1
    a.decref(pages[2])
    assert a.free_pages == 6


def test_radix_cache_lru_eviction_order():
    a = BlockAllocator(4, first_page=1)
    rc = RadixPrefixCache(2, a)
    p1 = a.alloc(1)
    p2 = a.alloc(1)
    rc.insert([1, 2], p1)
    rc.insert([3, 4], p2)
    for p in p1 + p2:
        a.decref(p)
    rc.match([1, 2])                       # touch branch 1 -> MRU
    rc.evict(3)                            # need one page back
    assert rc.nodes == 1
    assert rc.match([1, 2])[1] == 2        # MRU branch survived
    assert rc.match([3, 4])[1] == 0        # LRU branch evicted


def test_radix_insert_dedups_existing_chunks():
    a = BlockAllocator(8, first_page=1)
    rc = RadixPrefixCache(2, a)
    first = a.alloc(2)
    rc.insert([1, 2, 3, 4], first)
    dup = a.alloc(2)
    added = rc.insert([1, 2, 3, 4, 5, 6], dup + a.alloc(1))
    assert added == 1                      # only the NEW third chunk
    got, n = rc.match([1, 2, 3, 4, 5, 6])
    assert n == 6
    assert got[:2] == first                # original pages kept


# ---------------------------------------------------------------------------
# Paged decode kernels


@pytest.mark.parametrize(
    "cfg", [GPT_CFG,
            pytest.param(LLAMA_CFG, marks=pytest.mark.slow)],
    ids=["gpt", "llama"])
def test_paged_chunk_step_matches_contiguous(cfg):
    """Block-table attention with SCRAMBLED page order must produce the
    same logits as the contiguous-cache kernels, chunked prefill and
    per-row-depth decode alike."""
    params = _params(cfg)
    psz, nblk = 4, 6                       # virtual width 24
    lens = [5, 9]
    seqs = [jax.random.randint(jax.random.PRNGKey(40 + i), (1, n), 1,
                               cfg.vocab_size) for i, n in enumerate(lens)]
    # contiguous oracle: per-request caches
    solo = []
    for i, (seq, n) in enumerate(zip(seqs, lens)):
        c = decode.init_cache(cfg, 1, max_seq=nblk * psz)
        _, c = decode.prefill(params, seq, cfg, c)
        tok = jnp.asarray([7 + i], jnp.int32)
        lg, c = decode.decode_step(params, tok, jnp.int32(n), c, cfg)
        solo.append((lg, c))
    # paged: one pool, rows own interleaved non-contiguous pages
    # (page 0 deliberately unused, mirroring the engine's trash page)
    pool = decode.init_paged_cache(cfg, 2 * nblk + 1, psz)
    tables = np.asarray([[2, 4, 6, 8, 10, 12],
                         [11, 3, 9, 1, 7, 5]], np.int32)
    for i, (seq, n) in enumerate(zip(seqs, lens)):
        lg, pool = decode.paged_chunk_step(
            params, seq, jnp.int32(0), pool,
            jnp.asarray(tables[i:i + 1]), cfg)
        np.testing.assert_allclose(
            np.asarray(lg[0, n - 1]),
            np.asarray(decode.prefill(
                params, seq, cfg,
                decode.init_cache(cfg, 1, max_seq=nblk * psz))[0][0, n - 1]),
            rtol=1e-6, atol=1e-7)
    toks = jnp.asarray([7, 8], jnp.int32)
    pos = jnp.asarray(lens, jnp.int32)
    logits, pool = decode.paged_decode_step(params, toks, pos, pool,
                                            jnp.asarray(tables), cfg)
    for i in range(2):
        np.testing.assert_allclose(np.asarray(logits[i]),
                                   np.asarray(solo[i][0][0]),
                                   rtol=1e-6, atol=1e-7)
        # gathered pages hold exactly the contiguous cache's content
        pk = np.asarray(pool["k"])[:, tables[i]].reshape(
            cfg.n_layers, nblk * psz, -1)
        sk = np.asarray(solo[i][1]["k"])[:, 0].reshape(
            cfg.n_layers, nblk * psz, -1)
        cols = lens[i] + 1                 # written columns so far
        np.testing.assert_allclose(pk[:, :cols], sk[:, :cols],
                                   rtol=1e-6, atol=1e-7)


def test_paged_writes_touch_only_own_pages():
    """A row's scatter writes must land only in ITS block table's pages
    — the page-pool twin of the old touch-only-their-row test."""
    cfg, params = GPT_CFG, GPT_PARAMS
    psz = 4
    pool = decode.init_paged_cache(cfg, 7, psz)
    t1 = np.asarray([[1, 2, 3]], np.int32)
    t2 = np.asarray([[4, 5, 6]], np.int32)
    seq = jax.random.randint(jax.random.PRNGKey(50), (1, 8), 1,
                             cfg.vocab_size)
    _, pool = decode.paged_chunk_step(params, seq, jnp.int32(0), pool,
                                      jnp.asarray(t1), cfg)
    before = np.asarray(pool["k"])
    assert np.abs(before[:, [1, 2, 3]]).max() > 0
    assert np.abs(before[:, [4, 5, 6]]).max() == 0
    _, pool = decode.paged_chunk_step(params, seq, jnp.int32(0), pool,
                                      jnp.asarray(t2), cfg)
    after = np.asarray(pool["k"])
    np.testing.assert_array_equal(after[:, [1, 2, 3]],
                                  before[:, [1, 2, 3]])
    assert np.abs(after[:, [4, 5, 6]]).max() > 0


# ---------------------------------------------------------------------------
# Engine: the parity property sweep


@pytest.mark.parametrize("speculate", [0, 3], ids=["spec_off", "spec_on"])
def test_paged_parity_sweep_prefix_hits_and_speculation(speculate):
    """THE acceptance property: random arrival schedules x {full prefix
    hit, partial hit, miss, hit+extension, repetitive} — with and
    without in-engine speculation — all bit-identical to per-prompt
    greedy generate().  The warm request populates the radix cache, so
    later identical prompts take the shared-page path."""
    base = _prompt(123, 12)                # 3 full pages at page_size 4
    prompts = {
        "warm_miss": list(base),
        "full_hit": list(base),
        "partial_hit": base[:8] + _prompt(5, 4),
        "miss": _prompt(9, 10),
        "hit_extension": base + _prompt(11, 5),
        "repetitive": [5, 6, 7] * 4,       # prompt-lookup drafts fire
    }
    max_new = 8
    oracles = {k: _oracle(GPT_PARAMS, GPT_CFG, p, max_new)
               for k, p in prompts.items()}
    rng = random.Random(speculate)

    async def run():
        # ngram=1 so drafts actually FIRE against the real model (its
        # greedy chains repeat tokens within a few steps); most drafts
        # are then rejected by verification, which is exactly the hard
        # half of the parity property.
        eng = GenerationEngine(GPT_PARAMS, GPT_CFG, speculate_k=speculate,
                               speculate_ngram=1, **PAGED_KW)
        with eng:
            warm = eng.submit(prompts["warm_miss"], max_new_tokens=max_new)
            outs = {"warm_miss": [t async for t in warm]}
            order = [k for k in prompts if k != "warm_miss"]
            rng.shuffle(order)
            streams = {}
            for k in order:                # staggered random arrivals
                streams[k] = eng.submit(prompts[k], max_new_tokens=max_new)
                await asyncio.sleep(rng.random() * 0.05)
            for k in order:
                outs[k] = await streams[k].collect()
            st = eng.stats()
        return outs, st

    outs, st = asyncio.run(run())
    for k, want in oracles.items():
        np.testing.assert_array_equal(
            np.asarray(outs[k]), want,
            err_msg=f"case {k} diverged (speculate_k={speculate})")
    # full_hit, partial_hit, and hit_extension all matched cached pages
    assert st.prefix_cache_hits >= 3, st
    assert st.prefix_hit_tokens >= 8 + 8 + 12, st
    assert st.requests_completed == len(prompts)
    if speculate:
        assert st.spec_drafted_tokens > 0, st


def test_engine_admission_bounded_by_free_pages_not_rows():
    """num_slots rows available but a pool too small for all of them:
    admission must wait for pages, peak concurrency is page-bounded,
    and everything still completes with parity."""
    prompts = [_prompt(60 + i, 6) for i in range(4)]
    oracles = [_oracle(GPT_PARAMS, GPT_CFG, p, 6) for p in prompts]

    async def run():
        # 6+6 tokens -> 3 pages of 4 each; 6 usable pages -> 2 resident
        eng = GenerationEngine(GPT_PARAMS, GPT_CFG, num_slots=3,
                               max_seq=48, prefill_chunk=5, page_size=4,
                               kv_pages=6, enable_prefix_cache=False)
        peak = 0
        with eng:
            streams = [eng.submit(p, max_new_tokens=6) for p in prompts]
            outs = []
            for s in streams:
                outs.append(await s.collect())
                peak = max(peak, eng.stats().active_slots)
            end = eng.stats()
        return outs, peak, end

    outs, peak, end = asyncio.run(run())
    for got, want in zip(outs, oracles):
        np.testing.assert_array_equal(np.asarray(got), want)
    assert peak <= 2, peak                 # pages bind before rows
    assert end.requests_completed == 4
    assert end.kv_blocks_free == end.kv_blocks_total  # prefix cache off


def test_evicting_one_sharer_keeps_shared_pages_alive():
    """Two requests share prefix pages through the radix cache; the
    first finishing (and a forced cache eviction) must not corrupt the
    second mid-generation — the allocator refcount is what stands
    between them."""
    base = _prompt(77, 12)

    async def run():
        eng = GenerationEngine(GPT_PARAMS, GPT_CFG, **PAGED_KW)
        with eng:
            await eng.generate(base, max_new_tokens=4)  # warm the cache
            a = eng.submit(base, max_new_tokens=20)
            first = await a.__anext__()    # A resident, holding shares
            b = eng.submit(base, max_new_tokens=6)
            got_b = await b.collect()      # B shares A's prefix pages
            # force the tree to drop every node NOW; A must keep going
            # on its refcounted hold alone
            eng._prefix.evict(eng.kv_pages)
            got_a = [first] + [t async for t in a]
        return got_a, got_b

    got_a, got_b = asyncio.run(run())
    np.testing.assert_array_equal(
        np.asarray(got_a), _oracle(GPT_PARAMS, GPT_CFG, base, 20))
    np.testing.assert_array_equal(
        np.asarray(got_b), _oracle(GPT_PARAMS, GPT_CFG, base, 6))


def test_speculation_accepts_on_predictable_continuation():
    """A zero-weight model generates token 0 forever, so every
    prompt-lookup draft comes true: the engine's fused verify must
    accept drafts (counter > 0) while emitting the exact greedy
    output."""
    zero = jax.tree_util.tree_map(jnp.zeros_like, GPT_PARAMS)
    zero["ln_f"] = jnp.ones_like(zero["ln_f"])
    prompt = [0] * 8
    want = _oracle(zero, GPT_CFG, prompt, 16)

    async def run():
        eng = GenerationEngine(zero, GPT_CFG, speculate_k=3,
                               speculate_ngram=2, **PAGED_KW)
        with eng:
            out = await eng.generate(prompt, max_new_tokens=16)
            st = eng.stats()
        return out, st

    out, st = asyncio.run(run())
    np.testing.assert_array_equal(np.asarray(out), want)
    assert st.spec_accepted_tokens > 0, st
    assert st.spec_drafted_tokens >= st.spec_accepted_tokens


# ---------------------------------------------------------------------------
# Structured backpressure


def _parked_engine(**kw):
    """An engine whose worker is parked so admission state is
    deterministic (same trick as the HTTP 503 test)."""
    eng = GenerationEngine(GPT_PARAMS, GPT_CFG, **kw)
    eng.stop()
    eng.start = lambda: eng
    return eng


def test_submit_distinguishes_queue_full_from_kv_exhausted():
    # kv_exhausted: commit cap = 1.0 * 6 pages; each request wants
    # 3 pages (6+6 tokens at page 4) -> the third submit overflows the
    # cap long before the 50-deep queue fills.
    eng = _parked_engine(num_slots=2, max_seq=48, prefill_chunk=5,
                         page_size=4, kv_pages=6, max_queue_len=50,
                         kv_commit_factor=1.0)
    eng.submit(_prompt(1, 6), max_new_tokens=6)
    eng.submit(_prompt(2, 6), max_new_tokens=6)
    with pytest.raises(EngineOverloadedError) as ei:
        eng.submit(_prompt(3, 6), max_new_tokens=6)
    assert ei.value.reason == "kv_exhausted"
    assert ei.value.retry_after_s > 1.0
    assert eng.stats().requests_rejected == 1

    # queue_full: huge commit headroom, 1-deep queue.
    eng2 = _parked_engine(num_slots=2, max_seq=48, prefill_chunk=5,
                          page_size=4, kv_pages=40, max_queue_len=1,
                          kv_commit_factor=100.0)
    eng2.submit(_prompt(4, 6), max_new_tokens=6)
    with pytest.raises(EngineOverloadedError) as ei:
        eng2.submit(_prompt(5, 6), max_new_tokens=6)
    assert ei.value.reason == "queue_full"
    assert ei.value.retry_after_s == 1.0

    # a request the pool can NEVER hold is a caller error, not overload
    with pytest.raises(ValueError, match="KV pages"):
        eng.submit(_prompt(6, 20), max_new_tokens=20)


def test_commit_cap_releases_as_requests_finish():
    async def run():
        # identical shapes to the admission-bounded test above, so the
        # two share every jit compilation
        eng = GenerationEngine(GPT_PARAMS, GPT_CFG, num_slots=3,
                               max_seq=48, prefill_chunk=5, page_size=4,
                               kv_pages=6, max_queue_len=50,
                               kv_commit_factor=1.0,
                               enable_prefix_cache=False)
        with eng:
            await eng.generate(_prompt(1, 6), max_new_tokens=6)
            await eng.generate(_prompt(2, 6), max_new_tokens=6)
            # both finished -> demand released -> admission open again
            out = await eng.generate(_prompt(3, 6), max_new_tokens=6)
        return out

    assert len(asyncio.run(run())) == 6


def test_http_retry_after_tracks_overload_reason():
    """api.py maps queue_full -> Retry-After 1 and kv_exhausted -> a
    longer hint, both as structured 503s.  Float seconds on the wire:
    a tier-aware hint can be sub-second (one demotion sweep away) and
    integer rounding would turn it into a full second of idle client."""
    import json

    from ray_tpu.serve._private.replica import Request
    from ray_tpu.serve.llm.api import LLMServer

    def _call(srv):
        async def go():
            req = Request(method="POST", path="/", body=json.dumps(
                {"tokens": _prompt(7, 6), "max_new_tokens": 6}).encode())
            return await srv(req)
        return asyncio.run(go())

    srv = LLMServer(lambda: (GPT_PARAMS, GPT_CFG), engine_config=dict(
        num_slots=2, max_seq=48, prefill_chunk=5, page_size=4,
        kv_pages=6, max_queue_len=50, kv_commit_factor=1.0))
    try:
        srv.engine.stop()
        srv.engine.start = lambda: srv.engine
        srv.engine.submit(_prompt(1, 6), max_new_tokens=6)
        srv.engine.submit(_prompt(2, 6), max_new_tokens=6)
        out = _call(srv)
        assert out["__http__"] is True and out["status"] == 503
        assert ("Retry-After", "5.000") in out["headers"], out["headers"]
    finally:
        srv.engine.stop()

    srv2 = LLMServer(lambda: (GPT_PARAMS, GPT_CFG), engine_config=dict(
        num_slots=2, max_seq=48, prefill_chunk=5, page_size=4,
        kv_pages=40, max_queue_len=1))
    try:
        srv2.engine.stop()
        srv2.engine.start = lambda: srv2.engine
        srv2.engine.submit(_prompt(1, 6), max_new_tokens=6)
        out = _call(srv2)
        assert out["__http__"] is True and out["status"] == 503
        assert ("Retry-After", "1.000") in out["headers"], out["headers"]
    finally:
        srv2.engine.stop()


# ---------------------------------------------------------------------------
# Observability


def test_paged_metrics_exported_via_prometheus():
    async def run():
        eng = GenerationEngine(GPT_PARAMS, GPT_CFG, name="pagedprom",
                               speculate_k=3, speculate_ngram=2,
                               **PAGED_KW)
        with eng:
            await eng.generate(_prompt(99, 9), max_new_tokens=6)
            await eng.generate(_prompt(99, 9), max_new_tokens=6)
            st = eng.stats()
        return st

    st = asyncio.run(run())
    assert st.prefix_cache_hits >= 1 and st.prefix_cache_misses >= 1
    assert st.kv_blocks_total == PAGED_KW["kv_pages"]
    # completed requests release their holds; only radix-held prompt
    # pages stay out of the free list
    tree_held = 2 * (9 // PAGED_KW["page_size"])  # two cached prompts..
    assert st.kv_blocks_free >= st.kv_blocks_total - tree_held

    from ray_tpu.util.metrics import prometheus_text, registry_snapshot
    text = prometheus_text(registry_snapshot())
    for needle in ("serve_llm_kv_blocks_total",
                   "serve_llm_kv_blocks_free",
                   "serve_llm_prefix_cache_hits_total",
                   "serve_llm_prefix_cache_misses_total",
                   "serve_llm_spec_accepted_tokens_total"):
        assert needle in text, needle
    assert 'engine="pagedprom"' in text


def test_stats_surface_paging_fields_through_server():
    from ray_tpu.serve.llm.api import LLMServer
    srv = LLMServer(lambda: (GPT_PARAMS, GPT_CFG),
                    engine_config=dict(PAGED_KW))
    try:
        st = srv.stats()
        for key in ("kv_blocks_total", "kv_blocks_free", "page_size",
                    "prefix_cache_hits", "prefix_cache_misses",
                    "spec_accepted_tokens"):
            assert key in st, key
        assert st["page_size"] == PAGED_KW["page_size"]
    finally:
        srv.engine.stop()
