"""Actor tests (reference model: python/ray/tests/test_actor.py,
test_actor_failures.py)."""

import time

import pytest

import ray_tpu


def test_actor_basic(ray_start_regular):
    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def incr(self):
            self.n += 1
            return self.n

    c = Counter.remote()
    assert ray_tpu.get([c.incr.remote() for _ in range(5)],
                       timeout=120) == [1, 2, 3, 4, 5]


def test_actor_init_args(ray_start_regular):
    @ray_tpu.remote
    class Holder:
        def __init__(self, a, b=10):
            self.v = a + b

        def read(self):
            return self.v

    h = Holder.remote(5, b=20)
    assert ray_tpu.get(h.read.remote(), timeout=120) == 25


def test_named_actor(ray_start_regular):
    @ray_tpu.remote
    class Svc:
        def ping(self):
            return "pong"

    Svc.options(name="svc-test").remote()
    h = ray_tpu.get_actor("svc-test")
    assert ray_tpu.get(h.ping.remote(), timeout=120) == "pong"


def test_actor_init_failure_surfaces(ray_start_regular):
    @ray_tpu.remote
    class Bad:
        def __init__(self):
            raise RuntimeError("init boom")

        def f(self):
            return 1

    h = Bad.remote()
    with pytest.raises(Exception):
        ray_tpu.get(h.f.remote(), timeout=120)


def test_kill_actor(ray_start_regular):
    @ray_tpu.remote
    class Victim:
        def ping(self):
            return 1

    v = Victim.remote()
    assert ray_tpu.get(v.ping.remote(), timeout=120) == 1
    ray_tpu.kill(v)
    time.sleep(0.5)
    with pytest.raises(ray_tpu.ActorError):
        ray_tpu.get(v.ping.remote(), timeout=120)


def test_async_actor(ray_start_regular):
    @ray_tpu.remote
    class Async:
        async def work(self, x):
            import asyncio
            await asyncio.sleep(0.01)
            return x + 1

    a = Async.remote()
    assert ray_tpu.get([a.work.remote(i) for i in range(4)],
                       timeout=120) == [1, 2, 3, 4]


def test_mixed_sync_async_actor(ray_start_regular):
    @ray_tpu.remote
    class Mixed:
        def __init__(self):
            self.state = 7

        async def poll(self):
            return "async"

        def read(self):
            return self.state

    m = Mixed.remote()
    assert ray_tpu.get(m.poll.remote(), timeout=120) == "async"
    assert ray_tpu.get(m.read.remote(), timeout=120) == 7


def test_actor_restart(ray_start_regular):
    # max_restarts=2 because retries are AT-LEAST-ONCE: the unacked
    # `die` task is resent IN ORDER to incarnation 2 (reference:
    # direct_actor_task_submitter resends the unacked window), so the
    # poison pill legitimately kills it too; its retry budget (1) is
    # then spent and incarnation 3 serves the pings.
    @ray_tpu.remote(max_restarts=2, max_task_retries=1)
    class Fragile:
        def __init__(self):
            self.n = 0

        def pid(self):
            import os
            return os.getpid()

        def die(self):
            import os
            os._exit(1)

        def ping(self):
            self.n += 1
            return self.n

    f = Fragile.remote()
    pid1 = ray_tpu.get(f.pid.remote(), timeout=120)
    f.die.remote()
    time.sleep(1.0)
    # After the restarts, state is fresh and the pid differs.
    n = ray_tpu.get(f.ping.remote(), timeout=300)
    assert n == 1
    pid2 = ray_tpu.get(f.pid.remote(), timeout=120)
    assert pid2 != pid1
