"""Ops plane: log streaming, metrics, state API, timeline, job submission
(reference test style: python/ray/tests/test_state_api.py,
test_metrics_agent.py, dashboard/modules/job/tests)."""

import sys
import time

import pytest

import ray_tpu
from ray_tpu.experimental import state as state_api
from ray_tpu.util.metrics import Counter, Gauge, Histogram, prometheus_text


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_worker_logs_stream_to_driver(ray_init, capfd):
    @ray_tpu.remote
    def shout():
        print("HELLO_FROM_WORKER_TASK")
        sys.stdout.flush()
        return 1

    assert ray_tpu.get(shout.remote(), timeout=60) == 1
    deadline = time.time() + 20
    while time.time() < deadline:
        err = capfd.readouterr().err
        if "HELLO_FROM_WORKER_TASK" in err:
            break
        time.sleep(0.3)
    else:
        raise AssertionError("worker stdout never reached the driver")


def test_state_api_lists_cluster_entities(ray_init):
    @ray_tpu.remote
    class Sleeper:
        def ping(self):
            return "pong"

    a = Sleeper.options(name="state-test-actor").remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == "pong"

    nodes = state_api.list_nodes()
    assert len(nodes) == 1 and nodes[0]["state"] == "ALIVE"
    actors = state_api.list_actors()
    assert any(x["name"] == "state-test-actor" and x["state"] == "ALIVE"
               for x in actors)
    # A big object shows up in list_objects.
    import numpy as np
    ref = ray_tpu.put(np.zeros((600, 600)))
    objs = state_api.list_objects()
    assert any(o["size"] > 1_000_000 for o in objs)
    summary = state_api.summarize_objects()
    assert summary["total_bytes"] > 1_000_000


def test_metrics_and_prometheus_text(ray_init):
    c = Counter("test_requests_total", "requests", tag_keys=("route",))
    c.inc(1, tags={"route": "/a"})
    c.inc(2, tags={"route": "/a"})
    g = Gauge("test_queue_depth")
    g.set(7)
    h = Histogram("test_latency_s", boundaries=[0.1, 1.0])
    h.observe(0.05)
    h.observe(0.5)
    h.observe(5.0)
    from ray_tpu.util.metrics import registry_snapshot
    text = prometheus_text(registry_snapshot())
    assert 'test_requests_total{route="/a"} 3.0' in text
    assert "test_queue_depth 7.0" in text
    assert 'test_latency_s_bucket{le="+Inf"} 3' in text
    assert "test_latency_s_count 3" in text


@pytest.mark.slow
def test_timeline_records_task_events(ray_init):
    @ray_tpu.remote
    def traced():
        time.sleep(0.01)
        return 1

    ray_tpu.get([traced.remote() for _ in range(3)], timeout=60)
    deadline = time.time() + 15
    events = []
    while time.time() < deadline:
        events = ray_tpu.timeline()
        if any(e["name"] == "traced" for e in events):
            break
        time.sleep(0.5)
    assert any(e["name"] == "traced" and e["ph"] == "X" and e["dur"] > 0
               for e in events)


@pytest.mark.slow
def test_job_submission_end_to_end(ray_init):
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient

    client = JobSubmissionClient()
    sid = client.submit_job(
        entrypoint="python -c \"print('job says hi'); import sys; "
                   "sys.exit(0)\"")
    status = client.wait_until_finished(sid, timeout=120)
    assert status == JobStatus.SUCCEEDED
    assert "job says hi" in client.get_job_logs(sid)
    assert any(j["submission_id"] == sid for j in client.list_jobs())

    sid2 = client.submit_job(entrypoint="python -c 'import sys; "
                                        "sys.exit(3)'")
    assert client.wait_until_finished(sid2, timeout=120) == JobStatus.FAILED


def test_dashboard_head_serves_state_and_metrics(ray_init):
    import requests

    from ray_tpu.dashboard import start_dashboard

    @ray_tpu.remote
    class Pinger:
        def ping(self):
            return 1

    a = Pinger.options(name="dash-actor").remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == 1

    addr = start_dashboard()
    base = f"http://{addr['host']}:{addr['port']}"
    nodes = requests.get(f"{base}/api/nodes", timeout=30).json()
    assert nodes and nodes[0]["state"] == "ALIVE"
    actors = requests.get(f"{base}/api/actors", timeout=30).json()
    assert any(x["name"] == "dash-actor" for x in actors)
    # Metric round trip: the driver's registry pushes telemetry every
    # ~2s; poll until the scrape sees it.
    Counter("dash_test_counter").inc(5)
    deadline = time.time() + 20
    text = ""
    while time.time() < deadline:
        text = requests.get(f"{base}/metrics", timeout=30).text
        if "dash_test_counter" in text:
            break
        time.sleep(0.5)
    assert "dash_test_counter" in text


def test_cluster_events_recorded(ray_init):
    from ray_tpu.experimental import state

    @ray_tpu.remote(max_restarts=0)
    class Dier:
        def die(self):
            import os
            os._exit(1)

    a = Dier.remote()
    try:
        ray_tpu.get(a.die.remote(), timeout=60)
    except Exception:
        pass
    deadline = time.time() + 30
    events = []
    while time.time() < deadline:
        events = state.list_cluster_events()
        if any(e["label"] == "ACTOR_DEAD" for e in events):
            break
        time.sleep(0.5)
    assert any(e["label"] == "ACTOR_DEAD" for e in events)


def test_cli_surface(ray_init, capsys):
    from ray_tpu.scripts import cli

    @ray_tpu.remote
    class CliActor:
        def ping(self):
            return 1

    a = CliActor.options(name="cli-actor").remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == 1

    cli.main(["status"])
    out = capsys.readouterr().out
    assert "cluster:" in out and "ALIVE" in out

    cli.main(["list", "actors", "--format", "json"])
    out = capsys.readouterr().out
    assert "cli-actor" in out

    cli.main(["summary", "objects"])
    out = capsys.readouterr().out
    assert "total_objects" in out


def test_trace_context_links_nested_tasks(ray_init):
    @ray_tpu.remote
    def child():
        return 1

    @ray_tpu.remote
    def parent():
        return ray_tpu.get(child.remote(), timeout=60)

    assert ray_tpu.get(parent.remote(), timeout=120) == 1
    deadline = time.time() + 20
    while time.time() < deadline:
        events = ray_tpu.timeline()
        by_name = {}
        for e in events:
            if e.get("args", {}).get("trace_id"):
                by_name.setdefault(e["name"], []).append(e["args"])
        if "parent" in by_name and "child" in by_name:
            break
        time.sleep(0.5)
    p = by_name["parent"][0]
    c = by_name["child"][0]
    # Same trace; the child's parent span is the parent task's span.
    assert c["trace_id"] == p["trace_id"]
    assert c["parent_id"] == p["span_id"]


def test_node_hardware_reporter(ray_start_regular):
    """Per-node hardware utilization flows raylet -> GCS -> state API
    (reference: dashboard reporter agent relaying psutil stats)."""
    import time
    from ray_tpu.experimental import state
    from ray_tpu._private.reporter import format_utilization

    stats = {}
    deadline = time.monotonic() + 30
    while time.monotonic() < deadline:
        nodes = state.list_nodes()
        stats = nodes[0].get("node_stats", {})
        if stats.get("mem_total"):
            break
        time.sleep(1)
    assert stats.get("mem_total", 0) > 0
    assert stats.get("disk_total", 0) > 0
    assert stats.get("object_store_capacity", 0) > 0
    assert "cpu_percent" in stats
    line = format_utilization(stats)
    assert "mem" in line and "store" in line
