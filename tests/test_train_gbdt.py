"""GBDTTrainer: distributed histogram boosting on the WorkerGroup
substrate (reference: train/gbdt_trainer.py:70 + xgboost_trainer.py —
data-parallel shards, allreduced split statistics, checkpointed
booster)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.air.config import ScalingConfig


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def _make_dataset(n=600, seed=0):
    rng = np.random.RandomState(seed)
    x = rng.randn(n, 3)
    y = x[:, 0] * 2 + np.sin(x[:, 1]) + 0.1 * rng.randn(n)
    import ray_tpu.data as rd
    rows = [{"f0": float(a), "f1": float(b), "f2": float(c),
             "y": float(t)} for (a, b, c), t in zip(x, y)]
    return rd.from_items(rows, parallelism=4), x, y


@pytest.mark.slow
def test_gbdt_distributed_two_workers_matches_task(ray_init):
    """A 2-worker gang trains on sharded data; the allreduced
    histograms make the model fit the FULL dataset (each shard alone
    cannot), and the checkpoint round-trips into a working booster."""
    from ray_tpu.train import GBDTBoosterModel, GBDTTrainer

    ds, x, y = _make_dataset()
    trainer = GBDTTrainer(
        label_column="y",
        params={"num_boost_round": 25, "max_depth": 4, "eta": 0.3},
        datasets={"train": ds},
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}))
    result = trainer.fit()
    assert result.metrics["round"] == 24
    base = float(np.sqrt(np.mean((y - y.mean()) ** 2)))
    assert result.metrics["train-rmse"] < 0.3 * base

    model = GBDTBoosterModel.from_checkpoint(result.checkpoint)
    pred = model.predict(x)
    rmse = float(np.sqrt(np.mean((pred - y) ** 2)))
    assert rmse < 0.35 * base

    # Resume: a second fit from the checkpoint continues boosting
    # rather than restarting (round advances past the first run).
    trainer2 = GBDTTrainer(
        label_column="y",
        params={"num_boost_round": 30, "max_depth": 4, "eta": 0.3},
        datasets={"train": ds},
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}),
        resume_from_checkpoint=result.checkpoint)
    result2 = trainer2.fit()
    assert result2.metrics["round"] == 29
    assert result2.metrics["train-rmse"] <= result.metrics["train-rmse"]


@pytest.mark.slow
def test_gbdt_binary_logistic_single_worker(ray_init):
    from ray_tpu.train import GBDTBoosterModel, GBDTTrainer
    import ray_tpu.data as rd

    rng = np.random.RandomState(1)
    x = rng.randn(400, 2)
    y = (x[:, 0] + x[:, 1] > 0).astype(np.float64)
    rows = [{"f0": float(a), "f1": float(b), "y": float(t)}
            for (a, b), t in zip(x, y)]
    trainer = GBDTTrainer(
        label_column="y",
        params={"objective": "binary:logistic",
                "num_boost_round": 20, "max_depth": 3},
        datasets={"train": rd.from_items(rows, parallelism=2)},
        scaling_config=ScalingConfig(num_workers=1,
                                     resources_per_worker={"CPU": 1}))
    result = trainer.fit()
    assert result.metrics["train-logloss"] < 0.25
    model = GBDTBoosterModel.from_checkpoint(result.checkpoint)
    acc = float(np.mean((model.predict(x) > 0.5) == (y > 0.5)))
    assert acc > 0.93


@pytest.mark.slow
def test_gbdt_fast_plane_matches_coordinator_path(ray_init):
    """Histogram sync on the peer-to-peer collective fast plane grows
    EXACTLY the same trees as the coordinator path (the bit-parity
    contract of the rank-order fold)."""
    from ray_tpu.train import GBDTTrainer
    from ray_tpu.train.gbdt import _gbdt_train_loop

    ds, _x, _y = _make_dataset(n=500, seed=3)
    params = {"num_boost_round": 8, "max_depth": 3, "eta": 0.3}

    def _loop_with_plane(plane):
        def _loop(config):
            from ray_tpu._private.config import GLOBAL_CONFIG as cfg
            cfg.collective_data_plane = plane
            # Histograms are ~100KiB here; drop the threshold so the
            # fast plane actually engages at this toy size.
            cfg.collective_fastpath_min_bytes = 1024
            _gbdt_train_loop(config)
        return _loop

    models = {}
    for plane in ("coord", "auto"):
        trainer = GBDTTrainer(
            label_column="y", params=params,
            train_loop_per_worker=_loop_with_plane(plane),
            datasets={"train": ds},
            scaling_config=ScalingConfig(num_workers=2,
                                         resources_per_worker={"CPU": 1}))
        result = trainer.fit()
        state = result.checkpoint.to_dict()
        models[plane] = (state["trees"], np.asarray(state["edges"]))

    trees_c, edges_c = models["coord"]
    trees_f, edges_f = models["auto"]
    np.testing.assert_array_equal(edges_c, edges_f)
    assert trees_c == trees_f, \
        "fast-plane GBDT grew different trees than the coordinator path"


def test_xgboost_trainer_gated():
    try:
        import xgboost  # noqa: F401
        pytest.skip("xgboost installed; gate test n/a")
    except ImportError:
        pass
    from ray_tpu.train import XGBoostTrainer
    with pytest.raises(ImportError, match="GBDTTrainer"):
        XGBoostTrainer(label_column="y")
