"""train.torch loop utils (reference: train/torch/train_loop_utils.py
prepare_model :49, prepare_data_loader :262)."""

import torch
from torch.utils.data import DataLoader, TensorDataset

from ray_tpu.train.torch import prepare_data_loader, prepare_model


def test_prepare_model_no_group_is_identity():
    m = torch.nn.Linear(4, 2)
    assert prepare_model(m) is m


def test_prepare_data_loader_no_group_is_identity():
    ds = TensorDataset(torch.arange(8.0).reshape(8, 1))
    dl = DataLoader(ds, batch_size=2)
    assert prepare_data_loader(dl) is dl


def test_prepare_data_loader_with_group(monkeypatch):
    """Fake a 2-rank group: the loader gets a DistributedSampler that
    yields this rank's half of the dataset."""
    import pytest
    import torch.distributed as dist

    monkeypatch.setattr(dist, "is_initialized", lambda: True)
    monkeypatch.setattr(dist, "get_world_size", lambda: 2)
    monkeypatch.setattr(dist, "get_rank", lambda: 1)
    ds = TensorDataset(torch.arange(8.0).reshape(8, 1))
    dl = DataLoader(ds, batch_size=2, shuffle=True)
    out = prepare_data_loader(dl)
    from torch.utils.data.distributed import DistributedSampler
    assert isinstance(out.sampler, DistributedSampler)
    rows = sum(b[0].shape[0] for b in out)
    assert rows == 4  # half of 8
    # epoch advances per pass: shuffled order differs between epochs
    # (the per-rank SUBSET also changes: the sampler shuffles globally
    # then strides, so only count and inequality are stable)
    e1 = torch.cat([b[0] for b in out]).flatten().tolist()
    e2 = torch.cat([b[0] for b in out]).flatten().tolist()
    assert len(e1) == len(e2) == 4
    assert e1 != e2
    # already-prepared loaders pass through
    assert prepare_data_loader(out) is out
    # batch_sampler loaders are rejected loudly, not silently unbatched
    from torch.utils.data import BatchSampler, SequentialSampler
    bs_loader = DataLoader(ds, batch_sampler=BatchSampler(
        SequentialSampler(ds), batch_size=2, drop_last=False))
    with pytest.raises(ValueError, match="batch_sampler"):
        prepare_data_loader(bs_loader)
    # loader extras survive the rebuild
    def winit(_):
        pass
    dl2 = DataLoader(ds, batch_size=2, worker_init_fn=winit)
    out2 = prepare_data_loader(dl2)
    assert out2.worker_init_fn is winit
