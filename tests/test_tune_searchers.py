"""Searcher plugin seam, wrapper searchers, and the BOHB pair
(reference: tune/search/searcher.py, search/concurrency_limiter.py,
search/repeater.py, schedulers/hb_bohb.py + search/bohb/bohb_search.py).
"""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air.config import RunConfig
from ray_tpu.tune import Tuner, TuneConfig
from ray_tpu.tune.search import (BOHBSearcher, ConcurrencyLimiter,
                                 ExternalSearcher, HyperBandForBOHB,
                                 Repeater, SkoptLikeGP, Searcher,
                                 TPESearcher, uniform)


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


class _CountingOpt:
    """Minimal ask/tell optimizer: proposes a fixed sweep, records every
    observation — enough to verify the adapter's contract."""

    def __init__(self, values):
        self.values = list(values)
        self.i = 0
        self.told = []

    def ask(self):
        cfg = {"x": self.values[self.i % len(self.values)]}
        self.i += 1
        return cfg

    def tell(self, config, value):
        self.told.append((config["x"], value))


def test_external_searcher_protocol_unit():
    """ask() drives suggestions; tell() hears MINIMIZED objectives
    (mode=max negates); errors release the slot without a tell."""
    opt = _CountingOpt([0.1, 0.2, 0.3])
    s = ExternalSearcher(opt, metric="score", mode="max", num_samples=3)
    c1 = s.suggest("t1")
    c2 = s.suggest("t2")
    c3 = s.suggest("t3")
    assert [c["x"] for c in (c1, c2, c3)] == [0.1, 0.2, 0.3]
    assert s.suggest("t4") is None  # budget exhausted
    s.on_trial_complete("t1", {"score": 5.0})
    s.on_trial_complete("t2", error=True)
    s.on_trial_complete("t3", {"score": 7.0})
    assert opt.told == [(0.1, -5.0), (0.3, -7.0)]


def test_external_searcher_rejects_non_ask_tell():
    with pytest.raises(TypeError):
        ExternalSearcher(object(), metric="score")


def test_concurrency_limiter_defers_unit():
    opt = _CountingOpt([0.5])
    s = ConcurrencyLimiter(
        ExternalSearcher(opt, metric="score", num_samples=10),
        max_concurrent=2)
    assert s.suggest("a") is not None
    assert s.suggest("b") is not None
    # At the cap: DEFER (retry later), NOT None (exhausted).
    assert s.suggest("c") == Searcher.DEFER
    s.on_trial_complete("a", {"score": 1.0})
    assert s.suggest("d") is not None


def test_repeater_averages_unit():
    opt = _CountingOpt([0.1, 0.9])
    inner = ExternalSearcher(opt, metric="score", num_samples=4)
    s = Repeater(inner, repeat=3)
    cfgs = [s.suggest(f"t{i}") for i in range(3)]
    # One underlying suggestion evaluated three times.
    assert [c["x"] for c in cfgs] == [0.1, 0.1, 0.1]
    for i, v in enumerate((1.0, 2.0, 6.0)):
        s.on_trial_complete(f"t{i}", {"score": v})
    assert opt.told == [(0.1, 3.0)]  # the MEAN, told once
    # Next group gets the optimizer's next proposal.
    assert s.suggest("t3")["x"] == 0.9


@pytest.mark.slow
def test_sklearn_gp_through_seam(ray_init):
    """A real external library (scikit-learn) integrated purely through
    the ask/tell seam + ConcurrencyLimiter finds a 1-D optimum."""
    def objective(config):
        tune.report({"loss": (config["x"] - 0.62) ** 2, "done": True})

    opt = SkoptLikeGP({"x": (0.0, 1.0)}, n_startup=5, seed=3)
    search = ConcurrencyLimiter(
        ExternalSearcher(opt, metric="loss", mode="min", num_samples=16),
        max_concurrent=2)
    results = Tuner(
        objective,
        param_space={"x": uniform(0.0, 1.0)},
        tune_config=TuneConfig(metric="loss", mode="min",
                               search_alg=search),
    ).fit()
    assert len(results) == 16
    assert results.get_best_result().metrics["loss"] < 0.02
    # Every completed trial was told back to the external optimizer.
    assert len(opt._y) == 16


@pytest.mark.slow
def test_bohb_pair_budget_allocation(ray_init):
    """The scheduler/searcher PAIR: HyperBandForBOHB allocates budget by
    successive halving while feeding rung records to BOHBSearcher,
    whose model then concentrates proposals near the good region."""
    def objective(config):
        # Quality depends on x; separable from budget so rung scores
        # rank configs consistently at every budget.
        for i in range(9):
            tune.report(
                {"score": (1.0 - abs(config["x"] - 0.7)) * (i + 1)})

    space = {"x": uniform(0.0, 1.0)}
    searcher = BOHBSearcher(space, metric="score", mode="max",
                            num_samples=18, n_min=4, random_fraction=0.1,
                            seed=7)
    sched = HyperBandForBOHB(searcher=searcher, metric="score",
                             mode="max", max_t=9, grace_period=1,
                             reduction_factor=3)
    results = Tuner(
        objective,
        param_space=space,
        tune_config=TuneConfig(metric="score", mode="max",
                               search_alg=searcher, scheduler=sched),
        run_config=RunConfig(stop={"training_iteration": 9}),
    ).fit()
    assert len(results) == 18
    # Budget allocation engaged: someone was halted early, a winner ran
    # to max_t.
    iters = [r.metrics.get("training_iteration", 0) for r in results]
    assert max(iters) == 9
    assert min(iters) < 9
    # The model fired (observations crossed n_min) and steered: the
    # best found x is close to the optimum.
    assert searcher.model_suggestions > 0
    best = results.get_best_result()
    assert abs(best.config["x"] - 0.7) < 0.15
    # Rung-budget observations arrived via the scheduler coupling (not
    # just end-of-trial): multiple distinct budgets recorded.
    assert len(searcher._obs) >= 2


def test_optuna_search_gated():
    """Without optuna installed the wrapper raises a clear ImportError
    pointing at the native equivalents."""
    try:
        import optuna  # noqa: F401
        pytest.skip("optuna installed; gate test n/a")
    except ImportError:
        pass
    from ray_tpu.tune.search import OptunaSearch
    with pytest.raises(ImportError, match="TPESearcher"):
        OptunaSearch({"x": uniform(0, 1)}, metric="score")


def test_tpe_unaffected_by_seam(ray_init):
    """Native searchers still drive the runner after the DEFER-sentinel
    addition (regression guard for the runner change)."""
    def objective(config):
        tune.report({"loss": (config["x"] - 0.5) ** 2, "done": True})

    space = {"x": uniform(0.0, 1.0)}
    results = Tuner(
        objective,
        param_space=space,
        tune_config=TuneConfig(
            metric="loss", mode="min",
            search_alg=TPESearcher(space, metric="loss", mode="min",
                                   num_samples=6, n_startup=3, seed=1)),
    ).fit()
    assert len(results) == 6


@pytest.mark.slow
def test_limiter_with_hyperband_no_deadlock(ray_init):
    """Regression: ConcurrencyLimiter's DEFER + synchronous HyperBand.
    The bracket wants more members than the limiter admits; paused
    trials never complete, so the limiter defers forever — the runner
    must treat that like exhaustion and force-advance the under-full
    bracket instead of hanging."""
    from ray_tpu.tune.schedulers import HyperBandScheduler

    def objective(config):
        for i in range(9):
            tune.report({"score": config["x"] * (i + 1)})

    space = {"x": uniform(0.0, 1.0)}
    search = ConcurrencyLimiter(
        TPESearcher(space, metric="score", mode="max", num_samples=6,
                    n_startup=3, seed=4),
        max_concurrent=2)
    sched = HyperBandScheduler(metric="score", mode="max", max_t=9,
                               grace_period=3, reduction_factor=3)
    results = Tuner(
        objective,
        param_space=space,
        tune_config=TuneConfig(metric="score", mode="max",
                               search_alg=search, scheduler=sched),
        run_config=RunConfig(stop={"training_iteration": 9}),
    ).fit()
    assert len(results) == 6
