"""DatasetConfig ingest roles + trainer preprocessor fitting
(reference: air/config.py DatasetConfig fill_defaults — "train" splits
and fits the preprocessor, aux datasets ship whole; BaseTrainer
preprocess_datasets)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data
from ray_tpu.air import DatasetConfig, ScalingConfig, session
from ray_tpu.data.preprocessors import StandardScaler
from ray_tpu.train.jax import JaxConfig, JaxTrainer


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def _ingest_loop(config):
    train_n = session.get_dataset_shard("train").count()
    valid_n = session.get_dataset_shard("valid").count()
    session.report({"train_rows": train_n, "valid_rows": valid_n,
                    "rank": session.get_world_rank()})


def test_train_splits_valid_ships_whole(ray_init):
    train = data.from_items([{"x": float(i)} for i in range(40)],
                            parallelism=4)
    valid = data.from_items([{"x": float(i)} for i in range(10)],
                            parallelism=2)
    trainer = JaxTrainer(
        _ingest_loop,
        datasets={"train": train, "valid": valid},
        jax_config=JaxConfig(use_distributed=False),
        scaling_config=ScalingConfig(num_workers=2),
    )
    result = trainer.fit()
    assert result.error is None
    # rank 0's view: train was split in half, valid arrived whole
    assert result.metrics["train_rows"] == 20
    assert result.metrics["valid_rows"] == 10


def _scaled_loop(config):
    shard = session.get_dataset_shard("train")
    col = np.concatenate(
        [np.asarray(b["x"]) for b in shard.iter_batches(
            batch_size=64, batch_format="numpy")])
    session.report({"mean": float(col.mean()), "std": float(col.std())})


def test_preprocessor_fit_and_transform(ray_init):
    rows = [{"x": float(i)} for i in range(100)]
    train = data.from_items(rows, parallelism=4)
    trainer = JaxTrainer(
        _scaled_loop,
        datasets={"train": train},
        preprocessor=StandardScaler(columns=["x"]),
        jax_config=JaxConfig(use_distributed=False),
        scaling_config=ScalingConfig(num_workers=1),
    )
    result = trainer.fit()
    assert result.error is None
    assert abs(result.metrics["mean"]) < 1e-6
    assert abs(result.metrics["std"] - 1.0) < 0.05


def test_dataset_config_overrides_and_required(ray_init):
    ds = data.range(16, parallelism=2)
    merged = DatasetConfig.validated(
        {"train": DatasetConfig(split=False)}, {"train": ds})
    assert merged["train"].split is False
    assert merged["train"].fit is True  # role default survives override
    with pytest.raises(ValueError):
        DatasetConfig.validated(
            {"extra": DatasetConfig(required=True)}, {"train": ds})
