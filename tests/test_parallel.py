"""parallel/ tests on a virtual 8-device CPU mesh: ring attention, SPMD
pipeline, expert-parallel MoE — each against a dense single-device oracle."""

import numpy as np
import pytest

jax = pytest.importorskip("jax")
import jax.numpy as jnp  # noqa: E402

from ray_tpu.parallel import (MeshSpec, expert_parallel_moe, make_mesh,
                              pipeline_spmd, ring_attention)
from ray_tpu.parallel.moe import reference_moe
from ray_tpu.parallel.ring_attention import reference_attention


def _mesh(**axes):
    return make_mesh(MeshSpec(**axes))


@pytest.mark.parametrize("causal", [False, True])
def test_ring_attention_matches_dense(causal):
    mesh = _mesh(sp=4)
    rng = np.random.RandomState(0)
    b, t, h, d = 2, 32, 4, 8
    q = jnp.asarray(rng.randn(b, t, h, d), dtype=jnp.float32)
    k = jnp.asarray(rng.randn(b, t, h, d), dtype=jnp.float32)
    v = jnp.asarray(rng.randn(b, t, h, d), dtype=jnp.float32)
    out = ring_attention(q, k, v, mesh=mesh, causal=causal)
    ref = reference_attention(q, k, v, causal=causal)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-4, atol=2e-4)


@pytest.mark.slow
def test_ring_attention_grad_matches_dense():
    mesh = _mesh(sp=4)
    rng = np.random.RandomState(1)
    b, t, h, d = 1, 16, 2, 4
    q = jnp.asarray(rng.randn(b, t, h, d), dtype=jnp.float32)
    k = jnp.asarray(rng.randn(b, t, h, d), dtype=jnp.float32)
    v = jnp.asarray(rng.randn(b, t, h, d), dtype=jnp.float32)

    g1 = jax.grad(lambda q: ring_attention(
        q, k, v, mesh=mesh, causal=True).sum())(q)
    g2 = jax.grad(lambda q: reference_attention(
        q, k, v, causal=True).sum())(q)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=3e-4, atol=3e-4)


def test_pipeline_matches_sequential():
    mesh = _mesh(pp=4)
    rng = np.random.RandomState(2)
    stages, d = 4, 8

    w = jnp.asarray(rng.randn(stages, d, d) * 0.3, dtype=jnp.float32)

    def stage_fn(params, x):
        return jnp.tanh(x @ params)

    x = jnp.asarray(rng.randn(16, d), dtype=jnp.float32)
    out = pipeline_spmd(stage_fn, w, x, num_microbatches=4, mesh=mesh)

    ref = x
    for s in range(stages):
        ref = stage_fn(w[s], ref)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-5, atol=1e-5)


def test_moe_matches_single_device():
    mesh = _mesh(ep=4)
    rng = np.random.RandomState(3)
    b, t, d, f, e = 2, 8, 8, 16, 8
    x = jnp.asarray(rng.randn(b, t, d), dtype=jnp.float32)
    gate_w = jnp.asarray(rng.randn(d, e) * 0.5, dtype=jnp.float32)
    w_in = jnp.asarray(rng.randn(e, d, f) * 0.2, dtype=jnp.float32)
    w_out = jnp.asarray(rng.randn(e, f, d) * 0.2, dtype=jnp.float32)

    out = expert_parallel_moe(x, gate_w, w_in, w_out, mesh=mesh)
    ref = reference_moe(x, gate_w, w_in, w_out)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)


def test_mesh_spec_inference():
    spec = MeshSpec.infer(8, tp=2, sp=2)
    assert spec.dp == 2 and spec.world_size == 8
    mesh = make_mesh(spec)
    assert dict(zip(mesh.axis_names, mesh.devices.shape))["tp"] == 2
