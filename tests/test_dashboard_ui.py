"""Dashboard operator UI: every state-API entity has a view backed by
a live endpoint (VERDICT r4 missing #3 — multi-view client over the
head's REST; reference: dashboard/client/src/App.tsx routes)."""

import time

import pytest

import ray_tpu


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


@pytest.mark.slow
def test_dashboard_views_end_to_end(ray_init, tmp_path):
    import requests

    from ray_tpu.dashboard import start_dashboard
    from ray_tpu.util.placement_group import placement_group

    # --- create one of each entity ---------------------------------
    @ray_tpu.remote
    class ViewActor:
        def ping(self):
            return 1

    a = ViewActor.options(name="ui-actor").remote()
    assert ray_tpu.get(a.ping.remote(), timeout=60) == 1
    # Above max_direct_call_object_size (100KiB) so the put lands in
    # the shm store — the objects view lists store-resident primaries,
    # not owner-inline blobs.
    obj_ref = ray_tpu.put(b"x" * (1 << 20))
    pg = placement_group([{"CPU": 0.1}], name="ui-pg")
    ray_tpu.wait_placement_group_ready(pg, timeout=60)

    # A tiny tune experiment publishes to the dashboard's KV feed.
    from ray_tpu import tune
    from ray_tpu.tune import Tuner, TuneConfig

    def objective(config):
        tune.report({"score": config["x"], "done": True})

    Tuner(objective,
          param_space={"x": tune.grid_search([1.0, 2.0])},
          tune_config=TuneConfig(metric="score", mode="max"),
          ).fit()

    addr = start_dashboard()
    base = f"http://{addr['host']}:{addr['port']}"

    # --- the app shell serves every view's route -------------------
    html = requests.get(f"{base}/ui", timeout=30).text
    for view in ("overview", "nodes", "actors", "tasks", "objects",
                 "pgs", "jobs", "serve", "tune", "events"):
        assert f"'{view}'" in html, f"view {view} missing from shell"
    assert "vJobDetail" in html  # job drill-down + log tail view

    # --- each entity endpoint feeds its view -----------------------
    nodes = requests.get(f"{base}/api/nodes", timeout=30).json()
    assert nodes and nodes[0]["state"] == "ALIVE"
    actors = requests.get(f"{base}/api/actors", timeout=30).json()
    assert any(x.get("name") == "ui-actor" for x in actors)
    objs = requests.get(f"{base}/api/objects", timeout=30).json()
    assert any(o.get("size", 0) >= (1 << 20) for o in objs)
    pgs = requests.get(f"{base}/api/placement_groups",
                       timeout=30).json()
    assert any(p.get("name") == "ui-pg" for p in pgs)
    tasks = requests.get(f"{base}/api/tasks", timeout=30).json()
    assert isinstance(tasks, list)  # actor lease shows while alive

    exps = requests.get(f"{base}/api/tune", timeout=30).json()
    assert exps, "tune experiment not published to the dashboard"
    assert len(exps[0]["trials"]) == 2
    assert {t["status"] for t in exps[0]["trials"]} == {"TERMINATED"}

    # Jobs view + log tail drill-down.
    from ray_tpu.job_submission import JobStatus, JobSubmissionClient
    client = JobSubmissionClient()
    sid = client.submit_job(
        entrypoint="python -c 'print(\"ui log line\")'")
    deadline = time.time() + 120
    while time.time() < deadline:
        if requests.get(f"{base}/api/jobs/{sid}",
                        timeout=30).json()["status"] \
                == JobStatus.SUCCEEDED:
            break
        time.sleep(0.5)
    logs = requests.get(f"{base}/api/jobs/{sid}/logs", timeout=30).text
    assert "ui log line" in logs
    jobs = requests.get(f"{base}/api/jobs", timeout=30).json()
    assert any(x.get("submission_id") == sid for x in jobs)

    events = requests.get(f"{base}/api/events", timeout=30).json()
    assert isinstance(events, list)
    serve_st = requests.get(f"{base}/api/serve", timeout=30).json()
    assert isinstance(serve_st, (list, dict))

    ref_keep = obj_ref  # keep the put alive through the assertions
    del ref_keep
