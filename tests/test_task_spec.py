"""Typed task specs + RPC handler instrumentation (reference:
src/ray/common/task/task_spec.h; event_stats.h handler stats)."""

import pytest

import ray_tpu
from ray_tpu._private import protocol
from ray_tpu._private.ids import ObjectID, TaskID
from ray_tpu._private.task_spec import TaskSpec


def test_task_spec_typed_accessors_and_validate():
    tid = TaskID.from_random()
    rids = [ObjectID.for_task_return(tid, 0)]
    spec = TaskSpec.new(
        task_id=tid, fn_id=b"f" * 8, args_blob=b"", num_returns=1,
        owner_addr=("127.0.0.1", 1), return_ids=rids,
        resources={"CPU": 1.0}, strategy=None, max_retries=3,
        retry_exceptions=False, name="t", trace=None).validate()
    assert spec.task_id is tid
    assert spec.return_ids == rids
    assert spec.resources == {"CPU": 1.0}
    assert spec.max_retries == 3
    assert spec.pg_id is None and spec.bundle_index == -1
    # Wire compatibility: it IS the dict that rides the RPC plane.
    assert isinstance(spec, dict) and spec["fn_id"] == b"f" * 8

    bad = TaskSpec(spec)
    bad["return_ids"] = []
    with pytest.raises(ValueError):
        bad.validate()


def test_rpc_handler_stats_accumulate():
    ray_tpu.init(num_cpus=2, ignore_reinit_error=True)
    try:
        @ray_tpu.remote
        def one():
            return 1

        assert ray_tpu.get(one.remote(), timeout=60) == 1
        snap = protocol.handler_stats_snapshot()
        # The driver served at least one RPC (e.g. object pushes/locates);
        # every entry carries count/total/max/mean.
        assert snap, "no handler stats recorded"
        for stats in snap.values():
            assert stats["count"] >= 1
            assert stats["total_s"] >= 0
            assert stats["max_s"] >= 0
    finally:
        ray_tpu.shutdown()
