"""W&B / MLflow logger callbacks over injectable tracker clients
(reference: python/ray/air/tests/test_integration_wandb.py,
test_integration_mlflow.py — both also test against mocks)."""

import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air.config import RunConfig
from ray_tpu.air.integrations import (
    MLflowLoggerCallback,
    WandbLoggerCallback,
)


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


class _FakeWandbRun:
    def __init__(self, **kw):
        self.kw = kw
        self.logged = []
        self.finished = None

    def log(self, metrics, step=None):
        self.logged.append((step, metrics))

    def finish(self, exit_code=0):
        self.finished = exit_code


class _FakeWandb:
    def __init__(self):
        self.runs = []

    def init(self, **kw):
        run = _FakeWandbRun(**kw)
        self.runs.append(run)
        return run


class _FakeMlflowRunInfo:
    def __init__(self, run_id):
        self.run_id = run_id


class _FakeMlflowRun:
    def __init__(self, run_id):
        self.info = _FakeMlflowRunInfo(run_id)


class _FakeMlflowClient:
    def __init__(self):
        self.params, self.metrics, self.status = {}, {}, {}
        self._n = 0
        self.experiments = {}

    def get_experiment_by_name(self, name):
        return self.experiments.get(name)

    def create_experiment(self, name):
        self.experiments[name] = type(
            "E", (), {"experiment_id": f"exp-{name}"})()
        return f"exp-{name}"

    def create_run(self, experiment_id, tags=None):
        self._n += 1
        rid = f"run-{self._n}"
        self.params[rid], self.metrics[rid] = {}, []
        return _FakeMlflowRun(rid)

    def log_param(self, run_id, k, v):
        self.params[run_id][k] = v

    def log_metric(self, run_id, k, v, step=None):
        self.metrics[run_id].append((step, k, v))

    def set_terminated(self, run_id, status):
        self.status[run_id] = status


def _trainable(config):
    from ray_tpu.air import session
    for i in range(2):
        session.report({"score": config["x"] + i,
                        "training_iteration": i + 1})


def test_wandb_and_mlflow_callbacks(ray_init, tmp_path):
    wb = _FakeWandb()
    ml = _FakeMlflowClient()
    tuner = tune.Tuner(
        _trainable,
        param_space={"x": tune.grid_search([1.0, 5.0])},
        run_config=RunConfig(
            storage_path=str(tmp_path), name="exp",
            callbacks=[
                WandbLoggerCallback(project="p", group="g", module=wb),
                MLflowLoggerCallback(experiment_name="e", client=ml),
            ]))
    results = tuner.fit()
    assert len(results) == 2 and not results.errors

    # W&B: one run per trial, config captured, metrics at steps, closed.
    assert len(wb.runs) == 2
    xs = sorted(r.kw["config"]["x"] for r in wb.runs)
    assert xs == [1.0, 5.0]
    for r in wb.runs:
        steps = [s for s, _ in r.logged]
        assert steps[:2] == [1, 2]
        assert r.logged[0][1]["score"] == r.kw["config"]["x"]
        assert r.finished == 0

    # MLflow: params at start, per-step metrics, FINISHED status.
    assert len(ml.params) == 2
    assert sorted(float(p["x"]) for p in ml.params.values()) == [1.0, 5.0]
    for rid, metrics in ml.metrics.items():
        scores = [(s, v) for s, k, v in metrics if k == "score"]
        assert len(scores) >= 2
        assert ml.status[rid] == "FINISHED"
    assert ml.experiments["e"].experiment_id == "exp-e"


def test_missing_libraries_raise_clear_errors():
    try:
        import wandb  # noqa: F401
        has_wandb = True
    except ImportError:
        has_wandb = False
    if not has_wandb:
        with pytest.raises(RuntimeError, match="wandb"):
            WandbLoggerCallback(project="p")
    try:
        import mlflow  # noqa: F401
        has_mlflow = True
    except ImportError:
        has_mlflow = False
    if not has_mlflow:
        with pytest.raises(RuntimeError, match="mlflow"):
            MLflowLoggerCallback()


def test_retryable_failure_keeps_tracker_runs_open(ray_init, tmp_path):
    """A retried trial is not an END: ending a wandb/mlflow run is
    permanent, so loggers must keep runs open across retries
    (regression: on_trial_error fired before the retry decision)."""
    marker = str(tmp_path / "failed_once")

    def flaky(config):
        import os
        from ray_tpu.air import session
        session.report({"score": 1.0, "training_iteration": 1})
        if not os.path.exists(marker):
            open(marker, "w").write("x")
            raise RuntimeError("transient crash")
        session.report({"score": 2.0, "training_iteration": 2})

    from ray_tpu.air.config import FailureConfig
    wb = _FakeWandb()
    ml = _FakeMlflowClient()
    results = tune.Tuner(
        flaky, param_space={"x": 0},
        run_config=RunConfig(
            storage_path=str(tmp_path), name="exp",
            failure_config=FailureConfig(max_failures=2),
            callbacks=[WandbLoggerCallback(project="p", module=wb),
                       MLflowLoggerCallback(client=ml)]),
    ).fit()
    assert not results.errors
    # ONE wandb run, closed cleanly, with results from both attempts.
    assert len(wb.runs) == 1
    assert wb.runs[0].finished == 0
    scores = [m["score"] for _, m in wb.runs[0].logged
              if "score" in m]
    assert 2.0 in scores
    # ONE mlflow run, FINISHED (no spurious FAILED + duplicate).
    assert len(ml.status) == 1
    assert list(ml.status.values()) == ["FINISHED"]


class _FakeCometExperiment:
    def __init__(self, **kw):
        self.kw, self.name = kw, None
        self.params, self.metrics, self.ended = {}, [], False

    def set_name(self, name):
        self.name = name

    def log_parameters(self, params):
        self.params.update(params)

    def log_metrics(self, metrics, step=None):
        self.metrics.append((step, metrics))

    def end(self):
        self.ended = True


class _FakeComet:
    def __init__(self):
        self.experiments = []

    def Experiment(self, **kw):
        e = _FakeCometExperiment(**kw)
        self.experiments.append(e)
        return e


def test_comet_callback(ray_init, tmp_path):
    from ray_tpu.air.integrations import CometLoggerCallback

    cm = _FakeComet()
    results = tune.Tuner(
        _trainable,
        param_space={"x": tune.grid_search([1.0, 4.0])},
        run_config=RunConfig(
            storage_path=str(tmp_path), name="exp",
            callbacks=[CometLoggerCallback(project_name="p",
                                           module=cm)]),
    ).fit()
    assert not results.errors
    assert len(cm.experiments) == 2
    xs = sorted(e.params["x"] for e in cm.experiments)
    assert xs == [1.0, 4.0]
    for e in cm.experiments:
        assert e.kw["project_name"] == "p"
        assert e.ended
        assert any("score" in m for _, m in e.metrics)
