"""Device-trace capture (util/tpu_profiler.py) — works on the CPU
backend too; the artifact contract is a TensorBoard/Perfetto-loadable
trace directory."""

import glob
import os

import jax.numpy as jnp
import pytest

from ray_tpu.util import tpu_profiler


def test_trace_context_produces_artifacts(tmp_path):
    d = str(tmp_path / "prof")
    with tpu_profiler.trace(d) as got:
        assert got == d
        with tpu_profiler.annotate("matmul-region"):
            x = jnp.ones((64, 64))
            (x @ x).block_until_ready()
    files = glob.glob(os.path.join(d, "**", "*"), recursive=True)
    assert any(f.endswith(".trace.json.gz") or ".xplane." in f
               for f in files), files


def test_start_stop_guards(tmp_path):
    with pytest.raises(RuntimeError):
        tpu_profiler.stop()
    d = tpu_profiler.start(str(tmp_path / "p2"))
    with pytest.raises(RuntimeError):
        tpu_profiler.start(str(tmp_path / "p3"))
    out = tpu_profiler.stop()
    assert out == d
