"""Multi-replica serving that survives: stream failover, per-tenant QoS
with load shedding, drain-based scale-down, and autoscale hysteresis.

The robustness contract under test (reference: serve replica fault
tolerance, PAPER.md L10): a replica death mid-stream either RESUMES on
a healthy replica with the remaining greedy tokens bit-identical to an
uninterrupted run, or fails fast with a structured StreamInterrupted
carrying a resume cursor — never a silent hang; a hot tenant's overload
sheds with 429-style TenantThrottled instead of inflating the cold
tenant's p99; scale-down drains (in-flight streams finish) instead of
killing; and chaos-noisy gauges cannot flap the autoscaler.
"""

import asyncio
import time

import jax
import jax.numpy as jnp
import numpy as np
import pytest

import ray_tpu
from ray_tpu.models import decode, gpt
from ray_tpu.serve.exceptions import StreamInterrupted, TenantThrottled
from ray_tpu.serve._private.qos import TenantQoS

GPT_CFG = gpt.GPTConfig(vocab_size=97, d_model=32, n_heads=4,
                        n_layers=2, d_ff=64, max_seq=64,
                        dtype=jnp.float32, remat=False, use_flash=False)
ENGINE_KW = dict(num_slots=2, max_seq=40, prefill_chunk=4)


def _loader():
    cfg = GPT_CFG
    return gpt.init_params(cfg, jax.random.PRNGKey(0)), cfg


def _prompt(seed, n):
    return [int(t) for t in np.asarray(jax.random.randint(
        jax.random.PRNGKey(seed), (n,), 1, GPT_CFG.vocab_size))]


def _oracle(prompt, max_new):
    params, cfg = _loader()
    out = decode.generate(params, jnp.asarray([prompt]), cfg,
                          max_new_tokens=max_new)
    return [int(t) for t in np.asarray(out[0])]


@pytest.fixture
def serve_instance():
    from ray_tpu import serve
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    serve.start()
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def _stream_owner(replica_set):
    """(tag, actor) of the replica currently serving the in-flight
    stream, per the router's own accounting."""
    tag = next(t for t, n in replica_set._in_flight.items() if n > 0)
    actor = next(r["actor"] for r in replica_set._replicas
                 if r["replica_tag"] == tag)
    return tag, actor


# ---------------------------------------------------------------------------
# Stream failover


@pytest.mark.slow  # in `make chaos` explicitly; keeps tier-1 lean
def test_replica_kill_mid_stream_failover_token_identical(serve_instance):
    """THE failover acceptance: kill the replica serving a greedy
    stream mid-generation; the stream resumes on the surviving replica
    and the FULL token sequence is bit-identical to an uninterrupted
    run (the resume re-anchors the prompt at the cursor, so greedy
    continuation is exact)."""
    from ray_tpu.serve.llm.api import llm_deployment

    prompt = _prompt(0, 8)
    want = _oracle(prompt, 24)

    handle = llm_deployment(_loader, name="failover",
                            num_replicas=2,
                            engine_config=dict(ENGINE_KW)).deploy()
    sub = handle.options("stream")
    stream = sub.stream(prompt, max_new_tokens=24)
    got = []
    it = iter(stream)
    for _ in range(5):
        got.append(next(it))
    rs = sub._router.replica_set
    tag, actor = _stream_owner(rs)
    ray_tpu.kill(actor)
    got.extend(it)  # failover happens inside the iterator

    assert got == want, (got, want)
    assert rs.stats()["in_flight"] == 0
    # The dead replica is suppressed in the router's local view
    # immediately (no second stream can land on it before the
    # controller notices) — TTL-bounded, so a mis-classified transient
    # error can't shrink capacity forever.
    assert tag in rs._suppressed \
        or tag not in [r["replica_tag"] for r in rs._replicas]


@pytest.mark.slow  # in `make chaos` explicitly; keeps tier-1 lean
def test_stream_interrupted_structured_when_failover_disabled(
        serve_instance, monkeypatch):
    """With failover off, a replica death mid-stream surfaces as a
    structured StreamInterrupted carrying the resume cursor — within
    the RPC deadline, never a hang, never a raw ActorDiedError."""
    monkeypatch.setenv("RT_SERVE_STREAM_FAILOVER", "0")
    from ray_tpu.serve.llm.api import llm_deployment

    prompt = _prompt(1, 8)
    handle = llm_deployment(_loader, name="nofo", num_replicas=1,
                            engine_config=dict(ENGINE_KW)).deploy()
    sub = handle.options("stream")
    stream = sub.stream(prompt, max_new_tokens=24)
    it = iter(stream)
    got = [next(it) for _ in range(3)]
    rs = sub._router.replica_set
    _, actor = _stream_owner(rs)
    ray_tpu.kill(actor)
    t0 = time.monotonic()
    with pytest.raises(StreamInterrupted) as exc:
        for tok in it:
            got.append(tok)
    assert time.monotonic() - t0 < 30.0, "interruption was not fast"
    e = exc.value
    # The engine may deliver a few more tokens between the 3rd next()
    # and the kill landing; the cursor must match EXACTLY what this
    # consumer got, whatever that count is.
    assert e.delivered == len(got)
    assert 3 <= len(got) < 24
    assert e.resumable is True
    assert e.resume_cursor["deployment"] == "nofo"
    assert rs.stats()["in_flight"] == 0


def test_unary_retry_on_replica_death(serve_instance):
    """A replica that dies before answering a unary call is retried
    once on a different replica (zero bytes were delivered) instead of
    surfacing a raw ActorDiedError to the caller."""
    from ray_tpu import serve

    @serve.deployment(name="retries", num_replicas=2)
    class Echo:
        def __call__(self, x):
            from ray_tpu.serve import get_replica_context
            return get_replica_context().replica_tag

    from ray_tpu.serve._private.router import UNARY_RETRY_COUNTER

    handle = Echo.deploy()
    assert handle.remote(0).result(timeout=60)  # router warmed
    rs = handle._router.replica_set
    victim = rs._replicas[0]
    # Force the retry path deterministically: narrow the router's local
    # view to ONLY the victim, then kill it — the first call MUST hit
    # the dead replica, retry with it excluded, and wait out the
    # controller's membership broadcast for the survivor.
    rs._replicas = [victim]
    ray_tpu.kill(victim["actor"])
    retries0 = sum(UNARY_RETRY_COUNTER.snapshot()["values"].values())
    out = {handle.remote(i).result(timeout=60) for i in range(4)}
    assert out  # all resolved without raising
    assert victim["replica_tag"] not in out
    assert sum(UNARY_RETRY_COUNTER.snapshot()["values"].values()) \
        > retries0, "the retry path never fired"


# ---------------------------------------------------------------------------
# Per-tenant QoS


def test_token_bucket_sheds_and_accounts():
    qos = TenantQoS(rate=10.0, burst=2.0, max_queued=4)
    qos.admit("d", "hot", 0)
    qos.admit("d", "hot", 0)
    with pytest.raises(TenantThrottled) as exc:
        qos.admit("d", "hot", 0)
    assert exc.value.reason == "rate_limited"
    assert 0 < exc.value.retry_after_s <= 0.2
    # Refill: ~one token after 1/rate seconds.
    time.sleep(0.12)
    qos.admit("d", "hot", 0)
    # Per-tenant queue cap sheds with queue_full.
    with pytest.raises(TenantThrottled) as exc2:
        qos.admit("d", "cold", 4)
    assert exc2.value.reason == "queue_full"
    assert qos.shed_total == 2


def test_qos_from_env(monkeypatch):
    monkeypatch.delenv("RT_SERVE_QOS", raising=False)
    monkeypatch.delenv("RT_SERVE_TENANT_RATE", raising=False)
    monkeypatch.delenv("RT_SERVE_TENANT_WEIGHTS", raising=False)
    assert TenantQoS.from_env() is None
    monkeypatch.setenv("RT_SERVE_TENANT_RATE", "25")
    monkeypatch.setenv("RT_SERVE_TENANT_WEIGHTS", "gold:4,free:0.5")
    monkeypatch.setenv("RT_SERVE_TENANT_MAX_QUEUED", "9")
    q = TenantQoS.from_env()
    assert q.rate == 25.0 and q.max_queued == 9
    assert q.weight("gold") == 4.0 and q.weight("free") == 0.5
    assert q.weight("other") == 1.0
    monkeypatch.setenv("RT_SERVE_QOS", "0")
    assert TenantQoS.from_env() is None


def test_wfq_dispatch_is_weighted_fair():
    """12 waiters from two tenants contend for ONE replica slot: the
    dispatch order follows the virtual-finish tags, giving tenant a
    (weight 3) three slots for each of tenant b's (weight 1)."""
    from ray_tpu.serve._private.router import ReplicaSet

    async def run():
        qos = TenantQoS(weights={"a": 3.0, "b": 1.0}, max_queued=64)
        rs = ReplicaSet("d", asyncio.get_running_loop(), qos=qos)
        rs.update_replicas([{"replica_tag": "r1", "actor": None,
                             "max_concurrent_queries": 1}])
        first = await rs._acquire(5.0, tenant="a")
        order = []

        async def worker(tenant):
            c = await rs._acquire(10.0, tenant=tenant)
            order.append(tenant)
            rs._release(c["replica_tag"])

        tasks = [asyncio.ensure_future(worker("a")) for _ in range(6)]
        tasks += [asyncio.ensure_future(worker("b")) for _ in range(6)]
        await asyncio.sleep(0.05)  # everyone queued, WFQ tags assigned
        rs._release(first["replica_tag"])  # start the dispatch chain
        await asyncio.gather(*tasks)
        return order

    order = asyncio.run(run())
    assert order[:4].count("a") == 3 and order[:4].count("b") == 1, order
    assert order[4:8].count("a") == 3 and order[4:8].count("b") == 1, \
        order


def test_failover_reacquire_skips_admission():
    """A retry/failover of an ALREADY-ADMITTED request must not re-run
    the token bucket: a replica death mid-request must never convert
    into a 429, nor double-charge the tenant."""
    from ray_tpu.serve._private.router import ReplicaSet

    async def run():
        qos = TenantQoS(rate=1.0, burst=1.0, max_queued=4)
        rs = ReplicaSet("d", asyncio.get_running_loop(), qos=qos)
        rs.update_replicas([
            {"replica_tag": "r1", "actor": None,
             "max_concurrent_queries": 4},
            {"replica_tag": "r2", "actor": None,
             "max_concurrent_queries": 4}])
        c1 = await rs._acquire(5.0, tenant="t")  # burns the only token
        with pytest.raises(TenantThrottled):
            await rs._acquire(5.0, tenant="t")  # fresh request: shed
        # Failover re-acquisition: no admission charge, lands on the
        # OTHER replica.
        c2 = await rs._acquire(5.0, tenant="t",
                               exclude=(c1["replica_tag"],),
                               admit=False)
        assert c2["replica_tag"] != c1["replica_tag"]

    asyncio.run(run())


def test_hot_tenant_sheds_cold_tenant_latency_bounded(serve_instance):
    """Tenant isolation end-to-end: a hot tenant flooding far past its
    rate budget is shed (TenantThrottled, counted), while the cold
    tenant's requests all succeed with bounded latency."""
    from ray_tpu import serve
    from ray_tpu.serve.handle import _get_router_loop
    from ray_tpu.serve._private.router import Router

    @serve.deployment(name="qos_iso", num_replicas=1,
                      max_concurrent_queries=2)
    class Work:
        async def __call__(self, x):
            await asyncio.sleep(0.02)
            return x

    Work.deploy()
    loop = _get_router_loop()
    qos = TenantQoS(rate=20.0, burst=4.0, max_queued=8,
                    weights={"cold": 4.0, "hot": 1.0})
    router = asyncio.run_coroutine_threadsafe(
        _make_router(Work, qos), loop).result(timeout=30)

    async def flood_and_measure():
        sheds = 0
        oks = 0

        async def hot(i):
            nonlocal sheds, oks
            try:
                await router.assign_request("", (i,), {}, tenant="hot")
                oks += 1
            except TenantThrottled:
                sheds += 1

        hot_tasks = [asyncio.ensure_future(hot(i)) for i in range(60)]
        lats = []
        for i in range(10):
            t0 = time.monotonic()
            out = await router.assign_request("", (i,), {},
                                              tenant="cold")
            lats.append(time.monotonic() - t0)
            assert out == i
            # The cold tenant is WELL-BEHAVED: paced inside its own
            # rate budget — isolation means IT never gets punished for
            # the hot tenant's flood.
            await asyncio.sleep(0.08)
        await asyncio.gather(*hot_tasks)
        return sheds, oks, lats

    sheds, oks, lats = asyncio.run_coroutine_threadsafe(
        flood_and_measure(), loop).result(timeout=120)
    assert sheds > 0, "hot tenant was never shed"
    assert sheds + oks == 60
    assert qos.shed_total == sheds  # shed accounting is exact
    assert max(lats) < 10.0, f"cold tenant latency unbounded: {lats}"
    router.stop()


async def _make_router(dep, qos):
    from ray_tpu.serve._private.router import Router
    from ray_tpu.serve.api import _get_or_create_controller
    return Router(_get_or_create_controller(), dep.name,
                  loop=asyncio.get_running_loop(), qos=qos)


# ---------------------------------------------------------------------------
# Replica stream sweep reclaims the engine request


def test_stream_sweep_frees_engine_kv_pages():
    """Regression: a consumer that vanishes mid-generation (no polls,
    no cancel) must not leave the engine request generating into a dead
    TokenStream — the idle-TTL sweep cancels the pump task AND the
    engine request, reclaiming KV pages and the decode slot."""
    import cloudpickle

    from ray_tpu.serve._private.replica import RTServeReplica
    from ray_tpu.serve.llm.api import LLMServer

    async def run():
        rep = RTServeReplica(
            "d", "tag:sweep", cloudpickle.dumps(LLMServer), (_loader,),
            {"engine_config": dict(ENGINE_KW)}, None, "1")
        eng = rep.callable.engine
        free0 = eng.load_info()["kv_blocks_free"]
        started = await rep.handle_request_streaming(
            "stream", (_prompt(5, 6),), {"max_new_tokens": 30})
        assert started.get("resumable") is True
        sid = started["stream_id"]
        out = await rep.stream_next(sid, 0, timeout_s=10)
        assert out["items"]
        info = eng.load_info()
        assert info["kv_blocks_free"] < free0  # pages held
        # Consumer vanishes: stream goes idle past the TTL.
        rep._streams[sid]["last_poll"] -= rep.STREAM_IDLE_TTL_S + 1
        rep._sweep_stale_streams()
        assert sid not in rep._streams
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            info = eng.load_info()
            if info["active_slots"] == 0 \
                    and info["kv_blocks_free"] == free0:
                break
            await asyncio.sleep(0.05)
        assert info["active_slots"] == 0, info
        assert info["kv_blocks_free"] == free0, \
            f"KV pages leaked after sweep: {info} vs free0={free0}"
        if rep._sweep_task is not None:
            rep._sweep_task.cancel()
        eng.stop()

    asyncio.run(run())


# ---------------------------------------------------------------------------
# Autoscaling: engine gauges + hysteresis


def test_replica_load_uses_engine_gauges():
    from ray_tpu.serve._private.controller import _replica_load

    # Plain deployments: ongoing/target (the reference policy).
    assert _replica_load({"ongoing": 6}, 2.0) == 3.0
    # Engine slot pressure dominates a tame request count.
    m = {"ongoing": 1, "num_slots": 4, "active_slots": 4,
         "queue_depth": 4}
    assert _replica_load(m, 2.0) == 2.0
    # KV exhaustion dominates both.
    m = {"ongoing": 0, "num_slots": 8, "active_slots": 1,
         "queue_depth": 0, "kv_blocks_total": 100, "kv_blocks_free": 5}
    assert _replica_load(m, 2.0) == pytest.approx(0.95)


class _FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def monotonic(self):
        return self.t


class _ScriptedReplica:
    """RUNNING replica whose poll_load answers from a scripted ongoing
    trace (one sample per control tick)."""

    def __init__(self, trace):
        from ray_tpu.serve._private.deployment_state import RUNNING
        self.state = RUNNING
        self.replica_tag = "fake"
        self._trace = list(trace)
        self._i = 0

    def poll_load(self, now):
        v = self._trace[min(self._i, len(self._trace) - 1)]
        self._i += 1
        return {"ongoing": v}


def _autoscale_harness(monkeypatch, ac, trace_fn, ticks, dt=0.25,
                       start_replicas=2):
    """Run _autoscale_tick over a synthetic gauge trace with a fake
    clock; returns [(t, new_target), ...] decisions."""
    from ray_tpu.serve import _private as _p
    from ray_tpu.serve._private import controller as controller_mod
    from ray_tpu.serve._private.deployment_state import DeploymentState
    from ray_tpu.serve.config import DeploymentConfig

    clock = _FakeClock()
    monkeypatch.setattr(controller_mod, "time", clock)
    ctl = controller_mod.ServeController()
    ds = DeploymentState("d", ctl._long_poll)
    ds.target_config = DeploymentConfig(autoscaling_config=ac)
    ds.target_num_replicas = start_replicas
    reps = [_ScriptedReplica([trace_fn(k, i) for k in range(ticks)])
            for i in range(start_replicas)]
    ds.replicas = reps
    ctl._dsm._deployments["d"] = ds
    decisions = []
    last = ds.target_num_replicas
    for k in range(ticks):
        clock.t += dt
        ctl._autoscale_tick()
        if ds.target_num_replicas != last:
            decisions.append((clock.t, ds.target_num_replicas))
            last = ds.target_num_replicas
    return decisions


def test_autoscale_hysteresis_suppresses_noisy_gauge_flapping(
        monkeypatch):
    """Satellite acceptance: under a noisy gauge trace, scale decisions
    change at most once per cooldown window — chaos shake cannot flap
    replica counts."""
    from ray_tpu.serve.config import AutoscalingConfig

    rng = np.random.default_rng(7)
    noise = rng.integers(0, 9, size=400)  # 0..8 ongoing, pure noise

    ac = AutoscalingConfig(
        min_replicas=1, max_replicas=8,
        target_num_ongoing_requests_per_replica=1.0,
        upscale_delay_s=0.5, downscale_delay_s=0.5,
        decision_cooldown_s=10.0, load_ewma_alpha=0.3)
    decisions = _autoscale_harness(
        monkeypatch, ac, lambda k, i: int(noise[(k + 97 * i) % 400]),
        ticks=400)
    # 400 ticks * 0.25s = 100s of noise, 10s cooldown => <= 10 changes,
    # and every pair of consecutive decisions >= cooldown apart.
    for (t0, _), (t1, _) in zip(decisions, decisions[1:]):
        assert t1 - t0 >= ac.decision_cooldown_s - 1e-9, decisions
    assert len(decisions) <= 10, decisions


def test_autoscale_still_tracks_sustained_load(monkeypatch):
    """Flap suppression must not kill responsiveness: sustained real
    load walks the target up to demand (and back down when it ends)."""
    from ray_tpu.serve.config import AutoscalingConfig

    ac = AutoscalingConfig(
        min_replicas=1, max_replicas=8,
        target_num_ongoing_requests_per_replica=1.0,
        upscale_delay_s=0.5, downscale_delay_s=0.5,
        decision_cooldown_s=1.0, load_ewma_alpha=0.5)
    # 3 ongoing per replica sustained for 120 ticks, then idle.
    decisions = _autoscale_harness(
        monkeypatch, ac, lambda k, i: 3 if k < 120 else 0, ticks=300)
    assert decisions, "never scaled"
    peak = max(n for _, n in decisions)
    assert peak >= 5  # 2 replicas * 3 ongoing => 6 wanted (capped ewma)
    assert decisions[-1][1] == 1  # idles back down to min


def test_drain_based_scale_down_finishes_in_flight_work(serve_instance):
    """Scale-down must DRAIN: with graceful_shutdown_timeout_s far
    shorter than the in-flight work, the old kill-after-grace path
    would abort the requests; the drain path finishes them and only
    then retires the replica."""
    from ray_tpu import serve

    @serve.deployment(name="drainer", num_replicas=2, version="v1",
                      graceful_shutdown_timeout_s=1.0)
    class Sleeper:
        def work(self, s):
            time.sleep(s)
            return "done"

        def __call__(self, req):
            return "ok"

    handle = Sleeper.deploy()
    refs = [handle.work.remote(4.0) for _ in range(6)]
    time.sleep(0.3)  # requests land on both replicas
    Sleeper.options(num_replicas=1).deploy(_blocking=False)
    out = [r.result(timeout=120) for r in refs]
    assert out == ["done"] * 6  # nothing was killed mid-request
    deadline = time.monotonic() + 60
    while time.monotonic() < deadline:
        st = {s["name"]: s for s in serve.status()}["drainer"]
        if st["replica_states"].get("RUNNING") == 1 \
                and not st["replica_states"].get("DRAINING") \
                and not st["replica_states"].get("STOPPING"):
            break
        time.sleep(0.2)
    assert st["replica_states"].get("RUNNING") == 1, st


@pytest.mark.slow
def test_sse_failover_through_proxy_wire(serve_instance):
    """Regression (caught live, not by the handle-path tests): the
    proxy resolves the deployment INSTANCE for method_name "", so the
    @serve.resumable marker on __call__ must be honored there too —
    an SSE stream over the real HTTP wire survives the death of EVERY
    replica that could be serving it (both killed at token 4) by
    resuming cursor-exact on the controller's replacement replica."""
    import requests

    from ray_tpu import serve
    from ray_tpu.serve.llm.api import llm_deployment

    serve.start(_start_proxy=True)
    prompt = _prompt(2, 6)
    want = _oracle(prompt, 16)
    handle = llm_deployment(_loader, name="ssefo", num_replicas=2,
                            engine_config=dict(ENGINE_KW)).deploy()
    sub = handle.options("stats")
    sub.remote().result(timeout=60)
    tags = [i["replica_tag"]
            for i in sub._router.replica_set._replicas]
    addr = serve.get_proxy_address()
    base = f"http://{addr['host']}:{addr['port']}/ssefo"
    import json as _json
    with requests.post(base, json={"tokens": prompt,
                                   "max_new_tokens": 16},
                       stream=True, timeout=300,
                       headers={"Accept": "text/event-stream"}) as r:
        assert r.status_code == 200, r.status_code
        toks, killed, events = [], False, []
        for line in r.iter_lines():
            if not line.startswith(b"data: "):
                continue
            payload = line[len(b"data: "):]
            if payload == b"[DONE]":
                break
            ev = _json.loads(payload)
            events.append(ev)
            if not isinstance(ev, dict) or "token" not in ev:
                break  # terminal error event; assert below with detail
            toks.append(ev["token"])
            if len(toks) == 4 and not killed:
                killed = True
                for tag in tags:
                    ray_tpu.kill(ray_tpu.get_actor(
                        f"SERVE_REPLICA::{tag}"))
    assert toks == want, (toks, want, events)


# ---------------------------------------------------------------------------
# GCS faults during serving (the control plane is not on the token path)


@pytest.mark.slow
def test_gcs_faults_during_serve_streams(serve_instance):
    """Chaos scenario for `make chaos`: GCS requests black-holed while
    SSE-style streams are mid-flight.  Token delivery rides direct
    actor connections, so every stream must complete with exact parity
    during the outage, and the control plane must serve new deployments
    after the heal."""
    from ray_tpu._private import failpoints
    from ray_tpu.serve.llm.api import llm_deployment

    prompt = _prompt(9, 6)
    want = _oracle(prompt, 16)
    handle = llm_deployment(_loader, name="gcschaos", num_replicas=2,
                            engine_config=dict(ENGINE_KW)).deploy()
    sub = handle.options("stream")
    streams = [iter(sub.stream(prompt, max_new_tokens=16))
               for _ in range(4)]
    firsts = [next(it) for it in streams]  # all mid-flight
    failpoints.configure("worker.gcs_request=error")
    try:
        outs = [[f] + list(it) for f, it in zip(firsts, streams)]
    finally:
        failpoints.configure("")
    for got in outs:
        assert got == want, (got, want)
    # Control plane recovered: unary calls still work post-heal.
    got = handle.generate.remote(prompt, max_new_tokens=4).result(
        timeout=120)
    assert got == want[:4]
