"""RTC concurrency rules (ray_tpu.lint.concurrency) + the runtime
lock-order sanitizer (ray_tpu._private.locksan): one flagging and one
non-flagging fixture per RTC rule, noqa/baseline suppression, the CLI
surface (--format sarif, --jobs, --emit-lock-graph), and the seeded
two-lock deadlock fixture caught BOTH statically (RTC102) and
dynamically (locksan) with a gap-free static/dynamic diff."""

import json
import textwrap
import threading

import pytest

from ray_tpu._private import locksan
from ray_tpu.lint import (apply_baseline, collect_summaries, lint_paths,
                          lint_source, load_baseline, write_baseline)
from ray_tpu.lint.__main__ import main as lint_main
from ray_tpu.lint.concurrency import build_lock_graph, emit_lock_graph


def codes(src: str):
    return [f.code for f in lint_source(textwrap.dedent(src), "t.py")]


def messages(src: str, code: str):
    return [f.message for f in lint_source(textwrap.dedent(src), "t.py")
            if f.code == code]


# ------------------------------------------------------------- RTC101
def test_rtc101_flags_mixed_bare_and_guarded_writes():
    src = """
    import threading

    class Buf:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []
            self._thread = None

        def start(self):
            self._thread = threading.Thread(target=self._drain)
            self._thread.start()

        def _drain(self):
            with self._lock:
                self._items = []

        def add(self, x):
            self._items.append(x)
    """
    assert "RTC101" in codes(src)
    (msg,) = messages(src, "RTC101")
    assert "Buf._items" in msg and "WITHOUT the lock" in msg


def test_rtc101_clean_when_all_writes_guarded_or_no_threads():
    src = """
    import threading

    class Guarded:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []
            self._thread = None

        def start(self):
            self._thread = threading.Thread(target=self._drain)
            self._thread.start()

        def _drain(self):
            with self._lock:
                self._items = []

        def add(self, x):
            with self._lock:
                self._items.append(x)

    class SingleThreaded:
        def __init__(self):
            self._lock = threading.Lock()
            self._items = []

        def reset(self):
            with self._lock:
                self._items = []

        def add(self, x):
            self._items.append(x)  # no thread entry: loop-confined
    """
    assert "RTC101" not in codes(src)


def test_rtc101_locked_suffix_means_caller_holds_the_lock():
    src = """
    import threading

    class Conv:
        def __init__(self):
            self._lock = threading.Lock()
            self._eps = []
            threading.Thread(target=self._gc).start()

        def _gc(self):
            with self._lock:
                self._gc_locked()

        def _gc_locked(self):
            self._eps = [e for e in self._eps if e]
    """
    assert "RTC101" not in codes(src)


# ------------------------------------------------------------- RTC102
_DEADLOCK_SRC = textwrap.dedent("""
    from ray_tpu._private import locksan

    A = locksan.make_lock("deadlock_fixture.A")
    B = locksan.make_lock("deadlock_fixture.B")

    def ab():
        with A:
            with B:
                pass

    def ba():
        with B:
            with A:
                pass
""")


def test_rtc102_flags_seeded_two_lock_deadlock(tmp_path):
    mod = tmp_path / "deadlock_fixture.py"
    mod.write_text(_DEADLOCK_SRC)
    findings = lint_paths([str(mod)])
    rtc102 = [f for f in findings if f.code == "RTC102"]
    assert len(rtc102) == 1
    msg = rtc102[0].message
    assert "lock-order cycle" in msg
    assert "deadlock_fixture.A" in msg and "deadlock_fixture.B" in msg
    # The message carries TWO witness paths — one per direction.
    assert msg.count("deadlock_fixture.py:") >= 2


def test_rtc102_clean_when_order_is_consistent():
    src = """
    import threading

    A = threading.Lock()
    B = threading.Lock()

    def f():
        with A:
            with B:
                pass

    def g():
        with A:
            with B:
                pass
    """
    assert "RTC102" not in codes(src)


def test_rtc102_sees_cycles_through_the_call_graph():
    src = """
    import threading

    A = threading.Lock()
    B = threading.Lock()

    def _inner_b():
        with B:
            pass

    def f():
        with A:
            _inner_b()

    def g():
        with B:
            with A:
                pass
    """
    assert "RTC102" in codes(src)


# ------------------------------------------------------------- RTC103
def test_rtc103_flags_sleep_and_condition_on_other_lock():
    src = """
    import threading
    import time

    class W:
        def __init__(self):
            self._lock = threading.Lock()
            self._cond = threading.Condition()

        def tick(self):
            with self._lock:
                time.sleep(1)

        def park(self):
            with self._lock:
                with self._cond:
                    self._cond.wait()
    """
    out = codes(src)
    assert out.count("RTC103") == 2
    msgs = messages(src, "RTC103")
    assert any("time.sleep()" in m for m in msgs)
    assert any("releases only its own lock" in m for m in msgs)


def test_rtc103_clean_when_blocking_outside_locks():
    src = """
    import threading
    import time

    class W:
        def __init__(self):
            self._lock = threading.Lock()
            self._cond = threading.Condition()

        def tick(self):
            with self._lock:
                n = 1
            time.sleep(n)

        def park(self):
            with self._cond:
                self._cond.wait()  # waits on its OWN lock: fine
    """
    assert "RTC103" not in codes(src)


# ------------------------------------------------------------- RTC104
def test_rtc104_flags_lockless_object_shared_with_thread():
    src = """
    import threading

    class Pump:
        def __init__(self):
            self.rows = []
            self._t = None

        def start(self):
            self._t = threading.Thread(target=self._work)
            self._t.start()

        def _work(self):
            self.rows.append(1)

        def add(self, x):
            self.rows.append(x)
    """
    assert "RTC104" in codes(src)
    (msg,) = messages(src, "RTC104")
    assert "defines no lock" in msg and "self.rows" in msg


def test_rtc104_clean_with_lock_or_writes_before_start():
    src = """
    import threading

    class Locked:
        def __init__(self):
            self._lock = threading.Lock()
            self.rows = []

        def start(self):
            threading.Thread(target=self._work).start()

        def _work(self):
            with self._lock:
                self.rows.append(1)

    class WriteBeforeStart:
        def __init__(self):
            self.rows = []

        def start(self):
            self.rows = []  # happens-before Thread.start()
            threading.Thread(target=self._read).start()

        def _read(self):
            return len(self.rows)
    """
    assert "RTC104" not in codes(src)


# ------------------------------------------------- noqa and baseline
def test_noqa_suppresses_rtc_codes():
    base = """
    import threading
    import time

    class W:
        def __init__(self):
            self._lock = threading.Lock()

        def tick(self):
            with self._lock:
                time.sleep(1){noqa}
    """
    assert "RTC103" in codes(base.format(noqa=""))
    assert "RTC103" not in codes(base.format(noqa="  # noqa: RTC103"))
    assert "RTC103" not in codes(base.format(noqa="  # noqa"))
    assert "RTC103" in codes(base.format(noqa="  # noqa: RTC101"))


_RTC_FLAGGED = textwrap.dedent("""
    import threading
    import time

    class W:
        def __init__(self):
            self._lock = threading.Lock()

        def tick(self):
            with self._lock:
                time.sleep(1)
""")


def test_baseline_suppresses_rtc_findings(tmp_path):
    mod = tmp_path / "m.py"
    mod.write_text(_RTC_FLAGGED)
    findings = lint_paths([str(mod)])
    assert [f.code for f in findings] == ["RTC103"]

    bl = tmp_path / "bl.json"
    write_baseline(findings, str(bl), root=str(tmp_path))
    baseline = load_baseline(str(bl))
    assert baseline == {"m.py::RTC103": 1}
    assert apply_baseline(findings, baseline, root=str(tmp_path)) == []


def test_write_baseline_preserves_reason_strings(tmp_path):
    mod = tmp_path / "m.py"
    mod.write_text(_RTC_FLAGGED)
    bl = tmp_path / "bl.json"
    bl.write_text(json.dumps({
        "counts": {"m.py::RTC103": 1, "gone.py::RTC104": 1},
        "reasons": {"m.py::RTC103": "deliberate: warmup sleep",
                    "gone.py::RTC104": "stale entry"},
    }))
    findings = lint_paths([str(mod)])
    write_baseline(findings, str(bl), root=str(tmp_path))
    out = json.loads(bl.read_text())
    # Reasons survive regeneration for keys still baselined; reasons
    # for keys that dropped out of the baseline are pruned with them.
    assert out["counts"] == {"m.py::RTC103": 1}
    assert out["reasons"] == {"m.py::RTC103": "deliberate: warmup sleep"}


def test_checked_in_baseline_reasons_cover_every_rtc_key():
    """Satellite contract: every baselined RTC finding carries a
    justification string."""
    import os
    root = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    with open(os.path.join(root, ".rtlint-baseline.json")) as f:
        data = json.load(f)
    rtc_keys = {k for k in data["counts"] if "::RTC" in k}
    assert rtc_keys, "expected RTC entries in the checked-in baseline"
    missing = rtc_keys - set(data.get("reasons", {}))
    assert not missing, f"RTC baseline keys without a reason: {missing}"


def test_cli_strict_reasons_drops_unjustified_entries(tmp_path,
                                                      monkeypatch,
                                                      capsys):
    mod = tmp_path / "m.py"
    mod.write_text(_RTC_FLAGGED)
    monkeypatch.chdir(tmp_path)
    bl = tmp_path / ".rtlint-baseline.json"

    bl.write_text(json.dumps({"counts": {"m.py::RTC103": 1}}))
    assert lint_main([str(mod)]) == 0  # normal mode: suppressed
    # Strict mode: the entry has no reason, so the finding fails.
    assert lint_main([str(mod), "--strict-reasons"]) == 1

    bl.write_text(json.dumps({
        "counts": {"m.py::RTC103": 1},
        "reasons": {"m.py::RTC103": "deliberate warmup sleep"}}))
    assert lint_main([str(mod), "--strict-reasons"]) == 0
    capsys.readouterr()


# ----------------------------------------------------------- CLI flags
def test_cli_sarif_output_and_jobs(tmp_path, monkeypatch, capsys):
    mod = tmp_path / "m.py"
    mod.write_text(_RTC_FLAGGED)
    monkeypatch.chdir(tmp_path)

    assert lint_main([str(mod), "--no-baseline", "--format", "sarif",
                      "--jobs", "2"]) == 1
    sarif = json.loads(capsys.readouterr().out)
    assert sarif["version"] == "2.1.0"
    run = sarif["runs"][0]
    assert [r["ruleId"] for r in run["results"]] == ["RTC103"]
    assert run["results"][0]["locations"][0]["physicalLocation"][
        "region"]["startLine"] > 1
    assert [r["id"] for r in run["tool"]["driver"]["rules"]] == ["RTC103"]

    assert lint_main([str(mod), "--no-baseline", "--format",
                      "json"]) == 1
    blob = json.loads(capsys.readouterr().out)
    assert blob[0]["code"] == "RTC103"


def test_cli_emit_lock_graph(tmp_path, monkeypatch, capsys):
    mod = tmp_path / "deadlock_fixture.py"
    mod.write_text(_DEADLOCK_SRC)
    monkeypatch.chdir(tmp_path)
    out = tmp_path / "graph.json"
    assert lint_main([str(mod), "--no-baseline", "--emit-lock-graph",
                      str(out)]) == 1  # the RTC102 finding
    capsys.readouterr()
    graph = json.loads(out.read_text())
    edges = {tuple(e) for e in graph["edges"]}
    assert ("deadlock_fixture.A", "deadlock_fixture.B") in edges
    assert ("deadlock_fixture.B", "deadlock_fixture.A") in edges


# ------------------------------------------------- runtime sanitizer
@pytest.fixture
def san():
    was = locksan.enabled()
    locksan.reset()
    locksan.enable()
    yield locksan
    locksan.reset()
    if not was:
        locksan.disable()


def test_locksan_disabled_returns_raw_primitives():
    if locksan.enabled():  # pragma: no cover - chaos battery runs
        pytest.skip("sanitizer globally enabled")
    lk = locksan.make_lock("t.raw")
    assert type(lk) is type(threading.Lock())
    assert not isinstance(lk, locksan._SanLock)


def test_locksan_records_edges_and_violations(san):
    a = san.make_lock("t.A")
    b = san.make_lock("t.B")
    with a:
        with b:
            pass
    assert ("t.A", "t.B") in san.edges()
    assert san.violations() == []
    with b:
        with a:  # reverse order: the deadlock interleaving exists
            pass
    vio = san.violations()
    assert len(vio) == 1
    assert vio[0]["edge"] == ("t.B", "t.A")
    assert "deadlocks" in vio[0]["message"]
    assert "lock-order violation" in san.report()


def test_locksan_reentrant_same_key_is_not_an_edge(san):
    r = san.make_rlock("t.R")
    other = san.make_lock("t.O")
    with r:
        with r:  # reentrancy on one key: no self-edge
            with other:
                pass
    assert ("t.R", "t.R") not in san.edges()
    assert ("t.R", "t.O") in san.edges()
    assert san.violations() == []


def test_locksan_condition_wait_releases_its_key(san):
    cond = san.make_condition("t.C")
    outer = san.make_lock("t.OUT")
    hits = []

    def waiter():
        with cond:
            while not hits:
                cond.wait(timeout=5)

    t = threading.Thread(target=waiter)
    t.start()
    with outer:
        with cond:
            hits.append(1)
            cond.notify_all()
    t.join(5)
    assert not t.is_alive()
    assert ("t.OUT", "t.C") in san.edges()
    assert san.violations() == []


def test_locksan_static_dynamic_same_fixture(san, tmp_path):
    """Acceptance gate: the seeded two-lock deadlock is caught by the
    STATIC cycle detector (test_rtc102_flags_seeded_two_lock_deadlock)
    and — here — by the runtime sanitizer executing the very same
    source, with a gap-free diff between the two graphs."""
    mod = tmp_path / "deadlock_fixture.py"
    mod.write_text(_DEADLOCK_SRC)

    ns = {}
    exec(compile(_DEADLOCK_SRC, str(mod), "exec"), ns)
    ns["ab"]()
    ns["ba"]()
    vio = san.violations()
    assert len(vio) == 1
    assert set(vio[0]["edge"]) == {"deadlock_fixture.A",
                                   "deadlock_fixture.B"}

    static = san.load_static_graph(
        emit_lock_graph(collect_summaries([str(mod)])))
    diff = san.check_against_static(static)
    # Both dynamic orderings were predicted statically: no analyzer
    # gaps.  (Gaps here would be a bug in ray_tpu/lint/concurrency.py.)
    assert diff["gaps"] == []
    assert diff["unexercised"] == []


def test_locksan_flags_analyzer_gaps(san):
    a = san.make_lock("gap.A")
    b = san.make_lock("gap.B")
    with a:
        with b:
            pass
    diff = san.check_against_static({("gap.A", "gap.B"),
                                     ("gap.X", "gap.Y")})
    assert diff["gaps"] == []
    assert diff["unexercised"] == [("gap.X", "gap.Y")]
    # An edge the static graph does NOT predict is an analyzer gap.
    diff = san.check_against_static(set())
    assert ("gap.A", "gap.B") in diff["gaps"]


def test_lock_graph_merges_summaries_across_modules(tmp_path):
    (tmp_path / "m1.py").write_text(textwrap.dedent("""
        import threading
        A = threading.Lock()

        def f():
            with A:
                import m2
                m2.g()
    """))
    (tmp_path / "m2.py").write_text(textwrap.dedent("""
        import threading
        B = threading.Lock()

        def g():
            with B:
                pass
    """))
    adj = build_lock_graph(collect_summaries([str(tmp_path)]))
    assert "m2.B" in adj.get("m1.A", {})
