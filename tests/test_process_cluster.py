"""Out-of-process cluster + SIGKILL-grade fault injection.

Reference: python/ray/cluster_utils.py Cluster (real raylet processes per
node, killed mid-run in test_component_failures_*.py) and the NodeKiller
chaos harness (python/ray/_private/test_utils.py:1098).  Unlike the
in-process Cluster fixture, every node here is a real OS process group
(GCS process, raylet processes, forked workers), so death is SIGKILL —
no graceful coroutine teardown."""

import signal
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import ProcessCluster


@pytest.fixture
def proc_cluster():
    c = ProcessCluster()
    yield c
    c.shutdown()


@pytest.mark.slow
def test_two_process_groups_tasks_and_objects(proc_cluster):
    c = proc_cluster
    c.add_node(num_cpus=2)
    c.add_node(num_cpus=2, resources={"side": 1})
    assert c.wait_for_nodes(2)
    c.connect()

    @ray_tpu.remote
    def where():
        return ray_tpu.get_runtime_context().get_node_id()

    side = ray_tpu.get(where.options(resources={"side": 0.1}).remote(),
                       timeout=120)
    local = ray_tpu.get(where.remote(), timeout=120)
    assert side != local  # scheduled across real process groups

    @ray_tpu.remote
    def make():
        import numpy as np
        return np.random.bytes(2 * 1024 * 1024)

    ref = make.options(resources={"side": 0.1}).remote()
    assert len(ray_tpu.get(ref, timeout=120)) == 2 * 1024 * 1024


@pytest.mark.slow
def test_sigkill_raylet_actor_restarts(proc_cluster):
    c = proc_cluster
    c.add_node(num_cpus=2)  # head: the driver's node, never killed
    side1 = c.add_node(num_cpus=2, resources={"r": 1})
    side2 = c.add_node(num_cpus=2, resources={"r": 1})
    assert c.wait_for_nodes(3)
    c.connect()

    @ray_tpu.remote
    class Counter:
        def __init__(self):
            self.n = 0

        def bump(self):
            self.n += 1
            return self.n

        def port(self):
            import ray_tpu._private.worker as wm
            return wm.global_worker.raylet_addr[1]

    a = Counter.options(max_restarts=1, max_task_retries=2,
                        resources={"r": 0.1}, num_cpus=0).remote()
    first_port = ray_tpu.get(a.port.remote(), timeout=120)
    assert ray_tpu.get(a.bump.remote(), timeout=120) == 1

    # SIGKILL whichever side raylet the actor landed on — its workers
    # (including the actor) die with it; the twin node can host the
    # restart.
    victim = side1 if side1.raylet_addr[1] == first_port else side2
    assert victim.raylet_addr[1] == first_port
    victim.kill_raylet(sig=signal.SIGKILL)

    # The restarted incarnation loses state but must come back ALIVE on
    # the surviving twin and serve methods again (restart-aware resend).
    n = ray_tpu.get(a.bump.remote(), timeout=240)
    assert n == 1
    assert ray_tpu.get(a.port.remote(), timeout=120) != first_port


@pytest.mark.slow
def test_sigkill_raylet_lineage_reconstruction(proc_cluster):
    c = proc_cluster
    c.add_node(num_cpus=2)
    side1 = c.add_node(num_cpus=2, resources={"r": 1},
                       object_store_memory=256 * 1024 * 1024)
    side2 = c.add_node(num_cpus=2, resources={"r": 1},
                       object_store_memory=256 * 1024 * 1024)
    assert c.wait_for_nodes(3)
    c.connect()

    @ray_tpu.remote(num_returns=2)
    def make(tag):
        import numpy as np
        import ray_tpu._private.worker as wm
        return np.full(300_000, tag, dtype=np.int64), \
            wm.global_worker.raylet_addr[1]

    arr_ref, port_ref = make.options(resources={"r": 0.1},
                                     max_retries=2).remote(7)
    # Fetch only the small (inlined) return: the big array's primary stays
    # on the executing side node and is never copied to the head.
    port = ray_tpu.get(port_ref, timeout=120)

    victim = side1 if side1.raylet_addr[1] == port else side2
    victim.kill_raylet(sig=signal.SIGKILL)  # primary copy is gone

    # Owner-driven reconstruction must re-execute the task elsewhere.
    arr = ray_tpu.get(arr_ref, timeout=240)
    assert arr[0] == 7 and len(arr) == 300_000


@pytest.mark.slow
def test_sigkill_gcs_restart_cluster_survives(proc_cluster):
    c = proc_cluster
    c.add_node(num_cpus=2)
    assert c.wait_for_nodes(1)
    c.connect()

    @ray_tpu.remote
    def f(x):
        return x + 1

    assert ray_tpu.get(f.remote(1), timeout=120) == 2

    c.head.kill_gcs(sig=signal.SIGKILL)
    time.sleep(1)
    c.restart_gcs()

    # Raylet re-registers, driver's GCS client reconnects; scheduling and
    # GCS-backed verbs (nodes) keep working.
    assert ray_tpu.get(f.remote(41), timeout=240) == 42
    deadline = time.monotonic() + 120
    while time.monotonic() < deadline:
        try:
            if any(n["Alive"] for n in ray_tpu.nodes()):
                break
        except Exception:
            pass
        time.sleep(1)
    assert any(n["Alive"] for n in ray_tpu.nodes())


@pytest.mark.slow
def test_autoscaler_with_real_process_provider(proc_cluster):
    """Elasticity against REAL raylet processes: the autoscaler's
    provider launches OS-process nodes joined to the live GCS
    (reference role: fake_multi_node's docker variant), a queued task
    demand scales the cluster up, and the new capacity runs the task."""
    import time as _time

    import ray_tpu
    from ray_tpu.autoscaler import (LocalProcessNodeProvider,
                                    StandardAutoscaler)
    from ray_tpu._private import worker as worker_mod

    c = proc_cluster
    c.add_node(num_cpus=1)
    assert c.wait_for_nodes(1)
    c.connect()

    def gcs_request(method, body):
        w = worker_mod.global_worker
        return w._run(w._gcs_request(method, body))

    provider = LocalProcessNodeProvider(
        {"worker": {"resources": {"CPU": 1, "accel": 2},
                    "max_workers": 2}},
        gcs_addr=c.gcs_addr, session_dir=c.session_dir)
    autoscaler = StandardAutoscaler(provider, gcs_request,
                                    idle_timeout_s=120.0)

    @ray_tpu.remote(resources={"accel": 1})
    def on_accel():
        return ray_tpu.get_runtime_context().get_node_id()

    ref = on_accel.remote()  # no accel capacity anywhere yet
    deadline = _time.time() + 180
    result = None
    while _time.time() < deadline and result is None:
        autoscaler.update()
        try:
            result = ray_tpu.get(ref, timeout=5)
        except Exception:
            result = None
    assert result is not None, "scale-up never satisfied the task"
    live = provider.non_terminated_nodes()
    assert live, "provider reported no launched nodes"
    # Cleanup the provider-launched raylet processes.
    for n in list(live):
        provider.terminate_node(n["provider_id"])
