"""Per-algorithm smoke/learning tests for the wider RLlib family
(reference: rllib/algorithms/*/tests — each algorithm gets a
build-train-improve check)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (
    A2CConfig,
    APPOConfig,
    BCConfig,
    ESConfig,
    MARWILConfig,
    PGConfig,
    SACConfig,
)


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


@pytest.mark.slow
def test_a2c_cartpole_improves(ray_init):
    algo = (A2CConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=200)
            .training(train_batch_size=1000, lr=2e-3,
                      microbatch_size=0)
            .debugging(seed=5)
            .build())
    best = 0.0
    for _ in range(20):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
        if best >= 60:
            break
    algo.stop()
    # Random CartPole is ~22; A2C at this budget clearly improves.
    assert best >= 60, f"A2C failed to improve (best={best})"


@pytest.mark.slow
def test_appo_async_throughput_and_loss(ray_init):
    algo = (APPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=100)
            .training(min_steps_per_iteration=500)
            .build())
    first = algo.train()
    second = algo.train()
    assert second["timesteps_total"] > first["timesteps_total"] > 0
    assert second["info"]["num_batches_trained"] > 0
    assert np.isfinite(
        second["info"]["learner"].get("total_loss", np.inf))
    algo.stop()


@pytest.mark.slow
def test_es_cartpole_improves(ray_init):
    algo = (ESConfig()
            .environment("CartPole-v1")
            .training(pop_size=12, sigma=0.1, lr=0.1,
                      fcnet_hiddens=(16,), max_episode_steps=200)
            .debugging(seed=1)
            .build())
    best = 0.0
    for _ in range(12):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
        if best >= 80:
            break
    algo.stop()
    assert best >= 80, f"ES failed to improve (best={best})"
    assert r["timesteps_total"] > 0


def _expert_cartpole_data(n_steps: int, seed: int = 0):
    """Heuristic expert: push the cart toward the falling pole — scores
    ~200 on CartPole-v1, far above the ~22 random baseline."""
    import gymnasium as gym
    env = gym.make("CartPole-v1")
    obs, _ = env.reset(seed=seed)
    rows = {"obs": [], "actions": [], "rewards": [], "dones": []}
    for _ in range(n_steps):
        action = 1 if (obs[2] + 0.5 * obs[3]) > 0 else 0
        rows["obs"].append(obs)
        rows["actions"].append(action)
        obs, reward, terminated, truncated, _ = env.step(action)
        rows["rewards"].append(float(reward))
        rows["dones"].append(bool(terminated or truncated))
        if terminated or truncated:
            obs, _ = env.reset()
    env.close()
    return {"obs": np.asarray(rows["obs"], np.float32),
            "actions": np.asarray(rows["actions"], np.int32),
            "rewards": np.asarray(rows["rewards"], np.float32),
            "dones": np.asarray(rows["dones"], np.bool_)}


@pytest.mark.slow
def test_bc_clones_expert(ray_init):
    data = _expert_cartpole_data(3000)
    algo = (BCConfig()
            .environment("CartPole-v1")
            .offline_data(data)
            .training(num_sgd_iter=10, lr=1e-3, evaluation_steps=600)
            .debugging(seed=2)
            .build())
    best = 0.0
    for _ in range(5):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
    algo.stop()
    # The clone should far exceed the ~22 random baseline.
    assert best >= 100, f"BC failed to clone the expert (best={best})"


def test_sharded_learner_matches_single_chip():
    """learner_dp shards SGD minibatches over a dp mesh; the math must
    equal the single-device learner exactly (grad psum == full-batch
    mean)."""
    from ray_tpu.rllib.policy.jax_policy import JaxPolicy

    def make_batch(n=64, obs_dim=4, seed=0):
        rng = np.random.RandomState(seed)
        return {
            "obs": rng.randn(n, obs_dim).astype(np.float32),
            "actions": rng.randint(0, 2, n).astype(np.int32),
            "action_logp": (-0.7 * np.ones(n)).astype(np.float32),
            "advantages": rng.randn(n).astype(np.float32),
            "value_targets": rng.randn(n).astype(np.float32),
        }

    cfg = {"lr": 1e-2, "seed": 3, "fcnet_hiddens": (16,)}
    single = JaxPolicy(4, 2, dict(cfg))
    sharded = JaxPolicy(4, 2, dict(cfg, learner_dp=4))
    from ray_tpu.rllib.policy.sample_batch import SampleBatch
    for i in range(3):
        b = SampleBatch(make_batch(seed=i))
        s1 = single.learn_on_batch(b)
        s2 = sharded.learn_on_batch(b)
        assert s1["total_loss"] == pytest.approx(s2["total_loss"],
                                                 rel=1e-4)
    import jax
    for a, b in zip(jax.tree_util.tree_leaves(single.params),
                    jax.tree_util.tree_leaves(sharded.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=1e-4, atol=1e-5)


@pytest.mark.slow
def test_sac_cartpole_improves(ray_init):
    algo = (SACConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=0, rollout_fragment_length=200)
            .training(train_batch_size=500, learning_starts=500,
                      num_sgd_steps=64, lr=3e-3)
            .debugging(seed=9)
            .build())
    best = 0.0
    for _ in range(10):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
        if best >= 40:
            break
    algo.stop()
    assert np.isfinite(r["info"]["learner"].get("total_loss", np.nan))
    # Random CartPole is ~22; soft-Q learning clearly improves within the
    # step budget (the strict >=150 learning-regression bar is PPO's;
    # measured curve: ~38 by iter 8, entropy pulled to its target).
    assert best >= 40, f"SAC failed to improve (best={best})"


@pytest.mark.slow
def test_sac_continuous_pendulum(ray_init):
    """Continuous-action SAC: tanh-Gaussian policy on Pendulum-v1.
    Asserts mechanics (bounded actions, finite losses, temperature
    adaptation, reward not degenerate) within a small step budget."""
    algo = (SACConfig()
            .environment("Pendulum-v1")
            .rollouts(num_rollout_workers=0, rollout_fragment_length=200)
            .training(train_batch_size=400, learning_starts=400,
                      num_sgd_steps=40, lr=1e-3)
            .debugging(seed=3)
            .build())
    worker = algo.workers.local_worker
    assert not worker._discrete
    batch = worker.sample(64)
    acts = batch["actions"]
    assert acts.dtype == np.float32 and acts.shape[1] == 1
    assert np.all(acts >= -2.0 - 1e-5) and np.all(acts <= 2.0 + 1e-5)
    alpha0 = None
    for _ in range(4):
        r = algo.train()
        stats = r["info"]["learner"]
        if stats:
            assert np.isfinite(stats["total_loss"])
            if alpha0 is None:
                alpha0 = stats["alpha"]
    assert stats, "learner never ran"
    # The temperature optimizer actually moved alpha from its first
    # recorded value.
    assert abs(stats["alpha"] - alpha0) > 1e-6
    # Pendulum rewards are negative; a degenerate policy pegs ~-1600+.
    assert r["episode_reward_mean"] > -1650
    algo.stop()


def test_marwil_weighted_imitation(ray_init):
    data = _expert_cartpole_data(2000, seed=3)
    algo = (MARWILConfig()
            .environment("CartPole-v1")
            .offline_data(data)
            .training(beta=1.0, num_sgd_iter=10, lr=1e-3,
                      evaluation_steps=400)
            .debugging(seed=4)
            .build())
    r = algo.train()
    stats = r["info"]["learner"]
    assert np.isfinite(stats["total_loss"])
    assert stats["mean_weight"] > 0
    assert r["num_offline_steps_trained"] == 2000
    algo.stop()


@pytest.mark.slow
def test_pg_cartpole_improves(ray_init):
    algo = (PGConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=2, rollout_fragment_length=200)
            .training(train_batch_size=1000, lr=2e-3)
            .debugging(seed=8)
            .build())
    best = 0.0
    for _ in range(20):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
        if best >= 40:
            break
    algo.stop()
    # Random CartPole is ~22; REINFORCE-with-baseline clearly improves
    # (Monte Carlo advantages are noisier than GAE's, so the bar sits
    # below A2C's; measured ~47 by iter 20 at this seed).
    assert best >= 40, f"PG failed to improve (best={best})"
