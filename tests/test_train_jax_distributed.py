"""The REAL jax.distributed gang path (VERDICT r4 weak #2, SURVEY
hard-part #4): two OS worker processes, coordinator published through
the WorkerGroup wiring (train/jax/config.py JaxBackend.on_start), a
cross-process collective proving federation, then SIGKILL one worker
and verify the restarted gang re-initializes the coordination service
with a fresh coordinator.

Reference contract: python/ray/train/torch/config.py:54
(_setup_torch_process_group) — the reference wires NCCL/gloo process
groups the same way and re-runs the setup on gang restart.

Environment note: the axon sitecustomize hook pre-registers a PJRT
backend in every interpreter it sees PALLAS_AXON_POOL_IPS in; a
process whose backend already exists silently stays single-process
when jax.distributed.initialize later runs.  The fixture scrubs those
vars so gang worker interpreters start clean — exactly what a real
multi-host CPU/TPU pod looks like.
"""

import os
import signal
import threading
import time

import pytest

import ray_tpu
from ray_tpu.cluster_utils import ProcessCluster

TOTAL_STEPS = 5

_AXON_VARS = ("PALLAS_AXON_POOL_IPS", "PALLAS_AXON_REMOTE_COMPILE",
              "AXON_LOOPBACK_RELAY")


@pytest.fixture
def gang_cluster():
    saved = {k: os.environ.pop(k, None)
             for k in _AXON_VARS + ("JAX_PLATFORMS",)}
    os.environ["JAX_PLATFORMS"] = "cpu"
    c = ProcessCluster()
    yield c
    c.shutdown()
    os.environ.pop("JAX_PLATFORMS", None)
    for k, v in saved.items():
        if v is not None:
            os.environ[k] = v


def _dist_loop(config):
    import os
    import time

    import jax
    import jax.numpy as jnp
    from jax.experimental import multihost_utils

    from ray_tpu.air import session
    from ray_tpu.air.checkpoint import Checkpoint

    rank = session.get_world_rank()
    # Federation proof: every process sees the whole gang and a
    # cross-process allgather carries BOTH contributions.
    pc = jax.process_count()
    total = float(multihost_utils.process_allgather(
        jnp.ones(1) * (jax.process_index() + 1)).sum())
    ckpt = session.get_checkpoint()
    start = (ckpt.to_dict()["step"] + 1) if ckpt is not None else 0
    with open(os.path.join(config["dir"], f"starts_r{rank}"), "a") as f:
        f.write(f"{os.getpid()}:{pc}:{total}:{start}\n")
    for step in range(start, TOTAL_STEPS):
        time.sleep(0.4)
        session.report({"step": step, "gang_total": total},
                       checkpoint=Checkpoint.from_dict({"step": step}))


@pytest.mark.slow
def test_jax_distributed_gang_restart(gang_cluster, tmp_path):
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train import DataParallelTrainer, JaxConfig

    c = gang_cluster
    c.add_node(num_cpus=5)
    assert c.wait_for_nodes(1)
    c.connect()

    trainer = DataParallelTrainer(
        _dist_loop,
        train_loop_config={"dir": str(tmp_path)},
        backend_config=JaxConfig(use_distributed=True),
        scaling_config=ScalingConfig(num_workers=2,
                                     resources_per_worker={"CPU": 1}))
    out: dict = {}

    def _fit():
        try:
            out["result"] = trainer.fit()
        except BaseException as e:
            out["error"] = e

    t = threading.Thread(target=_fit, daemon=True)
    t.start()

    # Wait for rank 1's first federated start, then SIGKILL it mid-run.
    starts1 = os.path.join(str(tmp_path), "starts_r1")
    deadline = time.monotonic() + 240
    while time.monotonic() < deadline and not os.path.exists(starts1):
        time.sleep(0.3)
    assert os.path.exists(starts1), "rank 1 never started"
    victim_pid = int(open(starts1).read().splitlines()[0].split(":")[0])
    time.sleep(1.2)
    os.kill(victim_pid, signal.SIGKILL)

    t.join(timeout=300)
    assert not t.is_alive(), "fit() hung after gang worker death"
    assert "error" not in out, f"fit failed: {out.get('error')}"
    assert out["result"].metrics["step"] == TOTAL_STEPS - 1

    # EVERY incarnation of EVERY rank ran with a federated gang: the
    # coordination service came up for the first gang AND again for the
    # restarted one (fresh coordinator port, fresh processes).
    incarnations = 0
    for rank in (0, 1):
        lines = open(os.path.join(str(tmp_path),
                                  f"starts_r{rank}")).read().splitlines()
        for line in lines:
            _pid, pc, total, _start = line.split(":")
            assert int(pc) == 2, f"rank {rank} not federated: {line}"
            assert float(total) == 3.0, f"bad allgather: {line}"
        incarnations += len(lines)
    lines1 = open(starts1).read().splitlines()
    assert len(lines1) >= 2, f"no gang restart recorded: {lines1}"
    # The restarted rank 1 is a NEW process that re-initialized.
    assert lines1[1].split(":")[0] != lines1[0].split(":")[0]
    # And it resumed from the session checkpoint, not from scratch.
    assert int(lines1[1].split(":")[3]) > 0
