"""`rllib train` CLI + tuned_examples battery (reference: rllib/train.py
and tuned_examples/ replayed in CI)."""

import json
import os
import subprocess
import sys

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
EXAMPLES = os.path.join(REPO, "ray_tpu", "rllib", "tuned_examples")


def _run_cli(*argv, timeout=600):
    env = dict(os.environ, JAX_PLATFORMS="cpu",
               RT_DISABLE_TPU_DETECTION="1")
    return subprocess.run(
        [sys.executable, "-m", "ray_tpu.rllib.train", "-q", *argv],
        cwd=REPO, env=env, capture_output=True, text=True,
        timeout=timeout)


def test_unknown_algorithm_lists_available():
    r = _run_cli("--run", "NotAnAlgo", timeout=120)
    assert r.returncode != 0
    assert "PPO" in (r.stdout + r.stderr)


def test_tuned_example_league_passes():
    """The fastest tuned example end-to-end: the league reaches its
    exploitability bar and the CLI exits 0."""
    r = _run_cli("-f", os.path.join(EXAMPLES, "rps-league.json"))
    assert r.returncode == 0, r.stdout + r.stderr
    assert "PASSED" in r.stdout


def test_unmet_bar_fails(tmp_path):
    spec = {"run": "AlphaStar",
            "config": {"games_per_step": 64},
            "stop": {"episode_reward_mean": 1.0,  # unreachable (> 0 max)
                     "training_iteration": 2}}
    p = tmp_path / "impossible.json"
    p.write_text(json.dumps(spec))
    r = _run_cli("-f", str(p), timeout=300)
    assert r.returncode == 1
    assert "FAILED" in r.stdout


@pytest.mark.slow
def test_tuned_example_cartpole_dqn_passes():
    r = _run_cli("-f", os.path.join(EXAMPLES, "cartpole-dqn.json"))
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
