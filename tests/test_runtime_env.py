"""Runtime environments: env_vars, working_dir, py_modules
(reference test style: python/ray/tests/test_runtime_env*.py)."""

import os
import tempfile

import pytest

import ray_tpu


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_env_vars_per_task(ray_init):
    @ray_tpu.remote
    def read_env():
        return os.environ.get("MY_RT_FLAG")

    out = ray_tpu.get(read_env.options(
        runtime_env={"env_vars": {"MY_RT_FLAG": "42"}}).remote(),
        timeout=60)
    assert out == "42"


def test_py_modules_ship_code(ray_init):
    pkg_dir = tempfile.mkdtemp(prefix="rt_pymod_")
    mod_dir = os.path.join(pkg_dir, "shipped_mod")
    os.makedirs(mod_dir)
    with open(os.path.join(mod_dir, "__init__.py"), "w") as f:
        f.write("MAGIC = 'from-shipped-module'\n")

    @ray_tpu.remote
    def use_module():
        import shipped_mod
        return shipped_mod.MAGIC

    out = ray_tpu.get(use_module.options(
        runtime_env={"py_modules": [pkg_dir]}).remote(), timeout=60)
    assert out == "from-shipped-module"


def test_working_dir_files_visible(ray_init):
    wd = tempfile.mkdtemp(prefix="rt_wd_")
    with open(os.path.join(wd, "data.txt"), "w") as f:
        f.write("payload-123")

    @ray_tpu.remote
    def read_file():
        with open("data.txt") as f:
            return f.read()

    out = ray_tpu.get(read_file.options(
        runtime_env={"working_dir": wd}).remote(), timeout=60)
    assert out == "payload-123"


def test_runtime_env_on_actor(ray_init):
    @ray_tpu.remote
    class EnvActor:
        def flag(self):
            return os.environ.get("ACTOR_RT_FLAG")

    a = EnvActor.options(
        runtime_env={"env_vars": {"ACTOR_RT_FLAG": "on"}}).remote()
    assert ray_tpu.get(a.flag.remote(), timeout=60) == "on"


def test_unsupported_field_rejected(ray_init):
    from ray_tpu.runtime_env import RuntimeEnv
    with pytest.raises(ValueError):
        RuntimeEnv(conda={"dependencies": ["x"]})


def _make_pkg(tmpdir, version):
    """A tiny installable package whose module reports its version."""
    root = os.path.join(tmpdir, f"rtenvtestpkg_{version.replace('.', '_')}")
    os.makedirs(root, exist_ok=True)
    with open(os.path.join(root, "setup.py"), "w") as f:
        f.write(
            "from setuptools import setup\n"
            f"setup(name='rtenvtestpkg', version='{version}', "
            "py_modules=['rtenvtestpkg'])\n")
    with open(os.path.join(root, "rtenvtestpkg.py"), "w") as f:
        f.write(f"VERSION = '{version}'\n")
    return root


@pytest.mark.slow
def test_pip_venv_isolation(ray_init, tmp_path):
    """Two tasks in ONE cluster import DIFFERENT versions of the same
    package (reference: _private/runtime_env/pip.py — spec-hashed cached
    venvs; each pip task runs on a worker dedicated to its venv).  Local
    directory installs keep the test network-free."""
    v1 = _make_pkg(str(tmp_path), "1.0")
    v2 = _make_pkg(str(tmp_path), "2.0")

    @ray_tpu.remote
    def which_version():
        import rtenvtestpkg
        return rtenvtestpkg.VERSION

    r1 = which_version.options(runtime_env={"pip": [v1]}).remote()
    r2 = which_version.options(runtime_env={"pip": [v2]}).remote()
    assert ray_tpu.get(r1, timeout=300) == "1.0"
    assert ray_tpu.get(r2, timeout=300) == "2.0"

    # The base interpreter must NOT see the package at all.
    @ray_tpu.remote
    def base_has_pkg():
        try:
            import rtenvtestpkg  # noqa: F401
            return True
        except ImportError:
            return False

    assert ray_tpu.get(base_has_pkg.remote(), timeout=60) is False


def test_conda_key_canonical():
    """A conda env named 'myenv' and the same env given by its absolute
    prefix must hash to ONE worker-pool key (ADVICE r4: duplicate pools
    for one environment defeat warm-worker reuse).  The key is purely
    syntactic — no filesystem or CONDA_* lookups — so the driver and
    every raylet compute the SAME key even when their conda installs
    live at different roots."""
    from ray_tpu.runtime_env import worker_env_key
    by_name = worker_env_key({"conda": "myenv"})
    by_prefix = worker_env_key({"conda": "/opt/conda/envs/myenv"})
    by_other_root = worker_env_key({"conda": "/home/u/miniconda3/envs/myenv"})
    assert by_name == by_prefix == by_other_root
    assert by_name != worker_env_key({"conda": "otherenv"})
    # Non-standard prefixes (not <root>/envs/<name>) key on the path.
    assert worker_env_key({"conda": "/custom/envdir"}) \
        != worker_env_key({"conda": "/other/envdir"})
