"""Object spilling: the store moves primary copies to disk under memory
pressure and restores them on access (reference test style:
python/ray/tests/test_object_spilling.py)."""

import pytest
import numpy as np

import ray_tpu


def test_put_beyond_capacity_spills_and_restores(ray_start_cluster):
    cluster = ray_start_cluster
    # 40MB store; each object is ~8MB -> 10 objects need ~80MB.
    cluster.add_node(num_cpus=1, object_store_memory=40 * 1024 * 1024)
    cluster.connect()

    arrays = [np.full((1024, 1024), i, dtype=np.float64)
              for i in range(10)]
    refs = [ray_tpu.put(a) for a in arrays]
    # Everything must still be readable: earlier objects were spilled to
    # disk and come back on get.
    for i, (a, r) in enumerate(zip(arrays, refs)):
        np.testing.assert_array_equal(ray_tpu.get(r, timeout=120), a)
    # And again in reverse order (restores can evict/spill others).
    for a, r in zip(reversed(arrays), reversed(refs)):
        np.testing.assert_array_equal(ray_tpu.get(r, timeout=120), a)


@pytest.mark.slow
def test_spilled_object_served_to_remote_node(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, resources={"head": 1},
                     object_store_memory=40 * 1024 * 1024)
    cluster.add_node(num_cpus=1, resources={"away": 1},
                     object_store_memory=64 * 1024 * 1024)
    cluster.wait_for_nodes(2)
    cluster.connect()

    refs = [ray_tpu.put(np.full((1024, 1024), i)) for i in range(10)]

    @ray_tpu.remote(resources={"away": 1})
    def total(x):
        return float(x[0, 0])

    # The early refs are spilled on the head node by the time the remote
    # task pulls them; chunks are served from the spill files.
    outs = ray_tpu.get([total.remote(r) for r in refs], timeout=180)
    assert outs == [float(i) for i in range(10)]
