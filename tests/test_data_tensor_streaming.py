"""Tensor extension columns + streaming execution (reference:
air/util/tensor_extensions/arrow.py, data/_internal/pipeline_executor).
"""

import numpy as np
import pyarrow as pa
import pytest

import ray_tpu
from ray_tpu import data as rd
from ray_tpu.air.util.tensor_extensions import (ArrowTensorArray,
                                                ArrowTensorType,
                                                is_tensor_type)
from ray_tpu.data.block import BlockAccessor


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_tensor_array_numpy_roundtrip():
    arr = np.arange(24, dtype=np.float32).reshape(4, 2, 3)
    ext = ArrowTensorArray.from_numpy(arr)
    assert isinstance(ext.type, ArrowTensorType)
    assert ext.type.shape == (2, 3)
    assert len(ext) == 4
    np.testing.assert_array_equal(ext.to_numpy(), arr)


def test_tensor_columns_in_arrow_blocks():
    """dict block with an image-shaped column -> arrow table with a
    tensor extension column -> numpy batch round trip, with slicing."""
    block = {"img": np.random.RandomState(0).rand(10, 4, 4)
             .astype(np.float32),
             "label": np.arange(10)}
    table = BlockAccessor(block).to_arrow()
    assert is_tensor_type(table.column("img").type)
    out = BlockAccessor(table).to_numpy()
    np.testing.assert_array_equal(out["img"], block["img"])
    np.testing.assert_array_equal(out["label"], block["label"])
    # Slicing an arrow block keeps tensor columns intact.
    sl = BlockAccessor(table).slice(2, 5)
    got = BlockAccessor(sl).to_numpy("img")
    np.testing.assert_array_equal(got, block["img"][2:5])
    # Pandas view: object column of per-row ndarrays.
    df = BlockAccessor(table).to_pandas()
    assert df["img"].iloc[3].shape == (4, 4)


def test_tensor_parquet_roundtrip(ray_init, tmp_path):
    """Tensor columns survive a Parquet write/read (the registered
    extension type reconstructs from file metadata)."""
    ds = rd.range_tensor(32, shape=(3, 2), parallelism=4)
    ds = ds.map_batches(lambda b: {"data": b["data"] * 2.0},
                        batch_format="numpy")
    path = str(tmp_path / "tensors")
    ds.write_parquet(path)
    back = rd.read_parquet(path)
    batches = list(back.iter_batches(batch_size=32,
                                     batch_format="numpy"))
    data = np.concatenate([b["data"] for b in batches])
    assert data.shape == (32, 3, 2)
    expect = np.sort(
        (np.arange(32, dtype=np.float64) * 2.0))
    np.testing.assert_allclose(np.sort(data[:, 0, 0]), expect)


def test_streaming_iter_batches_bounded_window(ray_init, tmp_path):
    """iter_batches over a lazy map chain streams with BOUNDED
    submission: when the first batch is consumed, at most
    max_in_flight + 1 transform tasks have ever been launched (the
    whole dataset has NOT been materialized), yet by the end every
    block was transformed exactly once and arrived in order."""
    marker_dir = str(tmp_path)
    ds = rd.range(64, parallelism=16)

    def marking_double(batch, marker_dir=marker_dir):
        import os
        import uuid
        open(os.path.join(marker_dir, f"started-{uuid.uuid4().hex}"),
             "w").close()
        return [x * 2 for x in batch]

    import os

    ds = ds.map_batches(marking_double, batch_format=None)
    it = ds.iter_batches(batch_size=4, batch_format=None,
                         max_in_flight=4)
    first = next(it)
    started_at_first = len(os.listdir(marker_dir))
    rest = list(it)
    values = list(first) + [x for b in rest for x in b]
    assert values == [x * 2 for x in range(64)]
    assert len(os.listdir(marker_dir)) == 16  # every block, exactly once
    assert started_at_first <= 5, (
        f"{started_at_first} transform tasks had started when the "
        "first batch was consumed — the window (4) is not bounding "
        "submission")


def test_streaming_does_not_materialize_plan(ray_init):
    """Streaming consumption leaves the lazy plan in place (no hidden
    full materialization), while count() still materializes."""
    ds = rd.range(20, parallelism=4).map_batches(
        lambda b: [x + 1 for x in b], batch_format=None)
    assert len(ds._stages) == 1
    total = 0
    for batch in ds.iter_batches(batch_size=5, batch_format=None):
        total += sum(batch)
    assert total == sum(range(1, 21))
    assert len(ds._stages) == 1  # still lazy after streaming
    assert ds.count() == 20      # materializing path still works
    assert len(ds._stages) == 0
