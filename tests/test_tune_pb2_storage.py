"""PB2 scheduler + URI-pluggable checkpoint/experiment storage.

Reference: tune/schedulers/pb2.py and tune/syncer.py."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import tune
from ray_tpu.air.config import RunConfig
from ray_tpu.tune.storage import MemStorage, get_storage, register_storage


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def _quadratic(config):
    """score peaks at lr=0.6: PB2's bandit must steer lr toward it."""
    lr = config["lr"]
    for i in range(12):
        tune.report({"score": 10 - (lr - 0.6) ** 2 * 10 + 0.01 * i})


@pytest.mark.slow
def test_pb2_beats_random_on_toy_surface(ray_init):
    # PB2 population: exploits clone top performers and the GP proposes
    # their new lr inside the bounds.
    sched = tune.PB2(metric="score", mode="max",
                     perturbation_interval=2,
                     hyperparam_bounds={"lr": (0.0, 1.0)}, seed=7)
    tuner = tune.Tuner(
        _quadratic,
        param_space={"lr": tune.uniform(0.0, 0.05)},  # bad start corner
        tune_config=tune.TuneConfig(num_samples=6, metric="score",
                                    mode="max", scheduler=sched),
    )
    results = tuner.fit()
    best = results.get_best_result(metric="score", mode="max")
    # Random inside the start corner caps at 10 - 0.3^2... ≈ 6.99; an
    # exploit+GP proposal must have moved lr into better territory.
    assert best.metrics["score"] > 7.5, best.metrics
    # The GP actually observed data and proposed in-bounds values.
    assert all(0.0 <= t.config["lr"] <= 1.0 for t in results)


def test_pb2_explore_uses_gp_after_observations():
    sched = tune.PB2(metric="score", mode="max",
                     hyperparam_bounds={"lr": (0.0, 1.0)}, seed=3)

    class _T:
        def __init__(self, tid, lr):
            self.trial_id = tid
            self.config = {"lr": lr}

    # Feed observations: higher lr -> bigger score deltas.
    for step in range(1, 6):
        for i, lr in enumerate((0.1, 0.5, 0.9)):
            t = _T(f"t{i}", lr)
            sched.on_trial_result(
                t, {"score": step * (1 + lr), "training_iteration": step})
    out = [sched.explore({"lr": 0.1})["lr"] for _ in range(8)]
    assert all(0.0 <= v <= 1.0 for v in out)
    # GP fitted on >=4 observations: proposals should favor the
    # high-delta region more often than uniform would.
    assert np.mean(out) > 0.35, out


def test_storage_scheme_registry_and_mem_backend():
    st = get_storage("mem://bucket-a")
    st.write_bytes("x/y.bin", b"abc")
    assert st.exists("x/y.bin")
    assert get_storage("mem://bucket-a").read_bytes("x/y.bin") == b"abc"

    class _Custom(MemStorage):
        pass

    register_storage("customfs", lambda rest: _Custom("c-" + rest))
    assert isinstance(get_storage("customfs://z"), _Custom)
    with pytest.raises(ValueError):
        get_storage("unknownscheme://z")


def _trainable_with_ckpt(config):
    for i in range(5):
        tune.report({"score": config["a"] * (i + 1)})


@pytest.mark.slow
def test_experiment_sync_and_resume_via_storage(ray_init):
    """Run an experiment against mem:// storage, then resume a FRESH
    runner from the synced state alone (the local scratch dir of the
    first run is NOT reused)."""
    uri = "mem://tune-sync-test"
    name = "exp_sync"
    tuner = tune.Tuner(
        _trainable_with_ckpt,
        param_space={"a": tune.grid_search([1.0, 2.0])},
        run_config=RunConfig(storage_path=uri, name=name),
        tune_config=tune.TuneConfig(metric="score", mode="max"),
    )
    results = tuner.fit()
    assert len(list(results)) == 2
    st = get_storage(uri)
    assert st.exists(f"{name}/experiment_state.pkl")

    # Fresh runner, same URI: restore sees both trials as TERMINATED.
    from ray_tpu.tune.execution.trial_runner import TrialRunner
    from ray_tpu.tune.trainable import wrap_function
    runner = TrialRunner(
        wrap_function(_trainable_with_ckpt),
        run_config=RunConfig(storage_path=uri, name=name),
        metric="score", mode="max")
    assert runner.restore_experiment_state()
    assert len(runner.trials) == 2
    assert all(t.status == "TERMINATED" for t in runner.trials)
