"""Raylet TPU chip-slot accounting: fractional leases must not leave
float residue that blocks whole-chip grants (unit-level, no cluster)."""

from ray_tpu._private.raylet import Lease, Raylet


def _bare_raylet(num_chips: int) -> Raylet:
    r = Raylet.__new__(Raylet)
    r._tpu_slots = {i: 0.0 for i in range(num_chips)}
    return r


def _lease(amount: float) -> Lease:
    return Lease("l", None, {"TPU": amount}, None)


def test_nonbinary_fraction_release_snaps_to_zero():
    r = _bare_raylet(1)
    leases = [_lease(0.3) for _ in range(3)]
    for lease in leases:
        assert r._alloc_tpu_ids(lease) == [0]
    # 0.3 + 0.3 + 0.3 != 0.9 exactly in floats; after releasing all
    # three the slot must read exactly 0.0 again.
    for lease in leases:
        r._free_tpu_ids(lease)
    assert r._tpu_slots[0] == 0.0


def test_whole_chip_grant_after_fractional_churn():
    r = _bare_raylet(2)
    # Churn chip 0 with non-binary fractions, then demand both chips.
    for _ in range(5):
        fr = _lease(0.3)
        assert r._alloc_tpu_ids(fr), "fractional grant failed"
        r._free_tpu_ids(fr)
    whole = _lease(2.0)
    assert sorted(r._alloc_tpu_ids(whole)) == [0, 1]
    r._free_tpu_ids(whole)
    assert all(v == 0.0 for v in r._tpu_slots.values())


def test_fractions_binpack_and_keep_whole_chips_free():
    r = _bare_raylet(2)
    a, b = _lease(0.5), _lease(0.5)
    assert r._alloc_tpu_ids(a) == r._alloc_tpu_ids(b)  # share one chip
    whole = _lease(1.0)
    assert len(r._alloc_tpu_ids(whole)) == 1  # other chip still whole
