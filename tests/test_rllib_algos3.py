"""Round-4 algorithm additions, part 1: ARS, CRR, SlateQ, DT
(reference: rllib/algorithms/{ars,crr,slateq,dt}/tests)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import (ARSConfig, CRRConfig, DTConfig, SlateQConfig)


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


@pytest.mark.slow
def test_ars_linear_cartpole(ray_init):
    """ARS with a LINEAR policy solves CartPole (the result the ARS
    paper is known for); top-direction selection + return-std scaling +
    obs normalization are all exercised."""
    algo = (ARSConfig()
            .environment("CartPole-v1")
            .training(num_deltas=16, num_top=8, sigma=0.1, lr=0.05)
            .debugging(seed=3)
            .build())
    best = 0.0
    for _ in range(30):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
        if best >= 150:
            break
    assert algo.filter.n > 0  # obs filter actually accumulated
    algo.stop()
    assert best >= 150, f"ARS failed to improve (best={best})"


def _pendulum_random_data(n=4000, seed=0):
    import gymnasium as gym
    rng = np.random.RandomState(seed)
    env = gym.make("Pendulum-v1")
    rows = {"obs": [], "actions": [], "rewards": [], "dones": [],
            "new_obs": []}
    obs, _ = env.reset(seed=seed)
    for _ in range(n):
        a = rng.uniform(-2.0, 2.0, size=(1,)).astype(np.float32)
        obs2, r, term, trunc, _ = env.step(a)
        rows["obs"].append(obs)
        rows["actions"].append(a)
        rows["rewards"].append(r)
        rows["dones"].append(term)
        rows["new_obs"].append(obs2)
        obs = obs2
        if term or trunc:
            obs, _ = env.reset()
    env.close()
    return {k: np.asarray(v, np.float32 if k != "dones" else np.bool_)
            for k, v in rows.items()}


def test_crr_advantage_weighted_regression(ray_init):
    """CRR on offline Pendulum data: losses finite, the binary
    advantage weights are a proper fraction of the batch, and after
    training the actor's own action beats the average dataset action
    under the learned critic (policy improvement over behavior)."""
    import jax.numpy as jnp
    data = _pendulum_random_data()
    algo = (CRRConfig()
            .environment("Pendulum-v1")
            .offline_data(data)
            .training(num_sgd_steps=120, sgd_batch_size=256,
                      crr_weight_type="bin")
            .debugging(seed=1)
            .build())
    for _ in range(3):
        r = algo.train()
    stats = r["info"]["learner"]
    assert np.isfinite(stats["q_loss"])
    assert np.isfinite(stats["actor_loss"])
    assert 0.0 < stats["mean_weight"] < 1.0, (
        "binary advantage weights should select a strict subset "
        f"(got {stats['mean_weight']})")
    policy = algo.workers.local_worker.policy
    obs = jnp.asarray(data["obs"][:512])
    a_data = policy._normalize(jnp.asarray(data["actions"][:512]))
    a_pi, _, _ = policy.compute_actions(np.asarray(obs))
    a_pi = policy._normalize(jnp.asarray(a_pi))
    q_pi = np.asarray(jnp.minimum(*policy.q.apply(policy.q_params, obs,
                                                  a_pi)))
    q_data = np.asarray(jnp.minimum(*policy.q.apply(policy.q_params,
                                                    obs, a_data)))
    algo.stop()
    assert q_pi.mean() > q_data.mean(), (
        f"CRR actor did not improve on behavior: Q(pi)={q_pi.mean():.2f}"
        f" <= Q(data)={q_data.mean():.2f}")


@pytest.mark.slow
def test_slateq_beats_random_slates():
    """SlateQ on the toy interest-evolution env: learned slates earn
    materially more engagement per session than random slates."""
    algo = (SlateQConfig()
            .environment(env_config={"num_candidates": 8,
                                     "slate_size": 2})
            .training(episodes_per_iter=8, num_sgd_steps=25,
                      epsilon_anneal_iters=8)
            .debugging(seed=0)
            .build())
    for _ in range(14):
        r = algo.train()
    learned = r["episode_reward_mean"]

    # Random-slate baseline on the same env distribution.
    from ray_tpu.rllib.env.recsim import InterestEvolutionRecSimEnv
    env = InterestEvolutionRecSimEnv({"num_candidates": 8,
                                      "slate_size": 2, "seed": 123})
    rng = np.random.RandomState(7)
    rand_rets = []
    for ep in range(40):
        env.reset(seed=1000 + ep)
        total, done = 0.0, False
        while not done:
            slate = rng.choice(8, 2, replace=False)
            _, rew, done, _, _ = env.step(slate)
            total += rew
        rand_rets.append(total)
    random_mean = float(np.mean(rand_rets))
    algo.stop()
    assert learned > random_mean * 1.25, (
        f"SlateQ ({learned:.2f}) should beat random slates "
        f"({random_mean:.2f}) by >=25%")


def _cartpole_mixed_episodes(n_expert=30, n_random=30, seed=0):
    """Offline CartPole: heuristic 'expert' (angle+angvel controller)
    episodes plus random ones — DT must learn to imitate the GOOD
    episodes when conditioned on a high return-to-go."""
    import gymnasium as gym
    rng = np.random.RandomState(seed)
    env = gym.make("CartPole-v1")
    episodes = []
    for i in range(n_expert + n_random):
        expert = i < n_expert
        obs, _ = env.reset(seed=seed * 1000 + i)
        rows = {"obs": [], "actions": [], "rewards": []}
        for _ in range(200):
            if expert:
                a = int(obs[2] + 0.5 * obs[3] > 0)
            else:
                a = int(rng.randint(2))
            obs2, r, term, trunc, _ = env.step(a)
            rows["obs"].append(obs)
            rows["actions"].append(a)
            rows["rewards"].append(r)
            obs = obs2
            if term or trunc:
                break
        episodes.append({
            "obs": np.asarray(rows["obs"], np.float32),
            "actions": np.asarray(rows["actions"], np.int64),
            "rewards": np.asarray(rows["rewards"], np.float32)})
    env.close()
    return episodes


@pytest.mark.slow
def test_dt_return_conditioned_cartpole():
    """DT trained on mixed-quality offline CartPole reaches near-expert
    return when conditioned on a high target return."""
    episodes = _cartpole_mixed_episodes()
    expert_mean = float(np.mean(
        [e["rewards"].sum() for e in episodes[:30]]))
    algo = (DTConfig()
            .environment("CartPole-v1")
            .offline_data(episodes)
            .training(context_len=20, num_sgd_steps=150,
                      target_return=expert_mean,
                      num_eval_episodes=5, max_episode_steps=200)
            .debugging(seed=0)
            .build())
    best = 0.0
    for _ in range(4):
        r = algo.train()
        best = max(best, r["episode_reward_mean"])
        if best >= 120:
            break
    assert np.isfinite(r["action_nll"])
    algo.stop()
    assert best >= 120, (
        f"DT conditioned on R={expert_mean:.0f} should approach expert "
        f"performance (best={best}, random~20)")
