"""Cross-host proof: one cluster spanning two NETWORK NAMESPACES.

Reference: python/ray/autoscaler/_private/fake_multi_node/test_utils.py
(docker-compose fake multi-node harness).  Here `ip netns` + a veth pair
give each node its own network stack and routable IP, so the
bind-vs-advertise path (`rt start --address ... --node-ip ...`) is
exercised across a real network boundary: loopback of one namespace is
unreachable from the other, so any 127.0.0.1 address leaking into
advertised state breaks these tests immediately."""

import json
import os
import signal
import subprocess
import sys
import time

import pytest

HEAD_NS = "rt_head_ns"
WORKER_NS = "rt_worker_ns"
HEAD_IP = "10.200.77.1"
WORKER_IP = "10.200.77.2"


def _run(argv, timeout=60, check=True, **kw):
    proc = subprocess.run(argv, capture_output=True, text=True,
                          timeout=timeout, **kw)
    if check and proc.returncode != 0:
        raise RuntimeError(f"{argv} failed:\n{proc.stdout}\n{proc.stderr}")
    return proc


def _netns_available() -> bool:
    if os.geteuid() != 0:
        return False
    try:
        _run(["ip", "netns", "add", "rt_probe_ns"])
        _run(["ip", "netns", "del", "rt_probe_ns"])
        return True
    except Exception:
        return False


pytestmark = pytest.mark.skipif(not _netns_available(),
                                reason="needs root + ip netns")


@pytest.fixture(scope="module")
def netns_pair():
    """Two namespaces joined by a veth pair; loopback up in both."""
    for ns in (HEAD_NS, WORKER_NS):
        _run(["ip", "netns", "del", ns], check=False)
    _run(["ip", "netns", "add", HEAD_NS])
    _run(["ip", "netns", "add", WORKER_NS])
    _run(["ip", "link", "add", "rtveth0", "type", "veth",
          "peer", "name", "rtveth1"])
    _run(["ip", "link", "set", "rtveth0", "netns", HEAD_NS])
    _run(["ip", "link", "set", "rtveth1", "netns", WORKER_NS])
    for ns, dev, ip in ((HEAD_NS, "rtveth0", HEAD_IP),
                        (WORKER_NS, "rtveth1", WORKER_IP)):
        _run(["ip", "netns", "exec", ns, "ip", "addr", "add",
              f"{ip}/24", "dev", dev])
        _run(["ip", "netns", "exec", ns, "ip", "link", "set", dev,
              "up"])
        _run(["ip", "netns", "exec", ns, "ip", "link", "set", "lo",
              "up"])
    # Sanity: worker can reach head over the veth (no ping binary in
    # the image — a TCP connect probe is equivalent: ECONNREFUSED means
    # the packet ROUTED and the peer answered with RST).
    probe = _run(_in_ns(WORKER_NS, [sys.executable, "-S", "-c",
                 "import socket,sys\n"
                 "s = socket.socket()\n"
                 "s.settimeout(2)\n"
                 f"rc = s.connect_ex(('{HEAD_IP}', 1))\n"
                 "print('REACH' if rc in (111, 0) else rc)"]),
                 check=False)
    if "REACH" not in probe.stdout:
        pytest.skip(f"veth routing unavailable: {probe.stdout} "
                    f"{probe.stderr}")
    yield
    for ns in (HEAD_NS, WORKER_NS):
        _run(["ip", "netns", "del", ns], check=False)


def _env():
    return dict(os.environ, RT_DISABLE_TPU_DETECTION="1",
                JAX_PLATFORMS="cpu")


def _in_ns(ns, argv):
    return ["ip", "netns", "exec", ns] + argv


@pytest.fixture(scope="module")
def cross_host_cluster(netns_pair):
    """Head in one namespace, worker joining via rt start --address
    with a routable --node-ip in the other."""
    state_file = "/tmp/ray_tpu/started_nodes.json"
    if os.path.exists(state_file):
        os.rename(state_file, state_file + ".bak")
    procs_to_sweep = []
    try:
        up = _run(_in_ns(HEAD_NS, [
            sys.executable, "-m", "ray_tpu.scripts.cli", "start",
            "--head", "--node-ip", HEAD_IP, "--num-cpus", "2"]),
            timeout=180, env=_env(), cwd="/root/repo")
        gcs_line = [ln for ln in up.stdout.splitlines()
                    if "GCS address" in ln][0]
        gcs = gcs_line.split()[-1]
        assert gcs.startswith(HEAD_IP), f"head advertised {gcs}"

        _run(_in_ns(WORKER_NS, [
            sys.executable, "-m", "ray_tpu.scripts.cli", "start",
            "--address", gcs, "--node-ip", WORKER_IP, "--num-cpus", "2",
            "--resources", json.dumps({"side": 2})]),
            timeout=180, env=_env(), cwd="/root/repo")

        with open(state_file) as f:
            entries = json.load(f)
        procs_to_sweep = [pid for e in entries
                          for pid in e["pids"].values()]
        worker_raylet_pid = [
            e["pids"]["raylet"] for e in entries
            if e["raylet_address"].startswith(WORKER_IP)][0]
        yield {"gcs": gcs, "worker_raylet_pid": worker_raylet_pid}
    finally:
        # Re-read the state file: a failure between head and worker
        # start leaves pids recorded there that procs_to_sweep missed.
        try:
            with open(state_file) as f:
                for e in json.load(f):
                    procs_to_sweep += list(e.get("pids", {}).values())
        except (OSError, ValueError):
            pass
        for pid in set(procs_to_sweep):
            try:
                os.kill(pid, signal.SIGKILL)
            except ProcessLookupError:
                pass
        if os.path.exists(state_file):
            os.unlink(state_file)
        if os.path.exists(state_file + ".bak"):
            os.rename(state_file + ".bak", state_file)


def _driver(ns, script, timeout=300):
    return _run(_in_ns(ns, [sys.executable, "-c", script]),
                timeout=timeout, env=_env(), cwd="/root/repo")


@pytest.mark.slow
def test_cross_namespace_tasks_and_objects(cross_host_cluster):
    gcs = cross_host_cluster["gcs"]
    out = _driver(HEAD_NS, f"""
import numpy as np
import ray_tpu
ray_tpu.init(address="{gcs}")

@ray_tpu.remote
def where():
    return ray_tpu.get_runtime_context().get_node_id()

local = ray_tpu.get(where.remote(), timeout=180)
remote = ray_tpu.get(where.options(resources={{"side": 0.1}}).remote(),
                     timeout=180)
assert local != remote, "task did not cross the namespace boundary"

@ray_tpu.remote(resources={{"side": 0.1}})
def make():
    import numpy as np
    return np.arange(500_000, dtype=np.int64)

arr = ray_tpu.get(make.remote(), timeout=180)
assert arr.sum() == 124999750000, arr.sum()
print("CROSS_OK nodes=%d" % sum(1 for n in ray_tpu.nodes() if n["Alive"]))
ray_tpu.shutdown()
""")
    assert "CROSS_OK nodes=2" in out.stdout


def test_cross_namespace_train_e2e(cross_host_cluster):
    """Train gang spanning both namespaces: one rank per node."""
    gcs = cross_host_cluster["gcs"]
    out = _driver(HEAD_NS, f"""
import ray_tpu
from ray_tpu.air import session
from ray_tpu.air.config import ScalingConfig
from ray_tpu.train import DataParallelTrainer, JaxConfig

ray_tpu.init(address="{gcs}")

def loop(config):
    import socket
    from ray_tpu.air import session
    for step in range(3):
        session.report({{"step": step,
                        "host": session.get_world_rank()}})

trainer = DataParallelTrainer(
    loop,
    backend_config=JaxConfig(use_distributed=False),
    scaling_config=ScalingConfig(num_workers=2,
                                 resources_per_worker={{"CPU": 1}}))
result = trainer.fit()
assert result.metrics["step"] == 2
print("TRAIN_OK")
ray_tpu.shutdown()
""", timeout=420)
    assert "TRAIN_OK" in out.stdout


@pytest.mark.slow
def test_cross_namespace_sigkill_worker_node(cross_host_cluster):
    """SIGKILL the other namespace's raylet mid-run: the head detects
    the remote node's death across the network boundary, the dead
    node's exclusive resource becomes infeasible (its actor dies with a
    meaningful error), and the surviving node keeps serving."""
    gcs = cross_host_cluster["gcs"]
    pid = cross_host_cluster["worker_raylet_pid"]
    out = _driver(HEAD_NS, f"""
import os
import signal
import time
import ray_tpu
from ray_tpu.exceptions import ActorDiedError, RayTpuError
ray_tpu.init(address="{gcs}")

@ray_tpu.remote
class Counter:
    def __init__(self):
        self.n = 0
    def bump(self):
        self.n += 1
        return self.n

# Pinned to the worker namespace's node by its exclusive resource.
a = Counter.options(max_restarts=1, max_task_retries=2, num_cpus=0,
                    resources={{"side": 0.1}}).remote()
assert ray_tpu.get(a.bump.remote(), timeout=180) == 1

os.kill({pid}, signal.SIGKILL)  # the worker namespace's raylet
time.sleep(2)
assert not os.path.exists("/proc/{pid}")

# 1. Node death is detected across the namespace boundary.
deadline = time.time() + 120
while time.time() < deadline:
    if sum(1 for x in ray_tpu.nodes() if x["Alive"]) == 1:
        break
    time.sleep(1)
assert sum(1 for x in ray_tpu.nodes() if x["Alive"]) == 1

# 2. The actor's resource died with its node: the restart is
# infeasible and surfaces as ActorDiedError, not a hang.
try:
    ray_tpu.get(a.bump.remote(), timeout=240)
    raise AssertionError("expected ActorDiedError")
except (ActorDiedError, RayTpuError):
    pass

# 3. The surviving node keeps serving generic work.
@ray_tpu.remote
def alive():
    return "ok"

assert ray_tpu.get(alive.remote(), timeout=180) == "ok"
# The lost node's resource is gone from the cluster view.
assert "side" not in ray_tpu.cluster_resources()
print("CHAOS_OK")
ray_tpu.shutdown()
""", timeout=540)
    assert "CHAOS_OK" in out.stdout
