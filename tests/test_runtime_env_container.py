"""Container + conda runtime envs (reference:
_private/runtime_env/{container,conda}.py).

No OCI runtime ships in this image, so the container path is exercised
against a FAKE runtime binary that implements the `run` CLI contract
(parses --rm/--network/-v/-e, provides the image's site-packages, execs
the worker command) — the framework-side plumbing (env-key worker
pooling, command assembly, bind mounts, env forwarding) is identical to
what podman/docker would receive; a real-runtime smoke test is gated on
podman/docker presence.
"""

import json
import os
import shutil
import stat
import sys
import tempfile
import textwrap

import pytest

import ray_tpu
from ray_tpu.runtime_env import RuntimeEnv, env_spec, worker_env_key


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()
    os.environ.pop("RT_CONTAINER_RUNTIME", None)


def _write_fake_runtime(root: str) -> str:
    """A podman-compatible `run` implementation for tests: applies -e,
    prepends the image's site-packages to PYTHONPATH, records its argv,
    and execs the worker command on the host."""
    path = os.path.join(root, "fakepodman")
    with open(path, "w") as f:
        f.write(textwrap.dedent(f"""\
            #!{sys.executable}
            import json, os, sys
            root = {root!r}
            argv = sys.argv[1:]
            with open(os.path.join(root, "invocations.jsonl"), "a") as f:
                f.write(json.dumps(argv) + "\\n")
            assert argv[0] == "run", argv
            i = 1
            mounts, image = [], None
            while i < len(argv):
                a = argv[i]
                if a in ("--rm", "--network=host"):
                    i += 1
                elif a == "-v":
                    mounts.append(argv[i + 1]); i += 2
                elif a == "--name":
                    i += 2
                elif a == "-e":
                    k, _, v = argv[i + 1].partition("=")
                    os.environ[k] = v; i += 2
                else:
                    image = a
                    inner = argv[i + 1:]
                    break
            site = os.path.join(root, "images", image, "site-packages")
            os.environ["PYTHONPATH"] = site + ":" + \\
                os.environ.get("PYTHONPATH", "")
            if inner[0] == "python":
                inner[0] = {sys.executable!r}
            os.execvpe(inner[0], inner, os.environ)
            """))
    os.chmod(path, os.stat(path).st_mode | stat.S_IEXEC)
    return path


def test_runtime_env_validation():
    with pytest.raises(ValueError, match="mutually exclusive"):
        RuntimeEnv(pip=["x"], container={"image": "img"})
    with pytest.raises(ValueError, match="image"):
        RuntimeEnv(container={"run_options": []})
    with pytest.raises(ValueError, match="existing env NAME"):
        RuntimeEnv(conda={"dependencies": ["x"]})
    # Distinct environments -> distinct worker-pool keys.
    k1 = worker_env_key({"container": {"image": "a", "run_options": []}})
    k2 = worker_env_key({"container": {"image": "b", "run_options": []}})
    k3 = worker_env_key({"conda": "envx"})
    assert len({k1, k2, k3, ""}) == 4
    assert env_spec({"env_vars": {"A": "1"}}) is None
    assert env_spec({"conda": "envx"}) == {"conda": "envx"}


def test_container_worker_runs_in_image(ray_init):
    """A task with a container runtime_env runs on a worker inside the
    image: it can import a package that exists ONLY in the image, and
    the runtime invocation carries the session-dir bind mount (shm
    store stays shared) and host networking (raylet reachable)."""
    root = tempfile.mkdtemp(prefix="rt_fake_oci_")
    runtime = _write_fake_runtime(root)
    site = os.path.join(root, "images", "testimg", "site-packages")
    os.makedirs(site)
    with open(os.path.join(site, "only_in_image.py"), "w") as f:
        f.write("MARKER = 'from-image'\n")
    os.environ["RT_CONTAINER_RUNTIME"] = runtime

    @ray_tpu.remote
    def probe():
        import only_in_image
        return only_in_image.MARKER, os.getpid()

    @ray_tpu.remote
    def base_probe():
        try:
            import only_in_image  # noqa: F401
            return "leaked"
        except ImportError:
            return "isolated"

    marker, _pid = ray_tpu.get(probe.options(
        runtime_env={"container": {"image": "testimg"}}).remote(),
        timeout=120)
    assert marker == "from-image"
    # Base-interpreter workers must NOT see the image's packages.
    assert ray_tpu.get(base_probe.remote(), timeout=120) == "isolated"

    with open(os.path.join(root, "invocations.jsonl")) as f:
        argv = json.loads(f.readline())
    assert "--network=host" in argv
    mounts = [argv[i + 1] for i, a in enumerate(argv) if a == "-v"]
    from ray_tpu._private import api as api_mod
    session_dir = api_mod._head_node.session_dir
    assert any(m.startswith(f"{session_dir}:") for m in mounts), mounts
    assert "testimg" in argv
    shutil.rmtree(root, ignore_errors=True)


def test_conda_env_worker(ray_init):
    """A task with a conda runtime_env runs under the named env's
    interpreter (resolved prefix/bin/python)."""
    root = tempfile.mkdtemp(prefix="rt_fake_conda_")
    prefix = os.path.join(root, "envs", "fakeenv")
    os.makedirs(os.path.join(prefix, "bin"))
    py = os.path.join(prefix, "bin", "python")
    with open(py, "w") as f:
        f.write("#!/bin/bash\n"
                f"export RT_FAKE_CONDA_ENV={prefix}\n"
                f"exec {sys.executable} \"$@\"\n")
    os.chmod(py, os.stat(py).st_mode | stat.S_IEXEC)

    @ray_tpu.remote
    def which_env():
        return os.environ.get("RT_FAKE_CONDA_ENV", "base")

    out = ray_tpu.get(which_env.options(
        runtime_env={"conda": prefix}).remote(), timeout=120)
    assert out == prefix
    assert ray_tpu.get(which_env.remote(), timeout=120) == "base"
    shutil.rmtree(root, ignore_errors=True)


@pytest.mark.slow
@pytest.mark.skipif(shutil.which("podman") is None
                    and shutil.which("docker") is None,
                    reason="no OCI runtime on host")
def test_container_real_runtime(ray_init):
    """Real podman/docker smoke (runs only where an OCI runtime
    exists): the worker boots inside python:3.12-slim with the repo
    mounted, proving the command assembly works against the real CLI."""
    @ray_tpu.remote
    def in_container():
        return os.path.exists("/.dockerenv") or \
            os.path.exists("/run/.containerenv")

    assert ray_tpu.get(in_container.options(
        runtime_env={"container": {"image": "python:3.12-slim"}}
        ).remote(), timeout=300)
