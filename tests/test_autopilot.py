"""Cluster autopilot: the SLO-driven resource arbiter.

Three layers of coverage:

* **Policy isolation** — ArbiterPolicy is a pure state machine with an
  injectable clock, so decision ordering (serve breach -> shrink the
  lowest-priority gang first, never below its floor; recovery -> grow
  the gang back before data re-soaks), flap bounds (two voluntary
  budget changes >= the cooldown apart), and quorum safety under a
  capacity crunch are all proven deterministically with a fake clock.
* **RPC integration** — the broker's GCS surface (register / report /
  resize_gang structured errors / status) and the revocable DataLease
  against a live in-process cluster.
* **Chaos** (slow, wired into `make chaos`) — SIGKILL a node mid-
  revocation (arbitration must converge, never direct a gang below
  quorum) and SIGKILL the GCS mid-arbitration (the snapshot must NOT
  resurrect stale grants; the table rebuilds from reports).
"""

import threading
import time

import pytest

import ray_tpu
from ray_tpu._private.arbiter import ArbiterPolicy, DataLease


class FakeClock:
    def __init__(self, t=1000.0):
        self.t = t

    def __call__(self):
        return self.t

    def advance(self, dt):
        self.t += dt
        return self.t


def _policy(clock, **kw):
    kw.setdefault("breach_window_s", 1.0)
    kw.setdefault("cooldown_s", 2.0)
    kw.setdefault("ewma_alpha", 1.0)
    kw.setdefault("revoke_grace_s", 2.0)
    kw.setdefault("stale_report_s", 60.0)
    return ArbiterPolicy(clock, **kw)


def _granted(p, wid):
    return p.get(wid).granted


# ---------------------------------------------------------------- policy


def test_allocation_order_floors_trains_serve_then_data():
    """Floors first, trains to full size, serve extra, data soaks the
    remainder — the order that makes 'grow the gang before data
    re-soaks' structural."""
    clk = FakeClock()
    p = _policy(clk)
    p.report("serve:s", want=2, units_now=1,
             kind="serve", priority=100, min_units=1,
             max_units=4, slo=0.5)
    p.report("train:g", want=4, units_now=4,
             kind="train", priority=50, min_units=2, max_units=4)
    p.report("data:d", want=100, units_now=0, kind="data", priority=0)
    p.tick(capacity=8)
    assert _granted(p, "train:g") == 4      # full declared size
    assert _granted(p, "serve:s") == 2      # its demand
    assert _granted(p, "data:d") == 2       # 8 - 4 - 2: only the idle


def test_serve_breach_shrinks_lowest_priority_gang_first():
    """A sustained p99 TTFT breach reclaims from the LOWEST-priority
    gang first; higher-priority gangs are untouched while the victim
    still has spare above its floor."""
    clk = FakeClock()
    p = _policy(clk)

    def _report(ttft, want_serve=3):
        p.report("serve:s", want=want_serve, units_now=1,
                 signals={"ttft_p99_s": ttft}, kind="serve",
                 priority=100, min_units=1, max_units=6, slo=0.5)
        p.report("train:hi", want=4, units_now=4, kind="train",
                 priority=60, min_units=2, max_units=4)
        p.report("train:lo", want=3, units_now=3, kind="train",
                 priority=40, min_units=1, max_units=3)

    _report(0.1)
    p.tick(capacity=8)
    assert _granted(p, "train:hi") == 4
    assert _granted(p, "train:lo") == 3
    assert _granted(p, "serve:s") == 1      # pool exhausted by trains

    # Breach must be SUSTAINED past the window before any reclaim.
    _report(2.0)
    clk.advance(0.25)
    p.tick(capacity=8)
    assert _granted(p, "train:lo") == 3, "reclaimed before the window"

    for _ in range(10):
        clk.advance(0.25)
        _report(2.0)
    decisions = p.tick(capacity=8)
    # Shortfall is 2 (serve wants 3, has 1): the prio-40 gang gives
    # both units; the prio-60 gang keeps its full size.
    assert _granted(p, "train:lo") == 1
    assert _granted(p, "train:hi") == 4
    assert _granted(p, "serve:s") == 3
    revs = [d for d in decisions if d["action"] == "revoke"]
    assert [d["wid"] for d in revs] == ["train:lo"]
    assert revs[0]["reason"] == "serve_slo_breach"


def test_breach_reclaim_never_directs_gang_below_floor():
    """Even an unbounded serve shortfall stops reclaiming at every
    gang's elastic_min_workers floor — the quorum-safety invariant."""
    clk = FakeClock()
    p = _policy(clk)
    for _ in range(12):
        p.report("serve:s", want=6, units_now=1,
                 signals={"ttft_p99_s": 9.9}, kind="serve",
                 priority=100, min_units=1, max_units=6, slo=0.5)
        p.report("train:hi", want=4, units_now=4, kind="train",
                 priority=60, min_units=2, max_units=4)
        p.report("train:lo", want=3, units_now=3, kind="train",
                 priority=40, min_units=1, max_units=3)
        clk.advance(0.25)
        p.tick(capacity=8)
    assert _granted(p, "train:lo") == 1     # its floor
    assert _granted(p, "train:hi") == 2     # its floor
    assert _granted(p, "serve:s") == 5      # 1 + the 4 reclaimed


def test_recovery_grows_gang_before_data_resoaks():
    """When the spike drains, allocation order hands the reclaimed
    units back to the gang BEFORE data may soak again."""
    clk = FakeClock()
    p = _policy(clk)

    def _report(ttft, want_serve):
        p.report("serve:s", want=want_serve, units_now=1,
                 signals={"ttft_p99_s": ttft}, kind="serve",
                 priority=100, min_units=1, max_units=6, slo=0.5)
        p.report("train:g", want=4, units_now=2, kind="train",
                 priority=50, min_units=2, max_units=4)
        p.report("data:d", want=100, units_now=0, kind="data",
                 priority=0)

    # Drive into breach: gang shrinks to its floor, data to zero.
    for _ in range(12):
        _report(9.9, 4)
        clk.advance(0.25)
        p.tick(capacity=6)
    assert _granted(p, "train:g") == 2
    assert _granted(p, "data:d") == 0

    # Spike drains: sustained-ok window + cooldown, then one tick.
    for _ in range(12):
        _report(0.05, 1)
        clk.advance(0.25)
        p.tick(capacity=6)
    assert _granted(p, "train:g") == 4, "gang did not grow back"
    assert _granted(p, "serve:s") == 1
    assert _granted(p, "data:d") == 1      # only what the gang left


def test_flap_bounds_decisions_at_least_cooldown_apart():
    """Voluntary budget changes for one workload are >= the cooldown
    apart no matter how hard demand oscillates."""
    clk = FakeClock()
    p = _policy(clk, cooldown_s=2.0)
    changes = []
    for i in range(60):
        p.report("serve:s", want=1 + (i % 2) * 3, units_now=1,
                 kind="serve", priority=100, min_units=1, max_units=4)
        for d in p.tick(capacity=8):
            if d["from"] != d["to"]:
                changes.append(clk.t)
        clk.advance(0.25)
    assert len(changes) >= 2, "demand oscillation never moved the grant"
    gaps = [b - a for a, b in zip(changes, changes[1:])]
    assert all(g >= 2.0 - 1e-9 for g in gaps), gaps


def test_capacity_crunch_overrides_cooldown_data_first():
    """Node death making the pinned grants infeasible bypasses the
    cooldown: data gives back first, the gang follows but NEVER goes
    below its floor — even if the pool stays short."""
    clk = FakeClock()
    p = _policy(clk, cooldown_s=10.0)
    p.report("train:g", want=4, units_now=4, kind="train",
             priority=50, min_units=2, max_units=4)
    p.report("data:d", want=100, units_now=0, kind="data", priority=0)
    p.tick(capacity=8)
    assert _granted(p, "train:g") == 4
    assert _granted(p, "data:d") == 4

    clk.advance(0.25)  # deep inside the cooldown
    p.report("train:g", want=4, units_now=4, kind="train",
             priority=50, min_units=2, max_units=4)
    p.report("data:d", want=100, units_now=4, kind="data", priority=0)
    decisions = p.tick(capacity=1)          # 7 of 8 nodes died
    assert _granted(p, "data:d") == 0       # data first, to zero
    assert _granted(p, "train:g") == 2      # floor, not lower
    kinds = {d["wid"]: d for d in decisions}
    assert kinds["data:d"]["action"] == "revoke"
    assert "grace_s" in kinds["data:d"]


def test_data_revoke_carries_grace_window():
    clk = FakeClock()
    p = _policy(clk, revoke_grace_s=3.5, cooldown_s=0.0)
    p.report("data:d", want=100, units_now=0, kind="data", priority=0)
    p.tick(capacity=4)
    assert _granted(p, "data:d") == 4
    clk.advance(0.5)
    p.report("data:d", want=100, units_now=4, kind="data", priority=0)
    p.report("train:g", want=4, units_now=0, kind="train",
             priority=50, min_units=4, max_units=4)
    (dec,) = [d for d in p.tick(capacity=4) if d["wid"] == "data:d"]
    assert dec["action"] == "revoke" and dec["grace_s"] == 3.5


def test_stale_workloads_garbage_collected():
    """A client that stops reporting (driver died without unregister)
    is dropped after the stale TTL and its budget returns."""
    clk = FakeClock()
    p = _policy(clk, stale_report_s=5.0)
    p.report("data:d", want=8, units_now=0, kind="data", priority=0)
    p.tick(capacity=8)
    assert _granted(p, "data:d") == 8
    clk.advance(6.0)
    p.tick(capacity=8)
    assert p.get("data:d") is None


def test_report_without_declaration_is_structured_error():
    p = _policy(FakeClock())
    reply = p.report("serve:ghost", want=1, units_now=0)
    assert reply["ok"] is False
    assert reply["error"]["code"] == "UNKNOWN_WORKLOAD"


def test_restart_cannot_resurrect_stale_grants():
    """Broker state is deliberately NOT snapshotted: a fresh policy
    (restarted GCS) starts with zero grants and rebuilds the table
    from the next round of reports — the report IS the registration."""
    clk = FakeClock()
    p1 = _policy(clk)
    p1.report("train:g", want=4, units_now=4, kind="train",
              priority=50, min_units=2, max_units=4)
    p1.tick(capacity=8)
    assert _granted(p1, "train:g") == 4

    p2 = _policy(clk)                       # the "restarted" broker
    assert p2.get("train:g") is None        # nothing resurrected
    reply = p2.report("train:g", want=4, units_now=4, kind="train",
                      priority=50, min_units=2, max_units=4)
    assert reply["ok"] and reply["granted"] == 0  # no stale grant
    p2.tick(capacity=8)
    assert _granted(p2, "train:g") == 4     # rebuilt in one period


def test_slo_breach_seconds_accumulates():
    clk = FakeClock()
    p = _policy(clk)
    for _ in range(8):
        p.report("serve:s", want=1, units_now=1,
                 signals={"ttft_p99_s": 9.0}, kind="serve",
                 priority=100, min_units=1, max_units=2, slo=0.5)
        clk.advance(0.25)
        p.tick(capacity=2)
    assert p.slo_breach_seconds >= 1.5


# ------------------------------------------------------- rpc integration


@pytest.fixture
def ray_4cpu():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def _gcs(method, body):
    from ray_tpu._private.worker import global_worker
    return global_worker.gcs_call(method, body, timeout=30)


def test_resize_gang_structured_errors(ray_4cpu):
    r = _gcs("resize_gang", {"gang": "nope", "target": 2})
    assert r["ok"] is False and r["error"]["code"] == "UNKNOWN_GANG"

    _gcs("arbiter_register", {"wid": "train:rigid", "kind": "train",
                              "min_units": 2, "max_units": 2,
                              "elastic": False})
    r = _gcs("resize_gang", {"gang": "rigid", "target": 1})
    assert r["ok"] is False and r["error"]["code"] == "NOT_ELASTIC"

    _gcs("arbiter_register", {"wid": "train:flex", "kind": "train",
                              "min_units": 2, "max_units": 4,
                              "elastic": True})
    r = _gcs("resize_gang", {"gang": "flex", "target": 1})
    assert r["ok"] is False and r["error"]["code"] == "BELOW_QUORUM"
    r = _gcs("resize_gang", {"gang": "flex", "target": 9})
    assert r["ok"] is False and r["error"]["code"] == "ABOVE_CAPACITY"

    r = _gcs("resize_gang", {"gang": "flex", "target": 3})
    assert r["ok"] and r["wid"] == "train:flex" and r["target"] == 3
    # The directive rides the gang's next report reply, exactly once.
    rep = _gcs("arbiter_report", {"wid": "train:flex", "want": 4,
                                  "units_now": 4})
    assert rep["ok"] and rep["directive"] == 3
    rep = _gcs("arbiter_report", {"wid": "train:flex", "want": 4,
                                  "units_now": 4})
    assert rep["directive"] is None


def test_arbiter_rpc_register_report_status(ray_4cpu):
    r = _gcs("arbiter_register", {"wid": "serve:x", "kind": "mystery"})
    assert r["ok"] is False and r["error"]["code"] == "BAD_DECLARATION"

    assert _gcs("arbiter_register", {
        "wid": "serve:x", "kind": "serve", "priority": 100,
        "min_units": 1, "max_units": 3, "slo": 0.5})["ok"]
    rep = _gcs("arbiter_report", {
        "wid": "serve:x", "want": 2, "units_now": 1,
        "signals": {"ttft_p99_s": 0.1}})
    assert rep["ok"]
    deadline = time.monotonic() + 15
    while time.monotonic() < deadline:
        st = _gcs("arbiter_status", {})
        wl = {w["wid"]: w for w in st["workloads"]}.get("serve:x")
        if wl is not None and wl["granted"] >= 2:
            break
        time.sleep(0.2)
    assert wl is not None and wl["granted"] >= 2, st
    assert st["capacity"] == 4
    assert _gcs("arbiter_unregister", {"wid": "serve:x"})["ok"]


def test_data_lease_granted_then_revoked_by_gang_floor(ray_4cpu):
    """End-to-end revocable lease: an idle cluster grants the soak
    lease real capacity; a gang's floor claim revokes it within a few
    report periods and admission drops to zero."""
    lease = DataLease("data:soak", want=64, priority=0)
    try:
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline and lease.allowed() < 4:
            time.sleep(0.2)
        assert lease.allowed() == 4, "lease never soaked idle capacity"

        stop = threading.Event()

        def _gang_reports():
            while not stop.is_set():
                try:
                    _gcs("arbiter_report", {
                        "wid": "train:greedy", "want": 4, "units_now": 4,
                        "decl": {"kind": "train", "priority": 50,
                                 "min_units": 4, "max_units": 4,
                                 "elastic": False}})
                except Exception:
                    pass
                stop.wait(0.2)

        t = threading.Thread(target=_gang_reports, daemon=True)
        t.start()
        try:
            deadline = time.monotonic() + 20
            while time.monotonic() < deadline and lease.allowed() > 0:
                time.sleep(0.2)
            assert lease.allowed() == 0, \
                "lease not revoked when the gang claimed its floor"
            assert lease.revoked_at is not None
        finally:
            stop.set()
            t.join(5)
            _gcs("arbiter_unregister", {"wid": "train:greedy"})
    finally:
        lease.stop()


# --------------------------------------------------- end-to-end elastic


def _resize_loop(config):
    import time as _t

    import numpy as np
    from ray_tpu.air import session
    from ray_tpu.train.collective import allreduce_gradients

    rank = session.get_world_rank()
    st = session.get_elastic_state()
    start = int(st["step"]) + 1 if st is not None else 0
    w = (np.asarray(st["w"], dtype=np.float64).copy()
         if st is not None else np.zeros(2))
    for step in range(start, int(config["steps"])):
        g = allreduce_gradients(np.ones(2) * (rank + 1.0))
        w = w + g
        session.stash_elastic_state({"step": step, "w": w})
        _t.sleep(0.25)
        session.report({"step": step})


@pytest.mark.slow
def test_rt_resize_directive_shrinks_then_grows_gang():
    """The `rt resize` path end-to-end: a resize_gang RPC's directive
    rides the gang agent's report reply into request_elastic_resize —
    shrink retires the highest rank and releases its bundle; a second
    directive grows back into the released bundle.  No cold restart."""
    from ray_tpu.air.config import ScalingConfig
    from ray_tpu.train.backend import BackendConfig
    from ray_tpu.train._internal import backend_executor as be

    ray_tpu.init(num_cpus=6, ignore_reinit_error=True)
    executor = be.BackendExecutor(
        BackendConfig(),
        ScalingConfig(num_workers=3, elastic=True, elastic_min_workers=2,
                      name="rzgang", resources_per_worker={"CPU": 1}))
    executor.start()
    try:
        executor.start_training(_resize_loop, {"steps": 40},
                                trial_name="t", trial_id="t")
        deadline = time.monotonic() + 30
        while time.monotonic() < deadline:
            st = _gcs("arbiter_status", {})
            if any(w["wid"] == "train:rzgang"
                   for w in st["workloads"]):
                break
            time.sleep(0.2)
        r = _gcs("resize_gang", {"gang": "rzgang", "target": 2})
        assert r["ok"], r

        def _pump_until(world, limit=120):
            end = time.monotonic() + limit
            while time.monotonic() < end:
                res = executor.get_next_results()
                if res is None:
                    return False
                if len(executor.worker_group.workers) == world:
                    return True
            return False

        assert _pump_until(2), "gang did not shrink to 2"
        assert executor._released_bundles, "shrink released no bundle"

        r = _gcs("resize_gang", {"gang": "rzgang", "target": 3})
        assert r["ok"], r
        assert _pump_until(3), "gang did not grow back to 3"
        assert not executor._released_bundles
    finally:
        executor.shutdown()
        ray_tpu.shutdown()


# ----------------------------------------------------------------- chaos


@pytest.mark.slow
def test_chaos_node_sigkill_mid_revocation():
    """`make chaos` leg 1: SIGKILL a node while the arbiter is mid-
    revocation (serve breach reclaiming from the gang).  Arbitration
    must converge on the shrunken capacity, keep the gang at or above
    its floor, and keep answering status RPCs (no deadlock)."""
    import signal  # noqa: F401  (parity with other chaos tests)

    from ray_tpu.cluster_utils import ProcessCluster
    pc = ProcessCluster()
    try:
        pc.add_node(num_cpus=1)
        for _ in range(3):
            pc.add_node(num_cpus=1)
        assert pc.wait_for_nodes(4)
        pc.connect()

        stop = threading.Event()

        def _reports():
            while not stop.is_set():
                try:
                    _gcs("arbiter_report", {
                        "wid": "serve:hot", "want": 3, "units_now": 1,
                        "signals": {"ttft_p99_s": 9.9},
                        "decl": {"kind": "serve", "priority": 100,
                                 "min_units": 1, "max_units": 3,
                                 "slo": 0.5}})
                    _gcs("arbiter_report", {
                        "wid": "train:g", "want": 3, "units_now": 3,
                        "decl": {"kind": "train", "priority": 50,
                                 "min_units": 2, "max_units": 3,
                                 "elastic": True}})
                except Exception:
                    pass
                stop.wait(0.2)

        t = threading.Thread(target=_reports, daemon=True)
        t.start()

        def _grants():
            st = _gcs("arbiter_status", {})
            return {w["wid"]: w["granted"] for w in st["workloads"]}

        # Wait for the revocation to begin (gang below its full size).
        deadline = time.monotonic() + 60
        while time.monotonic() < deadline:
            g = _grants()
            if g.get("train:g", 3) < 3:
                break
            time.sleep(0.2)
        assert g.get("train:g", 3) < 3, f"no revocation started: {g}"

        # SIGKILL a worker node mid-revocation.
        pc.remove_node(pc.nodes[-1])

        # Convergence: grants fit the shrunken capacity, the gang
        # holds quorum, and the grant table goes quiet.
        deadline = time.monotonic() + 90
        stable_since = None
        last = None
        while time.monotonic() < deadline:
            st = _gcs("arbiter_status", {})
            g = {w["wid"]: w["granted"] for w in st["workloads"]}
            cap = st["capacity"]
            fits = sum(g.values()) <= cap and g.get("train:g", 0) >= 2
            if fits and g == last:
                if stable_since is None:
                    stable_since = time.monotonic()
                elif time.monotonic() - stable_since >= 3.0:
                    break
            else:
                stable_since = None
            last = g
            time.sleep(0.25)
        stop.set()
        t.join(5)
        assert stable_since is not None and \
            time.monotonic() - stable_since >= 3.0, \
            f"arbitration never converged: {last} vs capacity {cap}"
        assert last.get("train:g", 0) >= 2, \
            f"gang directed below quorum: {last}"
    finally:
        pc.shutdown()


@pytest.mark.slow
def test_chaos_gcs_sigkill_mid_arbitration_no_stale_grants():
    """`make chaos` leg 2: SIGKILL the GCS while grants are live, then
    restart it from its snapshot.  Broker state is intentionally NOT in
    the snapshot — the restarted GCS must come back with an EMPTY
    workload table (stale grants cannot be resurrected) and rebuild it
    from the clients' next reports."""
    from ray_tpu.cluster_utils import ProcessCluster
    pc = ProcessCluster()
    try:
        pc.add_node(num_cpus=2)
        pc.add_node(num_cpus=2)
        assert pc.wait_for_nodes(2)
        pc.connect()

        def _report_once():
            _gcs("arbiter_report", {
                "wid": "train:g", "want": 4, "units_now": 4,
                "decl": {"kind": "train", "priority": 50,
                         "min_units": 2, "max_units": 4,
                         "elastic": True}})

        deadline = time.monotonic() + 30
        granted = 0
        while time.monotonic() < deadline and granted < 4:
            _report_once()
            st = _gcs("arbiter_status", {})
            granted = {w["wid"]: w["granted"]
                       for w in st["workloads"]}.get("train:g", 0)
            time.sleep(0.2)
        assert granted == 4, "gang never granted before the kill"
        time.sleep(2.0)  # let a snapshot cycle include current state

        pc.kill_gcs()
        time.sleep(1.0)
        pc.restart_gcs()

        # Immediately after restart (no reports yet): table is EMPTY.
        deadline = time.monotonic() + 60
        st = None
        while time.monotonic() < deadline:
            try:
                st = _gcs("arbiter_status", {})
                break
            except Exception:
                time.sleep(0.5)
        assert st is not None, "GCS never answered after restart"
        assert st["workloads"] == [], \
            f"snapshot resurrected broker state: {st['workloads']}"

        # Reports rebuild the table and the grant returns.
        deadline = time.monotonic() + 60
        granted = 0
        while time.monotonic() < deadline and granted < 4:
            try:
                _report_once()
                st = _gcs("arbiter_status", {})
                granted = {w["wid"]: w["granted"]
                           for w in st["workloads"]}.get("train:g", 0)
            except Exception:
                pass
            time.sleep(0.2)
        assert granted == 4, "grants not rebuilt after GCS restart"
    finally:
        pc.shutdown()
