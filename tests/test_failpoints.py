"""Chaos battery: deterministic, seeded message-level fault injection
(ray_tpu._private.failpoints) and the runtime hardening it exercises —
keepalive half-open detection, bounded request deadlines, jittered GCS
reconnects, partition/heal survival, duplicate-frame dedup.

Reference: FoundationDB's deterministic simulation (Zhou et al., SIGMOD
'21) — every red run replays from its seed (`make chaos
CHAOS_SEED=<printed seed>`); the Ray ownership paper (Wang et al., NSDI
'21) — recovery exercised at the message level, not just by killing
processes.
"""

import asyncio
import time

import pytest

import ray_tpu
from ray_tpu._private import failpoints, protocol, retry
from ray_tpu._private.config import GLOBAL_CONFIG as cfg
from ray_tpu._private.test_utils import node_tag


@pytest.fixture(autouse=True)
def _clean_fault_plane():
    """No test may leak an armed failpoint or partition rule."""
    yield
    failpoints.configure("")
    failpoints.clear_conn_rules()


def _run_async(coro):
    return asyncio.run(coro)


def _run(cluster, coro, timeout=120):
    return asyncio.run_coroutine_threadsafe(coro, cluster.loop).result(timeout)


# ------------------------------------------------- spec grammar + registry


def test_spec_parsing_grammar():
    fps = failpoints.parse(
        "a.b=error;c.d=delay(250)|p=0.5|hits=3-6;e.f=drop|times=2|peer=n1")
    assert [fp.name for fp in fps] == ["a.b", "c.d", "e.f"]
    assert fps[0].action.kind == "error"
    assert fps[1].action.kind == "delay"
    assert fps[1].action.delay_s == 0.25
    assert fps[1].prob == 0.5 and (fps[1].first, fps[1].last) == (3, 6)
    assert fps[2].times == 2 and fps[2].peer == "n1"


@pytest.mark.parametrize("bad", [
    "noequals", "=error", "a.b=frobnicate", "a.b=delay(5",
    "a.b=drop|wat=1",
])
def test_spec_parse_errors(bad):
    with pytest.raises(ValueError):
        failpoints.parse(bad)


def test_off_action_clears_and_configure_replaces():
    failpoints.configure("a.b=error")
    assert failpoints.check("a.b") is not None
    failpoints.set_failpoint("a.b=off")
    assert failpoints.check("a.b") is None
    failpoints.configure("c.d=drop")
    assert failpoints.check("a.b") is None
    assert failpoints.check("c.d").kind == "drop"
    failpoints.configure("")
    assert not failpoints.ACTIVE


def test_hits_window_times_and_peer_modifiers():
    failpoints.configure("w.x=drop|hits=3-5")
    fired = [failpoints.check("w.x") is not None for _ in range(8)]
    assert fired == [False, False, True, True, True, False, False, False]

    failpoints.configure("w.x=drop|times=2")
    fired = [failpoints.check("w.x") is not None for _ in range(5)]
    assert fired == [True, True, False, False, False]

    failpoints.configure("w.x=drop|peer=nodeA")
    assert failpoints.check("w.x", peer="raylet:nodeA->gcs") is not None
    assert failpoints.check("w.x", peer="raylet:nodeB->gcs") is None
    assert failpoints.check("w.x") is None  # no peer given -> no match


def test_same_seed_identical_schedule():
    """The acceptance gate: two runs with the same RT_CHAOS_SEED inject
    the identical fault schedule (decision log equality)."""
    failpoints.configure("x.y=drop|p=0.4", seed=1234)
    sched1 = [failpoints.check("x.y") is not None for _ in range(300)]
    log1 = list(failpoints.LOG)
    failpoints.configure("x.y=drop|p=0.4", seed=1234)
    sched2 = [failpoints.check("x.y") is not None for _ in range(300)]
    assert sched1 == sched2
    assert log1 == list(failpoints.LOG)
    assert any(sched1) and not all(sched1)  # p=0.4 really sampled
    failpoints.configure("x.y=drop|p=0.4", seed=4321)
    sched3 = [failpoints.check("x.y") is not None for _ in range(300)]
    assert sched3 != sched1  # a different seed is a different schedule


def test_streams_independent_of_interleaving():
    """Failpoint streams are per-name: hit #k of one failpoint draws
    the same decision no matter how other failpoints interleave."""
    spec = "a.a=drop|p=0.5;b.b=drop|p=0.5"
    failpoints.configure(spec, seed=7)
    alone = [failpoints.check("a.a") is not None for _ in range(60)]
    failpoints.configure(spec, seed=7)
    interleaved = []
    for _ in range(60):
        interleaved.append(failpoints.check("a.a") is not None)
        failpoints.check("b.b")  # extra draws on ANOTHER stream
    assert alone == interleaved


def test_apply_rpc_body_semantics():
    out = failpoints.apply_rpc({"specs": "m.n=error|times=1", "seed": 9})
    assert out["seed"] == 9
    assert [d["name"] for d in out["active"]] == ["m.n"]
    out = failpoints.apply_rpc({"add": "p.q=drop"})
    assert sorted(d["name"] for d in out["active"]) == ["m.n", "p.q"]
    out = failpoints.apply_rpc(
        {"conn_rules": [[["x->", "->y"], {"drop_tx": True}]]})
    assert out["conn_rules"] == [[["x->", "->y"], {"drop_tx": True}]]
    f = failpoints.conn_fault_for("x->somewhere->y")
    assert f is not None and f.drop_tx and not f.drop_rx
    assert failpoints.conn_fault_for("y->x") is None  # AND-match
    out = failpoints.apply_rpc({"specs": "", "conn_rules": []})
    assert out["active"] == [] and out["conn_rules"] == []


def test_backoff_full_jitter_bounded():
    b = retry.ExpBackoff(0.1, 1.0, rng=__import__("random").Random(3))
    delays = [b.next() for _ in range(10)]
    caps = [min(1.0, 0.1 * 2 ** i) for i in range(10)]
    assert all(0.001 <= d <= c for d, c in zip(delays, caps))
    b.reset()
    assert b.attempt == 0
    assert 1.5 <= retry.jittered(2.0, frac=0.25) <= 2.5


# ------------------------------------------------------ protocol plane


def test_recv_drop_then_recover():
    """A dropped request frame surfaces as a deadline, not a hang, and
    the connection keeps working once the hits window passes."""

    async def scenario():
        async def handler(conn, method, body):
            return body

        srv = protocol.RpcServer(handler, name="fp-srv")
        port = await srv.start(0)
        conn = await protocol.Connection.connect("127.0.0.1", port,
                                                 name="fp-cli")
        try:
            failpoints.configure("protocol.recv=drop|peer=fp-srv-peer"
                                 "|hits=1")
            with pytest.raises(asyncio.TimeoutError):
                await conn.request("echo", 1, timeout=0.4)
            assert await conn.request("echo", 2, timeout=5) == 2
        finally:
            failpoints.configure("")
            await conn.close()
            await srv.stop()

    _run_async(scenario())


def test_recv_delay_injects_latency():
    async def scenario():
        async def handler(conn, method, body):
            return body

        srv = protocol.RpcServer(handler, name="fp-srv")
        port = await srv.start(0)
        conn = await protocol.Connection.connect("127.0.0.1", port,
                                                 name="fp-cli")
        try:
            failpoints.configure("protocol.recv=delay(300)"
                                 "|peer=fp-srv-peer|times=1")
            t0 = time.monotonic()
            assert await conn.request("echo", 5, timeout=10) == 5
            assert time.monotonic() - t0 >= 0.28
        finally:
            failpoints.configure("")
            await conn.close()
            await srv.stop()

    _run_async(scenario())


def test_injected_disconnect_fails_inflight():
    async def scenario():
        async def handler(conn, method, body):
            return body

        srv = protocol.RpcServer(handler, name="fp-srv")
        port = await srv.start(0)
        conn = await protocol.Connection.connect("127.0.0.1", port,
                                                 name="fp-cli")
        try:
            failpoints.configure(
                "protocol.recv=disconnect|peer=fp-srv-peer|times=1")
            with pytest.raises(protocol.ConnectionLost):
                await conn.request("echo", 1, timeout=10)
        finally:
            failpoints.configure("")
            await conn.close()
            await srv.stop()

    _run_async(scenario())


def test_dup_push_frame_dispatched_twice():
    """The dup action really duplicates delivery (the runtime's dedup
    layers are tested separately on top of this primitive)."""

    async def scenario():
        hits = []

        async def handler(conn, method, body):
            hits.append((method, body))

        srv = protocol.RpcServer(handler, name="fp-srv")
        port = await srv.start(0)
        conn = await protocol.Connection.connect("127.0.0.1", port,
                                                 name="fp-cli")
        try:
            failpoints.configure("protocol.recv=dup|peer=fp-srv-peer")
            await conn.push("bump", 7)
            for _ in range(100):
                if len(hits) >= 2:
                    break
                await asyncio.sleep(0.01)
            assert hits == [("bump", 7), ("bump", 7)]
        finally:
            failpoints.configure("")
            await conn.close()
            await srv.stop()

    _run_async(scenario())


def test_default_request_deadline(monkeypatch):
    """An unspecified timeout gets the config deadline (no accidental
    unbounded wait); an explicit timeout=None still opts out."""

    async def scenario():
        async def handler(conn, method, body):
            await asyncio.sleep(0.6)
            return body

        srv = protocol.RpcServer(handler, name="ddl-srv")
        port = await srv.start(0)
        conn = await protocol.Connection.connect("127.0.0.1", port,
                                                 name="ddl-cli")
        try:
            monkeypatch.setattr(cfg, "rpc_request_timeout_s", 0.25)
            with pytest.raises(asyncio.TimeoutError):
                await conn.request("slow", 1)
            assert await conn.request("slow", 2, timeout=None) == 2
        finally:
            await conn.close()
            await srv.stop()

    _run_async(scenario())


def test_half_open_detected_by_keepalive(monkeypatch):
    """One direction of a link dies (replies and PONGs black-hole): the
    keepalive probe detects the silence and fails the in-flight future
    with ConnectionLost instead of letting it hang forever.  An idle
    connection with nothing in flight is NOT probed to death."""
    monkeypatch.setattr(cfg, "rpc_keepalive_idle_s", 0.3)
    monkeypatch.setattr(cfg, "rpc_keepalive_timeout_s", 0.3)

    async def scenario():
        async def handler(conn, method, body):
            return body

        srv = protocol.RpcServer(handler, name="ka-srv")
        port = await srv.start(0)
        conn = await protocol.Connection.connect("127.0.0.1", port,
                                                 name="ka-cli")
        try:
            # Idle + healthy: several keepalive cycles pass, no kill.
            await asyncio.sleep(1.0)
            assert not conn.closed
            assert await conn.request("echo", 1, timeout=5) == 1

            # Go half-open: everything the server sends back (replies,
            # PONGs) is dropped on the client's inbound side.
            failpoints.add_conn_rule(("ka-cli",), drop_rx=True)
            t0 = time.monotonic()
            with pytest.raises(protocol.ConnectionLost) as ei:
                await conn.request("echo", 2, timeout=None)
            assert time.monotonic() - t0 < 5.0  # detected, not hung
            assert "keepalive" in str(ei.value)
        finally:
            failpoints.clear_conn_rules()
            await conn.close()
            await srv.stop()

    _run_async(scenario())


def test_one_way_conn_rule_and_heal():
    """drop_tx black-holes outbound frames on a live connection (the
    rule is installed AFTER the conn exists — the live-conn sweep must
    re-resolve it), and heal() restores service."""

    async def scenario():
        async def handler(conn, method, body):
            return body

        srv = protocol.RpcServer(handler, name="ow-srv")
        port = await srv.start(0)
        conn = await protocol.Connection.connect("127.0.0.1", port,
                                                 name="ow-cli")
        try:
            assert await conn.request("echo", 1, timeout=5) == 1
            failpoints.add_conn_rule(("ow-cli",), drop_tx=True)
            with pytest.raises(asyncio.TimeoutError):
                await conn.request("echo", 2, timeout=0.4)
            failpoints.clear_conn_rules()
            assert await conn.request("echo", 3, timeout=5) == 3
        finally:
            failpoints.clear_conn_rules()
            await conn.close()
            await srv.stop()

    _run_async(scenario())


# ------------------------------------------------------- cluster plane


def test_one_way_partition_multi_source_pull(ray_start_cluster,
                                             monkeypatch):
    """Acceptance: a one-way partition during a multi-source transfer
    pull — the black-holed source's chunks reissue to the surviving
    source (keepalive turns the silent link into ConnectionLost, the
    windowed pull reroutes) and the transfer completes.  Never hangs."""
    monkeypatch.setattr(cfg, "rpc_keepalive_idle_s", 0.4)
    monkeypatch.setattr(cfg, "rpc_keepalive_timeout_s", 0.4)
    monkeypatch.setattr(cfg, "transfer_same_host_mmap", False)
    monkeypatch.setattr(cfg, "fetch_chunk_bytes", 512 * 1024)
    monkeypatch.setattr(cfg, "transfer_stripe_min_bytes", 1024 * 1024)
    cluster = ray_start_cluster
    a = cluster.add_node(num_cpus=1)
    b = cluster.add_node(num_cpus=1)
    c = cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes(3)
    cluster.connect()

    import numpy as np
    blob = np.random.RandomState(11).bytes(6 * 1024 * 1024)
    ref = ray_tpu.put(blob)
    oid = ref.id.binary()

    # Second sealed copy on C, visible in the GCS object directory so
    # B's pull stripes across {A, C}.
    assert _run(cluster, a.raylet.transfers.push(oid, c.raylet.node_id))
    gcs = cluster.head.gcs_server
    for _ in range(100):
        if c.raylet.node_id in gcs.object_locations.get(oid, ()):
            break
        time.sleep(0.05)
    assert c.raylet.node_id in gcs.object_locations.get(oid, ())

    def _bytes(node):
        async def _read():
            got = node.raylet.store.get(oid)
            assert got is not None and got[2]
            off, size, _ = got
            data = bytes(node.raylet.mapping.slice(off, size))
            node.raylet.store.release(oid)
            return data
        return _run(cluster, _read())

    # Source of truth: A's sealed store bytes (the object's serialized
    # form, not the raw blob — put() pickles).
    expected = _bytes(a)

    # Slow each chunk fetch so the windowed pull is still striping when
    # the partition lands — "partition DURING transfer", deterministically
    # (12 chunks x >=150ms each across a window of 4 keeps the pull in
    # flight for ~450ms+; we cut the link right after chunk #1 seals).
    failpoints.set_failpoint("transfer.pull_chunk=delay(150)")
    base_retries = b.raylet.transfers.stats["chunk_retries"]

    t0 = time.monotonic()
    fut = asyncio.run_coroutine_threadsafe(
        b.raylet._pull_object(oid, a.raylet.node_id,
                              time.monotonic() + 60), cluster.loop)
    for _ in range(2000):
        if b.raylet.transfers.stats["pull_chunks"] >= 1:
            break
        time.sleep(0.005)
    assert b.raylet.transfers.stats["pull_chunks"] >= 1, \
        "pull never issued its first chunk"

    # One-way partition mid-pull: B's frames toward C vanish (chunk
    # requests black-hole); C->B stays up.  Exactly the half-open case —
    # B's keepalive probe goes unanswered, the link fails with
    # ConnectionLost, and C's chunks reissue to A.
    cluster.partition(b, c, one_way=True)

    ok = fut.result(timeout=90)
    assert ok, "pull must complete via the surviving source"
    assert time.monotonic() - t0 < 60

    failpoints.clear("transfer.pull_chunk")
    assert _bytes(b) == expected
    stats = _run(cluster, b.raylet.rpc_transfer_stats(None, {}))
    assert stats["chunk_retries"] > base_retries, \
        "partitioned source's chunks must have been reissued"
    assert stats["striped_pulls"] >= 1, \
        "the pull must have striped across both sources"
    cluster.heal()


def test_fully_partitioned_single_source_times_out(ray_start_cluster,
                                                   monkeypatch):
    """With the ONLY source partitioned away, a driver get() surfaces
    GetTimeoutError — never a hang."""
    monkeypatch.setattr(cfg, "rpc_keepalive_idle_s", 0.4)
    monkeypatch.setattr(cfg, "rpc_keepalive_timeout_s", 0.4)
    monkeypatch.setattr(cfg, "transfer_same_host_mmap", False)
    cluster = ray_start_cluster
    a = cluster.add_node(num_cpus=1, resources={"a": 1})
    b = cluster.add_node(num_cpus=1, resources={"b": 1})
    cluster.wait_for_nodes(2)
    cluster.connect()

    import numpy as np

    @ray_tpu.remote(resources={"a": 1})
    def make():
        return np.arange(200_000)

    @ray_tpu.remote(resources={"b": 1})
    def consume(x):
        return int(x[0])

    ref = make.remote()
    assert ray_tpu.get(consume.remote(ref), timeout=60) == 0

    @ray_tpu.remote(resources={"a": 1})
    def big():
        return np.random.RandomState(5).bytes(2 * 1024 * 1024)

    ref2 = big.remote()
    ray_tpu.wait([ref2], timeout=60)
    cluster.partition(a, b)
    try:
        with pytest.raises(ray_tpu.exceptions.GetTimeoutError):
            ray_tpu.get(consume.remote(ref2), timeout=8)
    finally:
        cluster.heal()


def test_gcs_partition_and_heal_scheduling_throughout(ray_start_cluster,
                                                      monkeypatch):
    """Acceptance: partition a worker node from the GCS, heal inside
    the liveness grace window.  The rest of the cluster schedules
    throughout, the partitioned node is never falsely killed, and it
    resumes serving after the heal."""
    monkeypatch.setattr(cfg, "heartbeat_period_ms", 300)
    monkeypatch.setattr(cfg, "heartbeat_timeout_ms", 20000)
    monkeypatch.setattr(cfg, "rpc_keepalive_idle_s", 0.5)
    monkeypatch.setattr(cfg, "rpc_keepalive_timeout_s", 0.5)
    monkeypatch.setattr(cfg, "gcs_reconnect_base_s", 0.1)
    monkeypatch.setattr(cfg, "gcs_reconnect_cap_s", 0.5)
    cluster = ray_start_cluster
    head = cluster.add_node(num_cpus=2, resources={"head": 1})
    b = cluster.add_node(num_cpus=1, resources={"spot": 1})
    cluster.wait_for_nodes(2)
    cluster.connect()

    @ray_tpu.remote(resources={"head": 0.1})
    def on_head(x):
        return x + 1

    @ray_tpu.remote(resources={"spot": 0.1})
    def on_spot(x):
        return x * 2

    assert ray_tpu.get(on_spot.remote(3), timeout=60) == 6

    cluster.partition(b, "gcs")
    t_end = time.monotonic() + 2.5
    n = 0
    while time.monotonic() < t_end:
        # The control-plane partition of ONE node must not stall
        # scheduling elsewhere (sequential on purpose: each iteration
        # IS the end-to-end schedule-latency probe).
        assert ray_tpu.get(on_head.remote(n),  # noqa: RTL001
                           timeout=30) == n + 1
        n += 1
    assert n >= 3
    cluster.heal()

    # B re-registers (jittered bounded retries) and serves again.
    deadline = time.monotonic() + 30
    out = None
    while time.monotonic() < deadline:
        try:
            out = ray_tpu.get(on_spot.remote(5),  # noqa: RTL001
                              timeout=10)
            break
        except Exception:
            time.sleep(0.25)
    assert out == 10, "partitioned node never came back after heal"

    # Within the grace window the whole time: never marked dead.
    gcs = cluster.head.gcs_server
    info = gcs.nodes.get(b.raylet.node_id)
    assert info is not None and info.alive
    b_tag = b.raylet.node_id.hex()[:8]
    deaths = [e for e in gcs.events
              if e["label"] == "NODE_DEAD" and b_tag in e["message"]]
    assert deaths == [], f"node falsely declared dead: {deaths}"


def test_delayed_heartbeats_within_grace_not_killed(ray_start_cluster,
                                                    monkeypatch):
    """Acceptance: heartbeats delayed (via the failpoint armed OVER THE
    set_failpoints RPC, mid-run) still land inside the liveness grace
    window — the node must not be declared dead."""
    monkeypatch.setattr(cfg, "heartbeat_period_ms", 300)
    monkeypatch.setattr(cfg, "heartbeat_timeout_ms", 2500)
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    b = cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes(2)

    b_tag = node_tag(b)

    async def _toggle(body):
        conn = await protocol.Connection.connect(
            cluster.head.gcs_addr[0], cluster.head.gcs_addr[1],
            name="chaos-ctl")
        try:
            return await conn.request("set_failpoints", body, timeout=10)
        finally:
            await conn.close()

    # Arm mid-run over RPC (in-process cluster: the GCS shares the
    # failpoint registry with the raylets under test).
    out = _run(cluster, _toggle(
        {"add": f"raylet.heartbeat=delay(400)|peer={b_tag[-8:]}"}))
    assert any(d["name"] == "raylet.heartbeat" for d in out["active"])

    time.sleep(2.5)  # several delayed-but-delivered beats

    gcs = cluster.head.gcs_server
    info = gcs.nodes.get(b.raylet.node_id)
    assert info is not None and info.alive, \
        "delayed heartbeats within grace must not kill the node"
    assert any(name == "raylet.heartbeat" and fired
               for name, _hit, fired, _kind in failpoints.LOG), \
        "the delay failpoint never fired"

    out = _run(cluster, _toggle({"specs": ""}))
    assert out["active"] == []


def test_gcs_reconnect_bounded_with_terminal_error(ray_start_cluster,
                                                   monkeypatch):
    """Satellite: the core-worker GCS path retries with backoff and,
    when the GCS stays unreachable, fails with a terminal error naming
    the GCS address (was: reconnect exactly once)."""
    monkeypatch.setattr(cfg, "gcs_reconnect_attempts", 3)
    monkeypatch.setattr(cfg, "gcs_reconnect_base_s", 0.05)
    monkeypatch.setattr(cfg, "gcs_reconnect_cap_s", 0.1)
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1)
    cluster.wait_for_nodes(1)
    cw = cluster.connect()

    failpoints.configure("worker.gcs_request=error;"
                         "worker.gcs_reconnect=error")
    try:
        with pytest.raises(ConnectionError) as ei:
            _run(cluster, cw._gcs_request("get_nodes", {}))
        msg = str(ei.value)
        host, port = cluster.head.gcs_addr
        assert f"{host}:{port}" in msg and "3 reconnect attempt" in msg
    finally:
        failpoints.configure("")
    # And with the fault plane cleared the same path works again.
    assert _run(cluster, cw._gcs_request("get_nodes", {})) is not None
