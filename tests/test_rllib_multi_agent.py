"""Multi-agent RLlib: two policies, distinct mappings, both must learn.

Reference: rllib/env/multi_agent_env.py + policy_map.py + the multi-agent
paths of PPO's training_step.
"""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib.algorithms.ppo import PPOConfig
from ray_tpu.rllib.env.multi_agent_env import MultiAgentEnv, make_multi_agent
from ray_tpu.rllib.policy.policy_map import PolicySpec


class TwoTargetEnv(MultiAgentEnv):
    """Each step both agents see a one-hot target (dim 4).  agent_0 is
    rewarded for answering the target index, agent_1 for answering
    (target + 1) % 4 — so the two policies must learn DIFFERENT
    mappings.  Episode length 16."""

    possible_agents = ("agent_0", "agent_1")

    def __init__(self, config=None):
        self._rng = np.random.RandomState((config or {}).get("seed", 0))
        self._t = 0
        self._targets = {}

    def observation_space(self, agent_id):
        import gymnasium as gym
        return gym.spaces.Box(0.0, 1.0, shape=(4,), dtype=np.float32)

    def action_space(self, agent_id):
        import gymnasium as gym
        return gym.spaces.Discrete(4)

    def _obs(self):
        out = {}
        for aid in self.possible_agents:
            t = int(self._rng.randint(0, 4))
            self._targets[aid] = t
            onehot = np.zeros(4, np.float32)
            onehot[t] = 1.0
            out[aid] = onehot
        return out

    def reset(self, *, seed=None):
        if seed is not None:
            self._rng = np.random.RandomState(seed)
        self._t = 0
        return self._obs(), {aid: {} for aid in self.possible_agents}

    def step(self, action_dict):
        rewards = {}
        for aid, act in action_dict.items():
            want = self._targets[aid]
            if aid == "agent_1":
                want = (want + 1) % 4
            rewards[aid] = 1.0 if int(act) == want else 0.0
        self._t += 1
        done = self._t >= 16
        obs = {} if done else self._obs()
        terms = {aid: done for aid in action_dict}
        terms["__all__"] = done
        truncs = {aid: False for aid in action_dict}
        truncs["__all__"] = False
        return obs, rewards, terms, truncs, {}


@pytest.mark.slow
def test_multi_agent_ppo_two_policies_learn():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    try:
        config = (
            PPOConfig()
            .environment(TwoTargetEnv)
            .rollouts(num_rollout_workers=0, rollout_fragment_length=256)
            .training(train_batch_size=512, num_sgd_iter=8,
                      sgd_minibatch_size=128, lr=5e-3, entropy_coeff=0.01)
            .multi_agent(
                policies={"p0": PolicySpec(4, 4), "p1": PolicySpec(4, 4)},
                policy_mapping_fn=lambda aid, *a, **kw:
                    "p0" if aid == "agent_0" else "p1")
        )
        algo = config.build()
        best = -np.inf
        for _ in range(12):
            result = algo.step()
            r = result.get("episode_reward_mean")
            if r == r and r is not None:
                best = max(best, r)
        # Max per episode = 2 agents x 16 steps = 32; random ~8.
        assert best >= 24, f"multi-agent PPO failed to learn: best={best}"
        algo.cleanup()
    finally:
        ray_tpu.shutdown()


def test_make_multi_agent_wraps_single_env():
    class _Const:
        def __init__(self, cfg=None):
            import gymnasium as gym
            self.observation_space = gym.spaces.Box(
                0, 1, shape=(2,), dtype=np.float32)
            self.action_space = gym.spaces.Discrete(2)
            self._t = 0

        def reset(self, seed=None):
            self._t = 0
            return np.zeros(2, np.float32), {}

        def step(self, a):
            self._t += 1
            return (np.zeros(2, np.float32), 1.0, self._t >= 3, False, {})

    env_cls = make_multi_agent(lambda cfg: _Const(cfg))
    env = env_cls({"num_agents": 3})
    obs, _ = env.reset(seed=0)
    assert set(obs) == {"agent_0", "agent_1", "agent_2"}
    for _ in range(3):
        obs, rews, terms, truncs, _ = env.step({a: 0 for a in obs})
    assert terms["__all__"]
