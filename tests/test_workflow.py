"""Workflow: durable DAG execution with per-task checkpoints and resume
(reference test style: python/ray/workflow/tests)."""

import os
import tempfile

import pytest

import ray_tpu
from ray_tpu import workflow
from ray_tpu.dag import InputNode


@pytest.fixture
def wf_env():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    storage = tempfile.mkdtemp(prefix="rt_wf_")
    workflow.init(storage)
    yield storage
    ray_tpu.shutdown()


@pytest.mark.slow
def test_workflow_runs_dag(wf_env):
    @ray_tpu.remote
    def add(a, b):
        return a + b

    @ray_tpu.remote
    def double(x):
        return 2 * x

    with InputNode() as inp:
        dag = add.bind(double.bind(inp), 10)
    assert workflow.run(dag, 5, workflow_id="w1") == 20
    assert workflow.get_status("w1") == workflow.STATUS_SUCCESSFUL
    assert workflow.resume("w1") == 20
    assert any(w["workflow_id"] == "w1" for w in workflow.list_all())


@pytest.mark.slow
def test_workflow_resume_skips_completed_tasks(wf_env):
    calls_file = os.path.join(tempfile.gettempdir(),
                              f"wf_calls_{os.getpid()}")
    open(calls_file, "w").close()

    @ray_tpu.remote
    def counted(x):
        with open(calls_file, "a") as f:
            f.write("x\n")
        return x + 1

    @ray_tpu.remote
    def fail_once(x, should_fail):
        if should_fail:
            raise RuntimeError("boom")
        return x * 100

    with InputNode() as inp:
        dag = fail_once.bind(counted.bind(inp), True)
    with pytest.raises(Exception):
        workflow.run(dag, 1, workflow_id="w2")
    assert workflow.get_status("w2") == workflow.STATUS_FAILED
    assert len(open(calls_file).read().splitlines()) == 1

    # Re-run with the failure gone: counted's checkpoint replays, the
    # function does NOT execute again.
    with InputNode() as inp:
        dag2 = fail_once.bind(counted.bind(inp), False)
    assert workflow.run(dag2, 1, workflow_id="w2") == 200
    assert len(open(calls_file).read().splitlines()) == 1  # still one
    os.remove(calls_file)


def test_wait_for_event_durable(wf_env):
    """An event node blocks until its listener fires; once received the
    payload is checkpointed, so re-running the workflow does not wait
    again (reference: workflow events exactly-once contract)."""
    flag = os.path.join(wf_env, "fire-event")

    class FileEvent(workflow.EventListener):
        def poll_for_event(self, path):
            import time as _t
            for _ in range(200):
                if os.path.exists(path):
                    with open(path) as f:
                        return f.read()
                _t.sleep(0.1)
            raise TimeoutError("event never fired")

    @ray_tpu.remote
    def combine(payload, y):
        return f"{payload}+{y}"

    dag = combine.bind(workflow.wait_for_event(FileEvent, flag), 7)
    ref = workflow.run_async(dag, workflow_id="wev")
    _, pending = ray_tpu.wait([ref], timeout=1.5)
    assert pending, "workflow finished before the event fired"
    with open(flag, "w") as f:
        f.write("go")
    assert ray_tpu.get(ref, timeout=120) == "go+7"
    # Durability: the event payload replays from its checkpoint even
    # though the event source is gone.
    os.remove(flag)
    assert workflow.run(dag, workflow_id="wev") == "go+7"


def test_wait_for_event_type_check(wf_env):
    with pytest.raises(TypeError):
        workflow.wait_for_event(object)


def test_dynamic_sub_workflow(wf_env, tmp_path):
    """A task returning a DAG continues the workflow with it (reference:
    workflow.continuation / dynamic workflows), checkpointed under the
    parent task's key prefix."""
    import ray_tpu.workflow as wf
    wf.init(str(tmp_path / "wfs"))

    @ray_tpu.remote
    def fanout(n):
        # Decide the next stage at runtime.
        import ray_tpu.workflow as wf2
        parts = [double.bind(i) for i in range(n)]
        return wf2.continuation(total.bind(*parts))

    @ray_tpu.remote
    def double(x):
        return 2 * x

    @ray_tpu.remote
    def total(*xs):
        return sum(xs)

    out = wf.run(fanout.bind(4), workflow_id="dyn1")
    assert out == 2 * (0 + 1 + 2 + 3)
    # Resume replays from checkpoints (no recompute needed for result).
    assert wf.resume("dyn1") == 12


def test_virtual_actor_durable_state(wf_env, tmp_path):
    import ray_tpu.workflow as wf
    wf.init(str(tmp_path / "wfs"))

    @wf.virtual_actor
    class Counter:
        def __init__(self, start=0):
            self.n = start

        def add(self, k):
            self.n += k
            return self.n

        @wf.readonly
        def peek(self):
            return self.n

    c = Counter.get_or_create("acct-1", 10)
    assert c.add.run(5) == 15
    assert c.add.run(1) == 16
    assert c.peek.run() == 16

    # A FRESH handle (new driver/machine) resumes from storage.
    c2 = Counter.get_or_create("acct-1")
    assert c2.peek.run() == 16
    assert c2.add.run(4) == 20


def test_workflow_on_mem_storage(wf_env):
    """The storage seam is URI-pluggable end to end."""
    import ray_tpu.workflow as wf
    wf.init("mem://wf-bucket-test")

    @ray_tpu.remote
    def one():
        return 41

    @ray_tpu.remote
    def inc(x):
        return x + 1

    assert wf.run(inc.bind(one.bind()), workflow_id="memwf") == 42
    assert wf.resume("memwf") == 42
    assert {"workflow_id": "memwf", "status": "SUCCESSFUL"} in \
        wf.list_all()
    wf.delete("memwf")
    wf.init(None)
