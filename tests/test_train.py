"""Train: JaxTrainer end-to-end on an in-process cluster — the reference's
"minimum end-to-end slice" (SURVEY.md §7 phase 5): gang of worker actors,
mesh from ScalingConfig, pjit train loop, checkpoint back to the driver."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu.air import Checkpoint, RunConfig, ScalingConfig
from ray_tpu.train import JaxConfig, JaxTrainer


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def _linreg_loop(config):
    """Least-squares on a dp x tp mesh via pjit."""
    import jax
    import jax.numpy as jnp
    from jax.sharding import NamedSharding, PartitionSpec as P
    from ray_tpu.air import session
    from ray_tpu.train.jax import prepare_mesh

    mesh = prepare_mesh()
    rng = np.random.RandomState(0)
    x = rng.randn(32, 8).astype(np.float32)
    w_true = rng.randn(8, 4).astype(np.float32)
    y = x @ w_true

    w = jnp.zeros((8, 4))
    xs = jax.device_put(x, NamedSharding(mesh, P(("dp", "fsdp"), None)))
    ys = jax.device_put(y, NamedSharding(mesh, P(("dp", "fsdp"), None)))
    w = jax.device_put(w, NamedSharding(mesh, P(None, "tp")))

    @jax.jit
    def step(w, x, y):
        def loss(w):
            return jnp.mean((x @ w - y) ** 2)
        l, g = jax.value_and_grad(loss)(w)
        return w - 0.05 * g, l

    for epoch in range(config["epochs"]):
        w, l = step(w, xs, ys)
        session.report({"loss": float(l), "epoch": epoch},
                       checkpoint=Checkpoint.from_pytree({"w": w}))


@pytest.mark.slow
def test_jax_trainer_end_to_end(ray_init):
    trainer = JaxTrainer(
        _linreg_loop,
        train_loop_config={"epochs": 80},
        jax_config=JaxConfig(use_distributed=False, virtual_cpu_devices=8),
        scaling_config=ScalingConfig(num_workers=1, tp=2, fsdp=2),
    )
    result = trainer.fit()
    assert result.error is None
    assert result.metrics["loss"] < 1.0
    assert result.metrics["epoch"] == 79
    w = result.checkpoint.to_pytree()["w"]
    assert w.shape == (8, 4)
    assert np.isfinite(np.asarray(w)).all()


def _rank_report_loop(config):
    from ray_tpu.air import session
    session.report({"rank": session.get_world_rank(),
                    "world": session.get_world_size()})


@pytest.mark.slow
def test_worker_group_ranks(ray_init):
    trainer = JaxTrainer(
        _rank_report_loop,
        jax_config=JaxConfig(use_distributed=False),
        scaling_config=ScalingConfig(num_workers=2),
    )
    result = trainer.fit()
    assert result.metrics["rank"] == 0
    assert result.metrics["world"] == 2


def _dataset_ingest_loop(config):
    from ray_tpu.air import session
    shard = session.get_dataset_shard("train")
    total = sum(shard.take_all())
    session.report({"shard_sum": total,
                    "rank": session.get_world_rank()})


def test_dataset_ingest_shards_per_worker(ray_init):
    from ray_tpu import data as rd

    ds = rd.range(20, parallelism=4)
    trainer = JaxTrainer(
        _dataset_ingest_loop,
        jax_config=JaxConfig(use_distributed=False),
        scaling_config=ScalingConfig(num_workers=2),
        datasets={"train": ds},
    )
    result = trainer.fit()
    # rank 0's shard is half the blocks; both ranks' shards partition the
    # data (sum over both == sum(range(20)) checked via world view).
    assert result.metrics["rank"] == 0
    assert 0 < result.metrics["shard_sum"] < sum(range(20))


def _torch_ddp_loop(config):
    import torch
    import torch.distributed as dist
    from ray_tpu.air import session
    from ray_tpu.train.torch import prepare_model

    torch.manual_seed(0)
    model = prepare_model(torch.nn.Linear(4, 1))
    opt = torch.optim.SGD(model.parameters(), lr=0.1)
    x = torch.randn(16, 4)
    y = x.sum(dim=1, keepdim=True)
    for _ in range(5):
        opt.zero_grad()
        loss = ((model(x) - y) ** 2).mean()
        loss.backward()  # DDP allreduces grads across the gang
        opt.step()
    session.report({"loss": float(loss),
                    "world": dist.get_world_size()})


@pytest.mark.slow
def test_torch_trainer_ddp_gloo(ray_init):
    from ray_tpu.train.torch import TorchTrainer

    trainer = TorchTrainer(
        _torch_ddp_loop,
        scaling_config=ScalingConfig(num_workers=2),
    )
    result = trainer.fit()
    assert result.metrics["world"] == 2
    assert result.metrics["loss"] < 5.0


def test_sklearn_trainer(ray_init):
    import pandas as pd
    from sklearn.linear_model import LogisticRegression

    from ray_tpu import data as rd
    from ray_tpu.train.sklearn import SklearnTrainer

    rng = np.random.RandomState(0)
    df = pd.DataFrame({"a": rng.randn(200), "b": rng.randn(200)})
    df["y"] = (df["a"] + df["b"] > 0).astype(int)
    trainer = SklearnTrainer(
        estimator=LogisticRegression(),
        datasets={"train": rd.from_pandas(df.iloc[:150]),
                  "valid": rd.from_pandas(df.iloc[150:])},
        label_column="y",
    )
    result = trainer.fit()
    assert result.metrics["valid_score"] > 0.9
    model = SklearnTrainer.get_model(result.checkpoint)
    assert model.predict(df[["a", "b"]].iloc[:5]).shape == (5,)


def _hf_trainer_init(config):
    import torch
    from transformers import (GPT2Config, GPT2LMHeadModel, Trainer,
                              TrainingArguments)

    model = GPT2LMHeadModel(GPT2Config(
        vocab_size=64, n_positions=32, n_embd=32, n_layer=2, n_head=2))

    class Toy(torch.utils.data.Dataset):
        def __len__(self):
            return 32

        def __getitem__(self, i):
            ids = torch.randint(0, 64, (16,))
            return {"input_ids": ids, "labels": ids}

    args = TrainingArguments(
        output_dir=config["output_dir"], num_train_epochs=1,
        per_device_train_batch_size=8, report_to=[], logging_steps=1,
        use_cpu=True, save_strategy="no", disable_tqdm=True)
    return Trainer(model=model, args=args, train_dataset=Toy())


@pytest.mark.slow
def test_transformers_trainer(ray_init, tmp_path):
    from ray_tpu.train.huggingface import TransformersTrainer

    trainer = TransformersTrainer(
        _hf_trainer_init,
        trainer_init_config={"output_dir": str(tmp_path / "hf")},
        scaling_config=ScalingConfig(num_workers=1),
    )
    result = trainer.fit()
    assert result.metrics.get("train_loss") is not None or \
        result.metrics.get("loss") is not None
    state = result.checkpoint.to_dict()["model_state"]
    assert any(k.endswith("wte.weight") for k in state)
