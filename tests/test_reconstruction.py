"""Lineage reconstruction: lost objects are rebuilt by re-executing their
creating task (reference test style: python/ray/tests/test_reconstruction
*.py — kill the node holding the primary copy, then get())."""

import numpy as np
import pytest

import ray_tpu


@pytest.mark.slow
def test_reconstruct_lost_task_output(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, resources={"head": 1})
    worker_node = cluster.add_node(num_cpus=1, resources={"spot": 1})
    cluster.wait_for_nodes(2)
    cluster.connect()

    @ray_tpu.remote(resources={"spot": 1})
    def make_big(seed):
        rng = np.random.RandomState(seed)
        return rng.rand(400, 400)  # >100KiB: lives in the remote shm store

    ref = make_big.remote(7)
    first = ray_tpu.get(ref, timeout=120)

    cluster.remove_node(worker_node)
    # The primary (and only) copy died with the node.  A fresh node offers
    # the resource; the owner must re-execute the task.
    cluster.add_node(num_cpus=1, resources={"spot": 1})
    again = ray_tpu.get(ref, timeout=120)
    np.testing.assert_array_equal(first, again)


def test_reconstruct_chain_through_dependent_task(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, resources={"head": 1})
    worker_node = cluster.add_node(num_cpus=1, resources={"spot": 1})
    cluster.wait_for_nodes(2)
    cluster.connect()

    @ray_tpu.remote(resources={"spot": 1})
    def produce():
        return np.ones((400, 400))

    @ray_tpu.remote(resources={"head": 1})
    def consume(x):
        return float(x.sum())

    ref = produce.remote()
    ray_tpu.get(ref, timeout=120)  # materialize on the spot node
    cluster.remove_node(worker_node)
    cluster.add_node(num_cpus=1, resources={"spot": 1})
    # The consumer (on another node) borrows the lost ref; the owner
    # (driver) reconstructs it on the replacement node.
    out = ray_tpu.get(consume.remote(ref), timeout=120)
    assert out == 400 * 400


@pytest.mark.slow
def test_dynamic_sub_objects_reconstruct_after_outer_ref_release(
        ray_start_cluster):
    """Regression: a re-executed generator whose MAIN owned entry was
    released (user kept only yielded sub-refs) must still re-register
    its sub-objects — pending get()s used to hang forever because
    _record_results dropped the whole reply when the main entry was
    gone."""
    import gc

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, resources={"head": 1})
    worker_node = cluster.add_node(num_cpus=1, resources={"spot": 1})
    cluster.wait_for_nodes(2)
    cluster.connect()

    @ray_tpu.remote(resources={"spot": 1})
    def gen():
        for i in range(3):
            yield np.full((400, 400), i, np.float64)  # store-resident

    outer = gen.options(num_returns="dynamic").remote()
    sub_refs = list(ray_tpu.get(outer, timeout=120))
    assert len(sub_refs) == 3
    first = ray_tpu.get(sub_refs[1], timeout=120)

    # Drop the visible generator ref: the main owned entry goes away,
    # the deserialized sub-refs keep their own stakes.
    del outer
    gc.collect()

    cluster.remove_node(worker_node)
    cluster.add_node(num_cpus=1, resources={"spot": 1})
    # sub_refs[2] was NEVER fetched, so its only copy died with the
    # node (sub_refs[1] may survive as a local transfer copy): this
    # get() must re-execute the generator and unblock even though the
    # main entry is gone.
    fresh = ray_tpu.get(sub_refs[2], timeout=120)
    assert int(fresh[0, 0]) == 2 and fresh.shape == (400, 400)
    again = ray_tpu.get(sub_refs[1], timeout=120)
    np.testing.assert_array_equal(first, again)


def test_put_objects_are_not_reconstructable(ray_start_cluster):
    cluster = ray_start_cluster
    cluster.add_node(num_cpus=1, resources={"head": 1})
    worker_node = cluster.add_node(num_cpus=1, resources={"spot": 1})
    cluster.wait_for_nodes(2)
    cluster.connect()

    @ray_tpu.remote(resources={"spot": 1})
    def hold(x):
        return x  # returns the same array; new object owned by driver

    src = np.zeros((400, 400))
    ref = ray_tpu.put(src)

    # A put object's only copy lives on the head store — killing the spot
    # node must NOT affect it.
    cluster.remove_node(worker_node)
    np.testing.assert_array_equal(ray_tpu.get(ref, timeout=60), src)
