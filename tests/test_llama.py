"""LLaMA family: sharded-vs-single-device equivalence on the virtual
mesh (same oracle style as tests/test_models.py)."""

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from ray_tpu.models import llama
from ray_tpu.parallel.mesh import MeshSpec, make_mesh

KEY = jax.random.PRNGKey(0)


def _cfg(**kw):
    base = dict(vocab_size=96, d_model=32, n_heads=4, n_kv_heads=2,
                n_layers=2, d_ff=64, max_seq=64, dtype=jnp.float32,
                remat=False, use_flash=False)
    base.update(kw)
    return llama.LlamaConfig(**base)


def _tokens(b=4, t=33):
    return jax.random.randint(jax.random.PRNGKey(7), (b, t), 0, 96)


def test_forward_shapes_and_rope_shift():
    cfg = _cfg()
    params = llama.init_params(cfg, KEY)
    toks = _tokens()
    logits = llama.forward(params, toks[:, :-1], cfg)
    assert logits.shape == (4, 32, 96)
    # RoPE is position-dependent: shifting the sequence changes outputs.
    shifted = llama.forward(params, toks[:, 1:], cfg)
    assert not np.allclose(np.asarray(logits[:, 1:]),
                           np.asarray(shifted[:, :-1]), atol=1e-4)


@pytest.mark.parametrize("spec", [
    MeshSpec(dp=2, tp=2, sp=2),
    MeshSpec(fsdp=2, tp=2),
    MeshSpec(dp=2, fsdp=2, sp=2),
])
def test_sharded_matches_single_device(spec):
    cfg = _cfg()
    toks = _tokens()
    params = llama.init_params(cfg, KEY)
    dense = llama.loss_fn(params, toks, cfg)
    mesh = make_mesh(spec)
    state, _ = llama.make_train_state(cfg, KEY, mesh=mesh)
    sharded = llama.loss_fn(state["params"], toks, cfg, mesh)
    np.testing.assert_allclose(np.asarray(dense), np.asarray(sharded),
                               rtol=2e-4, atol=2e-4)


def test_train_step_reduces_loss():
    cfg = _cfg()
    mesh = make_mesh(MeshSpec(dp=2, tp=2, sp=2))
    toks = _tokens(b=8, t=33)
    state, _ = llama.make_train_state(cfg, KEY, mesh=mesh,
                                      learning_rate=1e-2)
    step = llama.make_train_step(cfg, mesh=mesh, learning_rate=1e-2,
                                 donate=False)
    state, m0 = step(state, toks)
    for _ in range(5):
        state, m = step(state, toks)
    assert float(m["loss"]) < float(m0["loss"])


def test_gqa_equals_mha_with_tiled_kv_weights():
    """GQA with each kv head's weights tiled to every head of its query
    group must equal full MHA — the oracle for the group-broadcast
    mapping."""
    cfg_gqa = _cfg(n_kv_heads=2)
    cfg_mha = _cfg(n_kv_heads=4)
    params = llama.init_params(cfg_gqa, KEY)
    rep = cfg_gqa.n_heads // cfg_gqa.n_kv_heads
    mha_params = {k: v for k, v in params.items()}
    mha_params["blocks"] = dict(params["blocks"])
    # wkv: [L, D, 2, Hkv, Dh] -> tile kv head g to query heads of group g.
    mha_params["blocks"]["wkv"] = np.repeat(
        np.asarray(params["blocks"]["wkv"]), rep, axis=3)
    toks = _tokens(b=2, t=17)
    out_gqa = llama.forward(params, toks, cfg_gqa)
    out_mha = llama.forward(mha_params, toks, cfg_mha)
    np.testing.assert_allclose(np.asarray(out_gqa), np.asarray(out_mha),
                               rtol=1e-5, atol=1e-5)


def test_max_seq_enforced():
    cfg = _cfg(max_seq=16)
    params = llama.init_params(cfg, KEY)
    with pytest.raises(ValueError, match="max_seq"):
        llama.forward(params, _tokens(b=1, t=17), cfg)
