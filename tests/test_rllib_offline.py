"""Offline dataset IO: rollout `output` -> json files -> BC training.

Reference: rllib/offline/{json_writer,json_reader}.py."""

import glob
import os

import numpy as np
import pytest

import ray_tpu
from ray_tpu.rllib import BCConfig, PPOConfig
from ray_tpu.rllib.offline import JsonReader, JsonWriter, read_sample_batches
from ray_tpu.rllib.policy.sample_batch import SampleBatch


@pytest.fixture
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_json_writer_reader_roundtrip(tmp_path):
    out = str(tmp_path / "ds")
    w = JsonWriter(out)
    b = SampleBatch({
        "obs": np.random.randn(10, 4).astype(np.float32),
        "actions": np.arange(10, dtype=np.int32) % 2,
        "rewards": np.ones(10, np.float32),
        "dones": np.zeros(10, bool),
    })
    w.write(b)
    w.write(b)
    w.close()
    files = glob.glob(os.path.join(out, "*.json"))
    assert files
    all_rows = read_sample_batches(out)
    assert all_rows.count == 20
    np.testing.assert_allclose(all_rows["obs"][:10], b["obs"], rtol=1e-6)
    # Streaming reader cycles forever.
    r = JsonReader(out)
    assert r.next().count == 10


def test_rollout_output_config_records(ray_init, tmp_path):
    """The worker-side writer branch: rollouts(output=dir) records every
    sampled fragment without any manual writer."""
    out = str(tmp_path / "auto_ds")
    algo = (PPOConfig()
            .environment("CartPole-v1")
            .rollouts(num_rollout_workers=0, rollout_fragment_length=200,
                      output=out)
            .training(train_batch_size=200, num_sgd_iter=2,
                      sgd_minibatch_size=64)
            .debugging(seed=0)
            .build())
    algo.train()
    algo.stop()
    files = glob.glob(os.path.join(out, "*.json"))
    assert files, "rollout output recorded nothing"
    assert read_sample_batches(out).count >= 200


@pytest.mark.slow
def test_collect_then_bc_from_files(ray_init, tmp_path):
    """PPO collects CartPole experience with rollout output=<dir>; BC
    then trains purely from the files (input_data=<path>)."""
    out = str(tmp_path / "cartpole_ds")
    collector = (PPOConfig()
                 .environment("CartPole-v1")
                 .rollouts(num_rollout_workers=0,
                           rollout_fragment_length=250)
                 .training(train_batch_size=1500, num_sgd_iter=6,
                           sgd_minibatch_size=128, lr=2e-3)
                 .debugging(seed=1)
                 .build())
    # Train FIRST, then record: the dataset holds the trained policy's
    # behavior, not the random warmup (expert data for cloning).
    for _ in range(6):
        collector.train()
    worker = collector.workers.local_worker
    writer = JsonWriter(out)
    for _ in range(4):
        writer.write(worker.sample(1000))
    writer.close()
    collector.stop()
    assert glob.glob(os.path.join(out, "*.json"))

    bc = (BCConfig()
          .environment("CartPole-v1")
          .training(num_sgd_iter=25, sgd_minibatch_size=256, lr=2e-3)
          .offline_data(input_data=out)
          .debugging(seed=2)
          .build())
    best = 0.0
    for _ in range(4):
        r = bc.train()
        best = max(best, r.get("episode_reward_mean") or 0.0)
    bc.stop()
    # Cloning the trained policy's behavior clearly beats random (~22).
    assert best >= 40, f"BC from offline files failed (best={best})"
