"""Serve: deploy/query/update/autoscale against a real in-process cluster
(reference test style: python/ray/serve/tests — controller/proxy tested
against a live local Serve instance)."""

import threading
import time

import pytest

import ray_tpu
from ray_tpu import serve


@pytest.fixture
def serve_instance():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    serve.start()
    yield
    serve.shutdown()
    ray_tpu.shutdown()


def test_deploy_function_and_query(serve_instance):
    @serve.deployment
    def echo(req):
        return {"got": req.json() if hasattr(req, "json") else req}

    handle = echo.deploy()
    resp = handle.remote("hello")
    assert resp.result(timeout=60) == {"got": "hello"}


def test_class_deployment_replicas_and_methods(serve_instance):
    @serve.deployment(name="counter", num_replicas=2)
    class Counter:
        def __init__(self, start):
            self.n = start

        def incr(self, by):
            self.n += by
            return self.n

        def __call__(self, req):
            return self.n

    handle = Counter.options(init_args=(10,)).deploy()
    out = handle.incr.remote(5).result(timeout=60)
    assert out == 15
    # Two replicas are running per the controller's status.
    st = {s["name"]: s for s in serve.status()}
    assert st["counter"]["replica_states"].get("RUNNING") == 2
    assert st["counter"]["status"] == "HEALTHY"


@pytest.mark.slow
def test_http_proxy_end_to_end(serve_instance):
    import requests

    @serve.deployment(name="hello")
    def hello(req):
        name = req.query.get("name", "world")
        return {"hello": name}

    serve.run(hello, _start_proxy=True)
    addr = serve.get_proxy_address()
    base = f"http://{addr['host']}:{addr['port']}"
    r = requests.get(f"{base}/hello?name=tpu", timeout=30)
    assert r.status_code == 200
    assert r.json() == {"hello": "tpu"}
    r = requests.get(f"{base}/nosuch", timeout=30)
    assert r.status_code == 404


def test_asgi_repeated_headers_survive_to_the_wire(serve_instance):
    """Multiple Set-Cookie headers from an ASGI app must all reach the
    HTTP client — carrying headers as a dict anywhere in the path
    collapses repeats."""
    import requests

    async def app(scope, receive, send):
        await send({"type": "http.response.start", "status": 200,
                    "headers": [(b"content-type", b"text/plain"),
                                (b"set-cookie", b"a=1; Path=/"),
                                (b"set-cookie", b"b=2; Path=/"),
                                (b"x-marker", b"yes")]})
        await send({"type": "http.response.body", "body": b"ok"})

    @serve.deployment(name="cookies")
    @serve.ingress(app)
    class Cookies:
        pass

    serve.run(Cookies, _start_proxy=True)
    addr = serve.get_proxy_address()
    base = f"http://{addr['host']}:{addr['port']}"
    r = requests.get(f"{base}/cookies", timeout=30)
    assert r.status_code == 200 and r.text == "ok"
    assert r.headers["x-marker"] == "yes"
    cookies = [v for k, v in r.raw.headers.items()
               if k.lower() == "set-cookie"]
    assert cookies == ["a=1; Path=/", "b=2; Path=/"]
    assert r.cookies["a"] == "1" and r.cookies["b"] == "2"


def test_run_asgi_returns_header_pairs():
    """_run_asgi itself must hand back (name, value) PAIRS, preserving
    order and repeats."""
    import asyncio

    from ray_tpu.serve._private.replica import Request
    from ray_tpu.serve.api import _run_asgi

    async def app(scope, receive, send):
        await send({"type": "http.response.start", "status": 201,
                    "headers": [(b"set-cookie", b"x=1"),
                                (b"set-cookie", b"y=2"),
                                (b"content-type", b"application/json")]})
        await send({"type": "http.response.body", "body": b"{}"})

    req = Request(method="GET", path="/", query={}, body=b"",
                  headers={})
    out = asyncio.new_event_loop().run_until_complete(_run_asgi(app, req))
    assert out["status"] == 201
    assert out["content_type"] == "application/json"
    assert out["headers"] == [("set-cookie", "x=1"), ("set-cookie", "y=2"),
                              ("content-type", "application/json")]


def test_rolling_update_zero_downtime(serve_instance):
    @serve.deployment(name="ver", num_replicas=2, version="1")
    def ver(req):
        return "v1"

    handle = ver.deploy()
    assert handle.remote(None).result(timeout=60) == "v1"

    failures = []
    seen = set()
    stop = threading.Event()

    def _hammer():
        while not stop.is_set():
            try:
                seen.add(handle.remote(None).result(timeout=60))
            except Exception as e:
                failures.append(e)
            time.sleep(0.02)

    t = threading.Thread(target=_hammer)
    t.start()
    try:
        @serve.deployment(name="ver", num_replicas=2, version="2")
        def ver2(req):
            return "v2"

        ver2.deploy()
        deadline = time.time() + 60
        while "v2" not in seen and time.time() < deadline:
            time.sleep(0.1)
    finally:
        stop.set()
        t.join(timeout=10)
    assert not failures, failures[:3]
    assert "v2" in seen  # new version took over
    # old version fully retired
    assert handle.remote(None).result(timeout=60) == "v2"
    st = {s["name"]: s for s in serve.status()}
    assert st["ver"]["version"] == "2"


def test_autoscaling_scales_up(serve_instance):
    @serve.deployment(
        name="slow",
        max_concurrent_queries=2,
        autoscaling_config={"min_replicas": 1, "max_replicas": 3,
                            "target_num_ongoing_requests_per_replica": 1,
                            "upscale_delay_s": 0.5,
                            "downscale_delay_s": 60.0})
    def slow(req):
        time.sleep(1.5)
        return "done"

    handle = slow.deploy()
    # Flood with concurrent requests to build up ongoing load.
    resps = [handle.remote(None) for _ in range(8)]
    deadline = time.time() + 60
    peak = 1
    while time.time() < deadline:
        st = {s["name"]: s for s in serve.status()}
        peak = max(peak, st["slow"]["target_num_replicas"])
        if peak >= 2:
            break
        time.sleep(0.25)
    for r in resps:
        assert r.result(timeout=120) == "done"
    assert peak >= 2, f"never scaled up (peak={peak})"


def test_serve_batch(serve_instance):
    @serve.deployment(name="batcher", max_concurrent_queries=64)
    class Batcher:
        def __init__(self):
            self.batch_sizes = []

        @serve.batch(max_batch_size=8, batch_wait_timeout_s=0.2)
        async def __call__(self, items):
            self.batch_sizes.append(len(items))
            return [x * 2 for x in items]

        def seen_batches(self):
            return self.batch_sizes

    handle = Batcher.deploy()
    resps = [handle.remote(i) for i in range(8)]
    assert [r.result(timeout=60) for r in resps] == [i * 2
                                                     for i in range(8)]
    sizes = handle.seen_batches.remote().result(timeout=60)
    assert max(sizes) > 1  # concurrent calls actually batched


def test_batch_queue_registry_evicts_dead_instances():
    """The per-instance @serve.batch queue registry must not leak dead
    instances (replica restarts) nor cross-wire two instances whose
    id() collides after reuse."""
    import asyncio
    import gc

    from ray_tpu import serve as serve_mod

    class Doubler:
        @serve_mod.batch(max_batch_size=4, batch_wait_timeout_s=0.01)
        async def call(self, xs):
            return [x * 2 for x in xs]

    registry = Doubler.call._rt_batch_queues

    async def use(obj):
        return await obj.call(21)

    a = Doubler()
    assert asyncio.run(use(a)) == 42
    assert len(registry) == 1
    del a
    gc.collect()
    assert len(registry) == 0, "dead instance leaked its batch queue"

    # id-reuse guard: an entry claiming a key must be ignored when the
    # weakref no longer points at the CALLING instance.
    b = Doubler()
    other = Doubler()
    import weakref
    sentinel = object()
    registry[id(b)] = (weakref.ref(other), sentinel)
    assert asyncio.run(use(b)) == 42
    wr, q = registry[id(b)]
    assert q is not sentinel and wr() is b

    # ...and from the WRITE side: a GC-deferred death callback firing
    # after its key was reused must not evict the successor's entry.
    c1 = Doubler()
    assert asyncio.run(use(c1)) == 42
    key = id(c1)
    successor = Doubler()
    sentinel2 = object()
    registry[key] = (weakref.ref(successor), sentinel2)
    del c1
    gc.collect()  # fires c1's callback; entry is no longer c1's
    assert registry[key][1] is sentinel2, \
        "deferred death callback evicted the successor's queue"


def test_batch_flush_uses_submit_loop():
    """_flush must run the batch on the loop that accepted the submits
    (not asyncio.get_event_loop() at flush time): drive submits from a
    non-main thread's loop, where get_event_loop() would fail/misfire."""
    import asyncio

    from ray_tpu import serve as serve_mod

    @serve_mod.batch(max_batch_size=8, batch_wait_timeout_s=0.05)
    async def doubler(x):
        return [v * 2 for v in x]

    results = []

    def run_in_thread():
        loop = asyncio.new_event_loop()
        asyncio.set_event_loop(None)  # no ambient loop for _flush to grab

        async def go():
            # fewer than max_batch_size: the timer path must flush
            return await asyncio.gather(doubler(1), doubler(2))

        results.extend(loop.run_until_complete(go()))
        loop.close()

    t = threading.Thread(target=run_in_thread)
    t.start()
    t.join(timeout=30)
    assert results == [2, 4]


def test_router_saturation_gauges(serve_instance):
    """ReplicaSet queue depth / in-flight counts surface as metrics
    gauges in the handle-holding process."""
    @serve.deployment(name="gauged")
    def gauged(x):
        return x + 1

    handle = gauged.deploy()
    assert handle.remote(1).result(timeout=60) == 2
    from ray_tpu.util.metrics import prometheus_text, registry_snapshot
    text = prometheus_text(registry_snapshot())
    assert 'serve_router_in_flight{deployment="gauged"}' in text
    assert 'serve_router_queue_depth{deployment="gauged"}' in text
    assert "serve_replica_in_flight" in text


def test_model_composition_child_deployments(serve_instance):
    @serve.deployment(name="preprocess")
    def preprocess(x):
        return x * 2

    @serve.deployment(name="ingress")
    class Ingress:
        def __init__(self, child):
            self.child = child  # DeploymentHandle injected by deploy()

        async def __call__(self, x):
            return await self.child.remote(x) + 1

    handle = Ingress.bind(preprocess).deploy()
    assert handle.remote(20).result(timeout=60) == 41
    st = {s["name"] for s in serve.status()}
    assert {"preprocess", "ingress"} <= st


def test_rest_deploy_via_dashboard(serve_instance):
    import requests

    from ray_tpu.dashboard import start_dashboard

    addr = start_dashboard()
    base = f"http://{addr['host']}:{addr['port']}"
    r = requests.put(f"{base}/api/serve/applications", json={
        "deployments": [{
            "import_path": "ray_tpu.serve.examples:rest_echo",
            "num_replicas": 1,
        }]}, timeout=120)
    assert r.status_code == 200, r.text
    assert r.json()["deployed"] == ["rest_echo"]
    h = serve.get_deployment_handle("rest_echo")
    assert h.remote("ping").result(timeout=60) == {"echo": "ping"}
    # Bad import path is a 400, not a hang.
    r = requests.put(f"{base}/api/serve/applications", json={
        "deployments": [{"import_path": "nosuch.module:thing"}]},
        timeout=60)
    assert r.status_code == 400


def test_route_prefix(serve_instance):
    import requests

    @serve.deployment(name="prefixed", route_prefix="/api/v2/echo")
    def prefixed(req):
        return {"path": req.path}

    serve.run(prefixed, _start_proxy=True)
    addr = serve.get_proxy_address()
    base = f"http://{addr['host']}:{addr['port']}"
    r = requests.get(f"{base}/api/v2/echo/sub/path", timeout=30)
    assert r.status_code == 200
    assert r.json() == {"path": "/sub/path"}
    assert requests.get(f"{base}/api/v2/other", timeout=30
                        ).status_code == 404


@pytest.mark.slow
def test_proxy_per_node(ray_start_cluster):
    import requests

    cluster = ray_start_cluster
    cluster.add_node(num_cpus=2, resources={"head": 1})
    cluster.add_node(num_cpus=2, resources={"other": 1})
    cluster.wait_for_nodes(2)
    cluster.connect()
    serve.start(_start_proxy=True,
                http_options={"location": "EveryNode"})
    try:
        @serve.deployment(name="everywhere")
        def everywhere(req):
            return "pong"

        everywhere.deploy()
        addrs = serve.get_proxy_addresses()
        assert len(addrs) == 2, addrs
        for addr in addrs:
            r = requests.get(
                f"http://{addr['host']}:{addr['port']}/everywhere",
                timeout=60)
            assert r.status_code == 200 and r.text == "pong"
    finally:
        serve.shutdown()
