"""Ray Data equivalent: blocks, transforms, shuffle, ingest (reference
test style: python/ray/data/tests/test_dataset.py)."""

import numpy as np
import pytest

import ray_tpu
from ray_tpu import data as rd


@pytest.fixture(scope="module")
def ray_init():
    ray_tpu.init(num_cpus=4, ignore_reinit_error=True)
    yield
    ray_tpu.shutdown()


def test_range_count_take(ray_init):
    ds = rd.range(100, parallelism=4)
    assert ds.count() == 100
    assert ds.take(5) == [0, 1, 2, 3, 4]
    assert ds.num_blocks() == 4


@pytest.mark.slow
def test_map_batches_and_filter(ray_init):
    ds = rd.range(32, parallelism=4)
    out = ds.map_batches(lambda b: [x * 2 for x in b],
                         batch_format="pylist") \
            .filter(lambda x: x % 4 == 0)
    vals = out.take_all()
    assert vals == [x * 2 for x in range(32) if (x * 2) % 4 == 0]


def test_map_and_flat_map(ray_init):
    ds = rd.from_items([1, 2, 3], parallelism=2)
    assert sorted(ds.map(lambda x: x + 1).take_all()) == [2, 3, 4]
    assert sorted(ds.flat_map(lambda x: [x, x]).take_all()) == \
        [1, 1, 2, 2, 3, 3]


def test_numpy_blocks_and_iter_batches(ray_init):
    arr = np.arange(40, dtype=np.float32)
    ds = rd.from_numpy(arr, parallelism=4)
    assert ds.count() == 40
    batches = list(ds.iter_batches(batch_size=16, batch_format="numpy"))
    total = np.concatenate([b["data"] for b in batches])
    assert np.array_equal(np.sort(total), arr)
    assert batches[0]["data"].shape[0] == 16


def test_random_shuffle_preserves_rows(ray_init):
    ds = rd.range(64, parallelism=4).random_shuffle(seed=7)
    vals = ds.take_all()
    assert sorted(vals) == list(range(64))
    assert vals != list(range(64))


def test_sort_and_groupby(ray_init):
    import pandas as pd
    df = pd.DataFrame({"k": [1, 2, 1, 2, 3], "v": [5, 1, 3, 2, 9]})
    ds = rd.from_pandas(df)
    sorted_v = rd.from_pandas(df).sort("v").to_pandas()["v"].tolist()
    assert sorted_v == [1, 2, 3, 5, 9]
    counts = ds.groupby("k").count().to_pandas()
    assert dict(zip(counts["k"], counts["count()"])) == {1: 2, 2: 2, 3: 1}
    sums = ds.groupby("k").sum("v").to_pandas()
    assert dict(zip(sums["k"], sums["v"])) == {1: 8, 2: 3, 3: 9}


def test_split_and_union(ray_init):
    ds = rd.range(30, parallelism=3)
    shards = ds.split(3)
    assert sum(s.count() for s in shards) == 30
    u = shards[0].union(*shards[1:])
    assert sorted(u.take_all()) == list(range(30))


def test_repartition_and_limit(ray_init):
    ds = rd.range(20, parallelism=2).repartition(5)
    assert ds.num_blocks() == 5
    assert ds.limit(7).count() == 7


def test_read_write_parquet_csv(ray_init, tmp_path):
    import pandas as pd
    df = pd.DataFrame({"a": range(10), "b": [x * x for x in range(10)]})
    ds = rd.from_pandas(df)
    ds.write_parquet(str(tmp_path / "pq"))
    back = rd.read_parquet(str(tmp_path / "pq"))
    assert back.count() == 10
    assert back.sum("b") == sum(x * x for x in range(10))
    ds.write_csv(str(tmp_path / "csv"))
    assert rd.read_csv(str(tmp_path / "csv")).count() == 10


def test_aggregates(ray_init):
    ds = rd.range(10, parallelism=2)
    assert ds.sum() == 45
    assert ds.min() == 0
    assert ds.max() == 9
    assert ds.mean() == 4.5


def test_actor_pool_strategy(ray_init):
    ds = rd.range(8, parallelism=4)
    out = ds.map_batches(lambda b: [x + 100 for x in b],
                         batch_format="pylist",
                         compute=rd.ActorPoolStrategy(size=2))
    assert sorted(out.take_all()) == [x + 100 for x in range(8)]


def test_iter_jax_batches(ray_init):
    import jax.numpy as jnp
    ds = rd.from_numpy(np.arange(16, dtype=np.float32))
    batches = list(ds.iter_jax_batches(batch_size=8))
    assert all(isinstance(b["data"], jnp.ndarray) for b in batches)


def test_pipeline_repeat(ray_init):
    pipe = rd.range(4, parallelism=2).repeat(3)
    rows = list(pipe.iter_rows())
    assert len(rows) == 12


def test_datasource_and_stats(ray_init):
    from ray_tpu.data import RangeDatasource, read_datasource

    ds = read_datasource(RangeDatasource(), parallelism=4, n=20)
    ds = ds.map(lambda x: x * 2)
    assert sorted(ds.take_all()) == [x * 2 for x in range(20)]
    assert "blocks" in ds.stats()


def test_preprocessors(ray_init):
    import pandas as pd
    from ray_tpu.data.preprocessors import (Chain, LabelEncoder,
                                            MinMaxScaler, StandardScaler)

    df = pd.DataFrame({"a": [1.0, 2.0, 3.0, 4.0],
                       "b": [10.0, 20.0, 30.0, 40.0],
                       "label": ["cat", "dog", "cat", "bird"]})
    ds = rd.from_pandas([df.iloc[:2], df.iloc[2:]])

    scaled = StandardScaler(["a"]).fit_transform(ds).to_pandas()
    assert abs(scaled["a"].mean()) < 1e-9
    assert abs(scaled["a"].std(ddof=0) - 1.0) < 1e-9

    mm = MinMaxScaler(["b"]).fit_transform(ds).to_pandas()
    assert mm["b"].min() == 0.0 and mm["b"].max() == 1.0

    enc = LabelEncoder("label").fit_transform(ds).to_pandas()
    assert set(enc["label"]) == {0, 1, 2}

    chain = Chain(StandardScaler(["a"]), MinMaxScaler(["a"]))
    out = chain.fit(ds).transform(ds).to_pandas()
    assert out["a"].min() == 0.0 and out["a"].max() == 1.0


def test_from_huggingface(ray_init):
    import datasets as hf

    hfds = hf.Dataset.from_dict({"x": list(range(12)),
                                 "y": ["a"] * 6 + ["b"] * 6})
    ds = rd.from_huggingface(hfds, parallelism=3)
    assert ds.count() == 12
    assert sorted(ds.to_pandas()["x"]) == list(range(12))


def test_zip_merges_rows(ray_init):
    import ray_tpu.data as rd
    a = rd.from_items([{"x": i} for i in range(20)], parallelism=3)
    b = rd.from_items([{"y": i * 10} for i in range(20)], parallelism=5)
    out = a.zip(b).take_all()
    assert out == [{"x": i, "y": i * 10} for i in range(20)]
    # conflicting column gets _1 suffix
    c = rd.from_items([{"x": -i} for i in range(20)], parallelism=2)
    row0 = a.zip(c).take(1)[0]
    assert row0 == {"x": 0, "x_1": 0}
    with pytest.raises(ValueError, match="equal row counts"):
        a.zip(rd.from_items([{"y": 1}], parallelism=1))


def test_random_sample(ray_init):
    import ray_tpu.data as rd
    ds = rd.range(1000, parallelism=4)
    got = ds.random_sample(0.2, seed=7).take_all()
    assert 100 < len(got) < 320          # ~200 expected
    assert got == sorted(got)            # order preserved within/between
    # reproducible with the same seed
    again = ds.random_sample(0.2, seed=7).take_all()
    assert got == again
    assert ds.random_sample(0.0).count() == 0
    assert ds.random_sample(1.0).count() == 1000


def test_split_at_indices_and_train_test_split(ray_init):
    import ray_tpu.data as rd
    ds = rd.range(30, parallelism=4)
    parts = ds.split_at_indices([10, 25])
    assert [p.count() for p in parts] == [10, 15, 5]
    assert parts[1].take(3) == [10, 11, 12]
    with pytest.raises(ValueError, match="sorted"):
        ds.split_at_indices([25, 10])

    train, test = ds.train_test_split(0.2)
    assert train.count() == 24 and test.count() == 6
    assert test.take_all() == list(range(24, 30))
    train, test = ds.train_test_split(7, shuffle=True, seed=3)
    assert train.count() == 23 and test.count() == 7
    assert sorted(train.take_all() + test.take_all()) == list(range(30))
